"""S3 signature auth — AWS V4 (SigV4) and V2, sign + verify.

Reference counterpart: objectnode/auth_signature_v4.go and auth_signature_v2.go
(header-based Authorization parsing, canonical request construction, derived
signing key chain) with the check driven from the router wrapper. Both the
verifier (server side) and a signer (client side, like the api clients and the
s3tests harness) live here so the two directions share one canonicalization.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from base64 import b64encode

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
V4_ALGO = "AWS4-HMAC-SHA256"


class AuthError(Exception):
    pass


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "~" if encode_slash else "~/"
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(raw_query: str, drop: frozenset = frozenset()) -> str:
    pairs = urllib.parse.parse_qsl(raw_query, keep_blank_values=True)
    enc = sorted((_uri_encode(k), _uri_encode(v)) for k, v in pairs
                 if k not in drop)
    return "&".join(f"{k}={v}" for k, v in enc)


# -- SigV4 ---------------------------------------------------------------------

def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    k = _hmac(b"AWS4" + secret.encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request_v4(method: str, path: str, raw_query: str,
                         headers: dict[str, str], signed_headers: list[str],
                         payload_hash: str) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers)
    return "\n".join([
        method.upper(),
        _uri_encode(path, encode_slash=False) or "/",
        _canonical_query(raw_query),
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign_v4(amz_date: str, scope: str, canonical_request: str) -> str:
    digest = hashlib.sha256(canonical_request.encode()).hexdigest()
    return "\n".join([V4_ALGO, amz_date, scope, digest])


def sign_v4(method: str, path: str, raw_query: str, headers: dict[str, str],
            access_key: str, secret_key: str, region: str = "cfs",
            payload: bytes = b"") -> dict[str, str]:
    """Client side: return headers with Authorization et al. attached.

    `headers` must already include `host`; x-amz-date and the payload hash are
    filled in here."""
    import time

    hdrs = {k.lower(): v for k, v in headers.items()}
    # canonicalize from the DECODED path (the server router verifies against
    # its decoded req.path); callers may pass the percent-encoded target
    path = urllib.parse.unquote(path)
    amz_date = hdrs.get("x-amz-date") or time.strftime("%Y%m%dT%H%M%SZ",
                                                       time.gmtime())
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = hashlib.sha256(payload).hexdigest()
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    signed = sorted(h for h in hdrs
                    if h in ("host", "content-type") or h.startswith("x-amz-"))
    creq = canonical_request_v4(method, path, raw_query, hdrs, signed,
                                hdrs["x-amz-content-sha256"])
    sts = string_to_sign_v4(amz_date, scope, creq)
    sig = hmac.new(signing_key(secret_key, date, region), sts.encode(),
                   hashlib.sha256).hexdigest()
    hdrs["authorization"] = (
        f"{V4_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return hdrs


def parse_auth_v4(auth_header: str) -> dict:
    if not auth_header.startswith(V4_ALGO):
        raise AuthError("not a v4 authorization header")
    fields: dict[str, str] = {}
    for item in auth_header[len(V4_ALGO):].split(","):
        k, _, v = item.strip().partition("=")
        fields[k] = v
    try:
        cred = fields["Credential"].split("/")
        return {
            "access_key": cred[0],
            "date": cred[1],
            "region": cred[2],
            "service": cred[3],
            "signed_headers": fields["SignedHeaders"].split(";"),
            "signature": fields["Signature"],
        }
    except (KeyError, IndexError) as e:
        raise AuthError(f"malformed v4 authorization: {e}") from None


def verify_v4(req, secret_key: str) -> bool:
    """req is an rpc Request; verifies header-based SigV4."""
    info = parse_auth_v4(req.header("authorization"))
    payload_hash = req.header("x-amz-content-sha256") or UNSIGNED_PAYLOAD
    if payload_hash not in (UNSIGNED_PAYLOAD,):
        if hashlib.sha256(req.body).hexdigest() != payload_hash:
            return False
    creq = canonical_request_v4(req.method, req.path, req.raw_query,
                                req.headers, info["signed_headers"],
                                payload_hash)
    amz_date = req.header("x-amz-date") or req.header("date")
    scope = f"{info['date']}/{info['region']}/{info['service']}/aws4_request"
    sts = string_to_sign_v4(amz_date, scope, creq)
    key = signing_key(secret_key, info["date"], info["region"], info["service"])
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return hmac.compare_digest(want, info["signature"])


# -- SigV4 presigned URLs (query auth) -----------------------------------------

def presign_v4(method: str, path: str, host: str, access_key: str,
               secret_key: str, region: str = "cfs", expires: int = 900,
               extra_query: str = "", amz_date: str | None = None) -> str:
    """Return the full query string of a presigned-V4 URL for `path`.

    Only `host` is signed (the aws-cli default); the payload is UNSIGNED."""
    import time

    path = urllib.parse.unquote(path)
    amz_date = amz_date or time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    q = {
        "X-Amz-Algorithm": V4_ALGO,
        "X-Amz-Credential": f"{access_key}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    raw = extra_query + ("&" if extra_query else "") + urllib.parse.urlencode(q)
    creq = canonical_request_v4(method, path, raw, {"host": host}, ["host"],
                                UNSIGNED_PAYLOAD)
    sts = string_to_sign_v4(amz_date, scope, creq)
    sig = hmac.new(signing_key(secret_key, date, region), sts.encode(),
                   hashlib.sha256).hexdigest()
    return raw + "&X-Amz-Signature=" + sig


def verify_presigned_v4(req, secret_key: str) -> bool:
    """Verify a query-auth (presigned) V4 request, including expiry."""
    import time

    import calendar

    q = {k: v[0] for k, v in req.query.items() if v}
    try:
        cred = q["X-Amz-Credential"].split("/")
        amz_date, expires = q["X-Amz-Date"], int(q["X-Amz-Expires"])
        signed_headers = q["X-Amz-SignedHeaders"].split(";")
        sig = q["X-Amz-Signature"]
        t0 = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
        date, region, service = cred[1], cred[2], cred[3]
    except (KeyError, IndexError, ValueError):
        return False  # malformed presigned params = failed auth, never a 500
    if time.time() > t0 + expires:
        return False
    raw = _canonical_query(req.raw_query, drop=frozenset(("X-Amz-Signature",)))
    creq = canonical_request_v4(req.method, req.path, raw, req.headers,
                                signed_headers, UNSIGNED_PAYLOAD)
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = string_to_sign_v4(amz_date, scope, creq)
    key = signing_key(secret_key, date, region, service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return hmac.compare_digest(want, sig)


# -- SigV2 ---------------------------------------------------------------------

_V2_SUBRESOURCES = ("acl", "cors", "delete", "lifecycle", "location", "policy",
                    "tagging", "uploads", "uploadId", "partNumber",
                    "versionId", "versioning", "versions")


def _canonical_resource_v2(path: str, raw_query: str) -> str:
    qs = urllib.parse.parse_qsl(raw_query, keep_blank_values=True)
    keep = sorted((k, v) for k, v in qs if k in _V2_SUBRESOURCES)
    out = path or "/"
    if keep:
        out += "?" + "&".join(k if v == "" else f"{k}={v}" for k, v in keep)
    return out


def string_to_sign_v2(method: str, path: str, raw_query: str,
                      headers: dict[str, str]) -> str:
    amz = sorted((k, v) for k, v in headers.items() if k.startswith("x-amz-"))
    amz_lines = "".join(f"{k}:{v}\n" for k, v in amz)
    return (f"{method.upper()}\n{headers.get('content-md5', '')}\n"
            f"{headers.get('content-type', '')}\n{headers.get('date', '')}\n"
            f"{amz_lines}{_canonical_resource_v2(path, raw_query)}")


def sign_v2(method: str, path: str, raw_query: str, headers: dict[str, str],
            access_key: str, secret_key: str) -> dict[str, str]:
    hdrs = {k.lower(): v for k, v in headers.items()}
    sts = string_to_sign_v2(method, urllib.parse.unquote(path), raw_query, hdrs)
    sig = b64encode(hmac.new(secret_key.encode(), sts.encode(),
                             hashlib.sha1).digest()).decode()
    hdrs["authorization"] = f"AWS {access_key}:{sig}"
    return hdrs


def verify_v2(req, secret_key: str) -> bool:
    auth = req.header("authorization")
    if not auth.startswith("AWS ") or ":" not in auth:
        return False
    _, sig = auth[4:].rsplit(":", 1)
    sts = string_to_sign_v2(req.method, req.path, req.raw_query, req.headers)
    want = b64encode(hmac.new(secret_key.encode(), sts.encode(),
                              hashlib.sha1).digest()).decode()
    return hmac.compare_digest(want, sig)


def presign_v2(method: str, path: str, access_key: str, secret_key: str,
               expires_at: int, subresource_query: str = "") -> str:
    """Query string of a V2 presigned URL (AWSAccessKeyId/Expires/Signature).

    `subresource_query` is any signed subresource the URL targets (e.g.
    "versionId=x"); it is part of the canonical resource, so the URL holder
    cannot retarget the signature at a different subresource."""
    path = urllib.parse.unquote(path)
    resource = _canonical_resource_v2(path, subresource_query)
    sts = f"{method.upper()}\n\n\n{expires_at}\n{resource}"
    sig = b64encode(hmac.new(secret_key.encode(), sts.encode(),
                             hashlib.sha1).digest()).decode()
    out = {"AWSAccessKeyId": access_key, "Expires": expires_at, "Signature": sig}
    q = urllib.parse.urlencode(out)
    return f"{subresource_query}&{q}" if subresource_query else q


def verify_presigned_v2(req, secret_key: str) -> bool:
    import time

    try:
        expires_at = int(req.query["Expires"][0])
        sig = req.query["Signature"][0]
    except (KeyError, IndexError, ValueError):
        return False
    if time.time() > expires_at:
        return False
    # the canonical resource includes the request's signed subresources
    # (auth params like Signature/Expires aren't in _V2_SUBRESOURCES, so the
    # filter drops them automatically)
    resource = _canonical_resource_v2(req.path, req.raw_query)
    sts = f"{req.method.upper()}\n\n\n{expires_at}\n{resource}"
    want = b64encode(hmac.new(secret_key.encode(), sts.encode(),
                              hashlib.sha1).digest()).decode()
    return hmac.compare_digest(want, sig)


def is_presigned(req) -> bool:
    return "X-Amz-Signature" in req.query or "Signature" in req.query


def verify_presigned(req, secret_key: str) -> bool:
    if "X-Amz-Signature" in req.query:
        return verify_presigned_v4(req, secret_key)
    return verify_presigned_v2(req, secret_key)


def access_key_of(req) -> str | None:
    """Pull the access key out of any auth flavor (router pre-step)."""
    auth = req.header("authorization")
    if auth.startswith(V4_ALGO):
        try:
            return parse_auth_v4(auth)["access_key"]
        except AuthError:
            return None
    if auth.startswith("AWS ") and ":" in auth:
        return auth[4:].rsplit(":", 1)[0]
    # presigned flavors carry the key in the query
    cred = req.query.get("X-Amz-Credential")
    if cred:
        return cred[0].split("/")[0]
    ak = req.query.get("AWSAccessKeyId")
    if ak:
        return ak[0]
    return None
