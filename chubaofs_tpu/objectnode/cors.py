"""Bucket CORS engine (objectnode CORS handling analog).

Reference counterpart: objectnode's CORS config (XML rules with
AllowedOrigin/AllowedMethod/AllowedHeader/ExposeHeader/MaxAgeSeconds) matched
against the Origin + Access-Control-Request-Method of a request; first
matching rule wins. Stored as JSON in the `oss:cors` xattr of the bucket root.
"""

from __future__ import annotations

import fnmatch
import json

XATTR_CORS = "oss:cors"


class CORSRule:
    def __init__(self, allowed_origins: list[str], allowed_methods: list[str],
                 allowed_headers: list[str] | None = None,
                 expose_headers: list[str] | None = None,
                 max_age_seconds: int = 0):
        self.allowed_origins = allowed_origins
        self.allowed_methods = [m.upper() for m in allowed_methods]
        self.allowed_headers = allowed_headers or []
        self.expose_headers = expose_headers or []
        self.max_age_seconds = max_age_seconds

    def matches(self, origin: str, method: str) -> bool:
        if method.upper() not in self.allowed_methods:
            return False
        return any(fnmatch.fnmatchcase(origin, pat)
                   for pat in self.allowed_origins)

    def to_dict(self) -> dict:
        return {
            "AllowedOrigin": self.allowed_origins,
            "AllowedMethod": self.allowed_methods,
            "AllowedHeader": self.allowed_headers,
            "ExposeHeader": self.expose_headers,
            "MaxAgeSeconds": self.max_age_seconds,
        }


class CORSConfig:
    def __init__(self, rules: list[CORSRule]):
        self.rules = rules

    @classmethod
    def from_json(cls, raw: bytes) -> "CORSConfig":
        rules = []
        for r in json.loads(raw.decode()).get("CORSRule", []):
            rules.append(CORSRule(r.get("AllowedOrigin", []),
                                  r.get("AllowedMethod", []),
                                  r.get("AllowedHeader", []),
                                  r.get("ExposeHeader", []),
                                  r.get("MaxAgeSeconds", 0)))
        return cls(rules)

    def to_json(self) -> bytes:
        return json.dumps({"CORSRule": [r.to_dict() for r in self.rules]}).encode()

    def match(self, origin: str, method: str) -> CORSRule | None:
        for rule in self.rules:
            if rule.matches(origin, method):
                return rule
        return None

    def headers_for(self, origin: str, method: str) -> dict[str, str]:
        rule = self.match(origin, method)
        if rule is None:
            return {}
        out = {
            "Access-Control-Allow-Origin":
                origin if "*" not in rule.allowed_origins else "*",
            "Access-Control-Allow-Methods": ", ".join(rule.allowed_methods),
        }
        if rule.allowed_headers:
            out["Access-Control-Allow-Headers"] = ", ".join(rule.allowed_headers)
        if rule.expose_headers:
            out["Access-Control-Expose-Headers"] = ", ".join(rule.expose_headers)
        if rule.max_age_seconds:
            out["Access-Control-Max-Age"] = str(rule.max_age_seconds)
        return out
