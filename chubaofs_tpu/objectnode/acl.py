"""S3 ACL engine (objectnode/acl*.go analog).

Reference counterpart: objectnode's ACL handling — canned ACLs
(x-amz-acl header) and grant XML, stored per bucket/object and consulted
before policy evaluation. Stored here as JSON in the `oss:acl` xattr of the
bucket root / object inode. Permissions follow the S3 model: READ, WRITE,
READ_ACP, WRITE_ACP, FULL_CONTROL.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

XATTR_ACL = "oss:acl"

ALL_USERS = "*"  # the AllUsers group URI, shortened
PERM_READ = "READ"
PERM_WRITE = "WRITE"
PERM_READ_ACP = "READ_ACP"
PERM_WRITE_ACP = "WRITE_ACP"
PERM_FULL = "FULL_CONTROL"

CANNED = {
    "private": [],
    "public-read": [(ALL_USERS, PERM_READ)],
    "public-read-write": [(ALL_USERS, PERM_READ), (ALL_USERS, PERM_WRITE)],
    "authenticated-read": [("authenticated", PERM_READ)],
}


@dataclass
class ACL:
    owner: str
    grants: list[tuple[str, str]] = field(default_factory=list)  # (grantee, perm)

    @classmethod
    def canned(cls, owner: str, name: str) -> "ACL":
        if name not in CANNED:
            raise ValueError(f"unknown canned acl {name!r}")
        return cls(owner, list(CANNED[name]))

    def allows(self, principal: str | None, perm: str) -> bool:
        if principal == self.owner:
            return True
        for grantee, granted in self.grants:
            if granted not in (perm, PERM_FULL):
                continue
            if grantee == ALL_USERS:
                return True
            if grantee == "authenticated" and principal is not None:
                return True
            if grantee == principal:
                return True
        return False

    def to_json(self) -> bytes:
        return json.dumps({"owner": self.owner, "grants": self.grants}).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ACL":
        d = json.loads(raw.decode())
        return cls(d["owner"], [tuple(g) for g in d["grants"]])

    def to_xml(self) -> str:
        grants = "".join(
            f"<Grant><Grantee>{g}</Grantee><Permission>{p}</Permission></Grant>"
            for g, p in ([(self.owner, PERM_FULL)] + self.grants))
        return (f'<AccessControlPolicy><Owner><ID>{self.owner}</ID></Owner>'
                f"<AccessControlList>{grants}</AccessControlList>"
                f"</AccessControlPolicy>")
