"""S3 multipart uploads (objectnode multipart + metanode multipart state).

Reference counterpart: objectnode's multipart handlers backed by metanode
multipart sessions (SURVEY §2.1 metanode "multipart state for S3"). Parts are
written straight to the data backend (EC on TPU for cold volumes) and their
locations parked in the raft-replicated session; CompleteMultipartUpload
LINKS the part locations into the final inode's obj_extents — completion is
zero-copy, no concatenation read-back. Cold volumes only: the hot tier's
extent keys are inode-bound, so the reference routes multipart to EC volumes
too.
"""

from __future__ import annotations

import hashlib

from chubaofs_tpu.meta.metanode import OpError
from chubaofs_tpu.objectnode.volume import (
    XATTR_CONTENT_TYPE, XATTR_ETAG, DEFAULT_CONTENT_TYPE, OSSVolume,
)


class NoSuchUpload(Exception):
    pass


class InvalidPart(Exception):
    pass


class MultipartManager:
    def __init__(self, vol: OSSVolume, data_backend):
        self.vol = vol
        self.meta = vol.fs.meta
        self.data = data_backend

    def initiate(self, key: str, content_type: str = "") -> str:
        upload_id = self.meta.multipart_create(key)
        if content_type:
            # session metadata rides a sidecar entry in the same session dict
            self.meta.multipart_put_part(upload_id, 0, {
                "content_type": content_type})
        return upload_id

    def put_part(self, upload_id: str, part_num: int, data: bytes) -> str:
        if part_num < 1 or part_num > 10000:
            raise InvalidPart(f"part number {part_num} out of [1, 10000]")
        etag = hashlib.md5(data).hexdigest()
        loc = self.data.write(data)
        try:
            old = self.meta.multipart_put_part(upload_id, part_num, {
                "loc": loc, "size": len(data), "etag": etag})
        except OpError:
            self.data.delete(loc)
            raise NoSuchUpload(upload_id) from None
        if old and "loc" in old:
            # retried part upload: reclaim the superseded data now
            try:
                self.data.delete(old["loc"])
            except Exception:
                pass
        return etag

    def list_parts(self, upload_id: str) -> tuple[str, list[dict]]:
        try:
            session = self.meta.multipart_get(upload_id)
        except OpError:
            raise NoSuchUpload(upload_id) from None
        parts = [dict(info, part_number=num)
                 for num, info in sorted(session["parts"].items()) if num != 0]
        return session["key"], parts

    def list_uploads(self) -> list[dict]:
        return [{"upload_id": uid, "key": s["key"]}
                for uid, s in sorted(self.meta.multipart_list().items())]

    def complete(self, upload_id: str, parts_spec: list[tuple[int, str]]) -> tuple[str, str]:
        """parts_spec: client-ordered [(part_number, etag)]. Returns (key, etag)."""
        try:
            session = self.meta.multipart_get(upload_id)
        except OpError:
            raise NoSuchUpload(upload_id) from None
        have = session["parts"]
        ordered = []
        md5s = b""
        for num, etag in parts_spec:
            info = have.get(num)
            if info is None or info["etag"].strip('"') != etag.strip('"'):
                raise InvalidPart(f"part {num}")
            ordered.append(info)
            md5s += bytes.fromhex(info["etag"])
        if not ordered:
            raise InvalidPart("no parts")
        final_etag = f"{hashlib.md5(md5s).hexdigest()}-{len(ordered)}"

        key = session["key"]
        path = "/" + key
        parent = path.rsplit("/", 1)[0]
        if parent:
            self.vol.fs.mkdirs(parent)
        fs = self.vol.fs
        try:
            ino = fs.resolve(path)
            fs.meta.truncate(ino, 0)
        except Exception:
            ino = fs.create(path)
        size = 0
        locations = []
        for info in ordered:
            locations.append({"loc": info["loc"], "size": info["size"]})
            size += info["size"]
        fs.meta.append_obj_extents(ino, locations, size)
        fs.setxattr(path, XATTR_ETAG, final_etag.encode())
        ct = (have.get(0) or {}).get("content_type", "")
        fs.setxattr(path, XATTR_CONTENT_TYPE, (ct or DEFAULT_CONTENT_TYPE).encode())
        # unused parts (uploaded but not listed in the complete spec) are orphan
        # data: delete them now, then drop the session
        linked = {info["loc"] for info in ordered}
        session = self.meta.multipart_complete(upload_id)
        for info in session["parts"].values():
            if "loc" in info and info["loc"] not in linked:
                try:
                    self.data.delete(info["loc"])
                except Exception:
                    pass
        return key, final_etag

    def abort(self, upload_id: str) -> None:
        try:
            session = self.meta.multipart_abort(upload_id)
        except OpError:
            raise NoSuchUpload(upload_id) from None
        for num, info in session["parts"].items():
            if "loc" in info:
                try:
                    self.data.delete(info["loc"])
                except Exception:
                    pass
