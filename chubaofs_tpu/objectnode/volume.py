"""Bucket-on-volume object semantics (objectnode/fs_volume.go analog).

Reference counterpart: objectnode/fs_volume.go — `Volume.PutObject` (:596)
maps an S3 key to a filesystem path inside the bucket's volume, creating
implicit intermediate directories; object metadata (etag, content type, user
meta, tags, ACL) live as xattrs on the object inode; listing walks the
directory tree in key order. Delete prunes now-empty parent directories so
phantom CommonPrefixes don't outlive their objects.
"""

from __future__ import annotations

import hashlib
import json
import time

from chubaofs_tpu.sdk.fs import FsClient, FsError

XATTR_ETAG = "oss:etag"
XATTR_CONTENT_TYPE = "oss:content-type"
XATTR_USER_META = "oss:meta"
XATTR_TAGGING = "oss:tagging"
XATTR_DIR_MARKER = "oss:dir"
XATTR_VERSIONING = "oss:versioning"  # bucket: "Enabled" | "Suspended"
XATTR_VERSION_ID = "oss:version-id"  # current object's version id
XATTR_DELETE_MARKER = "oss:delete-marker"

DEFAULT_CONTENT_TYPE = "application/octet-stream"
VERSIONS_ROOT = ".versions"  # hidden prefix hosting archived versions


class NoSuchKey(Exception):
    pass


class ReservedKey(Exception):
    """Key addresses the hidden version store — not a legal object key."""


def _guard_key(key: str):
    if key == VERSIONS_ROOT or key.startswith(VERSIONS_ROOT + "/"):
        raise ReservedKey(key)


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


class OSSVolume:
    """One bucket == one volume; verbs the S3 handlers call."""

    def __init__(self, fs: FsClient, bucket: str, owner: str = ""):
        self.fs = fs
        self.bucket = bucket
        self.owner = owner

    # -- write -------------------------------------------------------------------

    def put_object(self, key: str, data: bytes, content_type: str = "",
                   user_meta: dict | None = None, etag: str | None = None) -> str:
        _guard_key(key)
        if key.endswith("/"):
            # directory marker object (the console/aws-cli "create folder" shape)
            ino_path = "/" + key.rstrip("/")
            self.fs.mkdirs(ino_path)
            self.fs.setxattr(ino_path, XATTR_DIR_MARKER, b"1")
            self.fs.setxattr(ino_path, XATTR_ETAG, _etag(b"").encode())
            return _etag(b"")
        path = "/" + key
        parent = path.rsplit("/", 1)[0]
        if parent:
            self.fs.mkdirs(parent)
        self.fs.write_file(path, data)
        tag = etag or _etag(data)
        self.fs.setxattr(path, XATTR_ETAG, tag.encode())
        self.fs.setxattr(path, XATTR_CONTENT_TYPE,
                         (content_type or DEFAULT_CONTENT_TYPE).encode())
        if user_meta:
            self.fs.setxattr(path, XATTR_USER_META, json.dumps(user_meta).encode())
        return tag

    # -- read --------------------------------------------------------------------

    def info(self, key: str) -> dict:
        _guard_key(key)
        path = "/" + key.rstrip("/")
        try:
            st = self.fs.stat(path)
        except FsError:
            raise NoSuchKey(key) from None
        if st["is_dir"]:
            # only explicit dir markers are objects
            try:
                self.fs.getxattr(path, XATTR_DIR_MARKER)
            except FsError:
                raise NoSuchKey(key) from None
        out = {"key": key, "size": 0 if st["is_dir"] else st["size"],
               "mtime": st["mtime"], "is_dir": st["is_dir"],
               "etag": "", "content_type": DEFAULT_CONTENT_TYPE, "meta": {}}
        for xk, field in ((XATTR_ETAG, "etag"), (XATTR_CONTENT_TYPE, "content_type")):
            try:
                out[field] = self.fs.getxattr(path, xk).decode()
            except FsError:
                pass
        try:
            out["meta"] = json.loads(self.fs.getxattr(path, XATTR_USER_META))
        except FsError:
            pass
        return out

    def get_object(self, key: str, offset: int = 0, size: int | None = None) -> bytes:
        info = self.info(key)
        if info["is_dir"]:
            return b""
        try:
            return self.fs.read_file("/" + key, offset, size)
        except FsError:
            raise NoSuchKey(key) from None

    # -- delete ------------------------------------------------------------------

    def delete_object(self, key: str) -> None:
        """Idempotent like S3 DeleteObject (no error on missing key)."""
        _guard_key(key)
        path = "/" + key.rstrip("/")
        try:
            st = self.fs.stat(path)
        except FsError:
            return
        try:
            if st["is_dir"]:
                self.fs.rmdir(path)
            else:
                self.fs.unlink(path)
        except FsError:
            return  # non-empty dir marker: S3 leaves the prefix alive
        self._prune_empty_parents(path)

    def _prune_empty_parents(self, path: str):
        parts = [p for p in path.split("/") if p][:-1]
        while parts:
            parent = "/" + "/".join(parts)
            try:
                if self.fs.readdir(parent):
                    return
                # keep explicit dir markers even when empty
                try:
                    self.fs.getxattr(parent, XATTR_DIR_MARKER)
                    return
                except FsError:
                    pass
                self.fs.rmdir(parent)
            except FsError:
                return
            parts.pop()

    # -- tagging -----------------------------------------------------------------

    def get_tagging(self, key: str) -> dict:
        self.info(key)
        try:
            return json.loads(self.fs.getxattr("/" + key.rstrip("/"), XATTR_TAGGING))
        except FsError:
            return {}

    def set_tagging(self, key: str, tags: dict):
        self.info(key)
        self.fs.setxattr("/" + key.rstrip("/"), XATTR_TAGGING,
                         json.dumps(tags).encode())

    def delete_tagging(self, key: str):
        self.info(key)
        self.fs.removexattr("/" + key.rstrip("/"), XATTR_TAGGING)

    # -- object xattr passthrough (ref objectnode SetXAttr/GetXAttr/DeleteXAttr/
    # ListXAttrs, fs_volume.go:288-459). Deliberate divergence from the
    # reference: internal oss:* keys (ACL, etag, version ids, delete markers)
    # are NOT reachable through this API — the reference exposes them raw, but
    # here the ACL/versioning engines key their permission checks off those
    # xattrs, so a plain-WRITE principal writing oss:acl would bypass the
    # WRITE_ACP/READ_ACP split. The version store is guarded like every other
    # object verb. --------------------------------------------------------------

    _XATTR_INTERNAL = "oss:"

    def _xattr_path(self, key: str, name: str | None = None) -> str:
        _guard_key(key)
        if name is not None and name.startswith(self._XATTR_INTERNAL):
            raise ReservedKey(name)
        self.info(key)  # real objects only, like the tagging verbs (404 else)
        return "/" + key.rstrip("/")

    def set_xattr(self, key: str, name: str, value: bytes):
        self.fs.setxattr(self._xattr_path(key, name), name, value)

    def get_xattr(self, key: str, name: str) -> bytes:
        return self.fs.getxattr(self._xattr_path(key, name), name)

    def delete_xattr(self, key: str, name: str):
        self.fs.removexattr(self._xattr_path(key, name), name)

    def list_xattrs(self, key: str) -> list[str]:
        return [k for k in self.fs.listxattr(self._xattr_path(key))
                if not k.startswith(self._XATTR_INTERNAL)]

    # -- xattr passthrough for bucket-level configs (acl/policy/cors) ------------

    def get_bucket_xattr(self, key: str) -> bytes | None:
        try:
            return self.fs.getxattr("/", key)
        except FsError:
            return None

    def set_bucket_xattr(self, key: str, value: bytes):
        self.fs.setxattr("/", key, value)

    def del_bucket_xattr(self, key: str):
        self.fs.removexattr("/", key)

    # -- versioning (objectnode versioning semantics) ------------------------------
    #
    # Archived versions live under the hidden /.versions/<quoted-key>/<vid>
    # tree: an archive is ONE rename (the inode keeps its xattrs), never a data
    # copy. Version ids are zero-padded hex timestamps, so lexicographic order
    # IS recency order. A delete under versioning archives the current object
    # and records a delete-marker entry.

    def versioning_status(self) -> str:
        raw = self.get_bucket_xattr(XATTR_VERSIONING)
        return raw.decode() if raw else ""

    def set_versioning(self, status: str):
        if status not in ("Enabled", "Suspended"):
            raise ValueError(f"bad versioning status {status!r}")
        self.set_bucket_xattr(XATTR_VERSIONING, status.encode())

    @staticmethod
    def new_version_id() -> str:
        return f"{time.time_ns():020x}"

    def _vdir(self, key: str) -> str:
        import urllib.parse

        return f"/{VERSIONS_ROOT}/" + urllib.parse.quote(key, safe="")

    def archive_current(self, key: str) -> str | None:
        """Move the live object into the version store; returns its version id
        (the one it carried, or a fresh 'null'-era id), None if absent."""
        path = "/" + key
        try:
            st = self.fs.stat(path)
        except FsError:
            return None
        if st["is_dir"]:
            return None
        try:
            vid = self.fs.getxattr(path, XATTR_VERSION_ID).decode()
        except FsError:
            vid = self.new_version_id()
        self.fs.mkdirs(self._vdir(key))
        self.fs.rename(path, f"{self._vdir(key)}/{vid}")
        self._prune_empty_parents(path)
        return vid

    def put_delete_marker(self, key: str) -> str:
        vid = self.new_version_id()
        self.fs.mkdirs(self._vdir(key))
        marker = f"{self._vdir(key)}/{vid}"
        self.fs.write_file(marker, b"")
        self.fs.setxattr(marker, XATTR_DELETE_MARKER, b"1")
        return vid

    def list_versions(self, prefix: str = "") -> list[dict]:
        """All versions of all keys, newest first per key, currents included."""
        import urllib.parse

        out: list[dict] = []
        keys: set[str] = set()
        try:
            names = self.fs.readdir("/" + VERSIONS_ROOT)
        except FsError:
            names = []
        for quoted in names:
            key = urllib.parse.unquote(quoted)
            if prefix and not key.startswith(prefix):
                continue
            keys.add(key)
        contents, _, _, _ = self.list_objects(prefix=prefix, max_keys=100000)
        current_by_key = {o["key"]: o for o in contents}
        for key in sorted(keys | set(current_by_key)):
            entries = []
            cur = current_by_key.get(key)
            if cur is not None:
                vid = "null"
                try:
                    vid = self.fs.getxattr("/" + key, XATTR_VERSION_ID).decode()
                except FsError:
                    pass
                entries.append({"key": key, "version_id": vid, "is_latest": True,
                                "delete_marker": False, "size": cur["size"],
                                "mtime": cur["mtime"],
                                "etag": cur.get("etag", "")})
            vdir = self._vdir(key)
            try:
                vids = sorted(self.fs.readdir(vdir), reverse=True)
            except FsError:
                vids = []
            for i, vid in enumerate(vids):
                vp = f"{vdir}/{vid}"
                st = self.fs.stat(vp)
                marker = False
                try:
                    self.fs.getxattr(vp, XATTR_DELETE_MARKER)
                    marker = True
                except FsError:
                    pass
                etag = ""
                try:
                    etag = self.fs.getxattr(vp, XATTR_ETAG).decode()
                except FsError:
                    pass
                entries.append({"key": key, "version_id": vid,
                                "is_latest": cur is None and i == 0,
                                "delete_marker": marker, "size": st["size"],
                                "mtime": st["mtime"], "etag": etag})
            out.extend(entries)
        return out

    def _current_vid(self, key: str) -> str | None:
        try:
            return self.fs.getxattr("/" + key, XATTR_VERSION_ID).decode()
        except FsError:
            return None

    def _is_current(self, key: str, version_id: str) -> bool:
        """'null' names the current object only when it carries NO real
        version id (S3 null-version identity)."""
        cur = self._current_vid(key)
        return version_id == cur or (version_id == "null" and cur is None)

    def stat_version(self, key: str, version_id: str) -> dict:
        """Metadata of one version (current or archived) WITHOUT reading its
        body; raises NoSuchKey if absent or a delete marker."""
        if self._is_current(key, version_id):
            return self.info(key)
        vp = f"{self._vdir(key)}/{version_id}"
        try:
            st = self.fs.stat(vp)
        except FsError:
            raise NoSuchKey(f"{key}?versionId={version_id}") from None
        try:
            self.fs.getxattr(vp, XATTR_DELETE_MARKER)
            raise NoSuchKey(f"{key}?versionId={version_id} is a delete marker")
        except FsError:
            pass
        info = {"key": key, "size": st["size"], "mtime": st["mtime"],
                "is_dir": False, "etag": "", "meta": {},
                "content_type": DEFAULT_CONTENT_TYPE}
        for xk, field in ((XATTR_ETAG, "etag"), (XATTR_CONTENT_TYPE, "content_type")):
            try:
                info[field] = self.fs.getxattr(vp, xk).decode()
            except FsError:
                pass
        return info

    def read_version(self, key: str, version_id: str, offset: int = 0,
                     size: int | None = None) -> bytes:
        if self._is_current(key, version_id):
            return self.get_object(key, offset, size)
        vp = f"{self._vdir(key)}/{version_id}"
        try:
            return self.fs.read_file(vp, offset, size)
        except FsError:
            raise NoSuchKey(f"{key}?versionId={version_id}") from None

    def get_version(self, key: str, version_id: str) -> tuple[bytes, dict]:
        info = self.stat_version(key, version_id)
        return self.read_version(key, version_id), info

    def delete_version(self, key: str, version_id: str) -> None:
        """Permanently remove one version (current or archived); idempotent.
        Deleting the CURRENT version promotes the newest archived non-marker
        version back to live (S3: the previous version becomes latest)."""
        if self._is_current(key, version_id):
            self.delete_object(key)
            self._promote_newest(key)
            return
        vp = f"{self._vdir(key)}/{version_id}"
        try:
            self.fs.unlink(vp)
        except FsError:
            return
        try:
            if not self.fs.readdir(self._vdir(key)):
                self.fs.rmdir(self._vdir(key))
        except FsError:
            pass

    def _promote_newest(self, key: str) -> None:
        """Move the newest archived version back to the live path — unless it
        is a delete marker (then the key stays logically deleted)."""
        vdir = self._vdir(key)
        try:
            vids = sorted(self.fs.readdir(vdir), reverse=True)
        except FsError:
            return
        if not vids:
            return
        vp = f"{vdir}/{vids[0]}"
        try:
            self.fs.getxattr(vp, XATTR_DELETE_MARKER)
            return  # a marker stays latest: the key remains deleted
        except FsError:
            pass
        path = "/" + key
        parent = path.rsplit("/", 1)[0]
        if parent:
            self.fs.mkdirs(parent)
        self.fs.rename(vp, path)  # xattrs (etag, vid, meta) travel with it
        self.fs.setxattr(path, XATTR_VERSION_ID, vids[0].encode())
        try:
            if not self.fs.readdir(vdir):
                self.fs.rmdir(vdir)
        except FsError:
            pass

    # -- listing -----------------------------------------------------------------

    def _walk(self, dirpath: str, out: list[dict]):
        """DFS in lexicographic order; emits files and dir-marker dirs."""
        for name in sorted(self.fs.readdir(dirpath or "/")):
            if dirpath == "" and name == VERSIONS_ROOT:
                continue  # the version store is not part of the namespace
            child = f"{dirpath}/{name}"
            st = self.fs.stat(child)
            key = child.lstrip("/")
            if st["is_dir"]:
                try:
                    self.fs.getxattr(child, XATTR_DIR_MARKER)
                    out.append({"key": key + "/", "size": 0, "mtime": st["mtime"]})
                except FsError:
                    pass
                self._walk(child, out)
            else:
                out.append({"key": key, "size": st["size"], "mtime": st["mtime"]})

    def list_objects(self, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000):
        """Returns (contents, common_prefixes, is_truncated, next_marker).

        Flat walk + in-memory filtering: correct for the full S3 semantics
        (prefix, delimiter grouping, marker resume, max-keys truncation). The
        walk starts from the deepest directory implied by the prefix so cost
        scales with the listed subtree, not the bucket."""
        base = ""
        if "/" in prefix:
            cand = prefix.rsplit("/", 1)[0]
            try:
                if self.fs.stat("/" + cand)["is_dir"]:
                    base = "/" + cand
            except FsError:
                return [], [], False, ""
        everything: list[dict] = []
        try:
            self._walk(base, everything)
        except FsError:
            return [], [], False, ""

        contents: list[dict] = []
        prefixes: list[str] = []
        seen_prefixes: set[str] = set()
        truncated = False
        next_marker = ""
        for obj in everything:
            key = obj["key"]
            if prefix and not key.startswith(prefix):
                continue
            # marker compares against the ROLLED-UP name: with a delimiter,
            # keys that group into CommonPrefix "a/" are represented by "a/"
            # itself, so marker="a/" (a NextMarker that was a prefix) skips
            # the whole group instead of re-emitting it forever
            rolled = key
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    rolled = prefix + rest.split(delimiter, 1)[0] + delimiter
            if marker and rolled <= marker:
                continue
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = rolled
                    if cp not in seen_prefixes:
                        if len(contents) + len(seen_prefixes) >= max_keys:
                            truncated = True
                            break
                        seen_prefixes.add(cp)
                        prefixes.append(cp)
                        next_marker = cp  # resume point may be a prefix too
                    continue
            if len(contents) + len(seen_prefixes) >= max_keys:
                truncated = True
                break
            # etag lazily — only for emitted keys
            try:
                obj = dict(obj, etag=self.fs.getxattr(
                    "/" + key.rstrip("/"), XATTR_ETAG).decode())
            except FsError:
                obj = dict(obj, etag="")
            contents.append(obj)
            next_marker = key
        return contents, prefixes, truncated, (next_marker if truncated else "")

    def is_empty(self) -> bool:
        names = [n for n in self.fs.readdir("/")]
        return not names

    @staticmethod
    def http_time(ts: float) -> str:
        return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))
