"""Bucket-on-volume object semantics (objectnode/fs_volume.go analog).

Reference counterpart: objectnode/fs_volume.go — `Volume.PutObject` (:596)
maps an S3 key to a filesystem path inside the bucket's volume, creating
implicit intermediate directories; object metadata (etag, content type, user
meta, tags, ACL) live as xattrs on the object inode; listing walks the
directory tree in key order. Delete prunes now-empty parent directories so
phantom CommonPrefixes don't outlive their objects.
"""

from __future__ import annotations

import hashlib
import json
import time

from chubaofs_tpu.sdk.fs import FsClient, FsError

XATTR_ETAG = "oss:etag"
XATTR_CONTENT_TYPE = "oss:content-type"
XATTR_USER_META = "oss:meta"
XATTR_TAGGING = "oss:tagging"
XATTR_DIR_MARKER = "oss:dir"

DEFAULT_CONTENT_TYPE = "application/octet-stream"


class NoSuchKey(Exception):
    pass


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


class OSSVolume:
    """One bucket == one volume; verbs the S3 handlers call."""

    def __init__(self, fs: FsClient, bucket: str, owner: str = ""):
        self.fs = fs
        self.bucket = bucket
        self.owner = owner

    # -- write -------------------------------------------------------------------

    def put_object(self, key: str, data: bytes, content_type: str = "",
                   user_meta: dict | None = None, etag: str | None = None) -> str:
        if key.endswith("/"):
            # directory marker object (the console/aws-cli "create folder" shape)
            ino_path = "/" + key.rstrip("/")
            self.fs.mkdirs(ino_path)
            self.fs.setxattr(ino_path, XATTR_DIR_MARKER, b"1")
            self.fs.setxattr(ino_path, XATTR_ETAG, _etag(b"").encode())
            return _etag(b"")
        path = "/" + key
        parent = path.rsplit("/", 1)[0]
        if parent:
            self.fs.mkdirs(parent)
        self.fs.write_file(path, data)
        tag = etag or _etag(data)
        self.fs.setxattr(path, XATTR_ETAG, tag.encode())
        self.fs.setxattr(path, XATTR_CONTENT_TYPE,
                         (content_type or DEFAULT_CONTENT_TYPE).encode())
        if user_meta:
            self.fs.setxattr(path, XATTR_USER_META, json.dumps(user_meta).encode())
        return tag

    # -- read --------------------------------------------------------------------

    def info(self, key: str) -> dict:
        path = "/" + key.rstrip("/")
        try:
            st = self.fs.stat(path)
        except FsError:
            raise NoSuchKey(key) from None
        if st["is_dir"]:
            # only explicit dir markers are objects
            try:
                self.fs.getxattr(path, XATTR_DIR_MARKER)
            except FsError:
                raise NoSuchKey(key) from None
        out = {"key": key, "size": 0 if st["is_dir"] else st["size"],
               "mtime": st["mtime"], "is_dir": st["is_dir"],
               "etag": "", "content_type": DEFAULT_CONTENT_TYPE, "meta": {}}
        for xk, field in ((XATTR_ETAG, "etag"), (XATTR_CONTENT_TYPE, "content_type")):
            try:
                out[field] = self.fs.getxattr(path, xk).decode()
            except FsError:
                pass
        try:
            out["meta"] = json.loads(self.fs.getxattr(path, XATTR_USER_META))
        except FsError:
            pass
        return out

    def get_object(self, key: str, offset: int = 0, size: int | None = None) -> bytes:
        info = self.info(key)
        if info["is_dir"]:
            return b""
        try:
            return self.fs.read_file("/" + key, offset, size)
        except FsError:
            raise NoSuchKey(key) from None

    # -- delete ------------------------------------------------------------------

    def delete_object(self, key: str) -> None:
        """Idempotent like S3 DeleteObject (no error on missing key)."""
        path = "/" + key.rstrip("/")
        try:
            st = self.fs.stat(path)
        except FsError:
            return
        try:
            if st["is_dir"]:
                self.fs.rmdir(path)
            else:
                self.fs.unlink(path)
        except FsError:
            return  # non-empty dir marker: S3 leaves the prefix alive
        self._prune_empty_parents(path)

    def _prune_empty_parents(self, path: str):
        parts = [p for p in path.split("/") if p][:-1]
        while parts:
            parent = "/" + "/".join(parts)
            try:
                if self.fs.readdir(parent):
                    return
                # keep explicit dir markers even when empty
                try:
                    self.fs.getxattr(parent, XATTR_DIR_MARKER)
                    return
                except FsError:
                    pass
                self.fs.rmdir(parent)
            except FsError:
                return
            parts.pop()

    # -- tagging -----------------------------------------------------------------

    def get_tagging(self, key: str) -> dict:
        self.info(key)
        try:
            return json.loads(self.fs.getxattr("/" + key.rstrip("/"), XATTR_TAGGING))
        except FsError:
            return {}

    def set_tagging(self, key: str, tags: dict):
        self.info(key)
        self.fs.setxattr("/" + key.rstrip("/"), XATTR_TAGGING,
                         json.dumps(tags).encode())

    def delete_tagging(self, key: str):
        self.info(key)
        self.fs.removexattr("/" + key.rstrip("/"), XATTR_TAGGING)

    # -- xattr passthrough for bucket-level configs (acl/policy/cors) ------------

    def get_bucket_xattr(self, key: str) -> bytes | None:
        try:
            return self.fs.getxattr("/", key)
        except FsError:
            return None

    def set_bucket_xattr(self, key: str, value: bytes):
        self.fs.setxattr("/", key, value)

    def del_bucket_xattr(self, key: str):
        self.fs.removexattr("/", key)

    # -- listing -----------------------------------------------------------------

    def _walk(self, dirpath: str, out: list[dict]):
        """DFS in lexicographic order; emits files and dir-marker dirs."""
        for name in sorted(self.fs.readdir(dirpath or "/")):
            child = f"{dirpath}/{name}"
            st = self.fs.stat(child)
            key = child.lstrip("/")
            if st["is_dir"]:
                try:
                    self.fs.getxattr(child, XATTR_DIR_MARKER)
                    out.append({"key": key + "/", "size": 0, "mtime": st["mtime"]})
                except FsError:
                    pass
                self._walk(child, out)
            else:
                out.append({"key": key, "size": st["size"], "mtime": st["mtime"]})

    def list_objects(self, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000):
        """Returns (contents, common_prefixes, is_truncated, next_marker).

        Flat walk + in-memory filtering: correct for the full S3 semantics
        (prefix, delimiter grouping, marker resume, max-keys truncation). The
        walk starts from the deepest directory implied by the prefix so cost
        scales with the listed subtree, not the bucket."""
        base = ""
        if "/" in prefix:
            cand = prefix.rsplit("/", 1)[0]
            try:
                if self.fs.stat("/" + cand)["is_dir"]:
                    base = "/" + cand
            except FsError:
                return [], [], False, ""
        everything: list[dict] = []
        try:
            self._walk(base, everything)
        except FsError:
            return [], [], False, ""

        contents: list[dict] = []
        prefixes: list[str] = []
        seen_prefixes: set[str] = set()
        truncated = False
        next_marker = ""
        for obj in everything:
            key = obj["key"]
            if prefix and not key.startswith(prefix):
                continue
            # marker compares against the ROLLED-UP name: with a delimiter,
            # keys that group into CommonPrefix "a/" are represented by "a/"
            # itself, so marker="a/" (a NextMarker that was a prefix) skips
            # the whole group instead of re-emitting it forever
            rolled = key
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    rolled = prefix + rest.split(delimiter, 1)[0] + delimiter
            if marker and rolled <= marker:
                continue
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = rolled
                    if cp not in seen_prefixes:
                        if len(contents) + len(seen_prefixes) >= max_keys:
                            truncated = True
                            break
                        seen_prefixes.add(cp)
                        prefixes.append(cp)
                        next_marker = cp  # resume point may be a prefix too
                    continue
            if len(contents) + len(seen_prefixes) >= max_keys:
                truncated = True
                break
            # etag lazily — only for emitted keys
            try:
                obj = dict(obj, etag=self.fs.getxattr(
                    "/" + key.rstrip("/"), XATTR_ETAG).decode())
            except FsError:
                obj = dict(obj, etag="")
            contents.append(obj)
            next_marker = key
        return contents, prefixes, truncated, (next_marker if truncated else "")

    def is_empty(self) -> bool:
        names = [n for n in self.fs.readdir("/")]
        return not names

    @staticmethod
    def http_time(ts: float) -> str:
        return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))
