"""ObjectNode — S3-compatible gateway over the file/metadata cluster.

Reference: objectnode/ (router.go, api_handler_object.go, fs_volume.go,
auth_signature_v2/v4.go, policy/acl/cors/tagging engines).
"""

from chubaofs_tpu.objectnode.server import ObjectNode, S3Error

__all__ = ["ObjectNode", "S3Error"]
