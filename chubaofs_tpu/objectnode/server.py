"""ObjectNode — the S3-compatible gateway (objectnode/ analog).

Reference counterpart: objectnode/router.go:26 (gorilla/mux routing of the S3
action set), api_handler_object.go:1172 (putObjectHandler),
fs_volume.go:596 (Volume.PutObject), auth_signature_v2.go/v4.go, the
policy/acl/cors/tagging engines, objectnode/server.go. Buckets map 1:1 onto
volumes; object data rides the same meta+data planes as the POSIX client —
EC-on-TPU for cold volumes — so S3 and FUSE views of a volume agree
(CHANGELOG.md:12's blobstore docking).

Supported S3 actions (~60): ListBuckets, Create/Delete/Head Bucket,
GetBucketLocation, ListObjects V1/V2 (continuation tokens, delimiters),
Put/Get/Head/Delete Object, CopyObject (COPY/REPLACE metadata directive),
DeleteObjects (batch + Quiet), Range GET, GetObjectAttributes,
Bucket+Object ACL (grant XML + canned x-amz-acl), Bucket Policy +
GetBucketPolicyStatus, Bucket CORS (+ preflight), Bucket+Object Tagging,
full multipart (Initiate/UploadPart/UploadPartCopy with source ranges/
List/Complete/Abort/ListUploads), Bucket Versioning (Put/Get,
ListObjectVersions, versionId GET/HEAD/DELETE, delete markers, Suspended
semantics), Bucket Lifecycle (Put/Get/Delete + expiry sweeper),
presigned URLs (SigV4 query auth and SigV2 Expires/Signature).
"""

from __future__ import annotations

import base64
import re
import urllib.parse
import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape as esc

from chubaofs_tpu.objectnode import auth as s3auth
from chubaofs_tpu.objectnode.acl import ACL, XATTR_ACL
from chubaofs_tpu.objectnode.cors import CORSConfig, XATTR_CORS
from chubaofs_tpu.objectnode.multipart import (
    InvalidPart, MultipartManager, NoSuchUpload,
)
from chubaofs_tpu.objectnode.policy import (
    ACTION_DELETE, ACTION_GET, ACTION_LIST, ACTION_PUT, ALLOW, DENY, Policy,
    PolicyError, XATTR_POLICY,
)
from chubaofs_tpu.objectnode.volume import NoSuchKey, OSSVolume, ReservedKey
from chubaofs_tpu.rpc import Response, Router
from chubaofs_tpu.rpc.router import Request
from chubaofs_tpu.sdk.fs import FsError


XATTR_LIFECYCLE = "oss:lifecycle"


class S3Error(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        super().__init__(code)
        self.status = status
        self.code = code
        self.msg = msg or code


def _xml_error(e: S3Error, resource: str = "") -> Response:
    body = (f"<Error><Code>{esc(e.code)}</Code><Message>{esc(e.msg)}</Message>"
            f"<Resource>{esc(resource)}</Resource></Error>")
    return Response.xml(body, e.status)


def _parse_xml(body: bytes) -> ET.Element:
    """Parse an S3 request body, stripping the S3 namespace: boto/aws-cli send
    xmlns=http://s3.amazonaws.com/doc/2006-03-01/ and ElementTree would
    otherwise tag every element as {ns}Name. Malformed input is the client's
    fault — 400 MalformedXML, never a 500."""
    try:
        root = ET.fromstring(body.decode())
    except (ET.ParseError, UnicodeDecodeError) as e:
        raise S3Error(400, "MalformedXML", str(e)) from None
    for el in root.iter():
        el.tag = re.sub(r"^\{.*\}", "", el.tag)
    return root


def _text(el, tag: str, default: str = "") -> str:
    child = el.find(tag)
    return child.text or default if child is not None else default


def _etag_matches(header: str, etag: str) -> bool:
    """RFC 9110 If-(None-)Match list: `*`, or any listed etag equal to the
    object's — quoted or bare, weak prefixes tolerated (crc etags here are
    always strong, so W/ comparison degenerates to equality)."""
    for v in header.split(","):
        v = v.strip()
        if v == "*":
            return True
        if v.startswith("W/"):
            v = v[2:]
        if v.strip('"') == etag:
            return True
    return False


# sub-resources the reference routes to unsupportedOperationHandler
# (router.go; v3.2.1 also lists lifecycle/versioning/versions there, which
# THIS gateway implements)
_UNSUPPORTED_BUCKET_QUERIES = (
    "object-lock", "encryption", "website", "publicAccessBlock",
    "requestPayment", "replication",
)
_UNSUPPORTED_OBJECT_QUERIES = ("legal-hold", "retention", "torrent", "restore")


class ObjectNode:
    """cluster must provide: create_volume(name, cold), delete_volume(name),
    volume_names(), client(name) -> FsClient, data_backend. FsCluster does."""

    def __init__(self, cluster, users: dict[str, dict] | None = None,
                 region: str = "cfs", anonymous_ok: bool = False,
                 qos=None):
        self.cluster = cluster
        # users: access_key -> {"secret_key": ..., "uid": ...}
        self.users = users or {}
        self.region = region
        self.anonymous_ok = anonymous_ok
        self._vols: dict[str, OSSVolume] = {}
        self.router = self._build_router()
        # per-tenant QoS plane (ISSUE 14): pass one explicitly or arm via
        # CFS_QOS_* env. Unarmed (the default) installs NO middleware —
        # zero per-request overhead, not a disabled check
        from chubaofs_tpu.utils.qos import QosPlane

        self.qos = qos if qos is not None else QosPlane.from_env()
        if self.qos is not None:
            self.router.middleware.append(self._qos_middleware)

    def _qos_middleware(self, req: Request, nxt):
        """Admission/shaping BEFORE auth: tenant identity is the claimed
        sigv4 access key (throttling must cost less than the HMAC chain it
        protects — the signature check still rejects forgeries afterward).
        Request-body bytes charge the bandwidth plane up front; response
        bytes are debited after, driving the tenant's bucket negative
        until the debt refills."""
        tenant = s3auth.access_key_of(req)
        deny = self.qos.admit(tenant, len(req.body))
        if deny is not None:
            return deny
        resp = nxt(req)
        self.qos.debit_out(tenant, len(resp.body))
        return resp

    # -- volume plumbing ---------------------------------------------------------

    def _vol(self, bucket: str) -> OSSVolume:
        vol = self._vols.get(bucket)
        if vol is None:
            try:
                fs = self.cluster.client(bucket)
            except Exception:
                raise S3Error(404, "NoSuchBucket", bucket) from None
            vol = self._vols[bucket] = OSSVolume(fs, bucket)
        return vol

    def _mpu(self, bucket: str) -> MultipartManager:
        return MultipartManager(self._vol(bucket), self.cluster.data_backend)

    # -- auth --------------------------------------------------------------------

    def _authenticate(self, req: Request) -> str | None:
        """Returns the principal uid, or None for anonymous."""
        ak = s3auth.access_key_of(req)
        if ak is None:
            if self.anonymous_ok or not self.users:
                return None
            raise S3Error(403, "AccessDenied", "anonymous access disabled")
        user = self.users.get(ak)
        if user is None:
            raise S3Error(403, "InvalidAccessKeyId", ak)
        sk = user["secret_key"]
        if s3auth.is_presigned(req):
            # query-string auth (presigned URLs), expiry enforced
            if not s3auth.verify_presigned(req, sk):
                raise S3Error(403, "SignatureDoesNotMatch",
                              "presigned signature invalid or expired")
            return user.get("uid", ak)
        authz = req.header("authorization")
        ok = (s3auth.verify_v4(req, sk) if authz.startswith(s3auth.V4_ALGO)
              else s3auth.verify_v2(req, sk))
        if not ok:
            raise S3Error(403, "SignatureDoesNotMatch")
        return user.get("uid", ak)

    def _check(self, req: Request, bucket: str, action: str, key: str = "",
               perm: str | None = None):
        """Owner → policy (deny-overrides) → object ACL → bucket ACL → deny.

        perm names the ACL permission to demand; defaults to READ/WRITE by
        action. ACL mutation handlers pass READ_ACP/WRITE_ACP — a plain WRITE
        grant must NOT allow rewriting ACLs (S3's ACP permission split)."""
        principal = self._authenticate(req)
        vol = self._vol(bucket)
        if principal is not None and principal == self._owner(vol):
            return principal
        raw = vol.get_bucket_xattr(XATTR_POLICY)
        if raw:
            resource = f"{bucket}/{key}" if key else bucket
            verdict = Policy.from_json(raw).evaluate(action, resource, principal)
            if verdict == DENY:
                raise S3Error(403, "AccessDenied", "denied by bucket policy")
            if verdict == ALLOW:
                return principal
        if perm is None:
            perm = "READ" if action in (ACTION_GET, ACTION_LIST) else "WRITE"
        if key:
            try:
                raw = vol.fs.getxattr("/" + key.rstrip("/"), XATTR_ACL)
                if ACL.from_json(raw).allows(principal, perm):
                    return principal
            except FsError:
                pass
        raw = vol.get_bucket_xattr(XATTR_ACL)
        if raw:
            if ACL.from_json(raw).allows(principal, perm):
                return principal
        if principal is None and not self.users:
            return None  # wide-open dev mode: no user table configured
        raise S3Error(403, "AccessDenied")

    def _owner(self, vol: OSSVolume) -> str:
        raw = vol.get_bucket_xattr(XATTR_ACL)
        if raw:
            return ACL.from_json(raw).owner
        return vol.owner

    # -- router ------------------------------------------------------------------

    def _build_router(self) -> Router:
        r = Router()
        w = self._wrap
        # service
        r.get("/", w(self.list_buckets))
        # bucket sub-resources (query-matched routes bind tighter)
        r.get("/:bucket", w(self.get_bucket_location), queries={"location": None})
        r.get("/:bucket", w(self.get_bucket_acl), queries={"acl": None})
        r.put("/:bucket", w(self.put_bucket_acl), queries={"acl": None})
        r.get("/:bucket", w(self.get_bucket_policy_status),
              queries={"policyStatus": None})
        r.get("/:bucket", w(self.get_bucket_policy), queries={"policy": None})
        r.put("/:bucket", w(self.put_bucket_policy), queries={"policy": None})
        r.delete("/:bucket", w(self.delete_bucket_policy), queries={"policy": None})
        r.get("/:bucket", w(self.get_bucket_cors), queries={"cors": None})
        r.put("/:bucket", w(self.put_bucket_cors), queries={"cors": None})
        r.delete("/:bucket", w(self.delete_bucket_cors), queries={"cors": None})
        r.get("/:bucket", w(self.get_bucket_tagging), queries={"tagging": None})
        r.put("/:bucket", w(self.put_bucket_tagging), queries={"tagging": None})
        r.delete("/:bucket", w(self.delete_bucket_tagging), queries={"tagging": None})
        r.get("/:bucket", w(self.list_uploads), queries={"uploads": None})
        r.get("/:bucket", w(self.get_bucket_versioning), queries={"versioning": None})
        r.put("/:bucket", w(self.put_bucket_versioning), queries={"versioning": None})
        r.get("/:bucket", w(self.list_object_versions), queries={"versions": None})
        r.get("/:bucket", w(self.get_bucket_lifecycle), queries={"lifecycle": None})
        r.put("/:bucket", w(self.put_bucket_lifecycle), queries={"lifecycle": None})
        r.delete("/:bucket", w(self.delete_bucket_lifecycle),
                 queries={"lifecycle": None})
        r.get("/:bucket", w(self.list_objects_v2), queries={"list-type": "2"})
        r.post("/:bucket", w(self.delete_objects), queries={"delete": None})
        # unimplemented sub-resources answer 501 NotImplemented explicitly so
        # they can't fall through to the catch-all core routes (e.g. a
        # ?replication GET must not run ListObjects) — ref router.go registers
        # unsupportedOperationHandler for exactly these (api_handler.go:130)
        for q in _UNSUPPORTED_BUCKET_QUERIES:
            for meth in ("GET", "PUT", "DELETE"):
                r.handle(meth, "/:bucket", w(self.unsupported), queries={q: None})
        for q in _UNSUPPORTED_OBJECT_QUERIES:
            for meth in ("GET", "PUT", "DELETE", "POST"):
                r.handle(meth, "/:bucket/*key", w(self.unsupported),
                         queries={q: None})
        # bucket core
        r.get("/:bucket", w(self.list_objects_v1))
        r.put("/:bucket", w(self.create_bucket))
        r.delete("/:bucket", w(self.delete_bucket))
        r.head("/:bucket", w(self.head_bucket))
        r.handle("OPTIONS", "/:bucket", w(self.preflight))
        # object sub-resources
        r.get("/:bucket/*key", w(self.get_object_attributes),
              queries={"attributes": None})
        r.get("/:bucket/*key", w(self.get_object_acl), queries={"acl": None})
        r.put("/:bucket/*key", w(self.put_object_acl), queries={"acl": None})
        r.get("/:bucket/*key", w(self.get_object_tagging), queries={"tagging": None})
        r.put("/:bucket/*key", w(self.put_object_tagging), queries={"tagging": None})
        r.delete("/:bucket/*key", w(self.delete_object_tagging),
                 queries={"tagging": None})
        # object xattr (CubeFS-owned API, ref router.go:77-91,340-345; GET
        # branches on ?key= between single-get and list inside the handler)
        r.get("/:bucket/*key", w(self.get_object_xattr), queries={"xattr": None})
        r.put("/:bucket/*key", w(self.put_object_xattr), queries={"xattr": None})
        r.delete("/:bucket/*key", w(self.delete_object_xattr),
                 queries={"xattr": None})
        # multipart
        r.post("/:bucket/*key", w(self.initiate_multipart), queries={"uploads": None})
        r.put("/:bucket/*key", w(self.upload_part),
              queries={"partNumber": None, "uploadId": None})
        r.get("/:bucket/*key", w(self.list_parts), queries={"uploadId": None})
        r.post("/:bucket/*key", w(self.complete_multipart), queries={"uploadId": None})
        r.delete("/:bucket/*key", w(self.abort_multipart), queries={"uploadId": None})
        # object core
        r.put("/:bucket/*key", w(self.put_object))
        r.get("/:bucket/*key", w(self.get_object))
        r.head("/:bucket/*key", w(self.head_object))
        r.delete("/:bucket/*key", w(self.delete_object))
        r.handle("OPTIONS", "/:bucket/*key", w(self.preflight))
        return r

    def _wrap(self, fn):
        def handler(req: Request):
            try:
                return fn(req)
            except S3Error as e:
                return _xml_error(e, req.path)
            except NoSuchKey as e:
                return _xml_error(S3Error(404, "NoSuchKey", str(e)), req.path)
            except ReservedKey as e:
                return _xml_error(
                    S3Error(400, "InvalidArgument", f"key {e} is reserved"),
                    req.path)
            except NoSuchUpload as e:
                return _xml_error(S3Error(404, "NoSuchUpload", str(e)), req.path)
            except InvalidPart as e:
                return _xml_error(S3Error(400, "InvalidPart", str(e)), req.path)
            except PolicyError as e:
                return _xml_error(S3Error(400, "MalformedPolicy", str(e)), req.path)
            except FsError as e:
                code = "NoSuchKey" if e.code == "ENOENT" else "InternalError"
                status = 404 if e.code == "ENOENT" else 500
                return _xml_error(S3Error(status, code, str(e)), req.path)
        return handler

    # -- service -----------------------------------------------------------------

    def list_buckets(self, req: Request):
        self._authenticate(req)
        names = self.cluster.volume_names()
        buckets = "".join(
            f"<Bucket><Name>{esc(n)}</Name><CreationDate></CreationDate></Bucket>"
            for n in sorted(names))
        return Response.xml(
            "<ListAllMyBucketsResult><Buckets>"
            f"{buckets}</Buckets></ListAllMyBucketsResult>")

    # -- bucket ------------------------------------------------------------------

    def create_bucket(self, req: Request):
        principal = self._authenticate(req)
        bucket = req.params["bucket"]
        if bucket in self.cluster.volume_names():
            raise S3Error(409, "BucketAlreadyExists", bucket)
        self.cluster.create_volume(bucket, cold=True)
        vol = self._vol(bucket)
        canned = req.header("x-amz-acl", "private")
        vol.set_bucket_xattr(XATTR_ACL, ACL.canned(principal or "", canned).to_json())
        return Response(200, {"Location": f"/{bucket}"})

    def head_bucket(self, req: Request):
        self._authenticate(req)
        self._vol(req.params["bucket"])
        return Response(200)

    def delete_bucket(self, req: Request):
        bucket = req.params["bucket"]
        vol = self._vol(bucket)
        self._check(req, bucket, ACTION_DELETE)
        if not vol.is_empty():
            raise S3Error(409, "BucketNotEmpty", bucket)
        self.cluster.delete_volume(bucket)
        self._vols.pop(bucket, None)
        return Response(204)

    def get_bucket_location(self, req: Request):
        self._check(req, req.params["bucket"], ACTION_GET)
        self._vol(req.params["bucket"])
        return Response.xml(
            f"<LocationConstraint>{self.region}</LocationConstraint>")

    # -- listing -----------------------------------------------------------------

    def _list_common(self, req: Request, v2: bool):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_LIST)
        vol = self._vol(bucket)
        prefix = req.q("prefix")
        delim = req.q("delimiter")
        try:
            max_keys = min(int(req.q("max-keys", "1000")), 1000)
        except ValueError:
            raise S3Error(400, "InvalidArgument", "max-keys") from None
        marker = req.q("continuation-token") or req.q("start-after") if v2 \
            else req.q("marker")
        contents, prefixes, truncated, next_marker = vol.list_objects(
            prefix, marker, delim, max_keys)
        parts = [f"<Name>{esc(bucket)}</Name><Prefix>{esc(prefix)}</Prefix>",
                 f"<MaxKeys>{max_keys}</MaxKeys>",
                 f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"]
        if v2:
            parts.append(f"<KeyCount>{len(contents) + len(prefixes)}</KeyCount>")
            if truncated:
                parts.append(
                    f"<NextContinuationToken>{esc(next_marker)}</NextContinuationToken>")
        elif truncated:
            parts.append(f"<NextMarker>{esc(next_marker)}</NextMarker>")
        for o in contents:
            parts.append(
                f"<Contents><Key>{esc(o['key'])}</Key><Size>{o['size']}</Size>"
                f"<ETag>&quot;{o.get('etag', '')}&quot;</ETag>"
                f"<LastModified>{OSSVolume.http_time(o['mtime'])}</LastModified>"
                f"<StorageClass>STANDARD</StorageClass></Contents>")
        for p in prefixes:
            parts.append(f"<CommonPrefixes><Prefix>{esc(p)}</Prefix></CommonPrefixes>")
        tag = "ListBucketResult"
        return Response.xml(f"<{tag}>{''.join(parts)}</{tag}>")

    def list_objects_v1(self, req: Request):
        return self._list_common(req, v2=False)

    def list_objects_v2(self, req: Request):
        return self._list_common(req, v2=True)

    # -- object core -------------------------------------------------------------

    @staticmethod
    def _version_prologue(vol: OSSVolume, key: str) -> str | None:
        """Before overwriting `key`: retain the prior version per the bucket's
        versioning state. Enabled — archive whatever is current and mint a new
        version id for the incoming write. Suspended — archive only a current
        that carries a REAL version id (the 'null' version is overwritten, the
        versioned history is retained; S3 Suspended semantics); the incoming
        write stays the null version. Returns the new version id or None."""
        status = vol.versioning_status()
        if not status or key.endswith("/"):
            return None
        if status == "Enabled":
            vol.archive_current(key)
            return vol.new_version_id()
        if vol._current_vid(key) is not None:  # Suspended, real current
            vol.archive_current(key)
        return None

    @staticmethod
    def _version_epilogue(vol: OSSVolume, key: str, vid: str | None):
        if vid is not None:
            from chubaofs_tpu.objectnode.volume import XATTR_VERSION_ID

            vol.fs.setxattr("/" + key, XATTR_VERSION_ID, vid.encode())

    def put_object(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_PUT, key)
        vol = self._vol(bucket)
        src = req.header("x-amz-copy-source")
        if src:
            return self._copy_object(req, vol, key, src)
        acl = self._parse_canned_acl(req, vol, key)  # validate BEFORE writing
        vid = self._version_prologue(vol, key)
        user_meta = {k[len("x-amz-meta-"):]: v for k, v in req.headers.items()
                     if k.startswith("x-amz-meta-")}
        etag = vol.put_object(key, req.body, req.header("content-type"),
                              user_meta or None)
        self._version_epilogue(vol, key, vid)
        if acl is not None:
            vol.fs.setxattr("/" + key, XATTR_ACL, acl.to_json())
        headers = {"ETag": f'"{etag}"'}
        if vid is not None:
            headers["x-amz-version-id"] = vid
        return Response(200, headers)

    def _parse_canned_acl(self, req: Request, vol: OSSVolume,
                          key: str) -> ACL | None:
        """x-amz-acl header -> ACL, validated up front: a bad header must 400
        before any state changes (no object written, no version consumed)."""
        canned = req.header("x-amz-acl")
        if not canned or key.endswith("/"):
            return None
        try:
            return ACL.canned(self._owner(vol), canned)
        except ValueError:
            raise S3Error(400, "InvalidArgument",
                          f"x-amz-acl {canned!r}") from None

    def _copy_object(self, req: Request, vol: OSSVolume, key: str, src: str):
        src = urllib.parse.unquote(src).lstrip("/")
        src_bucket, _, src_key = src.partition("/")
        self._check(req, src_bucket, ACTION_GET, src_key)
        src_vol = self._vol(src_bucket)
        info = src_vol.info(src_key)
        data = src_vol.get_object(src_key)
        if req.header("x-amz-metadata-directive", "COPY").upper() == "REPLACE":
            content_type = req.header("content-type") or info["content_type"]
            meta = {k[len("x-amz-meta-"):]: v for k, v in req.headers.items()
                    if k.startswith("x-amz-meta-")}
        else:
            content_type, meta = info["content_type"], info["meta"]
        acl = self._parse_canned_acl(req, vol, key)
        vid = self._version_prologue(vol, key)
        etag = vol.put_object(key, data, content_type, meta or None)
        self._version_epilogue(vol, key, vid)
        if acl is not None:
            vol.fs.setxattr("/" + key, XATTR_ACL, acl.to_json())
        return Response.xml(
            f"<CopyObjectResult><ETag>&quot;{etag}&quot;</ETag>"
            f"<LastModified>{OSSVolume.http_time(info['mtime'])}</LastModified>"
            f"</CopyObjectResult>")

    def _object_headers(self, info: dict) -> dict:
        h = {"ETag": f'"{info["etag"]}"',
             "Content-Type": info["content_type"],
             "Last-Modified": OSSVolume.http_time(info["mtime"]),
             "Accept-Ranges": "bytes"}
        for k, v in info["meta"].items():
            h[f"x-amz-meta-{k}"] = v
        return h

    def get_object(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_GET, key)
        vol = self._vol(bucket)
        vid = req.q("versionId")
        if vid:
            info = vol.stat_version(key, vid)

            def read(off, sz):
                return vol.read_version(key, vid, off, sz)
        else:
            info = vol.info(key)

            def read(off, sz):
                return vol.get_object(key, off, sz)
        headers = self._object_headers(info)
        if vid:
            headers["x-amz-version-id"] = vid
        # conditional GET (RFC 9110 §13): the validator is the etag the crc
        # ledger already stamped on the object — If-Match guards a stale
        # reader (412), If-None-Match serves revalidations headers-only (304)
        im = req.header("if-match")
        if im and not _etag_matches(im, info["etag"]):
            raise S3Error(412, "PreconditionFailed", "If-Match")
        inm = req.header("if-none-match")
        if inm and _etag_matches(inm, info["etag"]):
            return Response(304, headers)
        rng = req.header("range")
        if rng and rng.startswith("bytes="):
            try:
                lo_s, _, hi_s = rng[len("bytes="):].partition("-")
                if lo_s == "":  # suffix form bytes=-N
                    length = int(hi_s)
                    lo = max(0, info["size"] - length)
                    hi = info["size"] - 1
                else:
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else info["size"] - 1
            except ValueError:
                raise S3Error(416, "InvalidRange", rng) from None
            if lo >= info["size"] or lo > hi:
                raise S3Error(416, "InvalidRange", rng)
            hi = min(hi, info["size"] - 1)
            headers["Content-Range"] = f"bytes {lo}-{hi}/{info['size']}"
            return Response(206, headers, read(lo, hi - lo + 1))
        return Response(200, headers, read(0, None))

    def head_object(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_GET, key)
        vol = self._vol(bucket)
        vid = req.q("versionId")
        # stat only — HEAD must never pay a whole-object read
        info = vol.stat_version(key, vid) if vid else vol.info(key)
        headers = self._object_headers(info)
        headers["Content-Length"] = str(info["size"])
        return Response(200, headers)

    def get_object_attributes(self, req: Request):
        """GetObjectAttributes: the metadata subset named by the
        x-amz-object-attributes header, without the body."""
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_GET, key)
        vol = self._vol(bucket)
        vid = req.q("versionId")
        info = vol.stat_version(key, vid) if vid else vol.info(key)
        want = {a.strip() for a in
                req.header("x-amz-object-attributes", "ETag,ObjectSize").split(",")}
        parts = []
        if "ETag" in want:
            parts.append(f"<ETag>{esc(info['etag'])}</ETag>")
        if "ObjectSize" in want:
            parts.append(f"<ObjectSize>{info['size']}</ObjectSize>")
        if "StorageClass" in want:
            parts.append("<StorageClass>STANDARD</StorageClass>")
        headers = {"Last-Modified": OSSVolume.http_time(info["mtime"])}
        if vid:
            headers["x-amz-version-id"] = vid
        return Response(200, {**headers, "Content-Type": "application/xml"},
                        ("<GetObjectAttributesOutput>" + "".join(parts) +
                         "</GetObjectAttributesOutput>").encode())

    def delete_object(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_DELETE, key)
        vol = self._vol(bucket)
        vid = req.q("versionId")
        if vid:
            vol.delete_version(key, vid)
            return Response(204, {"x-amz-version-id": vid})
        marker_vid = self._versioned_delete(vol, key)
        if marker_vid:
            return Response(204, {"x-amz-delete-marker": "true",
                                  "x-amz-version-id": marker_vid})
        return Response(204)

    @staticmethod
    def _versioned_delete(vol: OSSVolume, key: str) -> str | None:
        """Shared delete semantics for DeleteObject AND batch DeleteObjects:
        under versioning, retain history and record a marker (Suspended still
        removes the null current but keeps real versions); unversioned buckets
        delete outright. Returns the marker's version id, or None."""
        status = vol.versioning_status()
        if not status:
            vol.delete_object(key)
            return None
        if status == "Enabled" or vol._current_vid(key) is not None:
            vol.archive_current(key)
        else:
            vol.delete_object(key)
        return vol.put_delete_marker(key)

    def delete_objects(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_DELETE)
        vol = self._vol(bucket)
        root = _parse_xml(req.body)
        quiet = _text(root, "Quiet").lower() == "true"
        deleted = []
        for obj in root.iter("Object"):
            key = _text(obj, "Key")
            if key:
                self._versioned_delete(vol, key)
                deleted.append(key)
        body = "" if quiet else "".join(
            f"<Deleted><Key>{esc(k)}</Key></Deleted>" for k in deleted)
        return Response.xml(f"<DeleteResult>{body}</DeleteResult>")

    # -- acl ---------------------------------------------------------------------

    def get_bucket_acl(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_GET, perm="READ_ACP")
        raw = self._vol(bucket).get_bucket_xattr(XATTR_ACL)
        acl = ACL.from_json(raw) if raw else ACL(self._vol(bucket).owner)
        return Response.xml(acl.to_xml())

    def put_bucket_acl(self, req: Request):
        bucket = req.params["bucket"]
        principal = self._check(req, bucket, ACTION_PUT, perm="WRITE_ACP")
        vol = self._vol(bucket)
        canned = req.header("x-amz-acl", "private")
        owner = self._owner(vol) or principal or ""
        try:
            vol.set_bucket_xattr(XATTR_ACL, ACL.canned(owner, canned).to_json())
        except ValueError as e:
            raise S3Error(400, "InvalidArgument", str(e)) from None
        return Response(200)

    def get_object_acl(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_GET, key, perm="READ_ACP")
        vol = self._vol(bucket)
        vol.info(key)
        try:
            raw = vol.fs.getxattr("/" + key.rstrip("/"), XATTR_ACL)
            return Response.xml(ACL.from_json(raw).to_xml())
        except FsError:
            return Response.xml(ACL(self._owner(vol)).to_xml())

    def put_object_acl(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        principal = self._check(req, bucket, ACTION_PUT, key, perm="WRITE_ACP")
        vol = self._vol(bucket)
        vol.info(key)
        canned = req.header("x-amz-acl", "private")
        try:
            acl = ACL.canned(self._owner(vol) or principal or "", canned)
        except ValueError as e:
            raise S3Error(400, "InvalidArgument", str(e)) from None
        vol.fs.setxattr("/" + key.rstrip("/"), XATTR_ACL, acl.to_json())
        return Response(200)

    # -- policy ------------------------------------------------------------------

    def get_bucket_policy_status(self, req: Request):
        """GetBucketPolicyStatus: IsPublic when any Allow statement grants to
        the anonymous principal."""
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_GET, perm="READ_ACP")
        raw = self._vol(bucket).get_bucket_xattr(XATTR_POLICY)
        if not raw:
            # S3 distinguishes "no policy" (404) from "policy, not public"
            raise S3Error(404, "NoSuchBucketPolicy", bucket)
        # same matcher the request path uses: IsPublic must never diverge
        # from actual anonymous evaluation
        pol = Policy.from_json(raw)
        statements = pol.doc["Statement"]
        if isinstance(statements, dict):
            statements = [statements]
        public = any(
            st.get("Effect") == ALLOW
            and Policy._principal_matches(st, None)
            for st in statements)
        return Response.xml(
            f"<PolicyStatus><IsPublic>{str(public).lower()}</IsPublic>"
            f"</PolicyStatus>")

    def get_bucket_policy(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_GET)
        raw = self._vol(bucket).get_bucket_xattr(XATTR_POLICY)
        if not raw:
            raise S3Error(404, "NoSuchBucketPolicy", bucket)
        return Response(200, {"Content-Type": "application/json"}, raw)

    def put_bucket_policy(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_PUT)
        policy = Policy.from_json(req.body)  # validates
        self._vol(bucket).set_bucket_xattr(XATTR_POLICY, policy.to_json())
        return Response(204)

    def delete_bucket_policy(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_DELETE)
        self._vol(bucket).del_bucket_xattr(XATTR_POLICY)
        return Response(204)

    # -- cors --------------------------------------------------------------------

    def get_bucket_cors(self, req: Request):
        self._check(req, req.params["bucket"], ACTION_GET)
        raw = self._vol(req.params["bucket"]).get_bucket_xattr(XATTR_CORS)
        if not raw:
            raise S3Error(404, "NoSuchCORSConfiguration")
        return Response(200, {"Content-Type": "application/json"}, raw)

    def put_bucket_cors(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_PUT)
        try:
            cfg = self._parse_cors(req)
        except (ET.ParseError, ValueError) as e:
            raise S3Error(400, "MalformedXML", str(e)) from None
        self._vol(bucket).set_bucket_xattr(XATTR_CORS, cfg.to_json())
        return Response(200)

    @staticmethod
    def _parse_cors(req: Request) -> CORSConfig:
        if req.header("content-type", "").startswith("application/json"):
            return CORSConfig.from_json(req.body)
        root = _parse_xml(req.body)
        rules = []
        from chubaofs_tpu.objectnode.cors import CORSRule

        for rule in root.iter("CORSRule"):
            rules.append(CORSRule(
                [e.text for e in rule.findall("AllowedOrigin")],
                [e.text for e in rule.findall("AllowedMethod")],
                [e.text for e in rule.findall("AllowedHeader")],
                [e.text for e in rule.findall("ExposeHeader")],
                int(_text(rule, "MaxAgeSeconds", "0"))))
        return CORSConfig(rules)

    def delete_bucket_cors(self, req: Request):
        self._check(req, req.params["bucket"], ACTION_DELETE)
        self._vol(req.params["bucket"]).del_bucket_xattr(XATTR_CORS)
        return Response(204)

    def preflight(self, req: Request):
        bucket = req.params["bucket"]
        raw = self._vol(bucket).get_bucket_xattr(XATTR_CORS)
        origin = req.header("origin")
        method = req.header("access-control-request-method") or req.method
        if not raw or not origin:
            return Response(403)
        headers = CORSConfig.from_json(raw).headers_for(origin, method)
        return Response(200 if headers else 403, headers)

    # -- tagging -----------------------------------------------------------------

    @staticmethod
    def _parse_tagging(body: bytes) -> dict:
        root = _parse_xml(body)
        return {_text(t, "Key"): _text(t, "Value") for t in root.iter("Tag")}

    @staticmethod
    def _tagging_xml(tags: dict) -> str:
        inner = "".join(f"<Tag><Key>{esc(k)}</Key><Value>{esc(v)}</Value></Tag>"
                        for k, v in sorted(tags.items()))
        return f"<Tagging><TagSet>{inner}</TagSet></Tagging>"

    def get_bucket_tagging(self, req: Request):
        self._check(req, req.params["bucket"], ACTION_GET)
        vol = self._vol(req.params["bucket"])
        raw = vol.get_bucket_xattr("oss:tagging")
        import json

        tags = json.loads(raw) if raw else {}
        return Response.xml(self._tagging_xml(tags))

    def put_bucket_tagging(self, req: Request):
        import json

        self._check(req, req.params["bucket"], ACTION_PUT)
        vol = self._vol(req.params["bucket"])
        tags = self._parse_tagging(req.body)
        vol.set_bucket_xattr("oss:tagging", json.dumps(tags).encode())
        return Response(204)

    def delete_bucket_tagging(self, req: Request):
        self._check(req, req.params["bucket"], ACTION_DELETE)
        self._vol(req.params["bucket"]).del_bucket_xattr("oss:tagging")
        return Response(204)

    def get_object_tagging(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_GET, key)
        tags = self._vol(bucket).get_tagging(key)
        return Response.xml(self._tagging_xml(tags))

    def put_object_tagging(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_PUT, key)
        self._vol(bucket).set_tagging(key, self._parse_tagging(req.body))
        return Response(200)

    def delete_object_tagging(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_DELETE, key)
        self._vol(bucket).delete_tagging(key)
        return Response(204)

    def unsupported(self, req: Request):
        """501 for sub-resources the gateway deliberately does not implement
        (ref unsupportedOperationHandler, api_handler.go:130)."""
        self._authenticate(req)
        return _xml_error(
            S3Error(501, "NotImplemented",
                    "A header you provided implies functionality that is not "
                    "implemented."),
            req.path)

    # -- object xattr (CubeFS-owned extension, ref api_handler_object.go:1491-
    # 1691: XML bodies PutXAttrRequest/GetXAttrOutput/ListXAttrsResult) ----------

    def put_object_xattr(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_PUT, key)
        try:
            root = _parse_xml(req.body)  # <PutXAttrRequest><XAttr>...
            x = root.find("XAttr")
            if x is None:
                x = root
            name = _text(x, "Key")
            velem = x.find("Value")
            value = (velem.text or "") if velem is not None else ""
            # symmetric with get_object_xattr: a <Value encoding="base64">
            # carries raw bytes, so a GET -> PUT round-trip of a binary
            # xattr restores the original bytes, not the base64 text
            if velem is not None and velem.get("encoding") == "base64":
                # tolerate pretty-printed / line-wrapped payloads; still
                # reject non-alphabet garbage
                raw = base64.b64decode("".join(value.split()), validate=True)
            else:
                raw = value.encode()
        except S3Error:
            raise
        except Exception:
            raise S3Error(400, "BadRequest", "malformed PutXAttrRequest") from None
        if not name:
            return Response(200)  # ref: empty key is a silent no-op
        self._vol(bucket).set_xattr(key, name, raw)
        return Response(200)

    def get_object_xattr(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_GET, key)
        vol = self._vol(bucket)
        if not req.has_q("key"):  # ListXAttrs: GET ?xattr without key=
            keys = "".join(f"<Keys>{esc(k)}</Keys>" for k in vol.list_xattrs(key))
            return Response.xml(f"<ListXAttrsResult>{keys}</ListXAttrsResult>")
        name = req.q("key")
        if not name:
            raise S3Error(400, "InvalidArgument", "key is required")
        try:
            value = vol.get_xattr(key, name)
        except FsError as e:
            if e.code == "ENODATA":
                value = b""  # ref: missing attribute reads as empty value
            else:
                raise
        # a binary value set through the FUSE/sdk path cannot travel as XML
        # text: base64-encode it and flag the encoding, instead of a lossy
        # utf-8 'replace' that silently corrupts the bytes. Control bytes
        # other than tab/lf are valid UTF-8 but ILLEGAL in XML 1.0 (and \r
        # is legal yet normalized to \n by every parser), so those take the
        # base64 path too or the response is unparseable/corrupted.
        try:
            text, enc = value.decode("utf-8"), ""
            if any((ord(c) < 0x20 and c not in "\t\n")
                   or ord(c) in (0xFFFE, 0xFFFF) for c in text):
                raise UnicodeDecodeError("utf-8", value, 0, 1, "xml-invalid")
        except UnicodeDecodeError:
            text, enc = base64.b64encode(value).decode("ascii"), \
                ' encoding="base64"'
        return Response.xml(
            f"<GetXAttrOutput><XAttr><Key>{esc(name)}</Key>"
            f"<Value{enc}>{esc(text)}</Value>"
            f"</XAttr></GetXAttrOutput>")

    def delete_object_xattr(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_DELETE, key)
        name = req.q("key")
        if not name:
            raise S3Error(400, "InvalidArgument", "key is required")
        try:
            self._vol(bucket).delete_xattr(key, name)
        except FsError as e:
            if e.code != "ENODATA":
                raise
        return Response(204)

    # -- multipart ---------------------------------------------------------------

    def initiate_multipart(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_PUT, key)
        upload_id = self._mpu(bucket).initiate(key, req.header("content-type"))
        return Response.xml(
            f"<InitiateMultipartUploadResult><Bucket>{esc(bucket)}</Bucket>"
            f"<Key>{esc(key)}</Key><UploadId>{upload_id}</UploadId>"
            f"</InitiateMultipartUploadResult>")

    def upload_part(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_PUT, key)
        try:
            part_num = int(req.q("partNumber"))
        except ValueError:
            raise S3Error(400, "InvalidArgument", "partNumber") from None
        src = req.header("x-amz-copy-source")
        if src:
            return self._upload_part_copy(req, bucket, part_num, src)
        etag = self._mpu(bucket).put_part(req.q("uploadId"), part_num, req.body)
        return Response(200, {"ETag": f'"{etag}"'})

    def _upload_part_copy(self, req: Request, bucket: str, part_num: int,
                          src: str):
        """UploadPartCopy: the part's bytes come from an existing object
        (optionally a byte range), not the request body."""
        src = urllib.parse.unquote(src).lstrip("/")
        src_bucket, _, src_key = src.partition("/")
        self._check(req, src_bucket, ACTION_GET, src_key)
        src_vol = self._vol(src_bucket)
        info = src_vol.info(src_key)
        rng = req.header("x-amz-copy-source-range")
        if rng:
            m = re.fullmatch(r"bytes=(\d+)-(\d+)", rng.strip())
            if not m:
                raise S3Error(400, "InvalidArgument", rng)
            lo, hi = int(m.group(1)), int(m.group(2))
            if lo > hi or hi >= info["size"]:
                raise S3Error(416, "InvalidRange", rng)
            data = src_vol.get_object(src_key, lo, hi - lo + 1)
        else:
            data = src_vol.get_object(src_key)
        etag = self._mpu(bucket).put_part(req.q("uploadId"), part_num, data)
        return Response.xml(
            f"<CopyPartResult><ETag>&quot;{etag}&quot;</ETag>"
            f"<LastModified>{OSSVolume.http_time(info['mtime'])}</LastModified>"
            f"</CopyPartResult>")

    def list_parts(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_GET)
        key, parts = self._mpu(bucket).list_parts(req.q("uploadId"))
        inner = "".join(
            f"<Part><PartNumber>{p['part_number']}</PartNumber>"
            f"<ETag>&quot;{p['etag']}&quot;</ETag><Size>{p['size']}</Size></Part>"
            for p in parts)
        return Response.xml(
            f"<ListPartsResult><Bucket>{esc(bucket)}</Bucket><Key>{esc(key)}</Key>"
            f"<UploadId>{req.q('uploadId')}</UploadId>{inner}</ListPartsResult>")

    def list_uploads(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_LIST)
        ups = self._mpu(bucket).list_uploads()
        inner = "".join(
            f"<Upload><Key>{esc(u['key'])}</Key><UploadId>{u['upload_id']}</UploadId>"
            f"</Upload>" for u in ups)
        return Response.xml(
            f"<ListMultipartUploadsResult><Bucket>{esc(bucket)}</Bucket>{inner}"
            f"</ListMultipartUploadsResult>")

    def complete_multipart(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_PUT, key)
        root = _parse_xml(req.body)
        try:
            spec = [(int(_text(p, "PartNumber")), _text(p, "ETag"))
                    for p in root.iter("Part")]
        except ValueError:
            raise S3Error(400, "MalformedXML", "PartNumber") from None
        vol = self._vol(bucket)
        mpu = self._mpu(bucket)
        # archive against the SESSION's key (the one complete() overwrites)
        session_key, _ = mpu.list_parts(req.q("uploadId"))
        vid = self._version_prologue(vol, session_key)
        final_key, etag = mpu.complete(req.q("uploadId"), spec)
        self._version_epilogue(vol, final_key, vid)
        return Response.xml(
            f"<CompleteMultipartUploadResult><Bucket>{esc(bucket)}</Bucket>"
            f"<Key>{esc(final_key)}</Key><ETag>&quot;{etag}&quot;</ETag>"
            f"</CompleteMultipartUploadResult>")

    def abort_multipart(self, req: Request):
        bucket, key = req.params["bucket"], req.params["key"]
        self._check(req, bucket, ACTION_DELETE, key)
        self._mpu(bucket).abort(req.q("uploadId"))
        return Response(204)

    # -- versioning ----------------------------------------------------------------

    def get_bucket_versioning(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_GET)
        status = self._vol(bucket).versioning_status()
        inner = f"<Status>{status}</Status>" if status else ""
        return Response.xml(f"<VersioningConfiguration>{inner}"
                            f"</VersioningConfiguration>")

    def put_bucket_versioning(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_PUT)
        status = _text(_parse_xml(req.body), "Status")
        try:
            self._vol(bucket).set_versioning(status)
        except ValueError:
            raise S3Error(400, "MalformedXML", f"Status {status!r}") from None
        return Response(200)

    def list_object_versions(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_LIST)
        entries = self._vol(bucket).list_versions(prefix=req.q("prefix"))
        parts = []
        for e in entries:
            tag = "DeleteMarker" if e["delete_marker"] else "Version"
            body = (f"<Key>{esc(e['key'])}</Key>"
                    f"<VersionId>{e['version_id']}</VersionId>"
                    f"<IsLatest>{'true' if e['is_latest'] else 'false'}</IsLatest>"
                    f"<LastModified>{OSSVolume.http_time(e['mtime'])}</LastModified>")
            if not e["delete_marker"]:
                body += (f"<ETag>&quot;{e['etag']}&quot;</ETag>"
                         f"<Size>{e['size']}</Size>")
            parts.append(f"<{tag}>{body}</{tag}>")
        return Response.xml(
            f"<ListVersionsResult><Name>{esc(bucket)}</Name>"
            f"{''.join(parts)}</ListVersionsResult>")

    # -- lifecycle -----------------------------------------------------------------
    #
    # Rules persist as a JSON bucket xattr; apply_lifecycle() is the expiry
    # sweeper the deployment pumps (the reference runs it inside objectnode's
    # lifecycle service).

    def get_bucket_lifecycle(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_GET)
        raw = self._vol(bucket).get_bucket_xattr(XATTR_LIFECYCLE)
        if not raw:
            raise S3Error(404, "NoSuchLifecycleConfiguration", bucket)
        import json as _json

        rules = _json.loads(raw)
        inner = "".join(
            f"<Rule><ID>{esc(r['id'])}</ID>"
            f"<Filter><Prefix>{esc(r['prefix'])}</Prefix></Filter>"
            f"<Status>{r['status']}</Status>"
            f"<Expiration><Days>{r['days']}</Days></Expiration></Rule>"
            for r in rules)
        return Response.xml(
            f"<LifecycleConfiguration>{inner}</LifecycleConfiguration>")

    def put_bucket_lifecycle(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_PUT)
        root = _parse_xml(req.body)
        rules = []
        for rule in root.iter("Rule"):
            exp = rule.find("Expiration")
            days = _text(exp, "Days") if exp is not None else ""
            if not days:
                raise S3Error(400, "MalformedXML", "Expiration.Days required")
            filt = rule.find("Filter")
            prefix = _text(filt, "Prefix") if filt is not None else _text(rule, "Prefix")
            try:
                days_n = int(days)
            except ValueError:
                raise S3Error(400, "MalformedXML",
                              f"Expiration.Days {days!r}") from None
            rules.append({"id": _text(rule, "ID") or f"rule{len(rules)}",
                          "prefix": prefix,
                          "status": _text(rule, "Status") or "Enabled",
                          "days": days_n})
        if not rules:
            raise S3Error(400, "MalformedXML", "no Rule")
        import json as _json

        self._vol(bucket).set_bucket_xattr(XATTR_LIFECYCLE,
                                           _json.dumps(rules).encode())
        return Response(200)

    def delete_bucket_lifecycle(self, req: Request):
        bucket = req.params["bucket"]
        self._check(req, bucket, ACTION_DELETE)
        self._vol(bucket).del_bucket_xattr(XATTR_LIFECYCLE)
        return Response(204)

    def apply_lifecycle(self, now: float | None = None) -> int:
        """Expire objects per enabled rules; returns objects expired. The
        deployment pumps this like the master's background checks."""
        import json as _json
        import time as _time

        now = now if now is not None else _time.time()
        expired = 0
        for bucket in self.cluster.volume_names():
            try:
                vol = self._vol(bucket)
            except S3Error:
                continue
            raw = vol.get_bucket_xattr(XATTR_LIFECYCLE)
            if not raw:
                continue
            versioned = vol.versioning_status() == "Enabled"
            for rule in _json.loads(raw):
                if rule["status"] != "Enabled":
                    continue
                contents, _, _, _ = vol.list_objects(
                    prefix=rule["prefix"], max_keys=100000)
                cutoff = now - rule["days"] * 86400
                for obj in contents:
                    if obj["key"].endswith("/"):
                        continue  # dir markers never expire (and can't archive)
                    if obj["mtime"] <= cutoff:
                        if versioned:
                            vol.archive_current(obj["key"])
                            vol.put_delete_marker(obj["key"])
                        else:
                            vol.delete_object(obj["key"])
                        expired += 1
        return expired
