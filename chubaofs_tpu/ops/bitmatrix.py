"""GF(2^8) -> GF(2) bit-matrix lowering.

The TPU has no byte-gather-friendly GF(2^8) multiplier, but it has a very fast
integer/bf16 matmul unit (the MXU). Multiplication by a *constant* c in GF(2^8) is a
linear map over GF(2)^8, so a whole GF(2^8) matrix product lowers to a GF(2) matrix
product 8x larger per side:

    parity_bits = (G_bits @ data_bits) mod 2

where data bytes are unpacked to bits (LSB-first) along the contraction axis. The
mod-2 sum is computed with an ordinary integer matmul (exact: row sums < 2^31)
followed by a parity mask — this keeps the entire inner loop on the MXU.

This replaces the SIMD table-gather approach of klauspost/reedsolomon (reference
vendor/github.com/klauspost/reedsolomon galois_gen_amd64.s) with hardware the TPU
actually has. See ops/rs.py for the jitted kernels built on these matrices.
"""

from __future__ import annotations

import numpy as np

from chubaofs_tpu.ops import gf256

BITS = 8
# LSB-first bit order everywhere: bit b of byte d is (d >> b) & 1.
_BIT_POS = np.arange(BITS, dtype=np.uint8)


def mul_bit_matrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix M_c with bits(c * d) = M_c @ bits(d) mod 2.

    Column b is bits(c * x^b) since d = sum_b d_b x^b over the field.
    """
    cols = gf256.gf_mul(np.uint8(c), (np.uint8(1) << _BIT_POS))  # c * x^b, shape (8,)
    return ((cols[None, :] >> _BIT_POS[:, None]) & 1).astype(np.uint8)


def expand_matrix(m: np.ndarray) -> np.ndarray:
    """Lift a (r, c) GF(2^8) matrix to its (8r, 8c) GF(2) bit matrix.

    Block (i, j) is mul_bit_matrix(m[i, j]); row index 8*i+b selects output bit b of
    GF-row i, column index 8*j+b selects input bit b of GF-column j — matching the
    (shard, bit) -> shard*8+bit flattening used by unpack_bits.
    """
    m = np.asarray(m, dtype=np.uint8)
    r, c = m.shape
    # bits(m[i,j] * x^b) for all i,j,b at once
    basis = (np.uint8(1) << _BIT_POS)  # (8,)
    prod = gf256.gf_mul(m[:, :, None], basis[None, None, :])  # (r, c, 8): m_ij * x^b
    bits = (prod[:, :, None, :] >> _BIT_POS[None, None, :, None]) & 1  # (r, c, 8out, 8in)
    return bits.transpose(0, 2, 1, 3).reshape(r * BITS, c * BITS).astype(np.uint8)


def unpack_bits_np(data: np.ndarray) -> np.ndarray:
    """(..., n, k) uint8 -> (..., 8n, k) {0,1} uint8, row n*8+b = bit b (LSB-first)."""
    data = np.asarray(data, dtype=np.uint8)
    bits = (data[..., :, None, :] >> _BIT_POS[:, None]) & 1
    shape = data.shape[:-2] + (data.shape[-2] * BITS, data.shape[-1])
    return bits.reshape(shape)


def pack_bits_np(bits: np.ndarray) -> np.ndarray:
    """Inverse of unpack_bits_np: (..., 8m, k) {0,1} -> (..., m, k) uint8."""
    bits = np.asarray(bits, dtype=np.uint8)
    m = bits.shape[-2] // BITS
    b = bits.reshape(bits.shape[:-2] + (m, BITS, bits.shape[-1]))
    return (b << _BIT_POS[:, None]).sum(axis=-2).astype(np.uint8)
