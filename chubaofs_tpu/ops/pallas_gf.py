"""Fused Pallas kernel for the GF(2^8) bit-matrix product.

The plain XLA lowering (ops/rs.gf_matmul_bytes) materializes the 8x bit expansion
of the data in HBM (int8 bits in, int32 accumulator out), so encode throughput is
bandwidth-bound at ~an order of magnitude more HBM traffic than the payload. This
kernel keeps the whole unpack -> int8 MXU matmul -> parity-mask -> pack sequence in
VMEM: HBM sees only the uint8 payload in and the uint8 result out.

Layout choice (measured on v5e-1): the GF(2) matrix is stored PLANE-MAJOR — row
b*r+p is output-bit b of GF-row p, column b*n+j is input-bit b of GF-column j — so
the in-kernel unpack is eight scalar shifts producing whole bit-planes and the pack
is eight plane slices OR-ed together. The byte-major order (row p*8+b) used by
ops/bitmatrix would need (n, 8, kt) -> (8n, kt) sublane reshapes inside the kernel,
which cost more VPU time than the matmul itself. Mosaic constraints baked in here:
no 8/16-bit vector shifts (unpack runs in int32), no in-kernel bitwidth-changing
bitcast, iota only in 16/32 bit (avoided entirely).

Reference counterpart: the amd64 assembly loops of klauspost/reedsolomon (the only
"math kernel" in the reference, SURVEY §2.3) — this is its TPU replacement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BITS = 8
# measured on v5e-1 (slope-timed): 128KiB tiles edge out 32KiB (~54.4 vs
# ~53.4 GB/s encode) — fewer grid steps amortize per-tile overhead while the
# (12+4)x128KiB working set still double-buffers in VMEM
DEFAULT_TILE_K = 131072
# per-grid-step in+out block budget for the adaptive tile choice: ~2 MiB is
# the measured sweet spot at every stacking factor (G=1:128K, G=2:64K,
# G=4:32K tiles all sit on (n+r)*kt ~= 2 MiB and all beat their neighbours)
TILE_BYTES = 2 << 20


def _perm(dim: int) -> list[int]:
    """plane-major index b*dim+i -> byte-major index i*8+b, for one axis."""
    return [(i % dim) * BITS + i // dim for i in range(dim * BITS)]


def plane_major(mat_bits: np.ndarray) -> np.ndarray:
    """Permute a byte-major (8r, 8n) GF(2) matrix to the kernel's plane-major order."""
    r8, n8 = mat_bits.shape
    return np.asarray(mat_bits)[_perm(r8 // BITS)][:, _perm(n8 // BITS)]


def pick_group(b: int, r8: int, n8: int, cap: int | None = None) -> int:
    """Largest divisor g of the batch with g*r8 <= 128 and g*n8 <= 512.

    ``cap`` additionally bounds g (e.g. a dp-sharded caller passes b//dp so
    grouping never collapses the batch below the mesh's data-parallel axis).

    Block-diagonal generator stacking (PERF.md "paths past 100"): the stationary
    matrix of one EC(12,4) stripe is 32x96 on a 128x128 systolic array (~19%
    utilized). Stacking g stripes' generators block-diagonally (kron(I_g, mat))
    and viewing g stripes as one wide (g*n, k) stripe fills the MXU rows —
    measured on v5e-1: EC(12,4) encode 54 -> ~130 GB/s at g=4 (rows=128).
    Beyond 128 rows (a second row-tile) throughput regresses, hence the cap.

    The grouping MUST happen at the host boundary ((b, n, k) -> (b/g, g*n, k)
    is a free numpy view there): on device the same reshape physically
    rearranges the sublane-tiled HBM buffer (measured 131 -> 53 GB/s fed
    through an in-jit reshape), and every in-kernel merge variant (4D block +
    VMEM reshape, per-slab unpack + concat, slab-loop matmul accumulation)
    defeats Mosaic's streaming fusion and blows the 16M scoped-VMEM limit.
    rs.group_stack packages the host-side transform.
    """
    best = 1
    hi = min(b, 128) if cap is None else min(b, 128, cap)
    for g in range(2, hi + 1):
        if g * r8 > 128 or g * n8 > 512:
            break
        if b % g == 0:
            best = g
    return best


def _gf_kernel(mat_ref, data_ref, out_ref):
    """One (batch, k-tile) grid step: out = (mat @ bits(data)) mod 2, packed.

    mat_ref:  (8r, 8n) int8, plane-major — resident in VMEM for all grid steps
    data_ref: (1, n, kt) uint8
    out_ref:  (1, r, kt) uint8
    """
    r = out_ref.shape[1]
    data32 = data_ref[0].astype(jnp.int32)  # Mosaic has no 8-bit vector shifts
    planes = [((data32 >> b) & 1).astype(jnp.int8) for b in range(BITS)]
    bits = jnp.concatenate(planes, axis=0)  # (8n, kt), plane-major

    acc = jax.lax.dot_general(
        mat_ref[...],
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (8r, kt) int32, plane-major rows
    packed = acc[0:r] & 1
    for b in range(1, BITS):
        packed |= (acc[b * r : (b + 1) * r] & 1) << b
    out_ref[0] = packed.astype(jnp.uint8)


def gf_matmul_bytes_fused(
    mat_bits: jax.Array,
    shards: jax.Array,
    tile_k: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in fused equivalent of rs.gf_matmul_bytes.

    mat_bits: (8r, 8n) int8 in the standard byte-major order; shards:
    (..., n, k) uint8 -> (..., r, k) uint8. k is padded to the tile size
    internally and sliced back.

    Host numpy matrices (the rs.py contract: generator and repair matrices
    stay numpy) are permuted to the kernel's plane-major layout in numpy at
    trace time; traced/device matrices (e.g. repair plans fed as runtime args
    through shard_map) pay a tiny in-graph gather instead — one compiled
    program keeps serving every repair pattern with no recompilation.

    For MXU-filling batched throughput, feed GROUP-STACKED operands (see
    rs.group_stack / pick_group): a (8gr, 8gn) block-diagonal matrix over
    (b/g, g*n, k) host-viewed stripes.
    """
    r8, n8 = mat_bits.shape
    r, n = r8 // BITS, n8 // BITS
    lead = shards.shape[:-2]
    k = shards.shape[-1]
    assert shards.shape[-2] == n, (shards.shape, mat_bits.shape)
    if r8 == 0 or k == 0:
        return jnp.zeros((*lead, r, k), jnp.uint8)

    b = 1
    for d in lead:
        b *= d

    if isinstance(mat_bits, np.ndarray):
        # numpy at trace time: the device never sees the permutation
        mat_pm = plane_major(mat_bits).astype(np.int8)
    else:
        mat_pm = mat_bits[jnp.asarray(_perm(r))][:, jnp.asarray(_perm(n))]

    out = _fused_core(mat_pm, shards.reshape(b, n, k), tile_k=tile_k, interpret=interpret)
    return out.reshape(*lead, r, k)


@functools.partial(jax.jit, static_argnames=("tile_k", "interpret"))
def _fused_core(
    mat_pm: jax.Array,
    data: jax.Array,
    tile_k: int | None,
    interpret: bool,
) -> jax.Array:
    """Jitted core: (b, n, k) uint8 -> (b, r, k) uint8 via the Pallas kernel.

    mat_pm is already in the kernel's plane-major layout.
    """
    b, n, k = data.shape
    r8, n8 = mat_pm.shape
    r = r8 // BITS

    if tile_k is None:
        # keep the per-step in+out block near TILE_BYTES: measured sweet spot
        # at every matrix width ((12+4)x128K, (24+8)x64K, (48+16)x32K all win)
        tile_k = max(128, min(DEFAULT_TILE_K, TILE_BYTES // (n + r) // 128 * 128))

    # Mosaic pads sub-tile sublane counts up to full int8 tiles (32 sublanes),
    # so with few shard rows the unpack intermediates cost ~8*32 bytes/column
    # regardless of n and the scoped-VMEM stack blows the 16M limit at large
    # tiles (measured: n=3, r=1 at kt=128K needs 30.8M). Narrow tiles keep the
    # stack bounded; wide (possibly group-stacked) stripes keep larger tiles.
    if min(n, r) < 8:
        tile_k = min(tile_k, 32768)

    # pick the tile so the grid divides evenly with minimal padding: distribute
    # the 128-aligned length over ceil(k/tile_k) tiles (pad <= 128 * n_tiles
    # instead of up to a full tile)
    k128 = -(-k // 128) * 128
    n_tiles = max(1, -(-k128 // tile_k))
    kt = -(-k128 // n_tiles // 128) * 128
    kp = kt * n_tiles
    if kp != k:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, kp - k)))

    out = pl.pallas_call(
        _gf_kernel,
        grid=(b, kp // kt),
        in_specs=[
            pl.BlockSpec((r8, n8), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, kt), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, r, kt), lambda i, j: (i, 0, j)),
        out_shape=jax.ShapeDtypeStruct((b, r, kp), jnp.uint8),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(mat_pm, data)

    if kp != k:
        out = out[..., :k]
    return out
