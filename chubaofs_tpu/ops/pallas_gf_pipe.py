"""Manual-DMA double-buffered variant of the fused GF(2^8) kernel.

PERF.md headroom #1: in the auto-pipelined kernel (ops/pallas_gf.py) each
grid step runs unpack (VPU) -> bit-matmul (MXU) -> pack (VPU) as one
dependency chain, so the MXU idles during every unpack/pack and the VPU
during every matmul; Mosaic's automatic pipelining overlaps only the HBM
DMAs, not compute across steps. The cheap fixes measured in PERF.md all
lose because any in-kernel restructuring of the AUTO-pipelined body breaks
Mosaic's streaming fusion of the unpack chain.

This kernel takes the full rewrite instead (dimension_semantics=arbitrary):
one grid step per stripe row owns its whole k-range and drives a SKEWED
software pipeline with manual HBM<->VMEM DMAs —

    iteration t:  wait in-DMA(t)   -> unpack tile t     (VPU)
                  start in-DMA(t+1)
                  matmul tile t-1  (MXU)  + pack + start out-DMA(t-1)

unpack(t) writes bits[t%2] while the matmul reads bits[(t-1)%2]: no data
dependence, so the scheduler may overlap VPU and MXU work that the fused
kernel serializes. The price is materialized bit-planes (8n x kt int8 per
slot) — the very thing streaming fusion avoids — which caps the tile size
by VMEM (16 MB): all buffers are ~176*n bytes per column, so kt is chosen
to keep the resident set near 10 MB.

Whether the overlap beats the lost fusion is an empirical question the
bench answers per chip; rs.gf_matmul_dispatch keeps the fused kernel as
the default and selects this one via CFS_GF_PIPELINED=1.

Reference counterpart: same as pallas_gf.py — the klauspost/reedsolomon
assembly loops (SURVEY §2.3), which pipeline loads against GF multiplies
the same way at the x86 cache hierarchy scale.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chubaofs_tpu.ops.pallas_gf import BITS, _perm, plane_major

# resident VMEM per column of tile: data(2n) + bits(2*8n) + out(2r) + acc
# (8r int32) bytes; target ~10 MiB so the compiler keeps headroom for
# spills/alignment within the 16 MiB budget
VMEM_TARGET = 10 << 20


def _pick_tile(n: int, r: int, k: int) -> int:
    per_col = 2 * n + 2 * 8 * n + 2 * r + 4 * 8 * r
    kt = VMEM_TARGET // per_col // 128 * 128
    return max(128, min(kt, k, 65536))


def _stage_helpers(n: int, r: int, kt: int, mat_ref, data_hbm, out_hbm,
                   data_buf, bits_buf, out_buf, in_sems, out_sems):
    """(in_dma, out_dma, unpack, compute) shared by both slot strategies —
    only the loop-body SCHEDULING differs between the kernel factories."""
    i = pl.program_id(0)

    def in_dma(slot, t):
        return pltpu.make_async_copy(
            data_hbm.at[i].at[:, pl.ds(t * kt, kt)],
            data_buf.at[slot], in_sems.at[slot])

    def out_dma(slot, t):
        return pltpu.make_async_copy(
            out_buf.at[slot],
            out_hbm.at[i].at[:, pl.ds(t * kt, kt)], out_sems.at[slot])

    def unpack(slot):
        d32 = data_buf[slot].astype(jnp.int32)
        planes = [((d32 >> bb) & 1).astype(jnp.int8) for bb in range(BITS)]
        bits_buf[slot] = jnp.concatenate(planes, axis=0)

    def compute(slot):
        acc = jax.lax.dot_general(
            mat_ref[...], bits_buf[slot],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        packed = acc[0:r] & 1
        for bb in range(1, BITS):
            packed |= (acc[bb * r:(bb + 1) * r] & 1) << bb
        out_buf[slot] = packed.astype(jnp.uint8)

    return in_dma, out_dma, unpack, compute


def _skew_half(n_tiles, in_dma, out_dma, unpack, compute, t, slot, prev):
    """One skewed-pipeline iteration t with its two buffer slots: load +
    unpack tile t while computing + storing tile t-1."""

    @pl.when(t < n_tiles)
    def _load_unpack():
        in_dma(slot, t).wait()

        @pl.when(t + 1 < n_tiles)
        def _():
            in_dma(prev, t + 1).start()

        unpack(slot)

    @pl.when((t >= 1) & (t <= n_tiles))
    def _compute_store():
        tc = t - 1

        @pl.when(tc >= 2)
        def _():  # slot reuse: tile tc-2 used the same out slot
            out_dma(prev, tc - 2).wait()

        compute(prev)
        out_dma(prev, tc).start()


def _drain(out_dma, n_tiles):
    out_dma((n_tiles - 1) % 2, n_tiles - 1).wait()
    if n_tiles >= 2:
        out_dma((n_tiles - 2) % 2, n_tiles - 2).wait()


def _make_kernel(n: int, r: int, kt: int, n_tiles: int):
    """Kernel body for one stripe row: manual skewed double-buffer pipeline."""

    def kernel(mat_ref, data_hbm, out_hbm, data_buf, bits_buf, out_buf,
               in_sems, out_sems):
        in_dma, out_dma, unpack, compute = _stage_helpers(
            n, r, kt, mat_ref, data_hbm, out_hbm, data_buf, bits_buf,
            out_buf, in_sems, out_sems)
        in_dma(0, 0).start()

        def body(t, _):
            slot = jax.lax.rem(t, 2)
            prev = jax.lax.rem(t + 1, 2)  # == (t-1) % 2
            _skew_half(n_tiles, in_dma, out_dma, unpack, compute,
                       t, slot, prev)
            return 0

        jax.lax.fori_loop(0, n_tiles + 1, body, 0)
        _drain(out_dma, n_tiles)

    return kernel


def _make_kernel_static(n: int, r: int, kt: int, n_tiles: int):
    """Same skewed pipeline with STATIC buffer slots: the loop walks PAIRS
    of tiles, each half hard-coding slot 0/1. Plan B for the case where
    Mosaic rejects the dynamic `scratch.at[traced_slot]` indexing of
    _make_kernel — identical semantics, verified against it in interpret
    mode; kernel_ab falls back to it automatically on compile failure."""

    def kernel(mat_ref, data_hbm, out_hbm, data_buf, bits_buf, out_buf,
               in_sems, out_sems):
        in_dma, out_dma, unpack, compute = _stage_helpers(
            n, r, kt, mat_ref, data_hbm, out_hbm, data_buf, bits_buf,
            out_buf, in_sems, out_sems)
        in_dma(0, 0).start()

        def body(tp, _):
            t0 = 2 * tp
            # even tile: load slot0, compute slot1; odd tile: the reverse
            _skew_half(n_tiles, in_dma, out_dma, unpack, compute, t0, 0, 1)
            _skew_half(n_tiles, in_dma, out_dma, unpack, compute,
                       t0 + 1, 1, 0)
            return 0

        jax.lax.fori_loop(0, (n_tiles + 2) // 2, body, 0)
        _drain(out_dma, n_tiles)

    return kernel


def gf_matmul_bytes_pipelined(
    mat_bits: jax.Array,
    shards: jax.Array,
    tile_k: int | None = None,
    interpret: bool = False,
    static_slots: bool = False,
) -> jax.Array:
    """Drop-in equivalent of pallas_gf.gf_matmul_bytes_fused (same contract:
    byte-major (8r, 8n) matrix, (..., n, k) uint8 shards -> (..., r, k))."""
    r8, n8 = mat_bits.shape
    r, n = r8 // BITS, n8 // BITS
    lead = shards.shape[:-2]
    k = shards.shape[-1]
    assert shards.shape[-2] == n, (shards.shape, mat_bits.shape)
    if r8 == 0 or k == 0:
        return jnp.zeros((*lead, r, k), jnp.uint8)
    b = 1
    for d in lead:
        b *= d
    if isinstance(mat_bits, np.ndarray):
        mat_pm = plane_major(mat_bits).astype(np.int8)
    else:
        mat_pm = mat_bits[jnp.asarray(_perm(r))][:, jnp.asarray(_perm(n))]
    out = _pipe_core(mat_pm, shards.reshape(b, n, k), tile_k=tile_k,
                     interpret=interpret, static_slots=static_slots)
    return out.reshape(*lead, r, k)


@functools.partial(jax.jit,
                   static_argnames=("tile_k", "interpret", "static_slots"))
def _pipe_core(mat_pm, data, tile_k, interpret, static_slots=False):
    b, n, k = data.shape
    r8, n8 = mat_pm.shape
    r = r8 // BITS

    kt = tile_k or _pick_tile(n, r, k)
    k128 = -(-k // 128) * 128
    n_tiles = max(1, -(-k128 // kt))
    kt = -(-k128 // n_tiles // 128) * 128
    kp = kt * n_tiles
    if kp != k:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, kp - k)))

    make = _make_kernel_static if static_slots else _make_kernel
    out = pl.pallas_call(
        make(n, r, kt, n_tiles),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((r8, n8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # whole array stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((b, r, kp), jnp.uint8),
        scratch_shapes=[
            pltpu.VMEM((2, n, kt), jnp.uint8),       # data tiles
            pltpu.VMEM((2, 8 * n, kt), jnp.int8),    # unpacked bit-planes
            pltpu.VMEM((2, r, kt), jnp.uint8),       # packed results
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(mat_pm, data)

    if kp != k:
        out = out[..., :k]
    return out
