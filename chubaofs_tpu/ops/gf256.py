"""GF(2^8) arithmetic: tables, matrices, and a numpy reference codec.

This is the scalar/CPU foundation of the erasure-coding core. The reference
implementation (CubeFS) delegates GF(2^8) math to klauspost/reedsolomon's SIMD
assembly (reference blobstore/common/ec/encoder.go:21,86). Here the field math is
built from first principles:

  * log/exp tables over GF(2^8) with the 0x11d primitive polynomial (the same field
    used by klauspost/reedsolomon and virtually every storage RS codec),
  * a systematic Cauchy generator matrix (every square submatrix of a Cauchy matrix
    is invertible, so any N of the N+M shards can recover the data — the MDS
    property; Vandermonde-derived constructions need the extra inversion step to
    guarantee this),
  * Gauss-Jordan inversion over the field for decode matrices,
  * a pure-numpy encode/reconstruct used as the correctness oracle for the TPU
    kernels and as a host-side fallback.

The TPU path does NOT use these tables at runtime: it lowers GF(2^8) matrix
products to GF(2) bit-matrix products on the MXU (see ops/bitmatrix.py and
ops/rs.py). These tables are used at *setup* time to build generator/decode
matrices and to cross-check results.
"""

from __future__ import annotations

import functools

import numpy as np

# x^8 + x^4 + x^3 + x^2 + 1 — primitive polynomial of the storage-RS field.
POLY = 0x11D
FIELD = 256
ORDER = FIELD - 1  # multiplicative group order


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables; exp is doubled to ORDER*2 so mul never needs a mod."""
    exp = np.zeros(ORDER * 2, dtype=np.uint8)
    log = np.zeros(FIELD, dtype=np.int32)
    x = 1
    for i in range(ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    exp[ORDER : ORDER * 2] = exp[:ORDER]
    log[0] = -1  # sentinel: log(0) undefined
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()


@functools.lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """Full 256x256 GF(2^8) product table (uint8)."""
    a = np.arange(256, dtype=np.int32)
    la = LOG_TABLE[a]
    t = EXP_TABLE[(la[:, None] + la[None, :]) % ORDER].astype(np.uint8)
    t[0, :] = 0
    t[:, 0] = 0
    return t


def gf_mul(a, b):
    """Element-wise GF(2^8) product of uint8 arrays/scalars."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    return mul_table()[a, b]


def gf_inv(a):
    """Multiplicative inverse; a must be nonzero."""
    a = np.asarray(a, dtype=np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("gf_inv(0)")
    return EXP_TABLE[ORDER - LOG_TABLE[a]]


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_pow(a: int, n: int) -> int:
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) * n) % ORDER])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): XOR-accumulated gf_mul. Oracle-grade, O(n^3)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    assert a.ndim == 2 and b.ndim == 2 and a.shape[1] == b.shape[0]
    t = mul_table()
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for k in range(a.shape[1]):
        out ^= t[a[:, k][:, None], b[k, :][None, :]]
    return out


def gf_inv_matrix(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8). Raises if singular."""
    m = np.array(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    aug = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    t = mul_table()
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(aug[col, col])
        aug[col] = t[aug[col], inv_p]
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= t[aug[row, col], aug[col]]
    return aug[:, n:].copy()


def cauchy_parity_matrix(n: int, m: int) -> np.ndarray:
    """m x n Cauchy block C[i,j] = 1/(x_i + y_j), x_i = n+i, y_j = j.

    Distinctness of {x_i} ∪ {y_j} over GF(2^8) requires n + m <= 256. Every square
    submatrix of a Cauchy matrix is invertible, which makes the systematic generator
    [I ; C] MDS: any n surviving rows form an invertible matrix.
    """
    if n + m > FIELD:
        raise ValueError(f"n+m = {n + m} exceeds field size {FIELD}")
    x = np.arange(n, n + m, dtype=np.uint8)
    y = np.arange(n, dtype=np.uint8)
    return gf_inv(x[:, None] ^ y[None, :])


def systematic_generator(n: int, m: int) -> np.ndarray:
    """(n+m) x n systematic generator: identity on top, Cauchy parity below."""
    return np.concatenate([np.eye(n, dtype=np.uint8), cauchy_parity_matrix(n, m)], axis=0)


def decode_matrix(gen: np.ndarray, present_rows: list[int] | np.ndarray) -> np.ndarray:
    """n x n matrix mapping shards at `present_rows` (first n of them) back to data.

    gen is the (n+m) x n systematic generator; present_rows are indices of surviving
    shards. Uses the first n surviving rows. data = decode @ survivors.
    """
    n = gen.shape[1]
    rows = np.asarray(present_rows)[:n]
    if rows.shape[0] < n:
        raise ValueError(f"need {n} surviving shards, have {rows.shape[0]}")
    sub = gen[rows, :]
    return gf_inv_matrix(sub)


# ---------------------------------------------------------------------------
# numpy reference codec (the oracle / host fallback)
# ---------------------------------------------------------------------------


def encode_numpy(gen: np.ndarray, data: np.ndarray) -> np.ndarray:
    """data: (n, k) uint8 -> all shards (n+m, k): parity rows = C @ data."""
    n = gen.shape[1]
    assert data.shape[0] == n
    parity = gf_matmul(gen[n:, :], data)
    return np.concatenate([data, parity], axis=0)


def reconstruct_numpy(
    gen: np.ndarray, shards: np.ndarray, bad_idx: list[int], data_only: bool = False
) -> np.ndarray:
    """Fill the rows of `shards` listed in bad_idx from the surviving rows.

    shards: (n+m, k) uint8 with garbage in bad rows. Returns a new array.
    """
    total, n = gen.shape
    bad = set(int(i) for i in bad_idx)
    present = [i for i in range(total) if i not in bad]
    dec = decode_matrix(gen, present)
    survivors = shards[np.asarray(present[:n]), :]
    out = np.array(shards, copy=True)
    bad_data = sorted(i for i in bad if i < n)
    bad_parity = sorted(i for i in bad if i >= n)
    if bad_data:
        rows = gf_matmul(dec[np.asarray(bad_data), :], survivors)
        out[np.asarray(bad_data), :] = rows
    if bad_parity and not data_only:
        # parity row i = gen[i] @ data (data rows already repaired above)
        data = out[:n, :]
        rows = gf_matmul(gen[np.asarray(bad_parity), :], data)
        out[np.asarray(bad_parity), :] = rows
    return out
