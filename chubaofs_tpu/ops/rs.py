"""Reed-Solomon encode/reconstruct as MXU bit-matrix products (the TPU hot loop).

Reference counterpart: klauspost/reedsolomon's Encode/Reconstruct SIMD loops behind
CubeFS's ec.Encoder (reference blobstore/common/ec/encoder.go:41-151). Here both
operations are ONE primitive: a GF(2) matrix product

    out_bits = (M_bits @ shard_bits) mod 2

executed as an int8 matmul on the MXU with int32 accumulation and a parity mask.
Encode uses the generator's parity block for M; reconstruct uses rows of
gen[missing] @ inv(gen[survivors]) computed on the host in numpy (tiny, O(n^3) on
n<=36 matrices) and shipped to the device as a runtime argument — so ONE compiled
kernel per shape serves every encode, decode, and repair pattern, with no
recompilation when the set of missing shards changes.

Batching: all kernels take (..., n, k) with arbitrary leading batch dims; the
scheduler's bulk-repair path stacks thousands of stripes into one call
(reference analog: blobstore/scheduler migrate batches, SURVEY §3.5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from chubaofs_tpu import chaos
from chubaofs_tpu.ops import bitmatrix, gf256

BITS = 8


def unpack_bits(x: jax.Array) -> jax.Array:
    """(..., n, k) uint8 -> (..., 8n, k) int8 of {0,1}, LSB-first rows."""
    bitpos = jnp.arange(BITS, dtype=jnp.uint8)
    b = (x[..., :, None, :] >> bitpos[:, None]) & jnp.uint8(1)
    return b.reshape(*x.shape[:-2], x.shape[-2] * BITS, x.shape[-1]).astype(jnp.int8)


def pack_bits(bits: jax.Array) -> jax.Array:
    """(..., 8m, k) {0,1} -> (..., m, k) uint8."""
    m = bits.shape[-2] // BITS
    b = bits.reshape(*bits.shape[:-2], m, BITS, bits.shape[-1]).astype(jnp.int32)
    weights = jnp.left_shift(jnp.int32(1), jnp.arange(BITS, dtype=jnp.int32))
    return jnp.sum(b * weights[:, None], axis=-2).astype(jnp.uint8)


@jax.jit
def gf_matmul_bytes(mat_bits: jax.Array, shards: jax.Array) -> jax.Array:
    """GF(2^8) matrix product via the bit-matrix lowering (portable XLA path).

    mat_bits: (8r, 8n) int8 GF(2) matrix (from bitmatrix.expand_matrix).
    shards:   (..., n, k) uint8.
    returns:  (..., r, k) uint8 = GFmat @ shards, per batch element.
    """
    bits = unpack_bits(shards)
    acc = jnp.einsum(
        "pi,...ik->...pk",
        mat_bits.astype(jnp.int8),
        bits,
        preferred_element_type=jnp.int32,
    )
    return pack_bits(acc & 1)


def _use_fused() -> bool:
    """The fused Pallas kernel runs on real TPU backends only; the XLA einsum
    path serves CPU (tests, host fallback) and sharded tracing."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def gf_matmul_dispatch(mat_bits: jax.Array, shards: jax.Array) -> jax.Array:
    """Pick the fastest available lowering for a standalone (non-traced) call."""
    if _use_fused():
        import os

        pipe = os.environ.get("CFS_GF_PIPELINED", "")
        if pipe in ("1", "static"):
            # manual-DMA double-buffered variant (PERF.md headroom #1);
            # opt-in until the bench proves it beats streaming fusion.
            # "static" selects the static-slot plan-B lowering for chips
            # where Mosaic rejects dynamic scratch indexing (kernel_ab's
            # verdict names the variant to use).
            from chubaofs_tpu.ops import pallas_gf_pipe

            return pallas_gf_pipe.gf_matmul_bytes_pipelined(
                mat_bits, shards, static_slots=pipe == "static")
        from chubaofs_tpu.ops import pallas_gf

        return pallas_gf.gf_matmul_bytes_fused(mat_bits, shards)
    return gf_matmul_bytes(mat_bits, shards)


def group_stack(mat_bits: np.ndarray, batch: int) -> tuple[np.ndarray, int]:
    """(block-diagonal stacked byte-major matrix, g) for a batch of stripes.

    MXU row-filling (PERF.md): one EC(12,4) generator is 32x96 bits on the
    128x128 systolic array; kron(I_g, mat) over g stripes viewed as one wide
    (g*n, k) stripe raises encode from 54 to ~130 GB/s on v5e-1. g divides
    batch and respects the 128-row / 512-col caps (pallas_gf.pick_group);
    g == 1 (and the matrix unchanged) off-TPU or for indivisible batches.
    """
    mat_bits = np.asarray(mat_bits, np.int8)
    if not _use_fused() or mat_bits.shape[0] == 0:
        return mat_bits, 1
    from chubaofs_tpu.ops import pallas_gf

    g = pallas_gf.pick_group(batch, *mat_bits.shape)
    if g == 1:
        return mat_bits, 1
    return np.kron(np.eye(g, dtype=np.int8), mat_bits), g


def gf_matmul_hostbatch(mat_bits: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Host-boundary batched GF matmul with MXU group-stacking.

    shards: host (..., n, k) uint8 -> host (..., r, k). The group view
    (b, n, k) -> (b/g, g*n, k) is a free numpy reshape HERE; on device the
    same reshape physically rearranges the sublane-tiled HBM buffer (measured
    131 -> 53 GB/s), which is why stacking lives at the host boundary — where
    this storage system's stripes originate anyway (network buffers, chunk
    files). This is the batch entry the codec service and repair planes use.
    """
    shards = np.asarray(shards, np.uint8)
    mat_bits = np.asarray(mat_bits, np.int8)
    lead, n, k = shards.shape[:-2], shards.shape[-2], shards.shape[-1]
    r = mat_bits.shape[0] // BITS
    b = 1
    for d in lead:
        b *= d
    if b == 0 or r == 0 or k == 0:
        return np.zeros((*lead, r, k), np.uint8)
    mat_s, g = group_stack(mat_bits, b)
    out = gf_matmul_dispatch(mat_s, shards.reshape(b // g, g * n, k))
    return np.asarray(out).reshape(*lead, r, k)


@jax.jit
def xor_reduce(shards: jax.Array) -> jax.Array:
    """XOR over the shard axis: (..., n, k) -> (..., k). Used by CRC/verify paths."""
    return jax.lax.reduce(
        shards, np.uint8(0), jax.lax.bitwise_xor, dimensions=(shards.ndim - 2,)
    )


class RSKernel:
    """Compiled GF(2^8) codec for one (n, m) systematic layout.

    Host-side numpy builds the generator and per-repair decode matrices; the device
    only ever sees one shape-polymorphic bit-matmul. All methods accept numpy or
    jax arrays with shape (n_in, k) or (B, n_in, k).
    """

    def __init__(self, n: int, m: int):
        if n <= 0 or m < 0 or n + m > 256:
            raise ValueError(f"invalid RS layout n={n} m={m}")
        self.n = n
        self.m = m
        self.total = n + m
        self.gen = gf256.systematic_generator(n, m)  # (n+m, n) uint8
        # numpy, NOT jnp: committing to the default device here would break
        # sharded/CPU call sites (the multi-chip dryrun must never touch the
        # default backend); inside jit a numpy constant is embedded and placed
        # by XLA wherever the computation runs.
        self.parity_bits = bitmatrix.expand_matrix(self.gen[n:, :]).astype(np.int8)

    # -- encode ------------------------------------------------------------
    #
    # portable=True forces the XLA einsum lowering, which GSPMD partitions
    # cleanly over sharded operands; the fused Pallas kernel has no automatic
    # partitioning rule, so sharded call sites (parallel/mesh.py) must opt out
    # of the dispatch.

    def encode_parity(self, data: jax.Array, *, portable: bool = False) -> jax.Array:
        """(..., n, k) data -> (..., m, k) parity."""
        # hot-path failpoint: the guard test in tests/test_chaos.py pins this
        # to zero measurable overhead while unarmed
        chaos.failpoint("rs.encode")
        fn = gf_matmul_bytes if portable else gf_matmul_dispatch
        return fn(self.parity_bits, jnp.asarray(data))

    def encode(self, data: jax.Array, *, portable: bool = False) -> jax.Array:
        """(..., n, k) data -> (..., n+m, k) full stripe."""
        data = jnp.asarray(data)
        return jnp.concatenate(
            [data, self.encode_parity(data, portable=portable)], axis=-2
        )

    # -- reconstruct -------------------------------------------------------

    def repair_matrix(self, bad_idx: list[int], data_only: bool = False) -> tuple[np.ndarray, list[int], list[int]]:
        """Host-side: (matrix mapping survivors->missing, survivor rows, missing rows).

        survivor rows are the first n present indices; matrix is GF(2^8) of shape
        (len(missing), n), already verified invertible via decode_matrix.
        """
        bad = sorted(set(int(i) for i in bad_idx))
        for i in bad:
            if not 0 <= i < self.total:
                raise ValueError(f"bad shard index {i}")
        if len(bad) > self.m:
            raise ValueError(f"{len(bad)} missing shards > m={self.m}, unrecoverable")
        present = [i for i in range(self.total) if i not in set(bad)][: self.n]
        dec = gf256.decode_matrix(self.gen, present)  # (n, n)
        missing = [i for i in bad if i < self.n] if data_only else bad
        mat = gf256.gf_matmul(self.gen[np.asarray(missing), :], dec) if missing else np.zeros((0, self.n), np.uint8)
        return mat, present, missing

    def window_matrix(self, present: list[int], want: list[int]) -> np.ndarray:
        """Row-sliced decode matrix for ranged reads: the GF(2^8) map from
        exactly n survivor rows (in `present` order) to exactly the `want`
        shard rows — gen[want] @ inv(gen[present]).

        Unlike repair_matrix this takes the caller's survivor CHOICE as-is
        (the access layer's windowed gather already picked which shards to
        fetch) and computes only the rows the byte window needs, so degraded
        decode cost scales with the window, not the stripe. RS is column-
        independent, so the same matrix applied to column-sliced survivors
        yields the identical column slice of the wanted shards.
        """
        present = [int(i) for i in present]
        want = [int(i) for i in want]
        if len(present) != self.n:
            raise ValueError(
                f"window decode needs exactly n={self.n} survivors, "
                f"got {len(present)}")
        for i in present + want:
            if not 0 <= i < self.total:
                raise ValueError(f"bad shard index {i}")
        if not want:
            return np.zeros((0, self.n), np.uint8)
        dec = gf256.decode_matrix(self.gen, present)  # (n, n)
        return gf256.gf_matmul(self.gen[np.asarray(want), :], dec)

    def repair_plan(self, bad_idx: list[int], data_only: bool = False):
        """Device-ready repair plan: (repair_bits, present, missing) numpy arrays.

        Shared by reconstruct, the sharded codec step, and the benches so the
        bit-matrix repair lowering lives in exactly one place. Kept as numpy so
        closing over a plan inside jit never commits to the default device.
        """
        mat, present, missing = self.repair_matrix(bad_idx, data_only)
        return self._device_plan(mat, present, missing)

    @staticmethod
    def _device_plan(mat, present, missing):
        mat_bits = bitmatrix.expand_matrix(mat).astype(np.int8)
        return mat_bits, np.asarray(present, np.int32), np.asarray(missing, np.int32)

    def repair_plan_padded(self, bad_idx: list[int], data_only: bool = False):
        """Fixed-shape repair plan: always m repair rows, so ONE compiled step
        serves every missing pattern as runtime data — changing the set of
        missing shards never recompiles (the static-shape discipline the
        sharded codec step needs). Padded slots carry the GF identity row of
        survivor 0 and target survivor 0's own position: a value-level no-op
        write. Returns (repair_bits (8m, 8n) int8, present (n,), missing (m,)).
        """
        mat, present, missing = self.repair_matrix(bad_idx, data_only)
        pad = self.m - len(missing)
        if pad:
            id_rows = np.zeros((pad, self.n), np.uint8)
            id_rows[:, 0] = 1  # GF row e_0: recomputes survivor 0 exactly
            mat = np.concatenate([mat, id_rows], axis=0) if len(missing) else id_rows
            missing = list(missing) + [present[0]] * pad
        return self._device_plan(mat, present, missing)

    def apply_repair(self, plan, shards: jax.Array, *, portable: bool = False) -> jax.Array:
        """Apply a repair_plan to (..., n+m, k) shards (jit-friendly)."""
        mat_bits, present, missing = plan
        if missing.shape[0] == 0:
            return shards
        survivors = jnp.take(shards, present, axis=-2)
        fn = gf_matmul_bytes if portable else gf_matmul_dispatch
        rows = fn(mat_bits, survivors)
        return shards.at[..., missing, :].set(rows)

    def reconstruct(self, shards, bad_idx: list[int], data_only: bool = False):
        """shards (..., n+m, k) with garbage at bad_idx -> repaired (..., n+m, k)."""
        shards = jnp.asarray(shards)
        _, _, missing = self.repair_matrix(bad_idx, data_only)
        if not missing:
            return shards
        return self.apply_repair(self.repair_plan(bad_idx, data_only), shards)

    # -- verify ------------------------------------------------------------

    def verify(self, shards, *, portable: bool = False) -> jax.Array:
        """(..., n+m, k) -> scalar/batch bool: parity rows match re-encoded parity."""
        shards = jnp.asarray(shards)
        expect = self.encode_parity(shards[..., : self.n, :], portable=portable)
        got = shards[..., self.n :, :]
        return jnp.all(expect == got, axis=(-2, -1))


@functools.lru_cache(maxsize=64)
def get_kernel(n: int, m: int) -> RSKernel:
    """Process-wide kernel cache (generator construction is setup-time work)."""
    return RSKernel(n, m)
