"""TPU compute primitives: GF(2^8) arithmetic, bit-matrix RS kernels, CRC."""

from chubaofs_tpu.ops import gf256
from chubaofs_tpu.ops import bitmatrix

__all__ = ["gf256", "bitmatrix"]
