"""Client SDKs: metadata wrapper, filesystem facade (reference sdk/ equivalent)."""

from chubaofs_tpu.sdk.meta_wrapper import MetaWrapper
from chubaofs_tpu.sdk.fs import FsClient, FsError

__all__ = ["MetaWrapper", "FsClient", "FsError"]
