"""MetaWrapper — volume-view routing + leader-retry metadata client.

Reference counterpart: sdk/meta (meta.py:113-121 MetaWrapper with an
inode-range btree, api.go Create_ll/Lookup_ll/InodeGet_ll, operation.go's
retry/leader-switch). Routing: an inode belongs to the partition whose
[start, end) contains it; new inodes are created on the partition owning the
parent when it can still allocate (the combined-commit fast path), else on
the TAIL partition (the open range). Every op retries across the partition's
peers until it finds the leader.

Refresh-safe under splits (ISSUE 15): the view is CACHED (VIEW_TTL, with a
bisect index over partition starts so routing stays O(log n) at hundreds of
partitions) and a `EWRONGPART` reply — the metanode's "your view is stale /
this sub-range is frozen mid-split" signal — triggers a view refresh +
re-route instead of failing the op; mid-split the re-route loop rides the
same retry window the leader-switch path uses.
"""

from __future__ import annotations

import time

from chubaofs_tpu.master.master import MasterError, MetaPartitionView, VolumeView
from chubaofs_tpu.meta.metanode import MetaNode, OpError
from chubaofs_tpu.raft.server import NotLeaderError


class MetaWrapper:
    # cached-view lifetime: error-driven refresh (EWRONGPART) catches splits
    # the instant they matter, so the TTL only bounds how long COLD routing
    # data (new partitions a client never erred on) stays unseen
    VIEW_TTL = 5.0

    def __init__(self, master, metanodes: dict[int, MetaNode], volume: str):
        import itertools
        import uuid

        self.master = master
        self.metanodes = metanodes
        self.volume = volume
        # uniq-op identity (metanode/uniq_checker.go): every mutation carries
        # (client_id, seq) so a retried delivery replays the recorded answer
        # instead of double-applying — which is what makes EIO retries safe
        self.client_id = uuid.uuid4().hex[:16]
        self._uniq = itertools.count(1)
        self._cached_view: VolumeView | None = None
        self._view_expire = 0.0
        # bisect index rebuilt with the cache: (starts[], mps[]) published as
        # ONE tuple — a MetaWrapper is shared across threads (objectnode
        # serves one cached FsClient from many evloop workers), and a reader
        # must never pair a new starts list with an old mps list
        self._route: tuple[list[int], list[MetaPartitionView]] = ([], [])
        # partitions that answered ERANGE (inode range exhausted): skip
        # their combined-create fast path until a refresh shows otherwise
        self._full_pids: set[int] = set()

    # -- routing ---------------------------------------------------------------

    def _view(self) -> VolumeView:
        now = time.monotonic()
        if self._cached_view is None or now >= self._view_expire:
            return self.refresh_view()
        return self._cached_view

    def refresh_view(self) -> VolumeView:
        """Re-fetch the volume view and rebuild the routing index. In-process
        the view object is the master's LIVE state (mutated in place by raft
        apply), so the rebuild re-snapshots the partition list; remotely it
        is a fresh HTTP fetch."""
        view = self.master.get_volume(self.volume)
        mps = sorted(view.meta_partitions, key=lambda m: m.start)
        self._route = ([m.start for m in mps], mps)  # atomic publish
        self._cached_view = view
        self._view_expire = time.monotonic() + self.VIEW_TTL
        self._full_pids.clear()
        return view

    def partition_of(self, ino: int) -> MetaPartitionView:
        """The partition owning `ino`: one bisect over the cached start
        index (O(log n) at hundreds of partitions), with a containment
        re-check — a stale index (split since the last rebuild) misses, and
        ONE refresh re-routes before giving up."""
        import bisect

        self._view()  # ensure the cache is built / TTL-fresh
        for _ in range(2):
            starts, mps = self._route  # one read: starts stays aligned
            i = bisect.bisect_right(starts, ino) - 1
            if i >= 0:
                mp = mps[i]
                # containment re-check: in-process the cached mp objects are
                # LIVE (a split shrank mp.end in place), so a stale index
                # still answers correctly or falls through to the refresh
                if mp.start <= ino < mp.end:
                    return mp
            self.refresh_view()
        raise MasterError(f"no partition owns inode {ino}")

    def tail_partition(self) -> MetaPartitionView:
        self._view()
        return self._route[1][-1]

    # -- leader-retry op execution ---------------------------------------------

    # a fresh partition's raft group needs an election round before it serves;
    # the reference client retries for much longer (sdk/meta/operation.go's
    # SendToMetaPartitionWithTimeOut loop)
    RETRY_WINDOW = 10.0
    RETRY_SLEEP = 0.1

    def _on_partition(self, mp: MetaPartitionView, fn, idempotent: bool = True):
        """Run fn(metanode) on the partition's leader, retrying peers and
        waiting out elections (sdk/meta retry/leader-switch).

        ECONN (connect failed: nothing sent) and ENOPARTITION (replica not
        hosting the shard) always re-aim at another peer. EIO (connection
        died AFTER the request went out) retries only when `idempotent` —
        a mutation may have applied before the reply was lost, and blindly
        re-submitting turns success into EEXIST/ENOENT."""
        RETRYABLE = ("ECONN", "ENOPARTITION") + (("EIO",) if idempotent else ())

        deadline = time.monotonic() + self.RETRY_WINDOW
        last: Exception | None = None
        while True:
            order = [mp.leader] if mp.leader in mp.peers else []
            order += [p for p in mp.peers if p not in order]
            for peer in order:
                node = self.metanodes.get(peer)
                if node is None:
                    continue
                try:
                    return fn(node)
                except NotLeaderError as e:
                    last = e
                    hinted = self.metanodes.get(e.leader) if e.leader in mp.peers else None
                    if hinted is not None and e.leader != peer:
                        try:
                            return fn(hinted)
                        except NotLeaderError as e2:
                            last = e2
                        except OpError as e2:
                            if e2.code not in RETRYABLE:
                                raise
                            last = e2
                except OpError as e:
                    if e.code not in RETRYABLE:
                        raise
                    last = e
            if time.monotonic() >= deadline:
                break
            time.sleep(self.RETRY_SLEEP)
        raise last or MasterError(f"partition {mp.partition_id}: no leader reachable")

    def submit(self, mp: MetaPartitionView, op: str, **args):
        from chubaofs_tpu import chaos
        from chubaofs_tpu.blobstore import trace

        chaos.failpoint("meta.submit")
        # the uniq id makes the mutation idempotent end-to-end, so even an
        # after-send connection loss (EIO) may retry safely
        args["_uniq"] = (self.client_id, next(self._uniq))
        # wall time stamps ride the proposal so every replica applies the
        # identical ctime/mtime (no clock reads inside the state machine)
        args.setdefault("_now", time.time())
        # one child span per mutation: downstream hops (metanode service,
        # raft drain) hang their track entries off the same trace id
        with trace.child_of(trace.current_span(), f"meta.{op}") as span:
            err: Exception | None = None
            try:
                return self._on_partition(
                    mp,
                    lambda node: node.submit_sync(mp.partition_id, op, **args),
                    idempotent=True,
                )
            except Exception as e:
                err = e
                raise
            finally:
                span.append_track_log("meta", err=err)

    # -- split-safe routed execution -------------------------------------------
    #
    # EWRONGPART is the metanode's "this partition no longer (or not yet)
    # serves that inode" reply: the view is stale (a split swapped ownership)
    # or the sub-range is frozen mid-split. Nothing was mutated (the route
    # guard is a pre-check), so the op refreshes the view, re-routes, and
    # retries — once immediately for the common post-swap case, then inside
    # the same bounded window the leader-retry path uses for the brief
    # freeze-to-swap gap.

    def _retry_stale_view(self, attempt, codes: tuple = ("EWRONGPART",)):
        """Run attempt() to completion through stale-route errors: on a code
        in `codes`, refresh the view and retry — once immediately (the
        common post-swap case), then polling each RETRY_SLEEP inside the
        bounded RETRY_WINDOW for the brief freeze-to-swap gap. `attempt`
        re-resolves its own routing per call, so every retry runs against
        the refreshed view. The ONE retry policy for every routed op."""
        deadline = time.monotonic() + self.RETRY_WINDOW
        first = True
        while True:
            try:
                return attempt()
            except OpError as e:
                if e.code not in codes:
                    raise
                if not first:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(self.RETRY_SLEEP)
                first = False
                self.refresh_view()

    def _routed_submit(self, route_ino: int, op: str, **args):
        return self._retry_stale_view(
            lambda: self.submit(self.partition_of(route_ino), op, **args))

    def _routed_read(self, route_ino: int, fn):
        """fn(metanode, mp) on the owning partition's leader, re-routing on
        EWRONGPART like _routed_submit."""
        def attempt():
            mp = self.partition_of(route_ino)
            return self._on_partition(mp, lambda n, _mp=mp: fn(n, _mp))

        return self._retry_stale_view(attempt)

    # -- the ll API (api.go analogs) -------------------------------------------

    def create_inode(self, mode: int, uid: int = 0, gid: int = 0,
                     quota_ids: list[int] | None = None):
        """Allocate on the tail partition (the open range), refreshing
        through splits: ERANGE = the cached tail filled and split (cursor
        growth), EWRONGPART = the tail is mid-load-split and its free range
        is moving to the sibling — both re-route to the CURRENT tail."""
        return self._retry_stale_view(
            lambda: self.submit(self.tail_partition(), "create_inode",
                                mode=mode, uid=uid, gid=gid,
                                quota_ids=quota_ids or []),
            codes=("ERANGE", "EWRONGPART"))

    def create_file(self, parent: int, name: str, mode: int,
                    quota_ids: list[int] | None = None):
        """Inode + dentry in ONE commit when the partition owning the parent
        can still allocate inodes — always true on the tail (the open
        range), and true on cursor-split/load-split siblings until their
        bounded range fills. Falls back to the two-op flow (undo-on-conflict
        handled by the CALLER, as FsClient does) by returning None: on
        ERANGE the partition is remembered as full (skipped until the next
        view refresh), on EWRONGPART the view refreshes and the fast path
        RE-CHECKS against the new routing — a split between the route and
        the submit must not silently demote every create to two ops."""
        def attempt():
            mp = self.partition_of(parent)
            if mp.partition_id in self._full_pids:
                return None  # known-exhausted: caller takes the two-op path
            try:
                return self.submit(mp, "create_inode_dentry", parent=parent,
                                   name=name, mode=mode,
                                   quota_ids=quota_ids or [])
            except OpError as e:
                if e.code == "ERANGE":
                    self._full_pids.add(mp.partition_id)
                    return None
                raise

        return self._retry_stale_view(attempt)

    def create_dentry(self, parent: int, name: str, ino: int, mode: int,
                      quota_ids: list[int] | None = None):
        return self._routed_submit(parent, "create_dentry", parent=parent,
                                   name=name, ino=ino, mode=mode,
                                   quota_ids=quota_ids or [])

    def lookup(self, parent: int, name: str):
        return self._routed_read(
            parent, lambda n, mp: n.lookup(mp.partition_id, parent, name))

    def get_inode(self, ino: int):
        return self._routed_read(
            ino, lambda n, mp: n.get_inode(mp.partition_id, ino))

    def read_dir(self, parent: int):
        return self._routed_read(
            parent, lambda n, mp: n.read_dir(mp.partition_id, parent))

    def remove_entry(self, parent: int, name: str, want_dir: bool,
                     quota_ids: list[int] | None = None):
        """Combined lookup + delete_dentry + unlink_inode in one commit
        when the parent's partition also owns the child inode; returns
        (ino, nlink_after) or None when the child lives in another
        partition (caller falls back to the per-op flow)."""
        try:
            res = self._routed_submit(parent, "delete_dentry_unlink",
                                      parent=parent, name=name,
                                      want_dir=want_dir,
                                      quota_ids=quota_ids or [])
        except OpError as e:
            if e.code == "EXDEVPART":
                return None
            raise
        return res[0], res[1]

    def delete_dentry(self, parent: int, name: str,
                      quota_ids: list[int] | None = None):
        return self._routed_submit(parent, "delete_dentry", parent=parent,
                                   name=name, quota_ids=quota_ids or [])

    def unlink_inode(self, ino: int):
        return self._routed_submit(ino, "unlink_inode", ino=ino)

    def evict_inode(self, ino: int):
        return self._routed_submit(ino, "evict_inode", ino=ino)

    def update_inode(self, ino: int, **kw):
        return self._routed_submit(ino, "update_inode", ino=ino, **kw)

    def truncate(self, ino: int, size: int):
        return self._routed_submit(ino, "truncate", ino=ino, size=size)

    def append_extents(self, ino: int, extents: list[dict], size: int):
        return self._routed_submit(ino, "append_extents", ino=ino,
                                   extents=extents, size=size)

    def append_obj_extents(self, ino: int, locations: list[dict], size: int):
        return self._routed_submit(ino, "append_obj_extents", ino=ino,
                                   locations=locations, size=size)

    TX_TTL = 30.0  # prepared-txn lifetime before peers self-resolve

    def rename(self, src_parent: int, src_name: str, dst_parent: int,
               dst_name: str, src_quota_ids: list[int] | None = None,
               dst_quota_ids: list[int] | None = None):
        """POSIX replace semantics: an existing destination is displaced.
        Returns (displaced_ino, displaced_nlink, displaced_is_dir) when a
        destination was displaced (the caller owns its orphan/evict
        contract), else None. A stale-view EWRONGPART (split mid-rename)
        restarts the whole flow on the refreshed view: the local-vs-2PC
        decision itself depends on the routing, so per-op re-route is not
        enough."""
        return self._retry_stale_view(
            lambda: self._rename_once(src_parent, src_name, dst_parent,
                                      dst_name, src_quota_ids,
                                      dst_quota_ids))

    def _rename_once(self, src_parent: int, src_name: str, dst_parent: int,
                     dst_name: str, src_quota_ids: list[int] | None = None,
                     dst_quota_ids: list[int] | None = None):
        import stat as stat_mod

        src_mp = self.partition_of(src_parent)
        dst_mp = self.partition_of(dst_parent)
        if src_mp.partition_id == dst_mp.partition_id:
            # rename-over of a directory whose INODE lives in another
            # partition: the commit's own children check cannot see its
            # dentries, so emptiness is pre-checked at the owning partition
            # (best-effort; the owned case stays atomic in _op_rename_local)
            try:
                dd = self._on_partition(dst_mp, lambda n: n.lookup(
                    dst_mp.partition_id, dst_parent, dst_name))
            except OpError as e:
                if e.code != "ENOENT":
                    raise
                dd = None
            if dd is not None and stat_mod.S_ISDIR(dd.mode) and \
                    self.partition_of(dd.ino).partition_id != \
                    src_mp.partition_id and self._dir_has_children(dd.ino):
                raise OpError("ENOTEMPTY", f"{dst_name!r} in {dst_parent}")
            res = self.submit(
                src_mp, "rename_local", src_parent=src_parent, src_name=src_name,
                dst_parent=dst_parent, dst_name=dst_name,
                src_quota_ids=src_quota_ids or [], dst_quota_ids=dst_quota_ids or [],
            )
            _, ino, nlink, is_dir = res
            if not ino:
                return None
            if nlink == -1:
                # displaced inode lives in another partition: the combined
                # commit dropped its dentry; drop its link here
                nlink = self.unlink_inode(ino).nlink
            return (ino, nlink, is_dir)
        # cross-partition: two-phase transaction (metanode/transaction.go).
        # Prepare takes intent locks + validates on both shards. The DST
        # partition is the transaction manager: its commit is THE decision —
        # committed there means every expired participant rolls forward, not
        # back (metanode sweep asks the TM via tx_status).
        import uuid

        d = self._on_partition(src_mp, lambda n: n.lookup(src_mp.partition_id, src_parent, src_name))
        # replace semantics across partitions: the 2PC create would conflict
        # on Exists at prepare, so an existing destination is removed FIRST
        # in its own commit. Not atomic with the move (a reader can see dst
        # briefly missing), but never two destinations — the single-shard
        # common case stays fully atomic via rename_local above. If the move
        # then fails, the removed destination is restored best-effort below.
        displaced = None
        dd = None
        try:
            dd = self._on_partition(dst_mp, lambda n: n.lookup(
                dst_mp.partition_id, dst_parent, dst_name))
        except OpError as e:
            if e.code != "ENOENT":
                raise
        if dd is not None:
            if dd.ino == d.ino:
                return None  # hard links to one inode: POSIX no-op
            src_is_dir = stat_mod.S_ISDIR(d.mode)
            dst_is_dir = stat_mod.S_ISDIR(dd.mode)
            if src_is_dir and not dst_is_dir:
                raise OpError("ENOTDIR", f"{dst_name!r} in {dst_parent}")
            if not src_is_dir and dst_is_dir:
                raise OpError("EISDIR", f"{dst_name!r} in {dst_parent}")
            if dst_is_dir and self.partition_of(dd.ino).partition_id != \
                    dst_mp.partition_id and self._dir_has_children(dd.ino):
                raise OpError("ENOTEMPTY", f"{dst_name!r} in {dst_parent}")
            res = self.remove_entry(dst_parent, dst_name, want_dir=dst_is_dir,
                                    quota_ids=dst_quota_ids)
            if res is None:  # child inode on a third partition: per-op flow
                self.delete_dentry(dst_parent, dst_name,
                                   quota_ids=dst_quota_ids)
                res = (dd.ino, self.unlink_inode(dd.ino).nlink)
            displaced = (res[0], res[1], dst_is_dir)
        tx_id = f"tx-{self.client_id}-{uuid.uuid4().hex[:12]}"
        # the tx deadline rides the proposal and is compared by every
        # replica's sweep (now=time.time()) — cross-process wall time
        deadline = time.time() + self.TX_TTL  # wallclock: protocol stamp
        tm_pid = dst_mp.partition_id
        plans = [
            (dst_mp, [("create_dentry",
                       {"parent": dst_parent, "name": dst_name,
                        "ino": d.ino, "mode": d.mode,
                        "quota_ids": dst_quota_ids or []})]),
            (src_mp, [("delete_dentry",
                       {"parent": src_parent, "name": src_name,
                        "quota_ids": src_quota_ids or []})]),
        ]
        prepared = []
        try:
            for mp, ops in plans:
                self.submit(mp, "tx_prepare", tx_id=tx_id, ops=ops,
                            deadline=deadline, tm_pid=tm_pid)
                prepared.append(mp)
            # TM commit — the point of no return. After it lands, participant
            # commits are best-effort: the sweep rolls any straggler forward.
            self.submit(dst_mp, "tx_commit", tx_id=tx_id)
        except OpError:
            for mp in prepared:
                try:
                    self.submit(mp, "tx_rollback", tx_id=tx_id)
                except OpError:
                    pass  # expiry sweep covers it
            # a failed rename must leave the destination intact: restore the
            # dentry we removed above (link() also restores the file's
            # nlink; a dir gets its dentry back — best effort by design)
            if displaced is not None and dd is not None:
                try:
                    if stat_mod.S_ISDIR(dd.mode):
                        self.create_dentry(dst_parent, dst_name, dd.ino,
                                           dd.mode, quota_ids=dst_quota_ids)
                    else:
                        self.link(dst_parent, dst_name, dd.ino)
                except OpError:
                    pass  # dst stays missing; its inode is already orphaned
            raise
        try:
            self.submit(src_mp, "tx_commit", tx_id=tx_id)
        except OpError:
            pass  # resolved by the participant sweep against the TM
        return displaced

    def _dir_has_children(self, ino: int) -> bool:
        """Emptiness as seen by the partition that OWNS the directory's
        inode — a dir's child dentries route by the dir's ino, so a check on
        the dst dentry's partition is blind to children living elsewhere."""
        try:
            return bool(self._routed_read(
                ino, lambda n, mp: n.read_dir(mp.partition_id, ino)))
        except OpError as e:
            if e.code == "ENOENT":
                return False  # inode already gone: nothing to orphan
            # a transient failure must NOT read as "empty" — rename-over
            # would displace a non-empty dir and orphan its subtree
            raise

    # -- directory quotas (master_quota_manager + metanode quota analog) --------

    def set_quota(self, dir_ino: int, quota_id: int, max_files: int = 0,
                  max_bytes: int = 0) -> None:
        """Define a subtree quota: fan the definition to every partition (usage
        is charged wherever the op lands) and tag the directory inode."""
        for mp in self._view().meta_partitions:
            self.submit(mp, "set_quota_def", quota_id=quota_id,
                        max_files=max_files, max_bytes=max_bytes)
        ids = self.quota_ids_of(dir_ino)
        if quota_id not in ids:
            ids.append(quota_id)
        import json as _json

        self.set_xattr(dir_ino, "__quota_ids__", _json.dumps(ids).encode())

    def delete_quota(self, quota_id: int) -> None:
        for mp in self._view().meta_partitions:
            self.submit(mp, "delete_quota_def", quota_id=quota_id)

    def quota_ids_of(self, dir_ino: int) -> list[int]:
        """The quota ids a child of dir_ino inherits (client-side resolution,
        the reference's quota-id cache shape)."""
        import json as _json

        inode = self.get_inode(dir_ino)
        raw = inode.xattrs.get("__quota_ids__")
        return _json.loads(raw) if raw else []

    def quota_usage(self, quota_id: int) -> dict:
        """Aggregate usage across partitions (the master report loop's sum)."""
        total = {"files": 0, "bytes": 0}
        for mp in self._view().meta_partitions:
            for node_usage in [self._on_partition(
                    mp, lambda n, _mp=mp: n.quota_usage(_mp.partition_id))]:
                q = node_usage.get(quota_id)
                if q:
                    total["files"] += q["files"]
                    total["bytes"] += q["bytes"]
        return total

    def push_quota_flags(self) -> None:
        """Re-evaluate aggregated usage and distribute `exceeded` flags — one
        round of the reference's master quota report loop. Also re-fans quota
        DEFINITIONS to partitions that miss them (a tail split creates new
        partitions after set_quota ran; until this heals, the new partition
        silently skips those quota ids)."""
        defs: dict[int, dict] = {}
        usage: dict[int, dict] = {}
        per_mp: dict[int, set[int]] = {}
        mps = self._view().meta_partitions
        for mp in mps:
            node_usage = self._on_partition(
                mp, lambda n, _mp=mp: n.quota_usage(_mp.partition_id))
            per_mp[mp.partition_id] = set(node_usage)
            for qid, q in node_usage.items():
                defs[qid] = q
                agg = usage.setdefault(qid, {"files": 0, "bytes": 0})
                agg["files"] += q["files"]
                agg["bytes"] += q["bytes"]
        for qid, agg in usage.items():
            d = defs[qid]
            exceeded = bool(
                (d.get("max_files") and agg["files"] >= d["max_files"])
                or (d.get("max_bytes") and agg["bytes"] >= d["max_bytes"]))
            for mp in mps:
                if qid not in per_mp.get(mp.partition_id, ()):
                    self.submit(mp, "set_quota_def", quota_id=qid,
                                max_files=d.get("max_files", 0),
                                max_bytes=d.get("max_bytes", 0))
                self.submit(mp, "set_quota_flag", quota_id=qid,
                            exceeded=exceeded)

    def link(self, parent: int, name: str, ino: int):
        return self._routed_submit(parent, "link", parent=parent, name=name,
                                   ino=ino)

    def set_xattr(self, ino: int, key: str, value: bytes):
        return self._routed_submit(ino, "set_xattr", ino=ino, key=key,
                                   value=value)

    def remove_xattr(self, ino: int, key: str):
        return self._routed_submit(ino, "remove_xattr", ino=ino, key=key)

    # -- S3 multipart sessions (metanode multipart state, objectnode's backing) --
    # upload_id embeds the owning partition so later ops route without a
    # cluster-wide lookup: "<partition_id>.<random>".

    def multipart_create(self, key: str) -> str:
        import uuid

        mp = self.tail_partition()
        upload_id = f"{mp.partition_id}.{uuid.uuid4().hex[:16]}"
        return self.submit(mp, "multipart_create", key=key, upload_id=upload_id)

    def _multipart_partition(self, upload_id: str):
        try:
            pid = int(upload_id.split(".", 1)[0])
        except ValueError:
            raise OpError("ENOENT", f"malformed upload id {upload_id!r}") from None
        for fresh in (False, True):
            view = self.refresh_view() if fresh else self._view()
            for mp in view.meta_partitions:
                if mp.partition_id == pid:
                    return mp
            # cached view may predate the partition (a just-split sibling):
            # one refresh before declaring the upload gone
        raise OpError("ENOENT", f"partition {pid} for upload {upload_id}")

    def multipart_put_part(self, upload_id: str, part_num: int, location: dict):
        mp = self._multipart_partition(upload_id)
        return self.submit(mp, "multipart_put_part", upload_id=upload_id,
                           part_num=part_num, location=location)

    def multipart_complete(self, upload_id: str) -> dict:
        mp = self._multipart_partition(upload_id)
        return self.submit(mp, "multipart_complete", upload_id=upload_id)

    def multipart_abort(self, upload_id: str) -> dict:
        mp = self._multipart_partition(upload_id)
        return self.submit(mp, "multipart_abort", upload_id=upload_id)

    def multipart_get(self, upload_id: str) -> dict:
        mp = self._multipart_partition(upload_id)
        return self._on_partition(
            mp, lambda n: n.multipart_get(mp.partition_id, upload_id))

    def multipart_list(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for mp in self._view().meta_partitions:
            sessions = self._on_partition(
                mp, lambda n, _mp=mp: n.multipart_list(_mp.partition_id))
            out.update(sessions)
        return out
