"""FsClient — the POSIX-ish filesystem facade over meta + data planes.

Reference counterpart: the client-side verbs of libsdk/libsdk.go (cfs_open,
cfs_read, cfs_write, cfs_mkdirs, ...) and client/fs (file.go Read/Write via the
extent client, dir.go). Data placement follows the volume's tier:

  * cold volumes write file data through the blobstore access gateway (EC on
    TPU) and record the signed locations as obj_extents — the
    sdk/data/blobstore writer.go:472 + ObjExtentKey flow;
  * hot volumes write through the extent client to replicated datanodes
    (chubaofs_tpu/data) and record ExtentKeys.

Paths resolve component-by-component through MetaWrapper (the FUSE-side icache
is a straightforward addition; kept out of the core verbs)."""

from __future__ import annotations

import stat as stat_mod

from chubaofs_tpu.meta.metanode import OpError
from chubaofs_tpu.meta.partition import ROOT_INO
from chubaofs_tpu.sdk.meta_wrapper import MetaWrapper


class FsError(Exception):
    def __init__(self, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}")
        self.code = code


class VolQos:
    """Per-volume client throttle, rates owned by the MASTER (volume view
    qos_read_mbps/qos_write_mbps; ref master/limiter.go assignment flowing
    to clients). Shapes rather than rejects: callers block until tokens
    arrive. 0 = unlimited. With a `fetch` closure (() -> (read_mbps,
    write_mbps), normally a master get_volume call), limits RE-ARM every
    REFRESH_SECS from the data path — so tightening QoS on a misbehaving
    tenant reaches live clients without rebuilding them, at one metadata
    call per interval."""

    REFRESH_SECS = 30.0

    def __init__(self, read_mbps: int = 0, write_mbps: int = 0, fetch=None):
        import time as _time

        from chubaofs_tpu.utils.ratelimit import TokenBucket

        self.read = TokenBucket(read_mbps * (1 << 20))
        self.write = TokenBucket(write_mbps * (1 << 20))
        self._fetch = fetch
        self._next_fetch = _time.monotonic() + self.REFRESH_SECS

    @classmethod
    def from_view(cls, vol, fetch=None) -> "VolQos | None":
        """The one construction policy for both local and remote clients:
        with a fetch closure, always build (an unlimited volume may gain
        limits later); without one, only when a limit is set now."""
        if fetch is None and not (vol.qos_read_mbps or vol.qos_write_mbps):
            return None
        return cls(vol.qos_read_mbps, vol.qos_write_mbps, fetch=fetch)

    def refresh(self, read_mbps: int, write_mbps: int) -> None:
        self.read.rate = float(read_mbps * (1 << 20))
        self.read.burst = max(self.read.rate, 1.0)
        self.write.rate = float(write_mbps * (1 << 20))
        self.write.burst = max(self.write.rate, 1.0)

    def _maybe_refetch(self) -> None:
        import time as _time

        if self._fetch is None or _time.monotonic() < self._next_fetch:
            return
        self._next_fetch = _time.monotonic() + self.REFRESH_SECS
        try:
            r, w = self._fetch()
            self.refresh(r, w)
        except Exception:
            pass  # keep the last-known limits through master hiccups

    def _charge(self, bucket, nbytes: int) -> None:
        self._maybe_refetch()
        if bucket.rate <= 0:
            return  # unlimited: never loop per-byte against a 1-token burst
        # charge in burst-sized chunks: one huge IO must pay for ALL its
        # bytes (a single clamped acquire would let any write <= burst
        # through untouched), while still never requesting more than the
        # bucket can physically accrue
        while nbytes > 0:
            take = min(nbytes, bucket.burst)
            bucket.acquire(take)
            nbytes -= int(take)

    def throttle_read(self, nbytes: int) -> None:
        self._charge(self.read, nbytes)

    def throttle_write(self, nbytes: int) -> None:
        self._charge(self.write, nbytes)


class FsClient:
    def __init__(self, meta: MetaWrapper, data_backend, hot_backend=None,
                 cold: bool = True, bcache=None, qos: "VolQos | None" = None):
        """Cold volumes: data_backend implements write(data)->location_json,
        read(location_json, offset, size)->bytes, delete(location_json).
        Hot volumes: hot_backend is a chubaofs_tpu.sdk.stream.HotBackend
        (write(ino, offset, data), read(ino, offset, size), delete(ino, keys)).
        bcache: optional BcacheClient — cold reads go read-through local cache
        (sdk/data/blobstore/reader.go:30,66 bcache hooks). Cache keys hash the
        extent LOCATION (immutable identity), not (ino, offset): a truncate +
        rewrite reuses offsets but never locations, so stale hits are
        impossible by construction."""
        self.meta = meta
        self.data = data_backend
        self.hot = hot_backend
        self.cold = cold or hot_backend is None
        self.bcache = bcache
        self.qos = qos  # master-assigned per-volume throttle (VolQos)

    # -- path resolution --------------------------------------------------------

    def resolve(self, path: str) -> int:
        ino = ROOT_INO
        for part in [p for p in path.split("/") if p]:
            try:
                d = self.meta.lookup(ino, part)
            except OpError as e:
                raise FsError(e.code, path) from None
            ino = d.ino
        return ino

    def _resolve_parent(self, path: str) -> tuple[int, str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise FsError("EINVAL", "root")
        parent = ROOT_INO
        for part in parts[:-1]:
            parent = self.meta.lookup(parent, part).ino
        return parent, parts[-1]

    # -- directory verbs --------------------------------------------------------

    def _parent_quota_ids(self, parent: int) -> list[int]:
        try:
            return self.meta.quota_ids_of(parent)
        except OpError:
            return []

    def _undo_create(self, ino: int) -> None:
        """A failed dentry insert must not leak the fresh inode."""
        try:
            self.meta.unlink_inode(ino)
            self.meta.evict_inode(ino)
        except OpError:
            pass  # freelist sweeps catch stragglers

    def _create_node(self, parent: int, name: str, mode: int,
                     qids: list[int], path: str):
        """Create inode+dentry under parent, returning the Inode: ONE
        combined commit when the tail partition owns the parent
        (MetaWrapper.create_file), else the two-op flow with the
        undo-on-conflict contract. The ONE create implementation — the
        FUSE server delegates here too."""
        try:
            inode = self.meta.create_file(parent, name, mode, quota_ids=qids)
        except OpError as e:
            raise FsError(e.code, path) from None
        if inode is not None:
            return inode
        inode = self.meta.create_inode(mode, quota_ids=qids)
        try:
            self.meta.create_dentry(parent, name, inode.ino, inode.mode,
                                    quota_ids=qids)
        except OpError as e:
            self._undo_create(inode.ino)
            raise FsError(e.code, path) from None
        return inode

    def mkdir(self, path: str, mode: int = 0o755) -> int:
        parent, name = self._resolve_parent(path)
        qids = self._parent_quota_ids(parent)
        return self._create_node(parent, name, stat_mod.S_IFDIR | mode,
                                 qids, path).ino

    def mkdirs(self, path: str, mode: int = 0o755) -> int:
        """mkdir -p (libsdk cfs_mkdirs analog); returns the leaf inode."""
        ino = ROOT_INO
        for part in [p for p in path.split("/") if p]:
            try:
                d = self.meta.lookup(ino, part)
                if not stat_mod.S_ISDIR(d.mode):
                    raise FsError("ENOTDIR", path)
                ino = d.ino
            except OpError:
                qids = self._parent_quota_ids(ino)
                child = self.meta.create_inode(stat_mod.S_IFDIR | mode,
                                               quota_ids=qids)
                try:
                    self.meta.create_dentry(ino, part, child.ino, child.mode,
                                            quota_ids=qids)
                    ino = child.ino
                except OpError:
                    # lost a create race: take whoever won
                    self._undo_create(child.ino)
                    ino = self.meta.lookup(ino, part).ino
        return ino

    def readdir(self, path: str) -> list[str]:
        try:
            return [d.name for d in self.meta.read_dir(self.resolve(path))]
        except OpError as e:
            raise FsError(e.code, path) from None

    def _remove_node(self, parent: int, name: str, want_dir: bool,
                     path: str) -> tuple[int, int]:
        """Remove dentry + drop a link, returning (ino, nlink_after): ONE
        combined commit when one partition owns parent and child (also
        saving the pre-lookup round-trip), else the lookup + per-op flow.
        The ONE remove implementation — the FUSE server delegates here."""
        qids = self._parent_quota_ids(parent)
        try:
            res = self.meta.remove_entry(parent, name, want_dir,
                                         quota_ids=qids)
            if res is not None:
                return res
            # cross-partition child: classic flow
            d = self.meta.lookup(parent, name)
            if stat_mod.S_ISDIR(d.mode) != want_dir:
                raise FsError("ENOTDIR" if want_dir else "EISDIR", path)
            self.meta.delete_dentry(parent, name, quota_ids=qids)
            inode = self.meta.unlink_inode(d.ino)
        except OpError as e:
            raise FsError(e.code, path) from None
        return d.ino, inode.nlink

    def rmdir(self, path: str) -> None:
        parent, name = self._resolve_parent(path)
        ino, _ = self._remove_node(parent, name, want_dir=True, path=path)
        self.meta.evict_inode(ino)

    # -- file verbs --------------------------------------------------------------

    def create(self, path: str, mode: int = 0o644) -> int:
        parent, name = self._resolve_parent(path)
        qids = self._parent_quota_ids(parent)
        return self._create_node(parent, name, stat_mod.S_IFREG | mode,
                                 qids, path).ino

    def write_file(self, path: str, data: bytes) -> int:
        """Whole-file write (create-or-truncate), the common S3/batch shape."""
        try:
            ino = self.resolve(path)
            self.meta.truncate(ino, 0)
        except FsError:
            ino = self.create(path)
        if data:
            self.write_at(ino, 0, data)
        return ino

    def append_file(self, path: str, data: bytes) -> int:
        try:
            ino = self.resolve(path)
        except FsError:
            ino = self.create(path)
        if data:
            self.write_at(ino, self.meta.get_inode(ino).size, data)
        return ino

    def write_at(self, ino: int, offset: int, data: bytes) -> None:
        """Positional write, tier-dispatched (file.go:367-439 Write analog)."""
        if self.qos is not None:
            self.qos.throttle_write(len(data))
        try:
            if not self.cold:
                self.hot.write(ino, offset, data)
                return
            if offset != self.meta.get_inode(ino).size:
                raise FsError("EINVAL", "cold volumes are append-only")
            loc = self.data.write(data)
            self.meta.append_obj_extents(
                ino, [{"loc": loc, "size": len(data)}], offset + len(data))
        except OpError as e:  # e.g. EDQUOT from the quota charge
            raise FsError(e.code, f"ino {ino}") from None

    def read_file(self, path: str, offset: int = 0, size: int | None = None) -> bytes:
        return self.read_at(self.resolve(path), offset, size)

    def read_at(self, ino: int, offset: int = 0, size: int | None = None) -> bytes:
        """Positional read by inode — open files stay readable after their
        path is unlinked (the client orphan-inode contract)."""
        try:
            inode = self.meta.get_inode(ino)
        except OpError as e:
            raise FsError(e.code, f"ino {ino}") from None
        if size is None:
            size = inode.size - offset
        size = max(0, min(size, inode.size - offset))
        if self.qos is not None and size:
            self.qos.throttle_read(size)
        if not self.cold:
            return self.hot.read(inode.ino, offset, size)
        out = bytearray()
        pos = 0
        for ext in inode.obj_extents:
            ext_size = ext["size"]
            lo, hi = pos, pos + ext_size
            pos = hi
            if hi <= offset or lo >= offset + size:
                continue
            s = max(0, offset - lo)
            e = min(ext_size, offset + size - lo)
            out += self._read_extent(ext, s, e - s, ext_size)
        return bytes(out)

    # extents above this bypass the cache: a miss would otherwise turn a tiny
    # range read into a full-extent EC reconstruct + a cache fill that a
    # capacity-bounded LRU evicts straight away (thrash)
    BCACHE_MAX_EXTENT = 8 << 20

    def _read_extent(self, ext: dict, start: int, length: int,
                     ext_size: int) -> bytes:
        """One cold extent read, through the local block cache when present."""
        if self.bcache is None or ext_size > self.BCACHE_MAX_EXTENT:
            return self.data.read(ext["loc"], start, length)
        import hashlib

        key = "loc_" + hashlib.sha256(ext["loc"].encode()).hexdigest()[:32]
        blk = self.bcache.get(key, start, length)
        if blk is not None and len(blk) == length:
            return blk
        whole = self.data.read(ext["loc"], 0, ext_size)
        self.bcache.put(key, whole)
        return whole[start:start + length]

    def unlink(self, path: str, evict: bool = True) -> int:
        """Remove the dentry + drop a link. evict=False keeps the inode alive
        for holders of open handles (client orphan list); the caller must
        evict_ino() on last close. Returns the inode id."""
        parent, name = self._resolve_parent(path)
        ino, _ = self._remove_node(parent, name, want_dir=False, path=path)
        if evict:
            self.meta.evict_inode(ino)
        return ino

    def evict_ino(self, ino: int) -> None:
        """Release an orphaned inode once its last open handle closes."""
        try:
            self.meta.evict_inode(ino)
        except OpError as e:
            raise FsError(e.code, f"ino {ino}") from None

    def rename(self, src: str, dst: str, evict_displaced: bool = True):
        """POSIX replace semantics: an existing destination is displaced.
        With evict_displaced (default, mirrors unlink(evict=True)) a fully
        unlinked displaced inode is evicted here; callers holding their own
        open-handle tables (Mount, the FUSE server) pass False and apply
        their orphan contract to the returned (ino, nlink, is_dir)."""
        sp, sn = self._resolve_parent(src)
        dp, dn = self._resolve_parent(dst)
        try:
            displaced = self.meta.rename(
                sp, sn, dp, dn,
                src_quota_ids=self._parent_quota_ids(sp),
                dst_quota_ids=self._parent_quota_ids(dp))
        except OpError as e:
            raise FsError(e.code, f"{src} -> {dst}") from None
        if displaced and evict_displaced:
            ino, nlink, is_dir = displaced
            if ino and (is_dir or nlink <= 0):
                self.evict_ino(ino)
        return displaced

    def stat(self, path: str) -> dict:
        try:
            inode = self.meta.get_inode(self.resolve(path))
        except OpError as e:
            raise FsError(e.code, path) from None
        return {
            "ino": inode.ino,
            "mode": inode.mode,
            "size": inode.size,
            "nlink": inode.nlink,
            "uid": inode.uid,
            "gid": inode.gid,
            "mtime": inode.mtime,
            "is_dir": inode.is_dir,
        }

    def link(self, existing: str, new: str) -> None:
        ino = self.resolve(existing)
        parent, name = self._resolve_parent(new)
        try:
            self.meta.link(parent, name, ino)
        except OpError as e:
            raise FsError(e.code, new) from None

    def setxattr(self, path: str, key: str, value: bytes) -> None:
        try:
            self.meta.set_xattr(self.resolve(path), key, value)
        except OpError as e:
            raise FsError(e.code, path) from None

    def getxattr(self, path: str, key: str) -> bytes:
        try:
            inode = self.meta.get_inode(self.resolve(path))
        except OpError as e:
            raise FsError(e.code, path) from None
        if key not in inode.xattrs:
            raise FsError("ENODATA", key)
        return inode.xattrs[key]

    def removexattr(self, path: str, key: str) -> None:
        try:
            self.meta.remove_xattr(self.resolve(path), key)
        except OpError as e:
            raise FsError(e.code, path) from None

    def listxattr(self, path: str) -> list[str]:
        """All extended-attribute keys on path (ref objectnode ListXAttrs)."""
        try:
            inode = self.meta.get_inode(self.resolve(path))
        except OpError as e:
            raise FsError(e.code, path) from None
        return sorted(inode.xattrs)
