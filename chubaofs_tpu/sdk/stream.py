"""Extent client — the hot-tier streaming data SDK.

Reference counterpart: sdk/data/stream (ExtentClient extent_client.go,
Streamer.write stream_writer.go:278 with the flush-before-overwrite rule
:299-309, doWrite :433, ExtentHandler extent_handler.go:49-79 with its
sender/receiver pipeline, reader stream_reader.go) and sdk/data/wrapper's
KFasterRandomSelector (k_faster_random_selector.go:53-58).

Kept:
  * per-inode Streamer; appends ride an ExtentHandler that pipelines ≤128KiB
    packets over one pooled connection to the partition leader (acks are
    collected at flush — the sender/receiver goroutine pair collapsed into a
    send-now/ack-on-flush window);
  * overwrites FLUSH first, then go through the raft random-write op against
    the owning extent (stream_writer.go:299-309);
  * small first writes use the tiny-extent path — the datanode assigns the
    extent id + offset and the ack carries them back;
  * flush emits only the newly-acked extent suffix as ExtentKeys to the
    metanode (AppendExtentKey, sdk/meta/api.go:1137) so keys never overlap;
  * partition selection ranks by EWMA ack latency and picks randomly among
    the fastest half (KFasterRandom).
"""

from __future__ import annotations

import random
import socket
import time
from collections import deque

from chubaofs_tpu.proto.packet import (
    OP_CREATE_EXTENT, OP_MARK_DELETE, OP_RANDOM_WRITE, OP_STREAM_READ,
    OP_WRITE, Packet, RES_NOT_LEADER, RES_OK, is_tiny_extent, recv_packet,
    send_packet,
)
from chubaofs_tpu.utils.conn_pool import ConnPool

PACKET_SIZE = 128 * 1024
TINY_LIMIT = PACKET_SIZE  # first write ≤ this rides a tiny extent


class StreamError(Exception):
    pass


class ExtentClient:
    """Partition view + selector + conn pool shared by all streamers."""

    def __init__(self, refresh_partitions, pool: ConnPool | None = None,
                 follower_read: bool = False):
        """refresh_partitions() -> [{"pid": int, "hosts": [addr,...]}] — the
        master's data-partition view for the volume (wrapper.go analog).
        follower_read: volume option — reads may hit ANY replica, ranked by
        per-host EWMA latency (the reference's FollowerRead + the selector's
        read-side job, sdk/data/stream follower-read)."""
        self._refresh = refresh_partitions
        self.pool = pool or ConnPool()
        self.follower_read = follower_read
        self._parts: list[dict] = []
        self._lat: dict[int, float] = {}  # pid -> EWMA seconds
        self._host_lat: dict[str, float] = {}  # host -> EWMA seconds (reads)

    def partitions(self) -> list[dict]:
        if not self._parts:
            self._parts = list(self._refresh())
        return self._parts

    def refresh(self) -> None:
        self._parts = list(self._refresh())

    def select(self) -> dict:
        parts = self.partitions()
        if not parts:
            raise StreamError("no writable data partitions")
        ranked = sorted(parts, key=lambda p: self._lat.get(p["pid"], 0.0))
        k = max(1, len(ranked) // 2)
        return random.choice(ranked[:k])

    def record_latency(self, pid: int, dt: float) -> None:
        prev = self._lat.get(pid, dt)
        self._lat[pid] = 0.8 * prev + 0.2 * dt

    def record_host_latency(self, host: str, dt: float) -> None:
        prev = self._host_lat.get(host, dt)
        self._host_lat[host] = 0.8 * prev + 0.2 * dt

    def read_hosts(self, dp: dict) -> list[str]:
        """Replica order for a read. Follower-read ranks ALL hosts by EWMA
        and picks randomly among the fastest half (KFasterRandom applied to
        replicas, k_faster_random_selector.go:53-58), so a slow or dead
        leader never sets the read latency floor; the rest follow as
        fallbacks. Leader-only mode keeps the wire order (leader first)."""
        hosts = list(dp["hosts"])
        if not self.follower_read or len(hosts) <= 1:
            return hosts
        ranked = sorted(hosts, key=lambda h: self._host_lat.get(h, 0.0))
        k = max(1, len(ranked) // 2)
        first = random.choice(ranked[:k])
        return [first] + [h for h in ranked if h != first]

    def find_dp(self, pid: int) -> dict:
        for p in self.partitions():
            if p["pid"] == pid:
                return p
        self.refresh()
        for p in self.partitions():
            if p["pid"] == pid:
                return p
        raise StreamError(f"unknown partition {pid}")

    def delete_extents(self, keys) -> None:
        """MarkDelete every ExtentKey (dicts or dataclasses); raises on any
        failure so the metanode's purge queue retries the batch."""
        for key in keys:
            get = key.get if isinstance(key, dict) else lambda a, k=key: getattr(k, a)
            pid, eid = get("partition_id"), get("extent_id")
            dp = self.find_dp(pid)
            arg = {"followers": dp["hosts"][1:]}
            off = 0
            if is_tiny_extent(eid):
                arg["size"] = get("size")
                off = get("extent_offset")
            rep = self.request(dp, Packet(
                OP_MARK_DELETE, partition_id=pid, extent_id=eid,
                extent_offset=off, arg=arg), retry_hosts=False)
            if rep.result != RES_OK:
                raise StreamError(f"mark delete {pid}/{eid}: {rep.error()}")

    # -- one-shot requests with leader fallback --------------------------------

    # a raft election (or a restarted replica) makes every host answer
    # not-leader/conn-refused for a moment; ride it out like the meta client
    # does (sdk/data retry discipline)
    RETRY_WINDOW = 10.0
    RETRY_SLEEP = 0.1

    def request(self, dp: dict, pkt: Packet, retry_hosts: bool = True,
                hosts: list[str] | None = None) -> Packet:
        import time as _time

        from chubaofs_tpu.proto.packet import trace_inject, trace_merge

        trace_inject(pkt)  # datanode hops join the caller's trace
        last = None
        if hosts is None:
            hosts = dp["hosts"] if retry_hosts else dp["hosts"][:1]
        deadline = _time.monotonic() + (self.RETRY_WINDOW if retry_hosts else 0)
        while True:
            for addr in hosts:
                sock = self.pool.get(addr)
                t0 = _time.perf_counter()
                try:
                    send_packet(sock, pkt)
                    reply = recv_packet(sock)
                except (OSError, ConnectionError) as e:
                    self.pool.put(addr, sock, ok=False)
                    # a dead replica must sink in the read ranking, not
                    # stay at its last healthy EWMA
                    self.record_host_latency(addr, self.RETRY_WINDOW)
                    last = StreamError(f"{addr}: {e}")
                    continue
                self.pool.put(addr, sock)
                self.record_host_latency(addr, _time.perf_counter() - t0)
                if reply.result == RES_NOT_LEADER:
                    last = StreamError(f"{addr}: not leader")
                    continue
                trace_merge(reply)
                return reply
            if _time.monotonic() >= deadline:
                break
            _time.sleep(self.RETRY_SLEEP)
        raise last or StreamError("no hosts")

    def request_read(self, dp: dict, pkt: Packet) -> Packet:
        """Read with the volume's consistency mode: follower-read fans the
        attempt order across EWMA-ranked replicas (and flags the packet so
        followers serve it); leader-only keeps the plain request path."""
        if not self.follower_read:
            return self.request(dp, pkt)
        pkt.arg["follower_read"] = True
        return self.request(dp, pkt, hosts=self.read_hosts(dp))


class ExtentHandler:
    """One open extent on one partition: pipelined append packets
    (extent_handler.go:49-79)."""

    def __init__(self, client: ExtentClient, dp: dict, file_offset: int):
        self.client = client
        self.dp = dp
        self.leader = dp["hosts"][0]
        self.followers = dp["hosts"][1:]
        self.file_offset = file_offset  # file position where this extent begins
        self.extent_id: int | None = None
        self.size = 0  # bytes sent into the extent
        self.acked = 0  # bytes acked (suffix [acked, size) is in flight)
        self.emitted = 0  # bytes already reported to the metanode as keys
        self.sock: socket.socket | None = None
        self.inflight: deque[int] = deque()  # per-packet payload sizes

    def _conn(self) -> socket.socket:
        if self.sock is None:
            self.sock = self.client.pool.get(self.leader)
        return self.sock

    def open(self) -> None:
        from chubaofs_tpu.proto.packet import trace_inject, trace_merge

        t0 = time.perf_counter()
        req = trace_inject(Packet(OP_CREATE_EXTENT, partition_id=self.dp["pid"],
                                  arg={"followers": self.followers}))
        sock = self._conn()
        send_packet(sock, req)
        rep = recv_packet(sock)
        trace_merge(rep)
        self.client.record_latency(self.dp["pid"], time.perf_counter() - t0)
        if rep.result != RES_OK:
            raise StreamError(f"create extent: {rep.error()}")
        self.extent_id = rep.extent_id

    def write(self, data: bytes) -> None:
        if self.extent_id is None:
            self.open()
        sock = self._conn()
        view = memoryview(data)
        while view:
            chunk = bytes(view[:PACKET_SIZE])
            view = view[len(chunk):]
            pkt = Packet(
                OP_WRITE, partition_id=self.dp["pid"], extent_id=self.extent_id,
                extent_offset=self.size, kernel_offset=self.file_offset + self.size,
                data=chunk, arg={"followers": self.followers},
            )
            send_packet(sock, pkt)
            self.inflight.append(len(chunk))
            self.size += len(chunk)

    def flush(self) -> list[dict]:
        """Drain acks; return ExtentKeys for the newly-acked suffix."""
        if self.extent_id is None:
            return []
        sock = self._conn()
        t0 = time.perf_counter()
        had_inflight = bool(self.inflight)
        while self.inflight:
            rep = recv_packet(sock)
            if rep.result != RES_OK:
                self._drop_conn()
                raise StreamError(f"write ack: {rep.error()}")
            self.acked += self.inflight.popleft()
        if had_inflight:
            self.client.record_latency(self.dp["pid"], time.perf_counter() - t0)
        if self.acked == self.emitted:
            return []
        key = {
            "file_offset": self.file_offset + self.emitted,
            "partition_id": self.dp["pid"],
            "extent_id": self.extent_id,
            "extent_offset": self.emitted,
            "size": self.acked - self.emitted,
        }
        self.emitted = self.acked
        return [key]

    def close(self) -> None:
        if self.sock is not None:
            self.client.pool.put(self.leader, self.sock, ok=not self.inflight)
            self.sock = None

    def _drop_conn(self) -> None:
        if self.sock is not None:
            self.client.pool.put(self.leader, self.sock, ok=False)
            self.sock = None
        self.inflight.clear()


class Streamer:
    """Per-inode write/read pipeline (stream_writer.go Streamer analog)."""

    def __init__(self, client: ExtentClient, meta, ino: int):
        self.client = client
        self.meta = meta  # MetaWrapper
        self.ino = ino
        inode = meta.get_inode(ino)
        self.size = inode.size
        self.handler: ExtentHandler | None = None

    # -- writes ----------------------------------------------------------------

    def sync_committed(self) -> None:
        """Re-anchor on the metanode's committed size; drops a handler whose
        file mapping went stale (truncate from another client/path)."""
        committed = self._committed_size()
        if self.handler is not None and not self.handler.inflight:
            if self.handler.file_offset + self.handler.emitted != committed:
                self.handler.close()
                self.handler = None
        if self.handler is None:
            self.size = committed

    def write(self, offset: int, data: bytes) -> int:
        """Classify overwrite vs append per stream_writer.go:278."""
        if not data:
            return 0
        n = len(data)
        committed = self._committed_size()
        if offset < committed:
            cut = min(offset + n, committed)
            self._overwrite(offset, data[: cut - offset])
            data = data[cut - offset:]
            offset = cut
        if data:
            self._append(offset, data)
        return n

    def _committed_size(self) -> int:
        return self.meta.get_inode(self.ino).size

    def _overwrite(self, offset: int, data: bytes) -> None:
        """Flush dirty appends, then raft random-writes into owning extents
        (the flush-before-overwrite rule, stream_writer.go:299-309).

        Ranges below the committed size that NO extent covers are holes a
        truncate-up left behind: they get fresh extents of their own (keys
        never overlap, so a hole-fill key at its file_offset slots straight
        into the read paste) — silently skipping them would drop the bytes."""
        self.flush()
        inode = self.meta.get_inode(self.ino)
        end = offset + len(data)
        covered: list[tuple[int, int]] = []
        for key in inode.extents:
            lo = max(offset, key.file_offset)
            hi = min(end, key.file_offset + key.size)
            if lo >= hi:
                continue
            covered.append((lo, hi))
            dp = self._dp_of(key.partition_id)
            pkt = Packet(
                OP_RANDOM_WRITE, partition_id=key.partition_id,
                extent_id=key.extent_id,
                extent_offset=key.extent_offset + (lo - key.file_offset),
                kernel_offset=lo, data=data[lo - offset: hi - offset],
            )
            rep = self.client.request(dp, pkt)
            if rep.result != RES_OK:
                raise StreamError(f"random write: {rep.error()}")
        # fill the uncovered holes with fresh extents
        covered.sort()
        pos = offset
        holes: list[tuple[int, int]] = []
        for lo, hi in covered:
            if pos < lo:
                holes.append((pos, lo))
            pos = max(pos, hi)
        if pos < end:
            holes.append((pos, end))
        for lo, hi in holes:
            h = ExtentHandler(self.client, self.client.select(), lo)
            try:
                h.write(data[lo - offset: hi - offset])
                keys = h.flush()
            finally:
                h.close()
            if keys:
                self.meta.append_extents(
                    self.ino, keys, max(inode.size, keys[-1]["file_offset"]
                                        + keys[-1]["size"]))

    def _append(self, offset: int, data: bytes) -> None:
        if offset > self.size:
            # zero-fill the gap so append lands at the watermark
            data = b"\0" * (offset - self.size) + data
            offset = self.size
        if offset != self.size:
            raise StreamError(f"append at {offset}, size {self.size}")
        if self.size == 0 and self.handler is None and len(data) <= TINY_LIMIT:
            self._tiny_write(data)
            self.size += len(data)
            return
        if self.handler is None:
            self.handler = ExtentHandler(self.client, self.client.select(), offset)
        self.handler.write(data)
        self.size += len(data)

    def _tiny_write(self, data: bytes) -> None:
        """Single-packet small write: datanode assigns tiny extent + offset."""
        dp = self.client.select()
        pkt = Packet(OP_WRITE, partition_id=dp["pid"], extent_id=0,
                     kernel_offset=0, data=data,
                     arg={"tiny": True, "followers": dp["hosts"][1:]})
        t0 = time.perf_counter()
        rep = self.client.request(dp, pkt, retry_hosts=False)
        self.client.record_latency(dp["pid"], time.perf_counter() - t0)
        if rep.result != RES_OK:
            raise StreamError(f"tiny write: {rep.error()}")
        self.meta.append_extents(self.ino, [{
            "file_offset": 0, "partition_id": dp["pid"],
            "extent_id": rep.extent_id, "extent_offset": rep.extent_offset,
            "size": len(data),
        }], len(data))

    def flush(self) -> None:
        if self.handler is None:
            return
        keys = self.handler.flush()
        if keys:
            new_size = max(self.size, keys[-1]["file_offset"] + keys[-1]["size"])
            self.meta.append_extents(self.ino, keys, new_size)

    def close(self) -> None:
        self.flush()
        if self.handler is not None:
            self.handler.close()
            self.handler = None

    # -- reads -----------------------------------------------------------------

    def _dp_of(self, pid: int) -> dict:
        return self.client.find_dp(pid)

    def read(self, offset: int, size: int) -> bytes:
        self.flush()
        inode = self.meta.get_inode(self.ino)
        size = max(0, min(size, inode.size - offset))
        if size == 0:
            return b""
        out = bytearray(size)
        for key in inode.extents:  # in-order paste; keys never overlap
            lo = max(offset, key.file_offset)
            hi = min(offset + size, key.file_offset + key.size)
            if lo >= hi:
                continue
            dp = self._dp_of(key.partition_id)
            pkt = Packet(
                OP_STREAM_READ, partition_id=key.partition_id,
                extent_id=key.extent_id,
                extent_offset=key.extent_offset + (lo - key.file_offset),
                arg={"size": hi - lo},
            )
            rep = self.client.request_read(dp, pkt)
            if rep.result != RES_OK:
                raise StreamError(f"read: {rep.error()}")
            out[lo - offset: hi - offset] = rep.data
        return bytes(out)


class HotBackend:
    """FsClient data backend over the extent client (replica tier).

    Mirrors the role sdk/data/blobstore's writer/reader play for cold volumes
    (chubaofs_tpu/deploy.BlobstoreBackend), but records ExtentKeys."""

    def __init__(self, client: ExtentClient, meta):
        self.client = client
        self.meta = meta
        self._streamers: dict[int, Streamer] = {}

    def streamer(self, ino: int) -> Streamer:
        s = self._streamers.get(ino)
        if s is None:
            s = self._streamers[ino] = Streamer(self.client, self.meta, ino)
        return s

    def write(self, ino: int, offset: int, data: bytes) -> None:
        s = self.streamer(ino)
        s.sync_committed()
        s.write(offset, data)
        s.flush()

    def read(self, ino: int, offset: int, size: int) -> bytes:
        return self.streamer(ino).read(offset, size)

    def delete(self, ino: int, extents: list) -> None:
        """MarkDelete every extent the inode owned (freelist drain analog)."""
        self._streamers.pop(ino, None)
        self.client.delete_extents(extents)
