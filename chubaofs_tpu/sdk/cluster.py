"""RemoteCluster — the SDK's network view of a running cluster.

Reference counterpart: the composition every CubeFS client performs —
sdk/master/client.go (master HTTP), sdk/meta (partition routing over TCP),
sdk/data/stream (extent TCP), sdk/data/blobstore (access API for cold
volumes). This object resolves everything from the master's registry: which
metanodes serve a volume's partitions, which datanodes host its extents, and
where the blobstore access gateway lives. Its surface matches the in-process
`FsCluster` (client/create_volume/delete_volume/volume_names/data_backend),
so ObjectNode and the FUSE-layer client run unchanged over the wire.
"""

from __future__ import annotations

import threading

from chubaofs_tpu.master.api_service import MasterClient
from chubaofs_tpu.master.master import (
    INF,
    DataPartitionView,
    MasterError,
    MetaPartitionView,
    VolumeView,
)
from chubaofs_tpu.meta.service import RemoteMetaNode
from chubaofs_tpu.sdk.fs import FsClient
from chubaofs_tpu.sdk.meta_wrapper import MetaWrapper
from chubaofs_tpu.sdk.stream import ExtentClient, HotBackend


class _MasterAdapter:
    """Duck-types the `master` object MetaWrapper routes over, fed by HTTP."""

    def __init__(self, mc: MasterClient):
        self.mc = mc

    def get_volume(self, name: str) -> VolumeView:
        d = self.mc.get_volume(name)
        vol = VolumeView(name=d["name"], vol_id=d["vol_id"], owner=d["owner"],
                         capacity=d["capacity"], cold=d["cold"],
                         follower_read=d.get("follower_read", False),
                         qos_read_mbps=d.get("qos_read_mbps", 0),
                         qos_write_mbps=d.get("qos_write_mbps", 0))
        for mp in d["meta_partitions"]:
            end = INF if mp["end"] < 0 else mp["end"]
            vol.meta_partitions.append(MetaPartitionView(
                mp["partition_id"], mp["start"], end,
                peers=list(mp["peers"]), leader=mp.get("leader")))
        for dp in d["data_partitions"]:
            vol.data_partitions.append(DataPartitionView(
                dp["partition_id"], peers=list(dp["peers"]),
                hosts=list(dp["hosts"]), status=dp.get("status", "rw")))
        return vol


class RemoteDataBackend:
    """Cold-tier backend over the access gateway (FsClient data_backend)."""

    def __init__(self, access_client):
        self.ac = access_client

    def write(self, data: bytes) -> str:
        return self.ac.put(data).to_json()

    def read(self, loc: str, offset: int, size: int) -> bytes:
        return self.ac.get(loc, offset, size)

    def delete(self, loc: str) -> None:
        self.ac.delete(loc)


class RemoteCluster:
    def __init__(self, master_addrs: list[str], access_addrs: list[str] | None = None,
                 admin_ticket=None):
        self.mc = MasterClient(master_addrs, admin_ticket=admin_ticket)
        self.adapter = _MasterAdapter(self.mc)
        self.access_addrs = access_addrs or []
        self._metanodes: dict[int, RemoteMetaNode] = {}
        self._lock = threading.Lock()
        self._backend = None

    # -- registry refresh ------------------------------------------------------

    def metanode_handles(self) -> dict[int, RemoteMetaNode]:
        """RemoteMetaNode per registered metanode; re-dials on addr change."""
        cluster = self.mc.get_cluster()
        with self._lock:
            for n in cluster["nodes"]:
                if n["kind"] != "meta" or not n["addr"]:
                    continue
                cur = self._metanodes.get(n["node_id"])
                if cur is None or cur.addr != n["addr"]:
                    if cur is not None:
                        cur.close()
                    self._metanodes[n["node_id"]] = RemoteMetaNode(n["addr"])
            return dict(self._metanodes)

    @property
    def data_backend(self):
        if self._backend is None:
            if not self.access_addrs:
                raise MasterError("no blobstore access gateway configured")
            from chubaofs_tpu.blobstore.gateway import AccessClient

            self._backend = RemoteDataBackend(AccessClient(self.access_addrs))
        return self._backend

    # -- FsCluster surface -----------------------------------------------------

    def create_volume(self, name: str, cold: bool = True) -> None:
        self.mc.create_volume(name, cold=cold)

    def delete_volume(self, name: str) -> None:
        self.mc.delete_volume(name)

    def volume_names(self) -> list[str]:
        return sorted(self.mc.get_cluster()["volumes"])

    def client(self, volume: str) -> FsClient:
        from chubaofs_tpu.sdk.fs import VolQos

        meta = MetaWrapper(self.adapter, self.metanode_handles(), volume)
        vol = self.adapter.get_volume(volume)
        backend = self.data_backend if self.access_addrs else None

        def fetch_limits():
            v = self.adapter.get_volume(volume)
            return v.qos_read_mbps, v.qos_write_mbps

        qos = VolQos.from_view(vol, fetch=fetch_limits)
        if vol.cold:
            return FsClient(meta, backend, cold=True, qos=qos)
        ec = ExtentClient(lambda: self.mc.data_partitions(volume),
                          follower_read=vol.follower_read)
        return FsClient(meta, backend, hot_backend=HotBackend(ec, meta),
                        cold=False, qos=qos)
