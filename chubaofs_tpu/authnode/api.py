"""AuthNode HTTP API (authnode/api_service.go surface over the rpc framework).

Routes mirror the reference's ticket + key admin endpoints:
  POST /client/getticket   {client_id, service_id, verifier, ts} -> {sealed}
  POST /admin/createkey    {id, role, caps?}          -> {id, key(b64)}
  POST /admin/deletekey    {id}
  POST /admin/addcaps      {id, caps}                 -> {caps}
Admin routes are protected by the shared-secret auth middleware
(common/rpc/auth analog), standing in for the reference's admin tickets.
"""

from __future__ import annotations

import base64

from chubaofs_tpu.authnode.server import AuthError, AuthNode, TicketError
from chubaofs_tpu.rpc import HTTPError, Response, Router
from chubaofs_tpu.rpc.server import auth_middleware


def build_router(node: AuthNode, admin_secret: bytes | None = None) -> Router:
    r = Router()

    def getticket(req):
        d = req.json()
        try:
            return node.get_ticket(d["client_id"], d["service_id"],
                                   d["verifier"], float(d["ts"]))
        except TicketError as e:
            raise HTTPError(403, "TicketDenied", str(e)) from None
        except AuthError as e:
            raise HTTPError(404, "NoSuchKey", str(e)) from None

    r.post("/client/getticket", getticket)

    admin = Router()

    def createkey(req):
        d = req.json()
        try:
            key = node.create_key(d["id"], d["role"], d.get("caps"))
        except AuthError as e:
            raise HTTPError(409, "KeyExists", str(e)) from None
        return {"id": d["id"], "key": base64.b64encode(key).decode()}

    def createkeys(req):
        # bulk bootstrap: every key in ONE raft group-commit round
        d = req.json()
        try:
            keys = node.create_keys([(e["id"], e["role"]) for e in d["entries"]])
        except AuthError as e:
            raise HTTPError(409, "KeyExists", str(e)) from None
        return {"keys": {i: base64.b64encode(k).decode()
                         for i, k in keys.items()}}

    def deletekey(req):
        try:
            node.delete_key(req.json()["id"])
        except AuthError as e:
            raise HTTPError(404, "NoSuchKey", str(e)) from None
        return Response(204)

    def addcaps(req):
        d = req.json()
        try:
            return {"caps": node.add_caps(d["id"], d["caps"])}
        except AuthError as e:
            raise HTTPError(404, "NoSuchKey", str(e)) from None

    if admin_secret is not None:
        admin.middleware.append(auth_middleware(admin_secret))
    admin.post("/admin/createkey", createkey)
    admin.post("/admin/createkeys", createkeys)
    admin.post("/admin/deletekey", deletekey)
    admin.post("/admin/addcaps", addcaps)

    # mount admin under the same router; its middleware applies to /admin/*
    def admin_dispatch(req):
        return admin.dispatch(req)

    r.post("/admin/:op", admin_dispatch)
    return r


class RemoteAuthNode:
    """HTTP twin of the in-process AuthNode ticket surface: lets AuthClient /
    RenewingTicket target a remote authnode daemon (sdk/auth over the wire)."""

    def __init__(self, addrs: list[str]):
        from chubaofs_tpu.rpc.client import RPCClient

        self.rpc = RPCClient(list(addrs), retries=3)

    def get_ticket(self, client_id: str, service_id: str, verifier: str,
                   ts: float) -> dict:
        return self.rpc.post("/client/getticket", {
            "client_id": client_id, "service_id": service_id,
            "verifier": verifier, "ts": ts,
        })
