"""AuthNode: ticket-granting service over a raft-replicated keystore.

Reference counterpart: authnode/api_service.go:37-114 (getTicket — the
Kerberos-ish flow: client proves key possession, authnode returns a session
key + a ticket sealed under the SERVICE's key carrying identity +
capabilities + expiry), authnode/keystore_fsm.go (raft-replicated keystore:
create/get/delete keys, capability grants), util/cryptoutil for the AEAD.

Flow (mirrors the reference's message shapes):
  1. client -> AuthNode: {client_id, service_id, verifier=HMAC(client_key, ts)}
  2. AuthNode verifies the verifier against the stored client key, mints a
     session key, returns:
       - sealed-for-client: {session_key, ticket} under client_key
       - the ticket itself is sealed under service_key:
         {client_id, session_key, caps, exp}
  3. client presents the ticket to the service; the service opens it with its
     own key and honors caps until exp. The service never talks to authnode.
"""

from __future__ import annotations

import base64
import fnmatch
import json
import time

from chubaofs_tpu.raft.server import MultiRaft, StateMachine
from chubaofs_tpu.utils import cryptoutil

AUTH_GROUP = 2  # master owns raft group 1; the auth keystore rides group 2

TICKET_TTL = 3600.0


class AuthError(Exception):
    pass


class TicketError(AuthError):
    pass


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode())


class KeystoreSM(StateMachine):
    """Raft-replicated keystore (authnode/keystore_fsm.go analog).

    Entries: id -> {key: b64, role: client|service, caps: [pattern...]}.
    Caps are "service:action" patterns granted to CLIENT entries and stamped
    into tickets."""

    def __init__(self):
        self.keys: dict[str, dict] = {}

    def apply(self, data, index: int):
        """Errors travel as ("err", msg) values, never exceptions — a raise
        here would kill the shared raft apply pump and poison WAL replay
        (same contract as MasterSM.apply)."""
        try:
            return ("ok", self._apply(data))
        except AuthError as e:
            return ("err", str(e))

    def _apply(self, data):
        op = data["op"]
        if op == "create_key":
            entry_id = data["id"]
            if entry_id in self.keys:
                raise AuthError(f"key {entry_id!r} exists")
            self.keys[entry_id] = {"key": data["key"], "role": data["role"],
                                   "caps": data.get("caps", [])}
            return entry_id
        if op == "delete_key":
            if data["id"] not in self.keys:
                raise AuthError(f"no key {data['id']!r}")
            del self.keys[data["id"]]
            return data["id"]
        if op == "add_caps":
            ent = self.keys.get(data["id"])
            if ent is None:
                raise AuthError(f"no key {data['id']!r}")
            ent["caps"] = sorted(set(ent["caps"]) | set(data["caps"]))
            return ent["caps"]
        raise AuthError(f"unknown keystore op {op!r}")

    def snapshot(self) -> bytes:
        return json.dumps(self.keys).encode()

    def restore(self, data: bytes) -> None:
        self.keys = json.loads(data.decode())

    def get(self, entry_id: str) -> dict:
        ent = self.keys.get(entry_id)
        if ent is None:
            raise AuthError(f"no key {entry_id!r}")
        return ent


class AuthNode:
    """One authnode replica: keystore ops route through raft; ticket grants
    are leader-local reads + crypto."""

    def __init__(self, raft: MultiRaft, sm: KeystoreSM):
        self.raft = raft
        self.sm = sm

    def _apply(self, **data):
        # rides raft group commit: concurrent keystore admins coalesce into
        # shared WAL-flush + replication rounds on AUTH_GROUP
        status, result = self.raft.propose(AUTH_GROUP, data).result(timeout=5.0)
        if status == "err":
            raise AuthError(result)
        return result

    def _apply_batch(self, datas: list[dict], timeout: float = 5.0) -> list:
        """Many keystore ops in ONE drained raft batch; each fails alone."""
        out = []
        for fut in self.raft.propose_batch(AUTH_GROUP, datas):
            status, result = fut.result(timeout=timeout)
            if status == "err":
                raise AuthError(result)
            out.append(result)
        return out

    def create_keys(self, entries: list[tuple[str, str]]) -> dict[str, bytes]:
        """Bootstrap helper: mint several (id, role) keys in one raft commit
        round (cluster bring-up creates client+service keys together)."""
        keys = {eid: cryptoutil.gen_key() for eid, _ in entries}
        self._apply_batch([
            {"op": "create_key", "id": eid, "key": _b64(keys[eid]),
             "role": role, "caps": []}
            for eid, role in entries])
        return keys

    # -- keystore admin ----------------------------------------------------------

    def create_key(self, entry_id: str, role: str, caps: list[str] | None = None,
                   key: bytes | None = None) -> bytes:
        key = key or cryptoutil.gen_key()
        self._apply(op="create_key", id=entry_id, key=_b64(key), role=role,
                    caps=caps or [])
        return key

    def delete_key(self, entry_id: str) -> None:
        self._apply(op="delete_key", id=entry_id)

    def add_caps(self, entry_id: str, caps: list[str]) -> list[str]:
        return self._apply(op="add_caps", id=entry_id, caps=caps)

    # -- ticket grant (api_service.go:37 getTicket) ------------------------------

    def get_ticket(self, client_id: str, service_id: str, verifier: str,
                   ts: float) -> dict:
        """verifier = b64(HMAC(client_key, f"{client_id}:{service_id}:{ts}"))."""
        if abs(time.time() - ts) > 300:
            raise TicketError("request timestamp outside replay window")
        client = self.sm.get(client_id)
        service = self.sm.get(service_id)
        if service["role"] != "service":
            raise TicketError(f"{service_id!r} is not a service")
        client_key = _unb64(client["key"])
        msg = f"{client_id}:{service_id}:{ts}".encode()
        if not cryptoutil.verify_hmac(client_key, msg, _unb64(verifier)):
            raise TicketError("client verifier mismatch")

        session_key = cryptoutil.gen_key()
        now = time.time()
        caps = [c for c in client["caps"]
                if c.split(":", 1)[0] in ("*", service_id)]
        ticket_plain = json.dumps({
            "client_id": client_id, "session_key": _b64(session_key),
            "caps": caps, "iat": now, "exp": now + TICKET_TTL,
        }).encode()
        ticket = cryptoutil.seal(_unb64(service["key"]), ticket_plain,
                                 aad=service_id.encode())
        reply_plain = json.dumps({
            "session_key": _b64(session_key),
            "ticket": _b64(ticket),
            "exp": now + TICKET_TTL,
        }).encode()
        return {"sealed": _b64(cryptoutil.seal(client_key, reply_plain,
                                               aad=client_id.encode()))}


def verify_ticket(service_id: str, service_key: bytes, ticket_b64: str,
                  action: str | None = None) -> dict:
    """Service side: open + validate a ticket, optionally demanding a cap
    ("service:action" pattern match). Returns the ticket claims."""
    try:
        plain = cryptoutil.open_sealed(service_key, _unb64(ticket_b64),
                                       aad=service_id.encode())
    except cryptoutil.AuthTagError:
        raise TicketError("ticket seal invalid") from None
    claims = json.loads(plain.decode())
    if claims["exp"] < time.time():
        raise TicketError("ticket expired")
    if action is not None:
        want = f"{service_id}:{action}"
        if not any(fnmatch.fnmatchcase(want, pat) or pat == "*"
                   for pat in claims["caps"]):
            raise TicketError(f"capability {want!r} not granted")
    return claims


class RenewingTicket:
    """Callable ticket provider with expiry-aware renewal.

    Daemons hold CLIENT CREDENTIALS, never a static ticket: a ticket is
    TTL'd (TICKET_TTL), so anything long-running must re-acquire before
    expiry or the cluster goes read-only an hour after boot. refresh()
    drops the cache (callers invoke it when the server answers denied —
    e.g. after an authnode-side capability change)."""

    def __init__(self, auth_client: "AuthClient", service_id: str,
                 margin: float = 300.0):
        import threading

        self.auth = auth_client
        self.service_id = service_id
        self.margin = margin
        self._grant: dict | None = None
        self._lock = threading.Lock()

    def __call__(self) -> str:
        with self._lock:
            if (self._grant is None
                    or time.time() > self._grant["exp"] - self.margin):
                self._grant = self.auth.get_ticket(self.service_id)
            return self._grant["ticket"]

    def refresh(self) -> None:
        with self._lock:
            self._grant = None


class AuthClient:
    """Client-side ticket acquisition (sdk/auth analog)."""

    def __init__(self, authnode: AuthNode, client_id: str, client_key: bytes):
        self.authnode = authnode
        self.client_id = client_id
        self.client_key = client_key

    def get_ticket(self, service_id: str) -> dict:
        ts = time.time()
        msg = f"{self.client_id}:{service_id}:{ts}".encode()
        verifier = _b64(cryptoutil.hmac_sha256(self.client_key, msg))
        reply = self.authnode.get_ticket(self.client_id, service_id, verifier, ts)
        plain = cryptoutil.open_sealed(self.client_key, _unb64(reply["sealed"]),
                                       aad=self.client_id.encode())
        return json.loads(plain.decode())
