"""AuthNode — Kerberos-like ticket service with a raft-replicated keystore.

Reference: authnode/ (api_service.go:37 getTicket, keystore_fsm.go) +
util/cryptoutil.
"""

from chubaofs_tpu.authnode.server import (
    AUTH_GROUP, AuthClient, AuthNode, KeystoreSM, TicketError,
)

__all__ = ["AuthNode", "AuthClient", "KeystoreSM", "AUTH_GROUP", "TicketError"]
