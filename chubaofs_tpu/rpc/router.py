"""Pattern router (blobstore/common/rpc router + gorilla/mux analog).

Reference counterpart: common/rpc's router (method + path patterns with
:params, e.g. /get/:vid) and gorilla/mux as used by objectnode/router.go:26.
Kept: method tables, ``:name`` path parameters, longest-literal-first match
order, per-route middleware chain, and query-condition matching (mux's
``Queries``) which S3 routing leans on (?uploads, ?acl, list-type=2...).
"""

from __future__ import annotations

import json
import urllib.parse
from dataclasses import dataclass, field


@dataclass
class Request:
    method: str
    path: str  # decoded path, no query
    query: dict[str, list[str]]
    headers: dict[str, str]  # lower-cased keys
    body: bytes
    params: dict[str, str] = field(default_factory=dict)
    remote: str = "-"
    raw_query: str = ""

    def q(self, key: str, default: str = "") -> str:
        v = self.query.get(key)
        return v[0] if v else default

    def q_int(self, key: str, default: int) -> int:
        """Integer query param; malformed values degrade to the default and
        negatives clamp to 0 (introspection endpoints must not 500 on a
        typo'd ?n=, and ?n=-5 must not invert a recency window)."""
        try:
            return max(0, int(self.q(key) or default))
        except ValueError:
            return default

    def has_q(self, key: str) -> bool:
        return key in self.query

    def header(self, key: str, default: str = "") -> str:
        return self.headers.get(key.lower(), default)

    def json(self):
        return json.loads(self.body.decode() or "null")


@dataclass
class Response:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    @classmethod
    def json(cls, obj, status: int = 200) -> "Response":
        return cls(status, {"Content-Type": "application/json"},
                   json.dumps(obj).encode())

    @classmethod
    def xml(cls, text: str, status: int = 200) -> "Response":
        return cls(status, {"Content-Type": "application/xml"}, text.encode())


class _Route:
    def __init__(self, method: str, pattern: str, handler, queries: dict | None):
        self.method = method
        self.parts = [p for p in pattern.split("/") if p != ""]
        self.wildcard = (self.parts[-1][1:]
                         if self.parts and self.parts[-1].startswith("*") else None)
        self.absolute = pattern == "/"
        self.handler = handler
        self.queries = queries or {}
        # more literal segments + more query conditions bind tighter
        self.rank = (sum(1 for p in self.parts if not p.startswith(":")),
                     len(self.queries))

    def match(self, method: str, path_parts: list[str],
              query: dict[str, list[str]]) -> dict | None:
        if method != self.method:
            return None
        if self.absolute:
            return {} if not path_parts else None
        if len(path_parts) != len(self.parts):
            # a trailing *param swallows extra segments (objectnode object
            # keys) but never matches empty — /b must not match /:bucket/*key
            if not (self.parts and self.parts[-1].startswith("*")
                    and len(path_parts) >= len(self.parts)):
                return None
        params: dict[str, str] = {}
        for i, spec in enumerate(self.parts):
            if spec.startswith("*"):
                params[spec[1:]] = "/".join(path_parts[i:])
                break
            if i >= len(path_parts):
                return None
            if spec.startswith(":"):
                params[spec[1:]] = path_parts[i]
            elif spec != path_parts[i]:
                return None
        for k, want in self.queries.items():
            got = query.get(k)
            if got is None:
                return None
            if want is not None and (not got or got[0] != want):
                return None
        return params


class Router:
    def __init__(self):
        self._routes: list[_Route] = []
        self.middleware: list = []  # callables: (request, next) -> Response

    def handle(self, method: str, pattern: str, handler, queries: dict | None = None):
        self._routes.append(_Route(method.upper(), pattern, handler, queries))
        self._routes.sort(key=lambda r: r.rank, reverse=True)

    def get(self, pattern: str, handler, **kw):
        self.handle("GET", pattern, handler, **kw)

    def post(self, pattern: str, handler, **kw):
        self.handle("POST", pattern, handler, **kw)

    def put(self, pattern: str, handler, **kw):
        self.handle("PUT", pattern, handler, **kw)

    def delete(self, pattern: str, handler, **kw):
        self.handle("DELETE", pattern, handler, **kw)

    def head(self, pattern: str, handler, **kw):
        self.handle("HEAD", pattern, handler, **kw)

    def dispatch(self, req: Request) -> Response:
        from chubaofs_tpu.rpc.errors import HTTPError

        parts = [p for p in req.path.split("/") if p != ""]
        chosen = None
        for route in self._routes:
            params = route.match(req.method, parts, req.query)
            if params is not None:
                # wildcard params keep the trailing slash (S3 dir-marker keys)
                if (route.wildcard and req.path.endswith("/")
                        and params.get(route.wildcard)):
                    params[route.wildcard] += "/"
                chosen = (route, params)
                break

        def run(r: Request) -> Response:
            if chosen is None:
                return Response(404, {}, b'{"error":"no route"}')
            r.params = chosen[1]
            try:
                out = chosen[0].handler(r)
            except HTTPError as e:
                return Response(e.status, {"Content-Type": "application/json"},
                                e.body())
            if isinstance(out, Response):
                return out
            if out is None:
                return Response(200)
            if isinstance(out, (bytes, bytearray)):
                return Response(200, {}, bytes(out))
            return Response.json(out)

        handler = run
        for mw in reversed(self.middleware):
            handler = (lambda m, nxt: lambda r: m(r, nxt))(mw, handler)
        try:
            return handler(req)
        except HTTPError as e:
            return Response(e.status, {"Content-Type": "application/json"}, e.body())
        except Exception as e:  # handler/middleware bug -> 500, never a dead socket
            body = json.dumps({"error": f"{type(e).__name__}: {e}",
                               "code": "InternalServerError"}).encode()
            return Response(500, {"Content-Type": "application/json"}, body)


def parse_request(method: str, target: str, headers, body: bytes,
                  remote: str = "-") -> Request:
    parsed = urllib.parse.urlsplit(target)
    query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
    hdrs = {k.lower(): v for k, v in headers.items()}
    return Request(method.upper(), urllib.parse.unquote(parsed.path), query,
                   hdrs, body, remote=remote, raw_query=parsed.query)
