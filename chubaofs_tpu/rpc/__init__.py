"""HTTP RPC framework (blobstore/common/rpc analog).

Router + typed errors + server + middleware (auditlog, shared-secret auth,
crc-protected bodies) + a small retrying client. Serves every HTTP surface in
the framework: blobstore gateways, objectnode S3, authnode, console, master
admin API.
"""

from chubaofs_tpu.rpc.errors import HTTPError, err_response
from chubaofs_tpu.rpc.router import Request, Response, Router
from chubaofs_tpu.rpc.server import RPCServer
from chubaofs_tpu.rpc.client import RPCClient
from chubaofs_tpu.rpc.pool import ConnectionPool, NullPool, default_pool

__all__ = ["HTTPError", "err_response", "Request", "Response", "Router",
           "RPCServer", "RPCClient", "ConnectionPool", "NullPool",
           "default_pool"]
