"""Typed RPC errors (blobstore/common/rpc error codes analog).

Reference counterpart: common/rpc's Error{Status,Code,Error} JSON body — every
blobstore service returns {"error": msg, "code": code} with an HTTP status;
clients re-hydrate the code. Kept: one exception type carrying status + code +
message, a JSON wire shape, and the well-known code table subset the rebuilt
services use.
"""

from __future__ import annotations

import json


class HTTPError(Exception):
    def __init__(self, status: int, code: str = "", msg: str = ""):
        super().__init__(msg or code or str(status))
        self.status = status
        self.code = code or str(status)
        self.msg = msg or code

    def body(self) -> bytes:
        return json.dumps({"error": self.msg, "code": self.code}).encode()

    @classmethod
    def from_body(cls, status: int, body: bytes) -> "HTTPError":
        try:
            d = json.loads(body.decode() or "{}")
            return cls(status, d.get("code", str(status)), d.get("error", ""))
        except (ValueError, AttributeError):
            return cls(status, str(status), body[:200].decode("utf-8", "replace"))


def err_response(status: int, code: str = "", msg: str = ""):
    raise HTTPError(status, code, msg)


# well-known codes (subset of blobstore/common/rpc/error codes)
CodeBadRequest = "BadRequest"
CodeNotFound = "NotFound"
CodeForbidden = "Forbidden"
CodeUnauthorized = "Unauthorized"
CodeConflict = "Conflict"
CodeInternal = "InternalServerError"
CodeCRCMismatch = "CrcMismatch"
CodeServiceUnavailable = "ServiceUnavailable"
