"""Event-loop packet server core — the thread-per-connection replacement.

Reference counterpart: Go's netpoller gives repl/repl_protocol.go a goroutine
pair per connection for free; at hundreds of concurrent clients a Python port
paying a full OS thread (8 MiB stack, GIL churn, scheduler pressure) per
connection hits the thread wall long before the network saturates (ROADMAP
item 5). This module is the event loop we have to build ourselves:

  * ONE acceptor thread owns the listener and deals new connections to loop
    shards round-robin;
  * N **loop shards** (`CFS_EVLOOP_SHARDS`), each a single thread owning a
    `selectors` instance and every connection registered on it. Sockets are
    non-blocking; each connection runs an incremental framing state machine
    (proto/packet.PacketFramer or the raft frame reader) that preallocates
    exactly the bytes the next stage needs and fills them with `recv_into` —
    the zero-copy receive discipline of the blocking path, resumable across
    partial reads;
  * a **write queue per connection** with backpressure: a reply takes an
    opportunistic direct non-blocking `sendmsg` from the worker when the
    queue is empty (ordering is the sender's, and the common case skips
    the wake-pipe round trip); any remainder is queued as iovecs and
    flushed by the owning shard under EVENT_WRITE. When either per-conn
    buffer — replies for a slow reader, or parsed requests ahead of a slow
    handler — crosses the high-water mark the shard STOPS READING from
    that connection (and only that one) until both drain below half — one
    wedged client costs itself throughput, never its shard neighbors;
  * a **bounded worker pool** (`CFS_EVLOOP_WORKERS` daemon threads) that
    dispatch hops to, so the existing blocking `dispatch(pkt) -> Packet`
    handlers (datanode operate + chain replicate, metanode raft submit)
    never stall a loop shard. Per-connection dispatch stays SERIAL and
    in-order — the pipelined write burst sdk/stream.py sends on one socket
    is acked in send order, exactly like the thread-per-conn path — while
    distinct connections share the pool.

Trace spans survive the loop→worker hop by construction: the trace carrier
rides the packet's arg blob, and the span is minted inside the handler on
the worker thread (datanode._dispatch / MetaService._handle are unchanged).
The hop itself is metered: `cfs_evloop_dispatch` observes parse-to-reply
latency including queue wait.

Instrumentation: `cfs_evloop_conns{srv,shard}` live connections per shard,
`cfs_evloop_dispatch{srv}` handler latency, `cfs_evloop_backpressure{srv,
shard}` pause events. Chaos: the `evloop.dispatch` failpoint fires before
every handler call — `delay` injects service latency, `error` (a
ConnectionError) drops that connection, exactly like a link cut mid-op.

`CFS_EVLOOP=0` restores the threaded accept loops in data/repl.py,
meta/service.py, and raft/transport.py for A/B and rollback.
"""

from __future__ import annotations

import os
import queue
import selectors
import socket
import threading
import time
from collections import deque
from itertools import count, islice

from chubaofs_tpu import chaos
from chubaofs_tpu.proto.packet import PacketFramer, advance_iov, packet_iov
from chubaofs_tpu.utils.exporter import registry
from chubaofs_tpu.utils.locks import SanitizedLock


def evloop_enabled() -> bool:
    """The CFS_EVLOOP escape hatch: default ON, =0 restores the threaded
    path (checked at server start, so one process can A/B both)."""
    return os.environ.get("CFS_EVLOOP", "1").lower() not in ("0", "false", "off")


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


# process-wide id so several same-name servers (one process, many nodes in
# tests) never share a settable metric series
_INSTANCE_IDS = count()

# per-connection buffer high-water mark: one full extent packet plus
# headroom. Crossing it on EITHER side — replies queued for a slow reader
# (wq_bytes) or parsed requests awaiting a slow handler (inbox_bytes) —
# pauses READS from that connection until both drain below half; classic
# high/low-water backpressure, so neither direction can balloon memory.
_WRITE_HWM = 8 << 20

# loop-lag heartbeat: each shard expects to pass through select() at least
# every _LAG_TICK seconds; how LATE the tick actually fires is the shard's
# scheduling lag — the saturation signal (a shard stuck flushing one conn's
# burst, or starved by the GIL, shows up as lag long before conns error).
# The tick equals the idle select timeout, so an IDLE shard's wakeup
# cadence is exactly what it was before the tick existed — the lag meter
# adds observations, not wakeups.
_LAG_TICK = 0.5
# lag histogram buckets in MILLISECONDS: sub-tick jitter up to multi-second
# stalls (the same decade ladder the lock-hold histogram uses)
LAG_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
               250.0, 1000.0)


class _Conn:
    """One registered connection: framing state + write queue + dispatch
    queue. Owned by exactly one loop shard; workers touch only the queues,
    under the shard lock."""

    __slots__ = ("sock", "fd", "framer", "buf", "view", "got", "wq",
                 "wq_bytes", "inbox", "inbox_bytes", "msg_bytes",
                 "dispatching", "paused", "closed", "events", "greedy",
                 "close_after")

    def __init__(self, sock: socket.socket, framer):
        self.sock = sock
        self.fd = sock.fileno()
        self.framer = framer
        self.buf: bytearray | None = None   # current stage buffer
        self.view: memoryview | None = None
        self.got = 0
        self.wq: deque = deque()            # pending outbound memoryviews
        self.wq_bytes = 0
        self.inbox: deque = deque()         # (msg, wire bytes) awaiting dispatch
        self.inbox_bytes = 0                # wire bytes parked in inbox
        self.msg_bytes = 0                  # stages consumed by the current msg
        self.dispatching = False            # a worker is draining inbox
        self.paused = False                 # reads stopped by backpressure
        self.closed = False
        self.events = 0                     # currently registered event mask
        # greedy framers (variable-length protocols: HTTP) consume whatever
        # arrived via feed_chunk() instead of the exact-size feed() stages
        self.greedy = bool(getattr(framer, "greedy", False))
        # a reply asked for connection teardown once it is fully flushed
        # (HTTP Connection: close); reads stop immediately
        self.close_after = False

    def arm_stage(self) -> None:
        n = self.framer.need()
        self.buf = bytearray(n)
        self.view = memoryview(self.buf)
        self.got = 0


class _Workers:
    """Bounded pool of daemon worker threads over one shared task queue,
    spawned LAZILY up to the bound: a process running several servers
    (MiniCluster's 3 datanodes + 3 metanodes) would otherwise idle at
    n-per-server fixed threads — the very cost the evloop removes. Tasks
    are per-connection drain loops, so the queue never holds more than one
    entry per live connection; daemon threads match the threaded path's
    shutdown semantics (a blocked handler cannot hang process exit)."""

    _SENTINEL = None

    def __init__(self, n: int, name: str):
        self.n = n
        self._name = name
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._lock = SanitizedLock(name=f"evloop.workers.{name}")

    def submit(self, fn) -> None:
        self._q.put(fn)
        with self._lock:
            if self._idle or len(self._threads) >= self.n:
                return
            t = threading.Thread(
                target=self._run, daemon=True,
                name=f"evw-{self._name}-{len(self._threads)}")
            self._threads.append(t)
        t.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            try:
                fn = self._q.get()
            finally:
                with self._lock:
                    self._idle -= 1
            if fn is self._SENTINEL:
                return
            try:
                fn()
            except Exception:
                pass  # a task's errors are handled at its conn; never kill a worker

    def stop(self) -> None:
        with self._lock:
            n_live = len(self._threads)
        for _ in range(n_live):
            self._q.put(self._SENTINEL)


class _LoopShard(threading.Thread):
    """One selector + the connections it owns. Everything that touches the
    selector runs HERE; cross-thread requests (new conns, queued writes,
    closes) arrive as closures through the inbox + wake pipe. Socket READS
    are loop-thread-only; writes are loop-thread OR a worker's direct send
    under the shard lock with an empty write queue (see send())."""

    def __init__(self, server: "EvloopServer", idx: int):
        super().__init__(daemon=True, name=f"evloop-{server.name}-{idx}")
        self.server = server
        self.idx = idx
        self.sel = selectors.DefaultSelector()
        self.conns: dict[int, _Conn] = {}
        self._rx, self._tx = os.pipe()
        os.set_blocking(self._tx, False)
        self.sel.register(self._rx, selectors.EVENT_READ, None)
        self._inbox: deque = deque()
        self._lock = SanitizedLock(name=f"evloop.shard.{server.name}")
        self._woken = False
        self._pipe_closed = False
        # the gauge is SET (not added), so several same-name servers in one
        # process (MiniCluster's 3 datanodes) would clobber a shared series
        # and the first stop() would unregister it for the survivors — the
        # labels carry a process-unique instance id
        self.gauge_labels = {"srv": server.name, "shard": str(idx),
                             "inst": str(server.instance)}
        self._gauge = server.reg.gauge("conns", self.gauge_labels)
        self._bp = server.reg.counter(
            "backpressure", {"srv": server.name, "shard": str(idx)})
        # cfs_evloop_loop_lag_ms: select-wakeup delay vs the expected tick —
        # the per-shard saturation histogram cfs-top reads
        self._lag = server.reg.summary(
            "loop_lag_ms", {"srv": server.name, "shard": str(idx)},
            buckets=LAG_BUCKETS)

    # -- cross-thread entry points --------------------------------------------

    def post(self, fn) -> bool:
        """Run `fn` on the loop thread (workers and the acceptor call this).
        The pipe write happens under the lock that also serializes teardown's
        close — a late post can never hit a recycled fd number. Returns False
        once teardown has run: the loop will never drain the inbox again, so
        enqueueing would silently drop the closure."""
        with self._lock:
            if self._pipe_closed or self.server.stopping.is_set():
                return False  # loop exited (or is exiting): nothing drains
            self._inbox.append(fn)
            if not self._woken:
                self._woken = True
                try:
                    os.write(self._tx, b"\0")
                except (BlockingIOError, OSError):
                    pass  # pipe full: a wakeup is already pending
        return True

    def wake(self) -> None:
        """Nudge the loop out of select() without enqueueing work — stop()'s
        see-the-flag-now signal (post() refuses once stopping is set)."""
        with self._lock:
            if self._pipe_closed or self._woken:
                return
            self._woken = True
            try:
                os.write(self._tx, b"\0")
            except (BlockingIOError, OSError):
                pass

    def adopt(self, sock: socket.socket) -> None:
        if not self.post(lambda: self._register(sock)):
            # accepted during the stop window onto a torn-down shard: the
            # _register closure will never run — close instead of leaking
            # the fd and hanging the client
            try:
                sock.close()
            except OSError:
                pass

    # -- loop ------------------------------------------------------------------

    def run(self) -> None:
        # loop-lag tick: how late each pass through select() fires vs the
        # _LAG_TICK deadline. An idle shard observes ~0; a shard pinned in
        # one pass (flushing a burst, a huge parse) records the stall.
        next_tick = time.monotonic() + _LAG_TICK
        while not self.server.stopping.is_set():
            now = time.monotonic()
            if now >= next_tick:
                self._lag.observe((now - next_tick) * 1e3)
                next_tick = now + _LAG_TICK
            for key, events in self.sel.select(
                    timeout=min(_LAG_TICK, max(0.0, next_tick - now))):
                if key.data is None:  # wake pipe
                    try:
                        os.read(self._rx, 4096)
                    except OSError:
                        pass
                    with self._lock:
                        self._woken = False
                        todo = list(self._inbox)
                        self._inbox.clear()
                    for fn in todo:
                        try:
                            fn()
                        except Exception:
                            pass  # a closure's errors end at its conn; the
                            # shard must outlive any one connection
                    continue
                conn: _Conn = key.data
                try:
                    if events & selectors.EVENT_WRITE:
                        self._flush(conn)
                    if events & selectors.EVENT_READ and not conn.closed:
                        self._readable(conn)
                except Exception:
                    # any unexpected per-connection error (e.g. a failed
                    # stage-buffer allocation) is conn-fatal, never
                    # shard-fatal: a dead shard thread would orphan every
                    # conn it owns AND everything the acceptor keeps dealing
                    self._close(conn)
        self._teardown()

    def _teardown(self) -> None:
        for conn in list(self.conns.values()):
            self._close(conn)
        try:
            self.sel.unregister(self._rx)
        except (KeyError, ValueError):
            pass
        with self._lock:
            self._pipe_closed = True
            os.close(self._rx)
            os.close(self._tx)
        self.sel.close()

    def _register(self, sock: socket.socket) -> None:
        try:
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            framer = self.server.framer_factory()
            if hasattr(framer, "on_connect"):
                # variable-length protocols want per-conn identity (the HTTP
                # framer stamps each request with the peer address)
                framer.on_connect(sock)
            conn = _Conn(sock, framer)
            conn.arm_stage()
            conn.events = selectors.EVENT_READ
            self.sel.register(sock, conn.events, conn)
        except (OSError, ValueError):
            try:
                sock.close()
            except OSError:
                pass
            return
        self.conns[conn.fd] = conn
        self._gauge.set(len(self.conns))

    def _set_events(self, conn: _Conn, events: int) -> None:
        if conn.closed or events == conn.events:
            return
        prev, conn.events = conn.events, events
        try:
            if not events:
                # fully paused with nothing to write: deregister rather than
                # poll EVENT_WRITE on an always-writable socket
                self.sel.unregister(conn.sock)
            elif not prev:
                self.sel.register(conn.sock, events, conn)
            else:
                self.sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            self._close(conn)

    def _close(self, conn: _Conn) -> None:
        # conn state is shared with workers (send/_drain check closed and
        # mutate the queues under the shard lock); mutate it under the same
        # lock so a racing worker can't pop from a cleared inbox or park
        # reply bytes on a dead conn
        with self._lock:
            if conn.closed:
                return
            conn.closed = True
            conn.wq.clear()
            conn.wq_bytes = 0
            conn.inbox.clear()
            conn.inbox_bytes = 0
        self.conns.pop(conn.fd, None)
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._gauge.set(len(self.conns))

    # -- read side -------------------------------------------------------------

    # per-wakeup read budget: a firehose sender on one connection yields the
    # shard back to its neighbors every budget's worth; the level-triggered
    # selector re-reports the remainder immediately
    _READ_BUDGET = 1 << 20

    def _readable(self, conn: _Conn) -> None:
        if conn.greedy:
            return self._readable_greedy(conn)
        consumed = 0
        while consumed < self._READ_BUDGET and not conn.paused \
                and not conn.closed:
            if conn.got < len(conn.buf):
                try:
                    n = conn.sock.recv_into(conn.view[conn.got:])
                except (BlockingIOError, InterruptedError):
                    return
                except OSError:
                    self._close(conn)
                    return
                if n == 0:  # peer closed
                    self._close(conn)
                    return
                conn.got += n
                consumed += n
                if conn.got < len(conn.buf):
                    return  # partial stage: resume on the next EVENT_READ
            try:
                msg = conn.framer.feed(conn.buf)
            except Exception:
                self._close(conn)  # bad magic/frame: hostile or corrupt
                return
            conn.msg_bytes += len(conn.buf)
            conn.arm_stage()
            if msg is None:
                continue
            nbytes, conn.msg_bytes = conn.msg_bytes, 0
            self._enqueue(conn, msg, nbytes)

    def _readable_greedy(self, conn: _Conn) -> None:
        """Read side for greedy (variable-length) framers: recv into the
        fixed scratch buffer and hand the framer whatever arrived; it
        buffers internally (bounded — an oversized header block is ITS
        error) and returns every message the chunk completed, so one recv
        can surface a whole pipelined burst."""
        consumed = 0
        while consumed < self._READ_BUDGET and not conn.paused \
                and not conn.closed and not conn.close_after:
            try:
                n = conn.sock.recv_into(conn.view)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._close(conn)
                return
            if n == 0:  # peer closed
                self._close(conn)
                return
            consumed += n
            try:
                msgs = conn.framer.feed_chunk(conn.view[:n])
            except Exception:
                self._close(conn)  # hostile or corrupt framing
                return
            for msg, nbytes in msgs:
                self._enqueue(conn, msg, nbytes)
            if n < len(conn.buf):
                return  # socket drained for now

    def _enqueue(self, conn: _Conn, msg, nbytes: int) -> None:
        """Park a parsed message for dispatch (shared by both read paths)."""
        newly_paused = False
        with self._lock:
            conn.inbox.append((msg, nbytes))
            conn.inbox_bytes += nbytes
            if conn.inbox_bytes > self.server.write_hwm \
                    and not conn.paused:
                # fast sender, slow handler: parsed requests are piling
                # up — stop READING so the flood stays in the kernel
                # socket buffer (TCP backpressure to the peer), like the
                # threaded path's one-recv-per-dispatch loop bounded it.
                # paused flips INSIDE the append's critical section: a
                # worker popping this very message must observe it, or
                # its low-water resume check can race the pause and
                # leave the conn read-paused forever
                conn.paused = True
                newly_paused = True
            start = not conn.dispatching
            if start:
                conn.dispatching = True
        if start:
            self.server.workers.submit(lambda c=conn: self._drain(c))
        if newly_paused:
            self._bp.add()
            self._emit_bp("backpressure_on", "inbox")
            self._set_events(conn, conn.events & ~selectors.EVENT_READ)

    # -- dispatch (worker threads) --------------------------------------------

    def _drain(self, conn: _Conn) -> None:
        """Serial per-connection dispatch: pop → handle → queue reply, until
        the inbox is empty. Runs on a worker thread; in-order replies fall
        out of the single-drainer-per-conn invariant."""
        while True:
            with self._lock:
                if not conn.inbox or conn.closed:
                    conn.dispatching = False
                    return
                msg, nbytes = conn.inbox.popleft()
                conn.inbox_bytes -= nbytes
                resume = conn.paused and \
                    conn.inbox_bytes <= self.server.write_hwm // 2
            if resume:
                # paused reads may be waiting on THIS drain (inbox pressure);
                # the loop thread re-checks both watermarks before resuming
                self.post(lambda c=conn: self._maybe_resume(c))
            t0 = time.perf_counter()
            try:
                chaos.failpoint("evloop.dispatch")
                reply = self.server.on_message(msg)
                self.server.dispatch_tp.observe(time.perf_counter() - t0)
                if reply is not None:
                    self.send(conn, self.server.encode(reply),
                              close_after=self.server.close_reply(reply))
            except Exception:
                # a handler- OR encode-escaping error is conn-fatal (the
                # threaded path's serve thread died the same way); an error
                # swallowed with dispatching still True would wedge the conn
                self.post(lambda c=conn: self._close(c))
                with self._lock:
                    conn.dispatching = False
                return

    # -- write side ------------------------------------------------------------

    def send(self, conn: _Conn, iov: list, close_after: bool = False) -> None:
        """Send an iovec on `conn` (worker-thread safe). Fast path: when the
        write queue is empty — no flush in flight, ordering is ours — try a
        direct non-blocking `sendmsg` right here under the shard lock. Most
        replies fit the kernel buffer whole, so the common case skips the
        wake-pipe → select → flush round trip entirely AND spreads the send
        syscalls over the worker pool instead of serializing them through
        the loop thread. Any remainder (EAGAIN/partial) is queued and the
        loop finishes it under EVENT_WRITE, same as the slow path.

        `close_after` tears the connection down once THIS iov is fully on
        the wire (HTTP `Connection: close`): reads stop immediately, the
        close itself waits for the flush."""
        total = sum(len(b) for b in iov)
        views = [memoryview(b) for b in iov]
        action = None
        with self._lock:
            if conn.closed:
                return
            if close_after:
                conn.close_after = True
            if not conn.wq and hasattr(conn.sock, "sendmsg"):
                try:
                    sent = conn.sock.sendmsg(views)
                except (BlockingIOError, InterruptedError):
                    sent = 0
                except OSError:
                    action = "close"
                    sent = total  # nothing left worth queuing
                if sent < total:
                    rest = advance_iov(views, sent)
                    conn.wq.extend(rest)
                    conn.wq_bytes += sum(len(v) for v in rest)
                    action = action or "flush"
                elif conn.close_after and action is None:
                    action = "close"  # reply fully on the wire: tear down now
            else:
                conn.wq.extend(views)
                conn.wq_bytes += total
                action = "flush"
        # post() takes the shard lock itself, so both follow-ups run after it
        if action == "flush":
            self.post(lambda c=conn: self._after_send(c))
        elif action == "close":
            self.post(lambda c=conn: self._close(c))

    def _after_send(self, conn: _Conn) -> None:
        if conn.closed:
            return
        self._flush(conn)
        if conn.wq_bytes > self.server.write_hwm and not conn.paused:
            # slow reader: its replies pile up — stop READING from it so the
            # pipeline quits growing, keep draining what's queued. Neighbors
            # on this shard never notice.
            conn.paused = True
            self._bp.add()
            self._emit_bp("backpressure_on", "write_queue")
            self._set_events(conn, selectors.EVENT_WRITE)

    def _flush(self, conn: _Conn) -> None:
        try:
            while conn.wq:
                with self._lock:
                    batch = list(islice(conn.wq, 64))
                try:
                    sent = conn.sock.sendmsg(batch) if hasattr(conn.sock, "sendmsg") \
                        else conn.sock.send(batch[0])
                except (BlockingIOError, InterruptedError):
                    break
                with self._lock:
                    if conn.closed:
                        return
                    conn.wq_bytes -= sent
                    # the loop thread is the only popper and direct sends
                    # only run on an EMPTY queue, so `batch` is still the
                    # exact head of wq: replace it with its unsent remainder
                    rest = advance_iov(batch, sent)
                    for _ in range(len(batch)):
                        conn.wq.popleft()
                    conn.wq.extendleft(reversed(rest))
        except OSError:
            self._close(conn)
            return
        if conn.wq:
            self._set_events(conn, conn.events | selectors.EVENT_WRITE)
        else:
            if conn.close_after:
                # the Connection: close reply is fully flushed — teardown
                self._close(conn)
                return
            self._set_events(conn, conn.events & ~selectors.EVENT_WRITE)
        self._maybe_resume(conn)

    def _maybe_resume(self, conn: _Conn) -> None:
        """Loop-thread re-arm of reads once BOTH watermarks (reply queue and
        parsed-request inbox) are below half — the low-water side of the
        high/low hysteresis."""
        if conn.closed or not conn.paused or conn.close_after:
            return
        with self._lock:
            low = conn.wq_bytes <= self.server.write_hwm // 2 \
                and conn.inbox_bytes <= self.server.write_hwm // 2
        if low:
            conn.paused = False
            self._emit_bp("backpressure_off", "low_water")
            self._set_events(conn, conn.events | selectors.EVENT_READ)

    def _emit_bp(self, etype: str, reason: str) -> None:
        """Backpressure engage/release -> timeline. A TRANSITION record (the
        hysteresis means one flip pair per pressure episode per conn, not
        per op); emit() never raises, so the loop/worker paths stay safe."""
        from chubaofs_tpu.utils import events

        events.emit(etype,
                    events.SEV_WARNING if etype == "backpressure_on"
                    else events.SEV_INFO,
                    entity=f"{self.server.name}/shard{self.idx}",
                    detail={"srv": self.server.name, "shard": self.idx,
                            "reason": reason})


class EvloopServer:
    """The server core: acceptor + shards + workers around an accepted-socket
    handler. `on_message(msg)` runs on a worker thread (blocking is fine) and
    returns a reply to encode, or None for fire-and-forget protocols.

    Defaults serve the shared binary Packet protocol (framer_factory =
    PacketFramer, encode = packet_iov); the raft transport passes its own
    frame reader and encode=None."""

    def __init__(self, listener: socket.socket, on_message, *,
                 name: str = "pkt", framer_factory=PacketFramer,
                 encode=packet_iov, shards: int | None = None,
                 workers: int | None = None, write_hwm: int | None = None,
                 close_reply=None):
        self.listener = listener
        self.on_message = on_message
        self.name = name
        self.framer_factory = framer_factory
        self.encode = encode or (lambda reply: [reply])
        # does THIS reply end its connection? (HTTP Connection: close); the
        # packet protocols never do — every conn outlives every reply
        self.close_reply = close_reply or (lambda reply: False)
        self.reg = registry("evloop")
        self.dispatch_tp = self.reg.summary("dispatch", {"srv": name})
        self.write_hwm = write_hwm if write_hwm is not None \
            else _env_int("CFS_EVLOOP_WRITEBUF", _WRITE_HWM)
        self.stopping = threading.Event()
        self.instance = next(_INSTANCE_IDS)  # disambiguates same-name
        # servers sharing this process's metric registry
        n_shards = shards or _env_int("CFS_EVLOOP_SHARDS", 2)
        n_workers = workers or _env_int("CFS_EVLOOP_WORKERS", 16)
        self.workers = _Workers(n_workers, name)
        self.shards = [_LoopShard(self, i) for i in range(n_shards)]
        self._next = 0
        self._accept_thread = threading.Thread(
            target=self._accept, daemon=True, name=f"evloop-{name}-accept")

    def start(self) -> None:
        for s in self.shards:
            s.start()
        self._accept_thread.start()

    def _accept(self) -> None:
        while not self.stopping.is_set():
            try:
                sock, _ = self.listener.accept()
            except OSError:
                return
            self.shards[self._next % len(self.shards)].adopt(sock)
            self._next += 1

    def stop(self) -> None:
        """Stop accepting, close every connection, release the workers. The
        caller owns (and closes) the listener, same as the threaded path."""
        self.stopping.set()
        for s in self.shards:
            s.wake()  # not post(): post refuses once stopping is set, and a
            # sleeping shard must still see the flag now, not a select
            # timeout later
        self.workers.stop()
        for s in self.shards:
            s.join(timeout=2.0)
            # a closed server's series must not render as a live idle shard
            self.reg.unregister("conns", s.gauge_labels)
