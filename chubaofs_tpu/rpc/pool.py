"""Keep-alive HTTP connection pool — the RPC hop's transport cache.

Reference counterpart: util/connpool (the packet-TCP pool the SDK stream
already rides, utils/conn_pool.py) applied to the HTTP control/data hops:
CubeFS's access layer streams stripes over a connection-pooled transport
instead of paying a TCP connect per request (SURVEY §blobstore). Same
policy here for `http.client.HTTPConnection`:

  * per-host bounded idle list (newest-first reuse, so a hot host keeps one
    warm socket instead of round-robining N cold ones),
  * idle TTL — a socket parked past the TTL is closed, not trusted (the
    server side may have torn it down),
  * health-evict — a connection that errored is closed on check-in, never
    re-parked,
  * thread-safe checkout (the RPCClient is shared across pool workers).

Every `HTTPConnection` in the process is constructed HERE (obslint enforces
it): the unpooled path is `NullPool`, which mints a fresh connection per
checkout and closes on check-in — so the pooled/unpooled A/B in perfbench
flips an object, not a code path.

Counters ride `registry("rpc")` (cfs_rpc_pool_*): reuse / miss / evict
{reason}, so a bench or `cfs-stat` diff shows the realized hit rate. The
`rpc.pool.checkout` failpoint lets chaos wedge or fail the checkout itself.
"""

from __future__ import annotations

import http.client
import os
import time

from chubaofs_tpu import chaos
from chubaofs_tpu.utils.exporter import registry
from chubaofs_tpu.utils.locks import SanitizedLock


def _counter(name: str, labels: dict | None = None):
    return registry("rpc").counter(name, labels)


class ConnectionPool:
    """Per-host keep-alive HTTPConnection pool.

    checkout(host) -> (conn, reused); check the conn back in with
    checkin(host, conn, ok=...) — broken/doubtful conns are closed and
    counted as evictions, healthy ones are parked for reuse (bounded,
    newest-first)."""

    def __init__(self, max_idle_per_host: int | None = None,
                 idle_ttl: float | None = None, timeout: float = 30.0):
        if max_idle_per_host is None:
            max_idle_per_host = int(os.environ.get("CFS_RPC_POOL_SIZE", "4"))
        if idle_ttl is None:
            idle_ttl = float(os.environ.get("CFS_RPC_POOL_TTL", "30"))
        self.max_idle_per_host = max(1, max_idle_per_host)
        self.idle_ttl = idle_ttl
        self.timeout = timeout
        self._idle: dict[str, list[tuple[http.client.HTTPConnection, float]]] = {}
        self._lock = SanitizedLock(name="rpc.pool")

    def checkout(self, host: str,
                 timeout: float | None = None) -> tuple[http.client.HTTPConnection, bool]:
        """A connection to `host`: a parked keep-alive one when available
        (reused=True), else a fresh one. TTL-expired parked conns are
        evicted on the way."""
        chaos.failpoint("rpc.pool.checkout")
        now = time.monotonic()
        reuse = None
        expired: list[http.client.HTTPConnection] = []
        with self._lock:
            bucket = self._idle.get(host)
            while bucket:
                conn, parked = bucket.pop()  # newest-first: warmest socket
                if now - parked <= self.idle_ttl:
                    reuse = conn
                    break
                # close OUTSIDE the lock: tearing down a dead socket can
                # block for ~100ms+, and every other checkout would queue
                # behind it (found by the cfs_lock_hold_ms audit)
                expired.append(conn)
        for conn in expired:
            conn.close()
            _counter("pool_evict", {"reason": "idle_ttl"}).add()
        if reuse is not None:
            if timeout is not None:
                # the parked socket keeps its creator's timeout;
                # rebind to THIS caller's budget
                reuse.timeout = timeout
                if reuse.sock is not None:
                    reuse.sock.settimeout(timeout)
            _counter("pool_reuse").add()
            return reuse, True
        _counter("pool_miss").add()
        conn = http.client.HTTPConnection(
            host, timeout=self.timeout if timeout is None else timeout)
        return conn, False

    def checkin(self, host: str, conn: http.client.HTTPConnection,
                ok: bool = True, reason: str = "error") -> None:
        """Park a healthy connection for reuse; close-and-count anything
        doubtful (IO error, server said Connection: close, response not
        fully read)."""
        if not ok:
            conn.close()
            _counter("pool_evict", {"reason": reason}).add()
            return
        with self._lock:
            bucket = self._idle.setdefault(host, [])
            if len(bucket) >= self.max_idle_per_host:
                # displace the OLDEST parked conn, keep the one that just
                # served a request — the warmest socket stays available
                old, _ = bucket.pop(0)
            else:
                old = None
            bucket.append((conn, time.monotonic()))
        if old is not None:
            old.close()
            _counter("pool_evict", {"reason": "overflow"}).add()

    def idle_count(self, host: str | None = None) -> int:
        with self._lock:
            if host is not None:
                return len(self._idle.get(host, ()))
            return sum(len(b) for b in self._idle.values())

    def flush_host(self, host: str) -> int:
        """Evict every parked conn for one host. Called when a reused conn
        proved stale: its parked siblings are OLDER sockets to the same
        (restarted) server and are dead too — draining them one counted
        retry at a time could exhaust a caller's whole retry budget."""
        with self._lock:
            bucket = self._idle.pop(host, [])
        for conn, _ in bucket:
            conn.close()
        if bucket:
            _counter("pool_evict", {"reason": "stale"}).add(len(bucket))
        return len(bucket)

    def close(self) -> None:
        """Close every parked connection (not counted as evictions: shutdown
        is lifecycle, not health)."""
        with self._lock:
            for bucket in self._idle.values():
                for conn, _ in bucket:
                    conn.close()
            self._idle.clear()


class NullPool:
    """Connect-per-request transport with the pool's interface: the unpooled
    control in A/B benches, and the opt-out for callers that must not hold
    sockets (CFS_RPC_POOL=0)."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def checkout(self, host: str,
                 timeout: float | None = None) -> tuple[http.client.HTTPConnection, bool]:
        chaos.failpoint("rpc.pool.checkout")
        conn = http.client.HTTPConnection(
            host, timeout=self.timeout if timeout is None else timeout)
        return conn, False

    def checkin(self, host: str, conn: http.client.HTTPConnection,
                ok: bool = True, reason: str = "error") -> None:
        conn.close()

    def idle_count(self, host: str | None = None) -> int:
        return 0

    def flush_host(self, host: str) -> int:
        return 0

    def close(self) -> None:
        pass


_default: ConnectionPool | NullPool | None = None
_default_lock = SanitizedLock(name="rpc.pool.default")


def default_pool() -> ConnectionPool | NullPool:
    """The process-wide pool every RPCClient rides unless handed its own.
    CFS_RPC_POOL=0 makes it a NullPool (connect-per-request everywhere)."""
    global _default
    with _default_lock:
        if _default is None:
            if os.environ.get("CFS_RPC_POOL", "1") == "0":
                _default = NullPool()
            else:
                _default = ConnectionPool()
        return _default


def reset_default_pool() -> None:
    """Close and forget the process pool (tests; daemon shutdown)."""
    global _default
    with _default_lock:
        if _default is not None:
            _default.close()
            _default = None
