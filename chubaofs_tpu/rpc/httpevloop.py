"""HTTP/1.1 serving core on the evloop shard/worker machinery.

Reference counterpart: the reference object gateway multiplexes thousands of
keep-alive S3 connections on Go's netpoller; our HTTP daemons (objectnode,
masters, console, the access gateway) rode a thread-per-request
ThreadingHTTPServer — the slice PR 8 explicitly deferred after proving the
packet-TCP evloop stays flat at 1024 clients where threads collapse ~9x.
This module closes it: the SAME acceptor/shard/worker core (rpc/evloop.py)
serving HTTP/1.1 instead of binary packets.

  * `HttpFramer` is a GREEDY framer (evloop's variable-length read mode):
    the shard recvs into a fixed scratch buffer and hands the framer
    whatever arrived; it accumulates header bytes into a BOUNDED block
    (`MAX_HEADER_BYTES` — one hostile megabyte header line can never
    balloon memory) and, once `Content-Length` is known and bounds-checked
    against the packet layer's `MAX_DATA_LEN` precedent, preallocates
    exactly the body it was promised. An absurd Content-Length is rejected
    BEFORE any allocation (413), an oversized header block at the bound
    (431) — both answered with a real HTTP error response, then the
    connection closes.
  * Keep-alive with PIPELINED in-order responses falls out of the evloop's
    per-connection serial dispatch invariant: one recv can complete several
    requests; they dispatch one at a time on the worker pool and reply in
    arrival order, exactly like the packet path's write bursts.
  * Write-queue + inbox backpressure are inherited unchanged: a slow-reading
    client (or a flood ahead of a slow handler) crossing the high-water mark
    pauses THAT connection's reads only.
  * `Connection: close` (and HTTP/1.0 without keep-alive) rides the evloop's
    close-after-flush path: the reply fully drains, then the conn tears down.

`CFS_EVLOOP_HTTP=0` restores the threaded ThreadingHTTPServer path in
rpc/server.py for A/B and rollback — the same escape-hatch contract as
CFS_EVLOOP on the packet servers.

Not implemented (the daemons' HTTP dialect never uses them, matching the
threaded path's Content-Length-only body reads): chunked transfer encoding
(501), obs-fold header continuations (400), interim 100-continue responses
(the body is read and the final status answers; no client of ours waits).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from http.client import responses as _REASONS

from chubaofs_tpu.proto.packet import MAX_DATA_LEN
from chubaofs_tpu.rpc.evloop import EvloopServer

# bound on the request line + header block, the MAX_DATA_LEN precedent for
# the header side: large enough for any signed S3 request (sigv4 auth +
# amz headers are well under 8 KiB), small enough that a hostile client
# can't park memory on the server before auth even runs
MAX_HEADER_BYTES = 32 << 10
# request bodies share the packet layer's receive bound
MAX_BODY_BYTES = MAX_DATA_LEN
# scratch recv buffer per connection (the greedy framer's `need()`)
_SCRATCH = 64 << 10


def http_evloop_enabled() -> bool:
    """The CFS_EVLOOP_HTTP escape hatch: default ON, =0 restores the
    threaded ThreadingHTTPServer path (checked at server construction, so
    one process can A/B both)."""
    return os.environ.get("CFS_EVLOOP_HTTP", "1").lower() \
        not in ("0", "false", "off")


class HttpRequest:
    """One parsed request, the framer's message unit. `err` carries a
    prepared error reply for framing violations (oversized header, absurd
    Content-Length): the dispatcher answers it without touching the router
    and the connection closes."""

    __slots__ = ("method", "target", "headers", "body", "remote", "close",
                 "err")

    def __init__(self, method: str = "", target: str = "",
                 headers: dict | None = None, body: bytes = b"",
                 remote: str = "-", close: bool = False, err=None):
        self.method = method
        self.target = target
        self.headers = headers or {}
        self.body = body
        self.remote = remote
        self.close = close
        self.err = err  # (status, reason-body) tuple for framing errors


class HttpReply:
    """What dispatch returns to the evloop: encode_reply() turns it into a
    header-bytes + body iovec (partial sends resume via packet.advance_iov
    in the shared shard flush)."""

    __slots__ = ("status", "headers", "body", "head_only", "close")

    def __init__(self, status: int, headers: dict, body: bytes,
                 head_only: bool = False, close: bool = False):
        self.status = status
        self.headers = headers
        self.body = body
        self.head_only = head_only
        self.close = close


def encode_reply(reply: HttpReply) -> list:
    """HttpReply -> iovec. The status line + headers serialize into ONE
    bytes object; the body rides as its own element (no join of a multi-MiB
    GET payload into the header buffer)."""
    reason = _REASONS.get(reply.status, "Unknown")
    lines = [f"HTTP/1.1 {reply.status} {reason}"]
    has_cl = False
    for k, v in reply.headers.items():
        if k.lower() == "content-length":
            has_cl = True  # a handler-set Content-Length wins (HEAD
            # responses describe the body they didn't send)
        lines.append(f"{k}: {v}")
    if not has_cl:
        lines.append(f"Content-Length: {len(reply.body)}")
    if reply.close:
        lines.append("Connection: close")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
    if reply.head_only or not reply.body:
        return [head]
    return [head, reply.body]


class HttpFramer:
    """Incremental HTTP/1.1 request framer — the evloop's greedy-mode read
    state machine. Contract (rpc/evloop.py consumes it): `greedy` marks the
    variable-length mode, `need()` sizes the scratch buffer once, and
    `feed_chunk(view)` consumes whatever the recv produced, returning
    [(HttpRequest, wire_bytes), ...] for every request the chunk completed
    (pipelining surfaces several). Framing violations come back as
    HttpRequest.err messages — the server answers a real HTTP error before
    the connection closes — after which the framer is dead and discards
    input."""

    greedy = True

    def __init__(self):
        self._buf = bytearray()      # bounded unparsed-bytes accumulator
        self._scan = 0               # \r\n\r\n search resume offset
        self._body: bytearray | None = None
        self._body_got = 0
        self._head_bytes = 0         # wire size of the current head
        self._msg: HttpRequest | None = None
        self._dead = False
        self._remote = "-"

    def on_connect(self, sock: socket.socket) -> None:
        try:
            self._remote = sock.getpeername()[0]
        except (OSError, IndexError):
            self._remote = "-"

    def need(self) -> int:
        return _SCRATCH

    # -- chunk consumption -----------------------------------------------------

    def feed_chunk(self, data) -> list:
        """One recv's worth in, every completed request out. Head bytes
        accumulate in the bounded `_buf`; body bytes copy straight from the
        chunk into the preallocated body buffer (over-read head remainders
        drain from `_buf` first), so `_buf` never holds more than one header
        block plus one scratch chunk."""
        out: list = []
        mv = memoryview(data)
        while not self._dead:
            if self._msg is not None:
                # body phase: leftover head over-read first, then the chunk
                need = len(self._body) - self._body_got
                if self._buf:
                    take = min(need, len(self._buf))
                    self._body[self._body_got:self._body_got + take] = \
                        self._buf[:take]
                    del self._buf[:take]
                elif len(mv):
                    take = min(need, len(mv))
                    self._body[self._body_got:self._body_got + take] = \
                        mv[:take]
                    mv = mv[take:]
                else:
                    break
                self._body_got += take
                if self._body_got == len(self._body):
                    msg, self._msg = self._msg, None
                    msg.body = bytes(self._body)
                    out.append((msg, self._head_bytes + self._body_got))
                    self._body, self._body_got = None, 0
                continue
            # head phase: everything unparsed lives in _buf
            idx = self._buf.find(b"\r\n\r\n", self._scan)
            if idx >= 0:
                head = bytes(self._buf[:idx])
                del self._buf[:idx + 4]
                self._head_bytes = idx + 4
                self._scan = 0
                self._parse_head(head, out)
                continue  # error sets _dead; else body/next-head follows
            # resume the terminator scan where this pass left off (minus
            # the 3 bytes a split \r\n\r\n could straddle) — no rescans
            self._scan = max(0, len(self._buf) - 3)
            if len(self._buf) > MAX_HEADER_BYTES:
                # bounded accumulation: the block never grew past the limit
                # plus one scratch chunk — reject, don't balloon
                self._error(out, 431, "request header block too large")
                break
            if not len(mv):
                break
            take = min(len(mv), MAX_HEADER_BYTES + 1 - len(self._buf))
            self._buf += mv[:take]
            mv = mv[take:]
        return out

    def _error(self, out: list, status: int, detail: str) -> None:
        out.append((HttpRequest(remote=self._remote, close=True,
                                err=(status, detail)),
                    len(self._buf) + self._body_got))
        self._dead = True

    def _parse_head(self, head: bytes, out: list) -> bool:
        try:
            text = head.decode("latin-1")
        except UnicodeDecodeError:  # latin-1 can't fail, but stay defensive
            self._error(out, 400, "undecodable header block")
            return False
        lines = text.split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
            self._error(out, 400, "malformed request line")
            return False
        method, target, version = parts
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            if line[0] in (" ", "\t"):  # obs-fold continuation: rejected
                self._error(out, 400, "folded header line")
                return False
            k, sep, v = line.partition(":")
            if not sep:
                self._error(out, 400, "malformed header line")
                return False
            headers[k.strip().lower()] = v.strip()
        if "transfer-encoding" in headers:
            self._error(out, 501, "transfer-encoding not supported")
            return False
        cl_raw = headers.get("content-length", "0")
        try:
            cl = int(cl_raw)
        except ValueError:
            self._error(out, 400, f"bad content-length {cl_raw!r}")
            return False
        if cl < 0:
            self._error(out, 400, f"bad content-length {cl_raw!r}")
            return False
        if cl > MAX_BODY_BYTES:
            # the hostile-header rule: bounds-checked BEFORE any allocation
            self._error(out, 413, f"content-length {cl} exceeds "
                                  f"{MAX_BODY_BYTES}")
            return False
        conn_toks = {t.strip().lower()
                     for t in headers.get("connection", "").split(",")}
        close = "close" in conn_toks or (
            version == "HTTP/1.0" and "keep-alive" not in conn_toks)
        msg = HttpRequest(method=method, target=target, headers=headers,
                          remote=self._remote, close=close)
        if cl == 0:
            out.append((msg, self._head_bytes))
            return True
        self._msg = msg
        self._body = bytearray(cl)
        self._body_got = 0
        return True


class HttpEvloopCore:
    """The evloop-backed HTTP server an RPCServer rides: owns the listener
    (SO_REUSEADDR so a restart rebinds the same port immediately — the PR-4
    reload bug class), wraps a `dispatch(Request) -> Response` callable, and
    carries the threaded path's stop contract: stop accepting, DRAIN
    in-flight handlers (bounded), let queued replies flush, then hard-close
    every lingering keep-alive socket so a pooled client sees EOF and
    reconnects fresh instead of being served by a stopped stack."""

    def __init__(self, dispatch, host: str = "127.0.0.1", port: int = 0,
                 name: str = "rpc"):
        from chubaofs_tpu.rpc.router import parse_request

        self._parse_request = parse_request
        self._dispatch = dispatch
        self._inflight = 0
        self._drain = threading.Condition()
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((host, port))
        listener.listen(512)
        self.listener = listener
        self.port = listener.getsockname()[1]
        self.addr = f"{host}:{self.port}"
        self.core = EvloopServer(
            listener, self._on_message, name=f"http-{name}",
            framer_factory=HttpFramer, encode=encode_reply,
            close_reply=lambda reply: reply.close)

    def start(self) -> "HttpEvloopCore":
        self.core.start()
        return self

    def _on_message(self, msg: HttpRequest) -> HttpReply:
        if msg.err is not None:
            import json

            status, detail = msg.err
            return HttpReply(status, {"Content-Type": "application/json"},
                             json.dumps({"error": detail}).encode(),
                             close=True)
        req = self._parse_request(msg.method, msg.target, msg.headers,
                                  msg.body, remote=msg.remote)
        with self._drain:
            self._inflight += 1
        try:
            resp = self._dispatch(req)
        finally:
            with self._drain:
                self._inflight -= 1
                self._drain.notify_all()
        return HttpReply(resp.status, resp.headers, resp.body,
                         head_only=(msg.method.upper() == "HEAD"),
                         close=msg.close)

    def _pending_write_bytes(self) -> int:
        total = 0
        for shard in self.core.shards:
            try:
                total += sum(c.wq_bytes for c in list(shard.conns.values()))
            except RuntimeError:
                return 1  # dict changed mid-iteration: something is pending
        return total

    def stop(self, drain_timeout: float = 10.0) -> None:
        # shutdown() BEFORE close(): a close alone doesn't interrupt the
        # acceptor thread blocked in accept(), and the kernel keeps the
        # LISTEN socket (and the port) alive until that syscall returns —
        # the restart-rebind would then fail with EADDRINUSE. shutdown pops
        # the blocked accept with an error; the acceptor exits on it.
        try:
            self.listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.listener.close()
        except OSError:
            pass
        deadline = time.monotonic() + drain_timeout
        with self._drain:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # wedged handler: don't hold the restart hostage
                self._drain.wait(remaining)
        # in-flight handlers finished; their replies may still sit on write
        # queues (the threaded path wrote synchronously inside the drained
        # handler) — give the shards a bounded window to flush before the
        # teardown hard-close discards them
        flush_deadline = time.monotonic() + min(2.0, drain_timeout)
        while self._pending_write_bytes() > 0 \
                and time.monotonic() < flush_deadline:
            time.sleep(0.01)
        self.core.stop()  # hard-closes every lingering keep-alive conn
