"""Threaded HTTP server bound to a Router, plus standard middleware.

Reference counterpart: common/rpc's server glue + middleware stack — auditlog
middleware (common/rpc/auditlog), shared-secret auth middleware
(common/rpc/auth: an HMAC of the request path with a cluster secret rides a
header), and crc-protected request bodies (clients send a crc32 header; the
server verifies before dispatch). The profile mux (common/profile: /metrics +
/debug endpoints always mounted) appears here as the default routes.
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from chubaofs_tpu import chaos
from chubaofs_tpu.rpc.router import Request, Response, Router, parse_request

AUTH_HEADER = "blob-auth"
CRC_HEADER = "x-crc-body"


def auth_middleware(secret: bytes):
    """common/rpc/auth analog: HMAC-SHA1(path) must ride AUTH_HEADER."""

    def mw(req: Request, nxt):
        want = hmac.new(secret, req.path.encode(), hashlib.sha1).hexdigest()
        if not hmac.compare_digest(req.header(AUTH_HEADER), want):
            return Response(403, {}, b'{"error":"auth mismatch"}')
        return nxt(req)

    return mw


def sign_path(secret: bytes, path: str) -> str:
    return hmac.new(secret, path.encode(), hashlib.sha1).hexdigest()


def crc_middleware(req: Request, nxt):
    """Verify crc32 of the body when the client attached CRC_HEADER."""
    want = req.header(CRC_HEADER)
    if want:
        try:
            expected = int(want)
        except ValueError:
            return Response(400, {}, b'{"error":"bad crc header","code":"CrcMismatch"}')
        if expected != (zlib.crc32(req.body) & 0xFFFFFFFF):
            return Response(400, {}, b'{"error":"body crc mismatch","code":"CrcMismatch"}')
    return nxt(req)


def audit_middleware(audit):
    """common/rpc/auditlog analog over utils.auditlog.AuditLog."""

    def mw(req: Request, nxt):
        t0 = time.perf_counter()
        resp = nxt(req)
        audit.log_http(req.method, req.path, resp.status,
                       int((time.perf_counter() - t0) * 1e6), req.remote,
                       len(req.body), len(resp.body))
        return resp

    return mw


class RPCServer:
    """ThreadingHTTPServer hosting one Router; /metrics mounted by default."""

    def __init__(self, router: Router, host: str = "127.0.0.1", port: int = 0,
                 registry=None):
        self.router = router
        if registry is not None:
            router.get("/metrics", lambda r: Response(
                200, {"Content-Type": "text/plain"}, registry.render().encode()))

        outer = self
        self._inflight = 0
        self._drain = threading.Condition()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence default stderr chatter
                pass

            def _serve(self):
                with outer._drain:
                    outer._inflight += 1
                try:
                    self._serve_inner()
                finally:
                    with outer._drain:
                        outer._inflight -= 1
                        outer._drain.notify_all()

            def _serve_inner(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req = parse_request(self.command, self.path,
                                    dict(self.headers.items()), body,
                                    remote=self.client_address[0])
                # error/hang here = handler dies before replying: the client
                # sees a dropped connection, its retry/backoff path fires
                chaos.failpoint("rpc.server.handle")
                resp = outer.router.dispatch(req)
                self.send_response(resp.status)
                payload = b"" if self.command == "HEAD" else resp.body
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                # a handler-set Content-Length wins (HEAD responses describe
                # the body they didn't send)
                if not any(k.lower() == "content-length" for k in resp.headers):
                    self.send_header("Content-Length", str(len(resp.body)))
                self.end_headers()
                if payload:
                    self.wfile.write(payload)

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _serve
            do_OPTIONS = _serve

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.addr = f"{host}:{self.httpd.server_address[1]}"
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name=f"rpc@{self.addr}", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_timeout: float = 10.0):
        """Stop accepting, then DRAIN: wait for in-flight handlers to finish
        (bounded) before returning — the graceful-restart contract the
        blobstore module reload depends on (blobstore/cmd/cmd.go analog)."""
        self.httpd.shutdown()
        self.httpd.server_close()
        deadline = time.monotonic() + drain_timeout
        with self._drain:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # wedged handler: don't hold the restart hostage
                self._drain.wait(remaining)
        if self._thread:
            self._thread.join(timeout=5)
