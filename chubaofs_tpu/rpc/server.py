"""Threaded HTTP server bound to a Router, plus standard middleware.

Reference counterpart: common/rpc's server glue + middleware stack — auditlog
middleware (common/rpc/auditlog), shared-secret auth middleware
(common/rpc/auth: an HMAC of the request path with a cluster secret rides a
header), and crc-protected request bodies (clients send a crc32 header; the
server verifies before dispatch). The profile mux (common/profile: /metrics +
/debug endpoints always mounted) appears here as the default routes.
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from chubaofs_tpu import chaos
from chubaofs_tpu.rpc.httpevloop import HttpEvloopCore, http_evloop_enabled
from chubaofs_tpu.rpc.router import Request, Response, Router, parse_request

AUTH_HEADER = "blob-auth"
CRC_HEADER = "x-crc-body"


def auth_middleware(secret: bytes):
    """common/rpc/auth analog: HMAC-SHA1(path) must ride AUTH_HEADER."""

    def mw(req: Request, nxt):
        want = hmac.new(secret, req.path.encode(), hashlib.sha1).hexdigest()
        if not hmac.compare_digest(req.header(AUTH_HEADER), want):
            return Response(403, {}, b'{"error":"auth mismatch"}')
        return nxt(req)

    return mw


def sign_path(secret: bytes, path: str) -> str:
    return hmac.new(secret, path.encode(), hashlib.sha1).hexdigest()


def crc_middleware(req: Request, nxt):
    """Verify crc32 of the body when the client attached CRC_HEADER."""
    want = req.header(CRC_HEADER)
    if want:
        try:
            expected = int(want)
        except ValueError:
            return Response(400, {}, b'{"error":"bad crc header","code":"CrcMismatch"}')
        if expected != (zlib.crc32(req.body) & 0xFFFFFFFF):
            return Response(400, {}, b'{"error":"body crc mismatch","code":"CrcMismatch"}')
    return nxt(req)


def audit_middleware(audit):
    """common/rpc/auditlog analog over utils.auditlog.AuditLog."""

    def mw(req: Request, nxt):
        t0 = time.perf_counter()
        resp = nxt(req)
        audit.log_http(req.method, req.path, resp.status,
                       int((time.perf_counter() - t0) * 1e6), req.remote,
                       len(req.body), len(resp.body))
        return resp

    return mw


def dispatch_request(router: Router, module: str, req: Request) -> Response:
    """ONE request through the router with the serving-model-independent
    plumbing both backends share: the `rpc.server.handle` failpoint (an
    error here = the handler died before replying, the client sees a
    dropped connection), trace-span continuation, and the Trace-* reply
    headers for traced callers. The evloop HTTP core and the threaded
    fallback both call exactly this — the serving model is the ONLY
    variable between them."""
    chaos.failpoint("rpc.server.handle")
    # continue (or root) the request's trace: handlers see the span via
    # trace.current_span(); its track log rides back on the response
    # headers for the caller to fold in
    from chubaofs_tpu.blobstore import trace

    # Trace-* response headers only when the REQUEST carried a trace id
    # (same guard as the packet carriers): untraced callers — every plain
    # S3 client, every scraper — pay zero extra reply bytes; the span
    # still exists for handlers' current_span() use
    traced = trace.extract_trace_id(req.headers) is not None
    span = trace.start_span(f"{module or 'rpc'}:{req.path}",
                            carrier=req.headers)
    trace.push_span(span)
    t0 = time.perf_counter()
    try:
        resp = router.dispatch(req)
    finally:
        span.append_track_log(module or "rpc", start=t0)
        span.finish()
        trace.pop_span()
    if traced:
        if span.track:
            resp.headers.setdefault(trace.TRACK_LOG_KEY,
                                    span.track_log_string())
        resp.headers.setdefault(trace.TRACE_ID_KEY, span.trace_id)
    return resp


class RPCServer:
    """HTTP server hosting one Router; /metrics mounted by default.

    Serving model (ISSUE 14): by default the evloop HTTP core
    (rpc/httpevloop.py) — acceptor + loop shards + bounded worker pool, the
    same machinery the packet servers ride, so thousands of keep-alive
    connections cost registered sockets instead of parked threads.
    `CFS_EVLOOP_HTTP=0` restores the ThreadingHTTPServer fallback for A/B
    and rollback; both backends dispatch through `dispatch_request`, so
    handlers, middleware, and the side-doors cannot tell them apart.

    /metrics renders the process's WHOLE registry set (the default registry
    plus every role registry — exporter.render_all), so any daemon role is
    scrapeable without its subsystems knowing about the server; an explicit
    `registry` argument is rendered first (legacy callers). A router that
    already mounted its own /metrics keeps it (registration order wins at
    equal rank). `module` names the daemon role in trace track-logs.
    `metrics=False` skips the mount — for PUBLIC-facing routers whose
    namespace the route would shadow (the objectnode S3 surface, where
    GET /metrics is a bucket listing and every route is auth-wrapped);
    such daemons expose a statsListen side-door instead.

    The same flag gates the trace/audit side-doors: `/traces?id=<trace-id>`
    and `/traces/recent` serve the process trace sink's span records, and
    `/slowops` the recent slow-op audit entries — so the console collector
    and `cfs-trace` can fetch one trace's spans from every daemon it
    crossed with nothing but the addresses `cfs-stat` already scrapes.

    The health-plane side-doors ride the same mount: `/debug/prof` serves
    the sampling profiler (`?seconds=N` runs an on-demand capture; bare, it
    reports the CFS_PROF_HZ continuous profile), `/metrics/history` the
    bounded snapshot ring with server-side `?rate=1`, and `/health` the SLO
    evaluation (ok/degraded/failing + reasons) the console `/api/health`
    rollup and `cfs-top` poll."""

    def __init__(self, router: Router, host: str = "127.0.0.1", port: int = 0,
                 registry=None, module: str = "", metrics: bool = True):
        self.router = router
        self.module = module

        def metrics_route(r):
            from chubaofs_tpu.utils import exporter

            text = (registry.render() if registry is not None else "")
            return Response(200, {"Content-Type": "text/plain"},
                            (text + exporter.render_all()).encode())

        def traces_route(r):
            from chubaofs_tpu.utils import tracesink

            tid = r.q("id")
            if not tid:
                return Response(400, {"Content-Type": "application/json"},
                                b'{"error":"missing ?id=<trace-id>"}')
            return Response.json(
                {"trace_id": tid,
                 "spans": tracesink.default_sink().records(tid)})

        def traces_recent_route(r):
            from chubaofs_tpu.utils import tracesink

            snk = tracesink.default_sink()
            return Response.json({"spans": snk.recent_records(r.q_int("n", 200)),
                                  "traces": snk.recent_traces()})

        def slowops_route(r):
            from chubaofs_tpu.utils.auditlog import recent_slowops

            return Response.json({"slowops": recent_slowops(r.q_int("n", 100))})

        def debug_prof_route(r):
            from chubaofs_tpu.utils import profiler

            secs = r.q("seconds")
            if secs:
                try:
                    seconds = float(secs)
                except ValueError:
                    return Response.json(
                        {"error": f"bad ?seconds={secs!r}"}, status=400)
                try:
                    hz = float(r.q("hz") or 0) or None
                except ValueError:
                    hz = None
                prof = profiler.capture(seconds, hz=hz)
            else:
                cont = profiler.active()
                if cont is None:
                    return Response.json(
                        {"error": "continuous profiling disarmed "
                                  "(set CFS_PROF_HZ) — or pass ?seconds=N "
                                  "for an on-demand capture"}, status=400)
                prof = cont.profile
            if r.q("json"):
                return Response.json(prof.to_dict())
            return Response(200, {"Content-Type": "text/plain"},
                            (prof.collapsed() + "\n").encode())

        def metrics_history_route(r):
            from chubaofs_tpu.utils import metrichist

            hist = metrichist.default_history()
            return Response.json(hist.query(n=r.q_int("n", 30),
                                            flt=r.q("filter"),
                                            rate=bool(r.q("rate"))))

        def health_route(r):
            from chubaofs_tpu.utils import slo

            # always HTTP 200: the status FIELD is the verdict, and a 503
            # would make the console collector count a degraded-but-
            # answering daemon as unreachable
            return Response.json(slo.health_report())

        def events_route(r):
            from chubaofs_tpu.utils import events

            types = tuple(t for t in (r.q("type") or "").split(",") if t)
            sevs = tuple(s for s in (r.q("severity") or "").split(",") if s)
            n = r.q_int("n", 200)
            j = events.default_journal()
            if r.has_q("since"):  # q_int clamps negatives: presence IS mode
                since = r.q_int("since", 0)
                # cursor-paged poller mode (the console rollup): oldest
                # first from the cursor, exactly-once delivery
                evs, cursor = j.query(since=since, n=n,
                                      types=types or None,
                                      severity=sevs or None)
            else:
                # one-shot mode (bare cfs-events, --correlate): the NEWEST
                # n matching events — a busy daemon's ring must not hide
                # fresh events behind its oldest page
                evs, cursor = events.recent_page(n, types or None,
                                                 sevs or None)
            return Response.json({"events": evs, "cursor": cursor})

        def alerts_route(r):
            from chubaofs_tpu.utils import alerts

            return Response.json(alerts.alerts_report())

        def autopilot_route(r):
            from chubaofs_tpu.autopilot import controller as ap_ctl

            op = r.q("op")
            if op:
                ap = ap_ctl.default_controller()
                if op == "enable":
                    ap.attach().set_enabled(True)
                    if not ap.armed:
                        ap.start(ap_ctl._env_f("CFS_AUTOPILOT_TICK_S", 5.0))
                elif op == "disable":
                    ap.set_enabled(False)
                elif op == "dry-run":
                    # arm in shadow mode (decisions logged, nothing runs);
                    # ?off=1 drops back to live actuation
                    ap.set_dry_run(not r.q("off"))
                    if not r.q("off"):
                        ap.attach().set_enabled(True)
                        if not ap.armed:
                            ap.start(
                                ap_ctl._env_f("CFS_AUTOPILOT_TICK_S", 5.0))
                else:
                    return Response.json(
                        {"error": f"unknown op {op!r} (enable | disable "
                                  "| dry-run)"}, status=400)
                return Response.json(ap.status())
            return Response.json(ap_ctl.autopilot_status())

        def debug_bundle_route(r):
            from chubaofs_tpu.utils import flightrec

            if not flightrec.enabled():
                return Response.json(
                    {"error": "flight recorder disarmed (set CFS_FLIGHT=1) "
                              "— alert-triggered and on-demand incident "
                              "bundles are off"}, status=400)
            rec = flightrec.default_recorder()
            if r.q("collect"):
                man = rec.capture(trigger=r.q("trigger") or "http",
                                  fingerprint=r.q("fingerprint") or "")
                # the sections ride INLINE so a console can assemble the
                # cross-daemon incident dir centrally — each daemon keeps
                # its own per-process bundle root
                return Response.json(
                    {"manifest": man,
                     "payload": flightrec.bundle_payload(man["bundle"])})
            return Response.json({"dir": rec.root,
                                  "bundles": rec.list_bundles()})

        if metrics:
            router.get("/metrics", metrics_route)
            router.get("/traces", traces_route)
            router.get("/traces/recent", traces_recent_route)
            router.get("/slowops", slowops_route)
            router.get("/debug/prof", debug_prof_route)
            router.get("/metrics/history", metrics_history_route)
            router.get("/health", health_route)
            router.get("/events", events_route)
            router.get("/alerts", alerts_route)
            router.get("/autopilot", autopilot_route)
            router.get("/debug/bundle", debug_bundle_route)
            # env-armed sinks go live at daemon boot, not first scrape —
            # and stay the documented no-op when their env knob is unset
            from chubaofs_tpu.autopilot import controller as _autopilot
            from chubaofs_tpu.utils import alerts, flightrec, metrichist, \
                profiler, tracesink

            tracesink.activate_from_env()
            profiler.activate_from_env()
            metrichist.activate_from_env()
            alerts.activate_from_env()
            flightrec.activate_from_env()
            _autopilot.activate_from_env()

        outer = self
        self._inflight = 0
        self._drain = threading.Condition()
        self._conns: set = set()  # live connection sockets (keep-alive aware)
        self.httpd = None
        self._evcore = None
        self._thread: threading.Thread | None = None

        if http_evloop_enabled():
            # the evloop HTTP core: acceptor + loop shards + worker pool
            # (rpc/httpevloop.py); drain/stop parity is the core's contract
            self._evcore = HttpEvloopCore(
                lambda req: dispatch_request(self.router, self.module, req),
                host=host, port=port, name=module or "rpc")
            self.addr = self._evcore.addr
            self.port = self._evcore.port
        else:
            # threaded fallback (CFS_EVLOOP_HTTP=0): ThreadingHTTPServer,
            # one thread per live connection — the pre-ISSUE-14 model, kept
            # for A/B and rollback
            class Handler(BaseHTTPRequestHandler):
                protocol_version = "HTTP/1.1"

                def setup(self):
                    super().setup()
                    with outer._drain:
                        outer._conns.add(self.connection)

                def finish(self):
                    with outer._drain:
                        outer._conns.discard(self.connection)
                    super().finish()

                def log_message(self, *a):  # silence default stderr chatter
                    pass

                def _serve(self):
                    with outer._drain:
                        outer._inflight += 1
                    try:
                        self._serve_inner()
                    finally:
                        with outer._drain:
                            outer._inflight -= 1
                            outer._drain.notify_all()

                def _serve_inner(self):
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    req = parse_request(self.command, self.path,
                                        dict(self.headers.items()), body,
                                        remote=self.client_address[0])
                    resp = dispatch_request(outer.router, outer.module, req)
                    self.send_response(resp.status)
                    payload = b"" if self.command == "HEAD" else resp.body
                    for k, v in resp.headers.items():
                        self.send_header(k, v)
                    # a handler-set Content-Length wins (HEAD responses
                    # describe the body they didn't send)
                    if not any(k.lower() == "content-length"
                               for k in resp.headers):
                        self.send_header("Content-Length",
                                         str(len(resp.body)))
                    self.end_headers()
                    if payload:
                        self.wfile.write(payload)

                do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _serve
                do_OPTIONS = _serve

            self.httpd = ThreadingHTTPServer((host, port), Handler)
            self.httpd.daemon_threads = True
            self.addr = f"{host}:{self.httpd.server_address[1]}"
            self.port = self.httpd.server_address[1]
        if metrics:
            # identity + boot stamp (the events satellite): every daemon
            # exports cfs_boot_time_seconds (wall, cross-process protocol —
            # scrapers derive UP and the restart cross-check from it) and a
            # role/version info gauge; the journal gets the role/addr stamp
            # and one daemon_boot timeline record
            import chubaofs_tpu
            from chubaofs_tpu.utils import events, exporter

            # cfs_boot_time_seconds + cfs_build_info{role,version}
            exporter.registry("boot").gauge("time_seconds").set(
                events.BOOT_TS)
            exporter.registry("build").gauge(
                "info", {"role": module or "rpc",
                         "version": chubaofs_tpu.__version__}).set(1)
            events.configure(role=module or "rpc", addr=self.addr)
            events.emit("daemon_boot", entity=module or "rpc",
                        detail={"role": module or "rpc", "addr": self.addr,
                                "version": chubaofs_tpu.__version__})

    def start(self):
        if self._evcore is not None:
            self._evcore.start()
            return self
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name=f"rpc@{self.addr}", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_timeout: float = 10.0):
        """Stop accepting, then DRAIN: wait for in-flight handlers to finish
        (bounded) before returning — the graceful-restart contract the
        blobstore module reload depends on (blobstore/cmd/cmd.go analog).
        Both backends then hard-close lingering keep-alive sockets, so a
        reload can never leave old-stack handlers serving pooled clients
        and the port rebinds immediately."""
        if self._evcore is not None:
            self._evcore.stop(drain_timeout)
            return
        self.httpd.shutdown()
        self.httpd.server_close()
        deadline = time.monotonic() + drain_timeout
        with self._drain:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # wedged handler: don't hold the restart hostage
                self._drain.wait(remaining)
            conns = list(self._conns)
        # keep-alive connections OUTLIVE shutdown(): their handler threads sit
        # in readline() waiting for the next request, and a pooled client
        # would keep being served by THIS stopped stack (a reload would leave
        # requests landing on closed components). Hard-close them — parked
        # client conns see EOF and their pool evicts + reconnects fresh.
        import socket as _socket

        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._thread:
            self._thread.join(timeout=5)
