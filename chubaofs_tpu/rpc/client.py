"""Retrying HTTP client (blobstore/common/rpc client + api/* typed clients).

Reference counterpart: common/rpc's LbClient — round-robin over hosts with
retry-on-5xx/conn-error, JSON bodies, crc-body headers, and error
re-hydration into typed codes (api/access/client.go:248 builds on it). Kept:
host rotation, bounded retries with backoff, HTTPError re-hydration, optional
auth signing and body crc.
"""

from __future__ import annotations

import http.client
import time
import zlib

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.rpc.errors import HTTPError
from chubaofs_tpu.rpc.server import AUTH_HEADER, CRC_HEADER, sign_path


class RPCClient:
    def __init__(self, hosts: list[str], retries: int = 3, timeout: float = 30.0,
                 auth_secret: bytes | None = None, backoff: float = 0.05):
        self.hosts = list(hosts)
        self.retries = retries
        self.timeout = timeout
        self.auth_secret = auth_secret
        self.backoff = backoff
        self._rr = 0

    def _next_host(self) -> str:
        h = self.hosts[self._rr % len(self.hosts)]
        self._rr += 1
        return h

    def do(self, method: str, path: str, body: bytes = b"",
           headers: dict | None = None, crc: bool = False) -> tuple[int, dict, bytes]:
        hdrs = dict(headers or {})
        if self.auth_secret is not None:
            # sign the DECODED path: the server router hands middleware the
            # percent-decoded form, so both ends must hash the same bytes
            import urllib.parse

            plain = urllib.parse.unquote(path.split("?", 1)[0])
            hdrs[AUTH_HEADER] = sign_path(self.auth_secret, plain)
        if crc and body:
            hdrs[CRC_HEADER] = str(zlib.crc32(body) & 0xFFFFFFFF)
        # cross-hop tracing: the caller's span id rides the request headers;
        # the server's track log rides back on the response and folds into
        # the same span (blobstore/common/trace's header carrier)
        span = trace.current_span()
        if span is not None:
            hdrs.setdefault(trace.TRACE_ID_KEY, span.trace_id)
        last: Exception | None = None
        for attempt in range(self.retries):
            host = self._next_host()
            try:
                # FailpointError IS a ConnectionError: an injected fault takes
                # the real retry/rotate path below, no special handling
                chaos.failpoint("rpc.client.do")
                conn = http.client.HTTPConnection(host, timeout=self.timeout)
                try:
                    conn.request(method, path, body=body or None, headers=hdrs)
                    resp = conn.getresponse()
                    data = resp.read()
                    if resp.status < 500:
                        headers_out = dict(resp.getheaders())
                        if span is not None:
                            span.merge_track(
                                headers_out.get(trace.TRACK_LOG_KEY))
                        return resp.status, headers_out, data
                    last = HTTPError.from_body(resp.status, data)
                finally:
                    conn.close()
            except (ConnectionError, OSError, http.client.HTTPException) as e:
                last = e
            time.sleep(self.backoff * (attempt + 1))
        raise last if last else HTTPError(503, msg="no hosts")

    def request_json(self, method: str, path: str, obj=None, **kw):
        import json

        body = json.dumps(obj).encode() if obj is not None else b""
        status, headers, data = self.do(method, path, body, **kw)
        if status >= 400:
            raise HTTPError.from_body(status, data)
        return json.loads(data.decode() or "null")

    def get(self, path: str, **kw):
        return self.request_json("GET", path, **kw)

    def post(self, path: str, obj=None, **kw):
        return self.request_json("POST", path, obj, **kw)
