"""Retrying HTTP client (blobstore/common/rpc client + api/* typed clients).

Reference counterpart: common/rpc's LbClient — round-robin over hosts with
retry-on-5xx/conn-error, JSON bodies, crc-body headers, and error
re-hydration into typed codes (api/access/client.go:248 builds on it). Kept:
host rotation, bounded retries with backoff, HTTPError re-hydration, optional
auth signing and body crc. Transport rides the keep-alive connection pool
(rpc/pool.py) — the packet-TCP path's pooling discipline applied to the HTTP
hops — so a request stream to one host reuses one warm socket instead of
paying a TCP connect per request.
"""

from __future__ import annotations

import http.client
import itertools
import time
import zlib

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.rpc import pool as rpc_pool
from chubaofs_tpu.rpc.errors import HTTPError
from chubaofs_tpu.rpc.server import AUTH_HEADER, CRC_HEADER, sign_path

_CONN_ERRORS = (ConnectionError, OSError, http.client.HTTPException)


class RPCClient:
    def __init__(self, hosts: list[str], retries: int = 3, timeout: float = 30.0,
                 auth_secret: bytes | None = None, backoff: float = 0.05,
                 pool=None, pooled: bool = True):
        self.hosts = list(hosts)
        self.retries = retries
        self.timeout = timeout
        self.auth_secret = auth_secret
        self.backoff = backoff
        # the client is shared across pool workers: host rotation must not
        # lose/duplicate slots under concurrent do() — count() is atomic
        self._rr = itertools.count()
        # pool=None -> the process-wide default; pooled=False -> a private
        # connect-per-request NullPool (A/B control, socket-averse callers)
        self._pool = pool if pool is not None else (
            None if pooled else rpc_pool.NullPool(timeout=timeout))

    @property
    def pool(self):
        return self._pool if self._pool is not None else rpc_pool.default_pool()

    def _next_host(self) -> str:
        return self.hosts[next(self._rr) % len(self.hosts)]

    def do(self, method: str, path: str, body: bytes = b"",
           headers: dict | None = None, crc: bool = False) -> tuple[int, dict, bytes]:
        hdrs = dict(headers or {})
        if self.auth_secret is not None:
            # sign the DECODED path: the server router hands middleware the
            # percent-decoded form, so both ends must hash the same bytes
            import urllib.parse

            plain = urllib.parse.unquote(path.split("?", 1)[0])
            hdrs[AUTH_HEADER] = sign_path(self.auth_secret, plain)
        if crc and body:
            hdrs[CRC_HEADER] = str(zlib.crc32(body) & 0xFFFFFFFF)
        # cross-hop tracing: the caller's trace + span ids ride the request
        # headers (the span id is the server span's cross-process parent);
        # the server's track log rides back on the response and folds into
        # the same span (blobstore/common/trace's header carrier)
        span = trace.current_span()
        if span is not None:
            hdrs.setdefault(trace.TRACE_ID_KEY, span.trace_id)
            hdrs.setdefault(trace.SPAN_ID_KEY, span.span_id)
        last: Exception | None = None
        for attempt in range(self.retries):
            host = self._next_host()
            try:
                # FailpointError IS a ConnectionError: an injected fault takes
                # the real retry/rotate path below, no special handling
                chaos.failpoint("rpc.client.do")
                status, headers_out, data = self._roundtrip(
                    host, method, path, body, hdrs)
                # every served hop's track log folds in here — for a 5xx
                # that means BEFORE the retry, or the failed hop vanishes
                # from the trace
                if span is not None:
                    span.merge_track(headers_out.get(trace.TRACK_LOG_KEY))
                if status < 500:
                    return status, headers_out, data
                last = HTTPError.from_body(status, data)
            except _CONN_ERRORS as e:
                last = e
            if attempt + 1 < self.retries:
                # no sleep after the FINAL attempt: a terminal failure must
                # raise now, not pay backoff*retries of pointless latency
                time.sleep(self.backoff * (attempt + 1))
        raise last if last else HTTPError(503, msg="no hosts")

    # methods safe to resend when a reused conn dies mid-flight: the server
    # may have executed the request before dropping the line, so the free
    # replay is limited to READ-ONLY methods (stricter than HTTP idempotency
    # — this framework's PUT /put allocates fresh bids per call); mutating
    # methods on a stale conn surface to the counted retry loop, whose
    # resend-on-conn-error semantics predate the pool
    _REPLAYABLE = frozenset({"GET", "HEAD", "OPTIONS"})

    def _roundtrip(self, host: str, method: str, path: str, body: bytes,
                   hdrs: dict) -> tuple[int, dict, bytes]:
        """One request over a pooled connection. A REUSED keep-alive socket
        that fails before yielding a response is a stale parked conn (the
        server tore it down while idle): evict it and try the next one —
        draining to a fresh connect — without consuming a retry attempt.
        Fresh-connection failures propagate to the real retry loop."""
        pool = self.pool
        span = trace.current_span()
        while True:
            t_pool = time.perf_counter()
            conn, reused = pool.checkout(host, timeout=self.timeout)
            if span is not None:
                # named stages for the critical-path analyzer: connection
                # checkout (reuse hit or TCP connect) vs time on the wire
                span.add_stage("rpc.pool", start=t_pool)
            t_wire = time.perf_counter()
            try:
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except _CONN_ERRORS as e:
                # a timeout is a SLOW server, not a stale socket: no free
                # replay (it would stack full timeout waits inside one
                # counted attempt) and no flushing of the host's warm pool
                is_timeout = isinstance(e, TimeoutError)
                # half-sent/half-read state is never re-parked
                pool.checkin(host, conn, ok=False,
                             reason="stale" if reused and not is_timeout
                             else "error")
                if not reused:
                    raise
                if not is_timeout:
                    # one stale parked conn means its OLDER siblings to the
                    # same (restarted) server are dead too: flush them, so
                    # whatever comes next — free replay or counted retry —
                    # connects fresh instead of burning the retry budget
                    # one corpse at a time
                    pool.flush_host(host)
                if method not in self._REPLAYABLE or is_timeout:
                    raise
                continue
            if span is not None:
                span.add_stage("rpc.wire", start=t_wire)
            headers_out = dict(resp.getheaders())
            # body fully read above: the conn is reusable unless the server
            # asked to close (will_close covers Connection: close and EOF-
            # delimited bodies)
            pool.checkin(host, conn, ok=not resp.will_close,
                         reason="server_close")
            return resp.status, headers_out, data

    def request_json(self, method: str, path: str, obj=None, **kw):
        import json

        body = json.dumps(obj).encode() if obj is not None else b""
        status, headers, data = self.do(method, path, body, **kw)
        if status >= 400:
            raise HTTPError.from_body(status, data)
        return json.loads(data.decode() or "null")

    def get(self, path: str, **kw):
        return self.request_json("GET", path, **kw)

    def post(self, path: str, obj=None, **kw):
        return self.request_json("POST", path, obj, **kw)
