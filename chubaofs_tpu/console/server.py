"""Console — web dashboard over the master's GraphQL API.

Reference counterpart: console/ (console/server.go:110 — a small HTTP server
hosting a SPA plus a GraphQL proxy to the master; console/service/). Kept:
the same split — static dashboard at /, a /graphql proxy that forwards the
browser's queries to the master (following leader redirects via
MasterClient's transport), and JSON convenience endpoints the dashboard
polls. The SPA is a single inline page: tables for nodes, volumes and users,
refreshed from /api/overview.
"""

from __future__ import annotations

import json
import os

from chubaofs_tpu.master.api_service import MasterClient
from chubaofs_tpu.rpc.errors import HTTPError
from chubaofs_tpu.rpc.router import Request, Response, Router
from chubaofs_tpu.rpc.server import RPCServer

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>chubaofs-tpu console</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#fafafa;color:#222}
 h1{font-size:1.4rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;min-width:40rem;background:#fff}
 th,td{border:1px solid #ddd;padding:.35rem .6rem;text-align:left;font-size:.9rem}
 th{background:#f0f0f0} .ok{color:#0a7d38} .warn{color:#b54708}
 #err{color:#b42318;margin:.5rem 0}
</style></head><body>
<h1>chubaofs-tpu console</h1>
<div id="err"></div>
<h2>Cluster</h2><div id="cluster"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Volumes</h2><table id="volumes"></table>
<h2>Users</h2><table id="users"></table>
<script>
function esc(v){
  return String(v).replace(/[&<>"']/g,
    ch=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[ch]));
}
function row(cells, tag){
  return "<tr>"+cells.map(c=>"<"+tag+">"+c+"</"+tag+">").join("")+"</tr>";
}
async function refresh(){
  try{
    const r = await fetch("/api/overview"); const d = await r.json();
    document.getElementById("err").textContent = "";
    const cv = d.clusterView;
    document.getElementById("cluster").textContent =
      "leader: node "+cv.leaderID+" · volumes: "+cv.volumeCount+
      " · nodes: "+cv.nodes.length;
    const now = Date.now()/1000;
    document.getElementById("nodes").innerHTML =
      row(["id","kind","addr","partitions","alive"],"th")+
      cv.nodes.map(n=>row([esc(n.id),esc(n.kind),esc(n.addr),esc(n.partitions),
        (now-n.lastHeartbeat<10)?"<span class=ok>yes</span>"
                               :"<span class=warn>stale</span>"],"td")).join("");
    document.getElementById("volumes").innerHTML =
      row(["name","owner","tier","meta partitions","data partitions"],"th")+
      d.volumeList.map(v=>row([esc(v.name),esc(v.owner||"-"),
        v.cold?"cold(EC)":"hot",
        v.metaPartitions.length,v.dataPartitions.length],"td")).join("");
    document.getElementById("users").innerHTML =
      row(["user","type","access key","own volumes"],"th")+
      d.userList.map(u=>row([esc(u.userID),esc(u.userType),esc(u.accessKey),
        esc(u.ownVols.join(", ")||"-")],"td")).join("");
  }catch(e){ document.getElementById("err").textContent = "refresh failed: "+e; }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""

OVERVIEW_QUERY = """{
  clusterView { leaderID volumeCount
    nodes { id kind addr partitions lastHeartbeat } }
  volumeList { name owner cold
    metaPartitions { partitionID } dataPartitions { partitionID } }
  userList { userID userType accessKey ownVols }
}"""


class Console:
    def __init__(self, master_addrs: list[str], host: str = "127.0.0.1",
                 port: int = 0, metrics_addrs: list[str] | None = None):
        """metrics_addrs: extra /metrics targets (metanode/datanode stats
        side-doors, blobstore gateway...) the /api/metrics rollup scrapes in
        addition to the masters."""
        self.mc = MasterClient(master_addrs)
        self.master_addrs = list(master_addrs)
        self.metrics_addrs = list(metrics_addrs or [])
        self.router = self._build()
        self.server = RPCServer(self.router, host=host, port=port,
                                module="console").start()
        self.addr = self.server.addr

    def _graphql(self, query: str, variables=None) -> dict:
        """Forward to any master replica — /graphql serves reads on followers
        too, and RPCClient already rotates hosts on connection failure. A 400
        carries the GraphQL errors array and is returned to the browser."""
        payload = json.dumps({"query": query,
                              "variables": variables or {}}).encode()
        status, _, body = self.mc.rpc.do(
            "POST", "/graphql", payload,
            headers={"Content-Type": "application/json"})
        if status not in (200, 400):
            raise HTTPError(status, msg=body.decode(errors="replace")[:200])
        return json.loads(body.decode() or "{}")

    def _build(self) -> Router:
        r = Router()
        r.get("/", lambda req: Response(
            200, {"Content-Type": "text/html; charset=utf-8"}, PAGE.encode()))

        def overview(req: Request):
            out = self._graphql(OVERVIEW_QUERY)
            if "errors" in out:
                return Response.json(out, status=502)
            return Response.json(out["data"])

        def graphql_proxy(req: Request):
            body = req.json() or {}
            return Response.json(self._graphql(body.get("query", ""),
                                               body.get("variables")))

        def scrape_one(addr: str) -> str:
            from chubaofs_tpu.tools.cfsstat import scrape

            try:
                # cfsstat.scrape raises on non-200 too, so a misconfigured
                # target (main API port instead of the stats side-door)
                # lands in the UNREACHABLE marker rather than splicing an
                # error body into the exposition
                return f"# == target {addr} ==\n{scrape(addr, timeout=3)}"
            except Exception as e:
                # a bad address (no port), a non-HTTP port, a dead daemon:
                # mark THIS target, keep serving the others
                return f"# == target {addr} UNREACHABLE: {e} ==\n"

        def metrics_rollup(req: Request):
            """Scrape every known daemon's /metrics and concatenate, each
            section prefixed with its target — the one-stop cluster scrape
            (exporter rollup; the Consul-registration consumer's view).
            Targets are scraped CONCURRENTLY so dead daemons cost one
            timeout, not one per corpse."""
            from concurrent.futures import ThreadPoolExecutor

            targets = self.master_addrs + self.metrics_addrs
            with ThreadPoolExecutor(max_workers=min(8, len(targets) or 1)) as pool:
                sections = list(pool.map(scrape_one, targets))
            return Response(200, {"Content-Type": "text/plain"},
                            "".join(sections).encode())

        def fetch_json(addr: str, path: str) -> dict | None:
            from chubaofs_tpu.tools.cfsstat import scrape

            try:
                return json.loads(scrape(addr, path, timeout=3))
            except Exception:
                return None  # dead/misconfigured target: skip, keep the rest

        def _fanout(path: str) -> list[tuple[str, dict | None]]:
            from concurrent.futures import ThreadPoolExecutor

            targets = self.master_addrs + self.metrics_addrs
            with ThreadPoolExecutor(max_workers=min(8, len(targets) or 1)) as pool:
                return list(zip(targets,
                                pool.map(lambda a: fetch_json(a, path),
                                         targets)))

        def trace_rollup(req: Request):
            """The collector: fetch one trace id's span records from EVERY
            known daemon's /traces side-door and reassemble them into one
            span set (deduped by span id) — the cross-process hop tree
            `cfs-trace` renders. Unreachable targets are reported, not
            fatal: a partial tree still explains most of the request."""
            tid = req.q("id")
            if not tid:
                return Response.json({"error": "missing ?id=<trace-id>"},
                                     status=400)
            import urllib.parse

            spans: dict[str, dict] = {}
            reached, missed = [], []
            # re-encode the id: a raw space/control char would make every
            # upstream request invalid and misreport the cluster as dark
            for addr, out in _fanout(f"/traces?id={urllib.parse.quote(tid)}"):
                if out is None:
                    missed.append(addr)
                    continue
                reached.append(addr)
                for rec in out.get("spans", ()):
                    if rec.get("span_id"):
                        spans.setdefault(rec["span_id"], rec)
            return Response.json(
                {"trace_id": tid, "targets": reached, "unreachable": missed,
                 "spans": sorted(spans.values(),
                                 key=lambda r: r.get("start", 0.0))})

        def health_rollup(req: Request):
            """The cluster health verdict: every known target's /health,
            rolled up worst-status-wins. An UNREACHABLE target is reported
            AS FAILING — a daemon that can't answer "are you healthy?" is
            the opposite of omittable, and silently dropping it would let a
            dead gateway render an all-green dashboard."""
            from chubaofs_tpu.utils.slo import FAILING, OK, RANK

            targets, missed = [], []
            worst = OK
            for addr, out in _fanout("/health"):
                if out is None or "status" not in out:
                    missed.append(addr)
                    entry = {"target": addr, "status": FAILING,
                             "reasons": ["unreachable"], "slos": {}}
                else:
                    entry = {"target": addr, **out}
                targets.append(entry)
                if RANK.get(entry["status"], RANK[FAILING]) > RANK[worst]:
                    # an unknown status string counts as failing too: a
                    # half-broken daemon must not launder itself to ok
                    worst = (entry["status"]
                             if entry["status"] in RANK else FAILING)
            return Response.json({"status": worst, "targets": targets,
                                  "unreachable": missed})

        def events_rollup(req: Request):
            """The cluster timeline: every target's /events merged by wall
            stamp, CURSOR-PAGED — `?cursor=` carries the per-target seq map
            from the previous poll (JSON), so repeated polls see each event
            exactly once; a target with no cursor yet serves its NEWEST
            page. Unreachable targets are REPORTED, never dropped (their
            cursors stay put so nothing is skipped). The merge itself is
            cfsevents.fetch_events — ONE implementation behind this rollup
            and the CLI's direct --addr mode."""
            from chubaofs_tpu.tools.cfsevents import fetch_events

            cursor: dict = {}
            raw = req.q("cursor")
            if raw:
                try:
                    cursor = {str(k): int(v)
                              for k, v in json.loads(raw).items()}
                except (ValueError, TypeError, AttributeError):
                    return Response.json(
                        {"error": "bad ?cursor= (JSON target->seq map)"},
                        status=400)
            merged, next_cursor, missed = fetch_events(
                None, self.master_addrs + self.metrics_addrs,
                cursor=cursor, n=req.q_int("n", 200),
                types=req.q("type"), severity=req.q("severity"),
                timeout=3.0)
            return Response.json({"events": merged, "cursor": next_cursor,
                                  "unreachable": missed})

        def alerts_rollup(req: Request):
            """Every target's /alerts merged: per-target alert lists plus
            the cluster firing total. An unreachable target is reported as
            such — an alert plane that can't answer is not 'no alerts'.
            Same shared implementation as `cfs-events --alerts --addr`."""
            from chubaofs_tpu.tools.cfsevents import fetch_alerts

            return Response.json(fetch_alerts(
                None, self.master_addrs + self.metrics_addrs, timeout=3.0))

        def autopilot_rollup(req: Request):
            """Every target's /autopilot controller state, per-target rows
            plus the cluster action-budget totals — which daemon is armed,
            what it decided lately. Unreachable targets are reported, and
            a target answering `enabled: false` is a DISARMED row, not an
            error (most daemons never arm a controller)."""
            rows, missed = [], []
            remaining = per_hour = 0
            for addr, out in _fanout("/autopilot"):
                if out is None or "enabled" not in out:
                    missed.append(addr)
                    continue
                rows.append({"target": addr, **out})
                b = out.get("budget") or {}
                remaining += int(b.get("remaining", 0) or 0)
                per_hour += int(b.get("per_hour", 0) or 0)
            return Response.json(
                {"targets": rows, "unreachable": missed,
                 "enabled": any(r.get("enabled") for r in rows),
                 "budget": {"remaining": remaining, "per_hour": per_hour}})

        def slowops_rollup(req: Request):
            """Recent slow-op audit entries from every daemon, each tagged
            with its source target — what `cfs-stat --slowops` renders next
            to the metric diff."""
            entries = []
            missed = []
            for addr, out in _fanout("/slowops"):
                if out is None:
                    missed.append(addr)
                    continue
                for rec in out.get("slowops", ()):
                    entries.append({**rec, "target": addr})
            entries.sort(key=lambda e: e.get("ts", ""))
            return Response.json({"slowops": entries, "unreachable": missed})

        def incident_rollup(req: Request):
            """The incident collector (ISSUE 18): fan out to every known
            daemon's /debug/bundle?collect=1 side-door and assemble ONE
            cross-daemon incident directory keyed by the triggering alert
            fingerprint, with the cause→evidence correlation cfs-doctor
            renders. ?fingerprint=/&trigger= select the key; with neither,
            the first firing alert in the cluster rollup is the cause.
            Unreachable daemons are listed, never fatal."""
            import urllib.parse

            from chubaofs_tpu.tools import cfsdoctor
            from chubaofs_tpu.utils import alerts as alertsmod
            from chubaofs_tpu.utils import flightrec

            fp = req.q("fingerprint") or ""
            trigger = req.q("trigger") or "console"
            alert = None
            if not fp:
                from chubaofs_tpu.tools.cfsevents import fetch_alerts

                rollup = fetch_alerts(
                    None, self.master_addrs + self.metrics_addrs,
                    timeout=3.0)
                for row in rollup.get("targets", []):
                    for a in row.get("alerts", []):
                        if a.get("state") == "firing":
                            alert = a
                            fp = alertsmod.fingerprint(
                                a.get("name", ""), a.get("labels"))
                            break
                    if alert is not None:
                        break
            q = (f"/debug/bundle?collect=1"
                 f"&trigger={urllib.parse.quote(trigger)}"
                 f"&fingerprint={urllib.parse.quote(fp)}")
            rows = _fanout(q)
            out_root = os.path.join(flightrec.flight_dir(), "incidents")
            incident = cfsdoctor.assemble_incident(
                rows, out_root, fingerprint=fp, trigger=trigger,
                alert=alert)
            return Response.json(incident)

        r.get("/api/overview", overview)
        r.get("/api/metrics", metrics_rollup)
        r.get("/api/health", health_rollup)
        r.get("/api/trace", trace_rollup)
        r.get("/api/slowops", slowops_rollup)
        r.get("/api/events", events_rollup)
        r.get("/api/alerts", alerts_rollup)
        r.get("/api/autopilot", autopilot_rollup)
        r.get("/api/incident", incident_rollup)
        r.post("/graphql", graphql_proxy)
        return r

    def stop(self):
        self.server.stop()


def main(argv=None) -> int:
    import argparse
    import time

    p = argparse.ArgumentParser(prog="cfs-console")
    p.add_argument("--addr", action="append", required=True,
                   help="master address (repeatable)")
    p.add_argument("--listen", default="127.0.0.1:8500")
    args = p.parse_args(argv)
    host, port = args.listen.rsplit(":", 1)
    console = Console(args.addr, host=host, port=int(port))
    print(json.dumps({"console": console.addr}), flush=True)  # obslint: boot line IS the stdout protocol (harness parses it)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        console.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
