"""Console — the web dashboard (console/ analog)."""

from chubaofs_tpu.console.server import Console

__all__ = ["Console"]
