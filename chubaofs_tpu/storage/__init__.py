"""Extent storage engine (storage/ analog)."""

from chubaofs_tpu.storage.extent_store import (  # noqa: F401
    BLOCK_SIZE, BrokenExtent, ExtentExists, ExtentNotFound, ExtentStore,
    MIN_NORMAL_EXTENT_ID, PAGE_SIZE, StorageError,
)
