"""ExtentStore — the on-disk extent engine backing one data partition.

Reference counterpart: storage/extent_store.go:102-124 (store layout comment
:103-107), Write :327, Read :378, MarkDelete :436, GetAllWatermarks :558,
tiny-extent channels :613-694; storage/extent.go (Extent); punch-hole shims
storage/fallocate_linux.go; block CRC persistence storage/persistence_crc.go.

Layout kept from the reference:
  * normal extents (id >= 65): one append-only file per extent, created on
    demand, deleted whole on MarkDelete;
  * 64 shared *tiny* extents (ids 1..64) for small files: appends are 4KiB
    page aligned, deletes punch holes (fallocate FALLOC_FL_PUNCH_HOLE when the
    filesystem supports it) and always land in a replicated delete journal so
    repair replays them (storage/extent_store.go tinyDelete flow);
  * per-64KiB-block CRC32 sidecar per extent, verified on read, recomputed for
    the blocks a write touches;
  * watermarks = {extent_id: committed size}, the repair currency
    (datanode/data_partition_repair.go:80's diff input).

Not kept: ext4-specific fallocate fast paths become best-effort; file handles
are opened per call (the OS page cache is the pool) rather than the
reference's fd cache.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import json
import os
import struct
import zlib

from chubaofs_tpu import chaos
from chubaofs_tpu.proto.packet import TINY_EXTENT_COUNT, is_tiny_extent
from chubaofs_tpu.utils.locks import SanitizedRLock

BLOCK_SIZE = 64 * 1024  # CRC granularity (storage/extent.go block crc)
PAGE_SIZE = 4096  # tiny-extent append alignment
MIN_NORMAL_EXTENT_ID = TINY_EXTENT_COUNT + 1

_FALLOC_FL_KEEP_SIZE = 0x01
_FALLOC_FL_PUNCH_HOLE = 0x02

_libc = None
if os.name == "posix":
    _name = ctypes.util.find_library("c")
    if _name:
        try:
            _libc = ctypes.CDLL(_name, use_errno=True)
        except OSError:
            _libc = None


def _punch_hole(fd: int, offset: int, size: int) -> bool:
    """Best-effort hole punch; False means the journal is the only record."""
    if _libc is None or not hasattr(_libc, "fallocate"):
        return False
    ret = _libc.fallocate(
        fd, _FALLOC_FL_PUNCH_HOLE | _FALLOC_FL_KEEP_SIZE,
        ctypes.c_longlong(offset), ctypes.c_longlong(size),
    )
    return ret == 0


class StorageError(Exception):
    pass


class ExtentNotFound(StorageError):
    pass


class ExtentExists(StorageError):
    pass


class BrokenExtent(StorageError):
    """CRC mismatch on read — the repair trigger."""


class ExtentStore:
    """One directory of extents + CRC sidecars + delete journal."""

    def __init__(self, root: str):
        self.root = root
        self.ext_dir = os.path.join(root, "extents")
        self.crc_dir = os.path.join(root, "crc")
        os.makedirs(self.ext_dir, exist_ok=True)
        os.makedirs(self.crc_dir, exist_ok=True)
        self._lock = SanitizedRLock(name="extent_store")
        self._deleted: set[int] = set()
        self._tiny_holes: dict[int, list[tuple[int, int]]] = {}
        self._delete_journal = os.path.join(root, "deleted.jsonl")
        self._load_journal()
        # tiny extents always exist (extent_store.go:613 initTinyExtents)
        for tid in range(1, TINY_EXTENT_COUNT + 1):
            p = self._path(tid)
            if not os.path.exists(p):
                open(p, "wb").close()
        self._tiny_rr = 0  # round-robin tiny allocator (availableTinyExtentC)

    # -- paths / journal -------------------------------------------------------

    def _path(self, extent_id: int) -> str:
        return os.path.join(self.ext_dir, str(extent_id))

    def _crc_path(self, extent_id: int) -> str:
        return os.path.join(self.crc_dir, str(extent_id))

    def _load_journal(self):
        if not os.path.exists(self._delete_journal):
            return
        with open(self._delete_journal) as f:
            for line in f:
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec[0] == "extent":
                    self._deleted.add(rec[1])
                elif rec[0] == "tiny":
                    self._tiny_holes.setdefault(rec[1], []).append((rec[2], rec[3]))

    def _journal(self, rec: list) -> None:
        with open(self._delete_journal, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- extent lifecycle ------------------------------------------------------

    def create(self, extent_id: int) -> None:
        """OpCreateExtent server side (wrap_prepare.go alloc path)."""
        if is_tiny_extent(extent_id):
            return  # tiny extents pre-exist
        with self._lock:
            p = self._path(extent_id)
            if os.path.exists(p):
                raise ExtentExists(str(extent_id))
            self._deleted.discard(extent_id)
            open(p, "wb").close()

    def has(self, extent_id: int) -> bool:
        return os.path.exists(self._path(extent_id)) and extent_id not in self._deleted

    def size(self, extent_id: int) -> int:
        p = self._path(extent_id)
        if not os.path.exists(p) or extent_id in self._deleted:
            raise ExtentNotFound(str(extent_id))
        return os.path.getsize(p)

    def extent_ids(self) -> list[int]:
        with self._lock:
            out = []
            for name in os.listdir(self.ext_dir):
                eid = int(name)
                if eid in self._deleted:
                    continue
                if is_tiny_extent(eid) and os.path.getsize(self._path(eid)) == 0:
                    continue
                out.append(eid)
            return sorted(out)

    # -- tiny allocation -------------------------------------------------------

    def alloc_tiny(self) -> tuple[int, int]:
        """Pick a tiny extent and its aligned append offset.

        The reference hands tiny extents out through a channel and the datanode
        assigns the store's watermark as the write offset
        (datanode/wrap_prepare.go tiny branch); round-robin keeps the 64 files
        evenly filled."""
        with self._lock:
            self._tiny_rr = self._tiny_rr % TINY_EXTENT_COUNT + 1
            tid = self._tiny_rr
            return tid, self._aligned_size(tid)

    def _aligned_size(self, extent_id: int) -> int:
        size = os.path.getsize(self._path(extent_id))
        return (size + PAGE_SIZE - 1) // PAGE_SIZE * PAGE_SIZE

    # -- IO --------------------------------------------------------------------

    def write(self, extent_id: int, offset: int, data: bytes,
              crc: int | None = None, overwrite: bool = False) -> None:
        """Append (or, for the raft random-write path, overwrite) one span.

        Append-only discipline of extent_store.go:327: a non-overwrite write
        must land at the current watermark (tiny: page-aligned watermark)."""
        chaos.failpoint("extent_store.write")
        if crc is not None and zlib.crc32(data) != crc:
            raise StorageError("payload crc mismatch")
        with self._lock:
            p = self._path(extent_id)
            if not os.path.exists(p) or extent_id in self._deleted:
                raise ExtentNotFound(str(extent_id))
            size = os.path.getsize(p)
            if not overwrite:
                expect = self._aligned_size(extent_id) if is_tiny_extent(extent_id) else size
                if offset != expect:
                    raise StorageError(
                        f"extent {extent_id}: append at {offset}, watermark {expect}")
            elif offset + len(data) > size:
                raise StorageError(f"extent {extent_id}: overwrite past watermark")
            with open(p, "r+b") as f:
                if offset > size:
                    f.truncate(offset)  # aligned gap in a tiny extent
                f.seek(offset)
                f.write(data)
            self._update_block_crcs(extent_id, offset, len(data))

    def read(self, extent_id: int, offset: int, size: int, verify: bool = True) -> bytes:
        chaos.failpoint("extent_store.read")
        with self._lock:
            p = self._path(extent_id)
            if not os.path.exists(p) or extent_id in self._deleted:
                raise ExtentNotFound(str(extent_id))
            if verify:
                self._verify_blocks(extent_id, offset, size)
            with open(p, "rb") as f:
                f.seek(offset)
                # corrupt-past-CRC: the block CRCs verified above read the
                # FILE again, so a flip here models the disk returning bad
                # bytes after a clean verify (the repair plane's blind spot
                # the inspector scrub exists for)
                return chaos.corrupt_bytes("extent_store.read.data",
                                           f.read(size))

    # -- delete ----------------------------------------------------------------

    def mark_delete(self, extent_id: int, offset: int = 0, size: int = 0) -> None:
        """Normal extents: drop whole file. Tiny extents: punch [offset, +size)
        (extent_store.go:436 MarkDelete; punch-hole storage/blobfile semantics
        live in the blobstore twin, chubaofs_tpu/blobstore/blobnode.py)."""
        with self._lock:
            if is_tiny_extent(extent_id):
                if size <= 0:
                    raise StorageError("tiny delete needs a range")
                with open(self._path(extent_id), "r+b") as f:
                    _punch_hole(f.fileno(), offset, size)
                self._tiny_holes.setdefault(extent_id, []).append((offset, size))
                self._journal(["tiny", extent_id, offset, size])
                return
            p = self._path(extent_id)
            if not os.path.exists(p):
                raise ExtentNotFound(str(extent_id))
            self._deleted.add(extent_id)
            self._journal(["extent", extent_id])
            os.unlink(p)
            cp = self._crc_path(extent_id)
            if os.path.exists(cp):
                os.unlink(cp)

    def tiny_holes(self, extent_id: int) -> list[tuple[int, int]]:
        return list(self._tiny_holes.get(extent_id, []))

    def is_deleted(self, extent_id: int) -> bool:
        return extent_id in self._deleted

    # -- CRC blocks ------------------------------------------------------------

    def _update_block_crcs(self, extent_id: int, offset: int, length: int) -> None:
        first = offset // BLOCK_SIZE
        last = (offset + length - 1) // BLOCK_SIZE if length else first
        p, cp = self._path(extent_id), self._crc_path(extent_id)
        if not os.path.exists(cp):
            open(cp, "wb").close()
        with open(p, "rb") as f, open(cp, "r+b") as cf:
            cf.seek(0, os.SEEK_END)
            if cf.tell() < (last + 1) * 4:
                cf.write(b"\0" * ((last + 1) * 4 - cf.tell()))
            for blk in range(first, last + 1):
                f.seek(blk * BLOCK_SIZE)
                payload = f.read(BLOCK_SIZE)
                cf.seek(blk * 4)
                cf.write(struct.pack("<I", zlib.crc32(payload)))

    def _verify_blocks(self, extent_id: int, offset: int, size: int) -> None:
        cp = self._crc_path(extent_id)
        if not os.path.exists(cp) or size <= 0:
            return
        with open(cp, "rb") as cf:
            crcs = cf.read()
        first, last = offset // BLOCK_SIZE, (offset + size - 1) // BLOCK_SIZE
        with open(self._path(extent_id), "rb") as f:
            for blk in range(first, last + 1):
                if (blk + 1) * 4 > len(crcs):
                    continue
                want = struct.unpack_from("<I", crcs, blk * 4)[0]
                f.seek(blk * BLOCK_SIZE)
                got = zlib.crc32(f.read(BLOCK_SIZE))
                if got != want:
                    from chubaofs_tpu.utils.exporter import registry

                    registry("datanode").counter("crc_mismatch_total").add()
                    raise BrokenExtent(f"extent {extent_id} block {blk}")

    def block_crc(self, extent_id: int, block: int) -> int:
        cp = self._crc_path(extent_id)
        if not os.path.exists(cp):
            return 0
        with open(cp, "rb") as cf:
            blob = cf.read()
        if (block + 1) * 4 > len(blob):
            return 0
        return struct.unpack_from("<I", blob, block * 4)[0]

    # -- repair currency -------------------------------------------------------

    def watermarks(self) -> dict[int, int]:
        """{extent_id: size} across live extents (GetAllWatermarks :558)."""
        out = {}
        for eid in self.extent_ids():
            out[eid] = self._aligned_size(eid) if is_tiny_extent(eid) else self.size(eid)
        return out

    def used_bytes(self) -> int:
        return sum(self.watermarks().values())
