"""Safe binary codec for raft wire frames and WAL entries — pickle's replacement.

The reference encodes raft commands and transport frames with explicit binary
encodings (depends/tiglabs/raft proto marshaling; metanode snapshot sections
carry their own CRCs, partition_store.go:57-1033) precisely so that a network
peer can never make the decoder execute anything. Round 1 shipped pickle behind
an HMAC gate; the advisor correctly flagged that as RCE-adjacent (a leaked or
defaulted secret turns the raft port into an eval server). This codec closes
that class entirely: decoding constructs only None/bool/int/float/str/bytes/
list/tuple/dict values, never objects.

Wire grammar (tag byte + payload):
    N                 -> None
    T / F             -> True / False
    i <zigzag varint> -> int (arbitrary precision via varint continuation)
    f <8B LE double>  -> float
    s <varint n> <n bytes utf8>  -> str
    b <varint n> <n bytes>       -> bytes
    l <varint n> <n values>      -> list
    t <varint n> <n values>      -> tuple
    d <varint n> <n (key value)> -> dict

Msg batches are encoded schema-less as plain values: a frame is the list of
per-Msg field lists (raft.transport owns the field order). Decode failures
raise CodecError — callers treat the frame as hostile and drop the connection.
"""

from __future__ import annotations

import struct

_F64 = struct.Struct("<d")

MAX_DEPTH = 64


class CodecError(ValueError):
    pass


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> (n.bit_length() + 1)) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _encode(out: bytearray, v, depth: int) -> None:
    if depth > MAX_DEPTH:
        raise CodecError("value too deeply nested")
    if v is None:
        out.append(ord("N"))
    elif v is True:
        out.append(ord("T"))
    elif v is False:
        out.append(ord("F"))
    elif isinstance(v, int):
        out.append(ord("i"))
        _write_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(ord("f"))
        out += _F64.pack(v)
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.append(ord("s"))
        _write_varint(out, len(raw))
        out += raw
    elif isinstance(v, (bytes, bytearray, memoryview)):
        raw = bytes(v)
        out.append(ord("b"))
        _write_varint(out, len(raw))
        out += raw
    elif isinstance(v, tuple):
        out.append(ord("t"))
        _write_varint(out, len(v))
        for x in v:
            _encode(out, x, depth + 1)
    elif isinstance(v, list):
        out.append(ord("l"))
        _write_varint(out, len(v))
        for x in v:
            _encode(out, x, depth + 1)
    elif isinstance(v, dict):
        out.append(ord("d"))
        _write_varint(out, len(v))
        for k, x in v.items():
            _encode(out, k, depth + 1)
            _encode(out, x, depth + 1)
    else:
        raise CodecError(f"unencodable type {type(v).__name__}")


def dumps(v) -> bytes:
    out = bytearray()
    _encode(out, v, 0)
    return bytes(out)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise CodecError("truncated value")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def varint(self) -> int:
        shift = 0
        n = 0
        while True:
            if self.pos >= len(self.buf):
                raise CodecError("truncated varint")
            if shift > 630:  # bounds attacker-supplied bignum growth
                raise CodecError("varint too long")
            b = self.buf[self.pos]
            self.pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7


def _decode(r: _Reader, depth: int):
    if depth > MAX_DEPTH:
        raise CodecError("value too deeply nested")
    tag = r.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _unzigzag(r.varint())
    if tag == b"f":
        return _F64.unpack(r.take(8))[0]
    if tag == b"s":
        try:
            return r.take(r.varint()).decode("utf-8")
        except UnicodeDecodeError as e:
            raise CodecError(f"bad utf8: {e}") from None
    if tag == b"b":
        return r.take(r.varint())
    if tag in (b"l", b"t"):
        n = r.varint()
        if n > len(r.buf):  # cheap bound: each element takes >= 1 byte
            raise CodecError("sequence length exceeds frame")
        seq = [_decode(r, depth + 1) for _ in range(n)]
        return tuple(seq) if tag == b"t" else seq
    if tag == b"d":
        n = r.varint()
        if n > len(r.buf):
            raise CodecError("dict length exceeds frame")
        out = {}
        for _ in range(n):
            k = _decode(r, depth + 1)
            try:
                out[k] = _decode(r, depth + 1)
            except TypeError:
                raise CodecError("unhashable dict key") from None
        return out
    raise CodecError(f"unknown tag {tag!r}")


def loads(buf: bytes):
    r = _Reader(bytes(buf))
    v = _decode(r, 0)
    if r.pos != len(r.buf):
        raise CodecError("trailing bytes after value")
    return v
