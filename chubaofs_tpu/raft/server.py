"""MultiRaft server — hosts many raft groups per node over one transport.

Reference counterpart: raftstore/raftstore.go:34-41 (RaftStore facade),
depends/tiglabs/raft/server.go:65 (NewRaftServer, many groups, merged
heartbeats). One MultiRaft instance per node hosts every partition's group:
master GroupID=1, one group per meta partition, one per random-write data
partition — same multiplexing the reference uses.

The transport is pluggable; InProcNet wires nodes in one process (the test
strategy of SURVEY §4) and batches per-destination messages the way tiglabs
merges heartbeats across groups. WAL persistence: term/vote + entries per
group as JSONL; snapshots delegate to the StateMachine and compact the log.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import Future

from chubaofs_tpu.raft import codec
from chubaofs_tpu.raft.core import Entry, Msg, NotLeaderError, RaftCore, ROLE_LEADER


class StateMachine:
    """What a replicated component implements (statemachine.go:23-30 analog)."""

    def apply(self, data, index: int):  # -> result delivered to the proposer
        raise NotImplementedError

    def snapshot(self) -> bytes:
        raise NotImplementedError

    def restore(self, data: bytes) -> None:
        raise NotImplementedError

    def on_leader_change(self, leader: int | None) -> None:
        pass


class InProcNet:
    """In-process transport connecting MultiRaft nodes; per-dst batching."""

    def __init__(self):
        self.nodes: dict[int, "MultiRaft"] = {}
        self.partitions: set[frozenset] = set()  # simulated network partitions
        self._lock = threading.Lock()

    def register(self, node: "MultiRaft"):
        with self._lock:
            self.nodes[node.node_id] = node

    def isolate(self, *node_ids: int):
        """Cut node_ids off from everyone else (fault injection)."""
        with self._lock:
            self.partitions.add(frozenset(node_ids))

    def heal(self):
        with self._lock:
            self.partitions.clear()

    def _blocked(self, a: int, b: int) -> bool:
        for part in self.partitions:
            if (a in part) != (b in part):
                return True
        return False

    def send(self, msgs: list[Msg]):
        by_dst: dict[int, list[Msg]] = {}
        for m in msgs:
            if self._blocked(m.src, m.dst):
                continue
            by_dst.setdefault(m.dst, []).append(m)
        for dst, batch in by_dst.items():
            node = self.nodes.get(dst)
            if node is not None:
                node.deliver(batch)


class _Group:
    def __init__(self, core: RaftCore, sm: StateMachine, wal_path: str | None):
        self.core = core
        self.sm = sm
        self.wal_path = wal_path
        self.wal = None
        self.waiters: dict[int, tuple[int, Future]] = {}  # index -> (term, future)
        self.last_leader: int | None = None
        if wal_path:
            self._recover()
            self.wal = open(wal_path, "a")

    def _recover(self):
        snap_path = self.wal_path + ".snap"
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                meta_len = int.from_bytes(f.read(4), "little")
                meta = json.loads(f.read(meta_len))
                payload = f.read()
            self.sm.restore(payload)
            self.core.offset = meta["index"]
            self.core.offset_term = meta["term"]
            self.core.commit = self.core.applied = meta["index"]
            if "peers" in meta:
                self.core.peers = [p for p in meta["peers"] if p != self.core.id]
        if os.path.exists(self.wal_path):
            with open(self.wal_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    rec = json.loads(line)
                    if rec[0] == "hs":  # hard state
                        self.core.term, self.core.voted_for = rec[1], rec[2]
                    elif rec[0] == "ent":
                        idx, term, blob = rec[1], rec[2], rec[3]
                        if idx <= self.core.offset:
                            continue
                        # truncate conflicts, then append
                        self.core.entries = self.core.entries[: idx - self.core.offset - 1]
                        try:
                            data = codec.loads(bytes.fromhex(blob)) if blob else None
                        except codec.CodecError:
                            raise RuntimeError(
                                f"{self.wal_path}: WAL entry is not in the "
                                "current (codec) format — this walDir was "
                                "written by an incompatible build; move it "
                                "aside to start fresh") from None
                        self.core.entries.append(Entry(term, data))
                    elif rec[0] == "commit":
                        idx = min(rec[1], self.core.last_index)
                        self.core.commit = max(self.core.commit, idx)
            # replay committed entries into the SM (config changes re-apply to
            # the core so the recovered membership matches pre-crash)
            for idx in range(self.core.offset + 1, self.core.commit + 1):
                ent = self.core.entry_at(idx)
                if ent.data is None:
                    continue
                if (isinstance(ent.data, tuple) and len(ent.data) == 3
                        and ent.data[0] == "__config_change__"):
                    self.core.apply_config(ent.data[1], ent.data[2])
                    continue
                self.sm.apply(ent.data, idx)
            self.core.applied = self.core.commit

    def persist(self, hard_state_changed: bool, new_entries: list[tuple[int, Entry]], commit: int):
        if not self.wal:
            return
        if hard_state_changed:
            self.wal.write(json.dumps(["hs", self.core.term, self.core.voted_for]) + "\n")
        for idx, ent in new_entries:
            blob = codec.dumps(ent.data).hex() if ent.data is not None else ""
            self.wal.write(json.dumps(["ent", idx, ent.term, blob]) + "\n")
        self.wal.write(json.dumps(["commit", commit]) + "\n")
        self.wal.flush()

    def take_snapshot(self):
        """Snapshot the SM at applied index and compact the log."""
        if not self.wal_path:
            self.core.compact(self.core.applied, self.core.term_at(self.core.applied))
            return
        idx = self.core.applied
        term = self.core.term_at(idx)
        payload = self.sm.snapshot()
        # membership travels with the snapshot: config entries before the
        # compaction point are gone from the log
        meta = json.dumps({"index": idx, "term": term,
                           "peers": list(self.core.peers)}).encode()
        tmp = self.wal_path + ".snap.tmp"
        with open(tmp, "wb") as f:
            f.write(len(meta).to_bytes(4, "little") + meta + payload)
        os.replace(tmp, self.wal_path + ".snap")
        self.core.compact(idx, term)
        self.wal.close()
        self.wal = open(self.wal_path, "w")
        self.wal.write(json.dumps(["hs", self.core.term, self.core.voted_for]) + "\n")
        for i in range(self.core.offset + 1, self.core.last_index + 1):
            ent = self.core.entry_at(i)
            blob = codec.dumps(ent.data).hex() if ent.data is not None else ""
            self.wal.write(json.dumps(["ent", i, ent.term, blob]) + "\n")
        self.wal.write(json.dumps(["commit", self.core.commit]) + "\n")
        self.wal.flush()


class MultiRaft:
    """All raft groups of one node + the tick/apply pump."""

    def __init__(self, node_id: int, net: InProcNet, wal_dir: str | None = None,
                 snapshot_every: int = 0):
        self.node_id = node_id
        self.net = net
        self.wal_dir = wal_dir
        self.snapshot_every = snapshot_every
        self.groups: dict[int, _Group] = {}
        self._lock = threading.RLock()
        net.register(self)

    # -- group lifecycle -----------------------------------------------------

    def create_group(self, group_id: int, peers: list[int], sm: StateMachine) -> None:
        with self._lock:
            core = RaftCore(group_id, self.node_id, peers)
            wal_path = None
            if self.wal_dir:
                os.makedirs(self.wal_dir, exist_ok=True)
                wal_path = os.path.join(self.wal_dir, f"g{group_id}.wal")
            g = _Group(core, sm, wal_path)

            def snap_fn():
                payload = sm.snapshot()
                return core.applied, core.term_at(core.applied), payload

            core.snapshot_fn = snap_fn
            self.groups[group_id] = g

    def remove_group(self, group_id: int) -> None:
        with self._lock:
            self.groups.pop(group_id, None)

    def is_leader(self, group_id: int) -> bool:
        g = self.groups.get(group_id)
        return g is not None and g.core.role == ROLE_LEADER

    def leader_of(self, group_id: int) -> int | None:
        g = self.groups.get(group_id)
        return g.core.leader if g else None

    # -- the pump ------------------------------------------------------------

    def tick(self):
        """One logical clock tick for every group; flush I/O.

        Quiescent leaders' liveness beats are MERGED: one group_hb message per
        peer carries every group's (term, commit) slice, so heartbeat traffic
        scales with peers, not partitions (tiglabs raft README:18).

        Outbound messages are sent AFTER the node lock is released: delivery
        acquires the destination node's lock, and holding two node locks at
        once would deadlock concurrent datanode/metanode handler threads."""
        out: list[Msg] = []
        merged: dict[int, list] = {}  # dst -> [[gid, term, commit], ...]
        with self._lock:
            for gid, g in self.groups.items():
                term0, vote0 = g.core.term, g.core.voted_for
                last0, commit0 = g.core.last_index, g.core.commit
                g.core.tick()
                for p in g.core.pending_hb:
                    merged.setdefault(p, []).append([gid, g.core.term, g.core.commit])
                g.core.pending_hb.clear()
                out += self._flush(g, term0, vote0, last0, commit0)
        for dst, slices in merged.items():
            out.append(Msg(type="group_hb", group=0, src=self.node_id, dst=dst,
                           term=0, hb=slices))
        if out:
            self.net.send(out)

    def deliver(self, msgs: list[Msg]):
        out: list[Msg] = []
        with self._lock:
            for m in msgs:
                if m.type == "group_hb":
                    out += self._on_group_hb(m)
                    continue
                if m.type == "group_hb_resp":
                    out += self._on_group_hb_resp(m)
                    continue
                g = self.groups.get(m.group)
                if g is None:
                    continue
                term0, vote0 = g.core.term, g.core.voted_for
                last0, commit0 = g.core.last_index, g.core.commit
                g.core.step(m)
                out += self._flush(g, term0, vote0, last0, commit0)
        if out:
            self.net.send(out)

    def _on_group_hb(self, m: Msg) -> list[Msg]:
        """Fan a merged heartbeat into each group; stale sender terms ride
        back in ONE merged response."""
        out: list[Msg] = []
        stale: list = []
        for gid, term, commit in m.hb:
            g = self.groups.get(gid)
            if g is None:
                continue
            term0, vote0 = g.core.term, g.core.voted_for
            last0, commit0 = g.core.last_index, g.core.commit
            ok = g.core.step_group_hb(m.src, term, commit)
            out += self._flush(g, term0, vote0, last0, commit0)
            if not ok:
                stale.append([gid, g.core.term])
        if stale:
            out.append(Msg(type="group_hb_resp", group=0, src=self.node_id,
                           dst=m.src, term=0, hb=stale))
        return out

    def _on_group_hb_resp(self, m: Msg) -> list[Msg]:
        """A peer saw a higher term for these groups: step down there."""
        out: list[Msg] = []
        for gid, term in m.hb:
            g = self.groups.get(gid)
            if g is None or term <= g.core.term:
                continue
            term0, vote0 = g.core.term, g.core.voted_for
            last0, commit0 = g.core.last_index, g.core.commit
            g.core._become_follower(term, None)
            out += self._flush(g, term0, vote0, last0, commit0)
        return out

    def _flush(self, g: _Group, term0: int, vote0, last0: int, commit0: int) -> list[Msg]:
        core = g.core
        msgs, committed = core.ready()
        new_entries = [
            (i, core.entry_at(i))
            for i in range(max(last0, core.offset) + 1, core.last_index + 1)
        ]
        hs_changed = core.term != term0 or core.voted_for != vote0
        if hs_changed or new_entries or core.commit != commit0:
            g.persist(hs_changed, new_entries, core.commit)
        for idx, ent in committed:
            if isinstance(ent.data, tuple) and len(ent.data) == 2 and ent.data[0] == "__install_snapshot__":
                g.sm.restore(ent.data[1])
                continue
            if (isinstance(ent.data, tuple) and len(ent.data) == 3
                    and ent.data[0] == "__config_change__"):
                g.core.apply_config(ent.data[1], ent.data[2])
                waiter = g.waiters.pop(idx, None)
                if waiter and ent.term == waiter[0]:
                    waiter[1].set_result(sorted(g.core.peers + [g.core.id]))
                continue
            result = g.sm.apply(ent.data, idx) if ent.data is not None else None
            waiter = g.waiters.pop(idx, None)
            if waiter:
                wterm, fut = waiter
                if ent.term == wterm:
                    fut.set_result(result)
                else:
                    fut.set_exception(NotLeaderError(core.leader))
        if g.last_leader != core.leader:
            g.last_leader = core.leader
            g.sm.on_leader_change(core.leader)
        if (
            self.snapshot_every
            and core.applied - core.offset >= self.snapshot_every
        ):
            g.take_snapshot()
        return msgs

    # -- client API ------------------------------------------------------------

    def propose_config(self, group_id: int, action: str, node_id: int) -> Future:
        """Single-server membership change ('add'/'remove' one node); the
        future resolves with the new peer set once the change commits."""
        assert action in ("add", "remove"), action
        return self.propose(group_id, ("__config_change__", action, node_id))

    def propose(self, group_id: int, data) -> Future:
        """Replicate one command; future resolves with sm.apply's result."""
        with self._lock:
            g = self.groups.get(group_id)
            if g is None:
                raise KeyError(f"no group {group_id} on node {self.node_id}")
            last0, commit0 = g.core.last_index, g.core.commit
            idx = g.core.propose(data)  # raises NotLeaderError when follower
            fut: Future = Future()
            g.waiters[idx] = (g.core.term, fut)
            out = self._flush(g, g.core.term, g.core.voted_for, last0, commit0)
        if out:
            self.net.send(out)
        return fut


def run_until(net: InProcNet, cond, max_ticks: int = 300, sleep: float = 0.0) -> bool:
    """Drive every node's clock until cond() or tick budget exhausted (tests)."""
    for _ in range(max_ticks):
        for node in list(net.nodes.values()):
            node.tick()
        if cond():
            return True
        if sleep:
            time.sleep(sleep)
    return cond()


class TickLoop:
    """Background wall-clock pump for live deployments (100ms/tick default)."""

    def __init__(self, nodes: list[MultiRaft], interval: float = 0.1):
        self.nodes = nodes
        self.interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True, name="raft-tick")

    def start(self):
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            for n in self.nodes:
                n.tick()
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)
