"""MultiRaft server — hosts many raft groups per node over one transport.

Reference counterpart: raftstore/raftstore.go:34-41 (RaftStore facade),
depends/tiglabs/raft/server.go:65 (NewRaftServer, many groups, merged
heartbeats). One MultiRaft instance per node hosts every partition's group:
master GroupID=1, one group per meta partition, one per random-write data
partition — same multiplexing the reference uses.

The transport is pluggable; InProcNet wires nodes in one process (the test
strategy of SURVEY §4) and batches per-destination messages the way tiglabs
merges heartbeats across groups. WAL persistence: term/vote + entries per
group as JSONL; snapshots delegate to the StateMachine and compact the log.

Group commit (raft.go:283-311 parity): `propose`/`propose_batch` ENQUEUE onto
the group's pending queue and WAKE the node's drain pump — the reference's
proposal-channel + run-goroutine shape. The pump drains the queue under the
node lock: one log-append pass, one WAL write+flush, and one AppendEntries
fan-out for the whole drained batch, so N concurrent clients coalesce into
~1 replication round instead of N. A proposer-inline drain would NOT batch
under the GIL (the first proposer runs its whole commit round before the
others get scheduled — measured 0.6x at 64 proposers, not 5x), so the pump
adds a sub-millisecond gather window, armed only while drains actually
batch, and keeps single-proposer latency at plain thread-handoff cost. The
tick pump drains too, as the safety net that fails stranded futures after
leadership loss.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from concurrent.futures import Future

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.utils.locks import SanitizedLock, SanitizedRLock
from chubaofs_tpu.raft import codec
from chubaofs_tpu.raft.core import Entry, Msg, NotLeaderError, RaftCore, ROLE_LEADER


class StateMachine:
    """What a replicated component implements (statemachine.go:23-30 analog)."""

    def apply(self, data, index: int):  # -> result delivered to the proposer
        raise NotImplementedError

    def snapshot(self) -> bytes:
        raise NotImplementedError

    def restore(self, data: bytes) -> None:
        raise NotImplementedError

    def on_leader_change(self, leader: int | None) -> None:
        pass


class InProcNet:
    """In-process transport connecting MultiRaft nodes; per-dst batching."""

    def __init__(self):
        self.nodes: dict[int, "MultiRaft"] = {}
        self.partitions: set[frozenset] = set()  # simulated network partitions
        self._lock = SanitizedLock(name="raft.net")

    def register(self, node: "MultiRaft"):
        with self._lock:
            self.nodes[node.node_id] = node

    def isolate(self, *node_ids: int):
        """Cut node_ids off from everyone else (fault injection)."""
        with self._lock:
            self.partitions.add(frozenset(node_ids))

    def heal(self):
        with self._lock:
            self.partitions.clear()

    def _blocked(self, a: int, b: int) -> bool:
        for part in self.partitions:
            if (a in part) != (b in part):
                return True
        return False

    def send(self, msgs: list[Msg]):
        by_dst: dict[int, list[Msg]] = {}
        for m in msgs:
            if self._blocked(m.src, m.dst):
                continue
            by_dst.setdefault(m.dst, []).append(m)
        for dst, batch in by_dst.items():
            try:
                # same site TcpNet exposes: injected link loss drops the whole
                # per-destination frame; raft re-sends via the next tick
                chaos.failpoint("raft.send", node=batch[0].src)
            except chaos.FailpointError:
                continue
            node = self.nodes.get(dst)
            if node is not None:
                node.deliver(batch)


# cache an entry's WAL encoding only when small: in-proc replicas share the
# Entry, so one encode serves all three logs — but pinning a hex copy of a
# 64 KiB datanode payload for the entry's whole uncompacted life is a worse
# trade than re-encoding it per replica
_WAL_HEX_CACHE_MAX = 1 << 13


def _ent_blob(ent: Entry) -> str:
    blob = ent.wal_hex
    if blob is None:
        blob = codec.dumps(ent.data).hex() if ent.data is not None else ""
        if len(blob) <= _WAL_HEX_CACHE_MAX:
            ent.wal_hex = blob
    return blob


class _Group:
    def __init__(self, core: RaftCore, sm: StateMachine, wal_path: str | None):
        self.core = core
        self.sm = sm
        self.wal_path = wal_path
        self.wal = None
        self.waiters: dict[int, tuple[int, Future]] = {}  # index -> (term, future)
        # group commit: futures FIFO-parallel to core.pending — both only
        # mutated under pending_lock, so queue order IS future order
        self.pending_lock = SanitizedLock(name="raft.pending")
        self.pending_futs: deque[Future] = deque()
        self.last_leader: int | None = None
        if wal_path:
            self._recover()
            self.wal = open(wal_path, "a")

    def _recover(self):
        snap_path = self.wal_path + ".snap"
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                meta_len = int.from_bytes(f.read(4), "little")
                meta = json.loads(f.read(meta_len))
                payload = f.read()
            self.sm.restore(payload)
            self.core.offset = meta["index"]
            self.core.offset_term = meta["term"]
            self.core.commit = self.core.applied = meta["index"]
            if "peers" in meta:
                self.core.peers = [p for p in meta["peers"] if p != self.core.id]
        if os.path.exists(self.wal_path):
            with open(self.wal_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    rec = json.loads(line)
                    if rec[0] == "hs":  # hard state
                        self.core.term, self.core.voted_for = rec[1], rec[2]
                    elif rec[0] in ("ent", "entb"):
                        # "ent": one [idx, term, blob]; "entb": a whole drained
                        # batch [[idx, term, blob], ...] in ONE record (group
                        # commit writes + flushes once per batch)
                        ents = [rec[1:]] if rec[0] == "ent" else rec[1]
                        for idx, term, blob in ents:
                            if idx <= self.core.offset:
                                continue
                            # truncate conflicts in place, then append (a
                            # per-record whole-list copy makes replay O(n^2))
                            if idx <= self.core.last_index:
                                del self.core.entries[idx - self.core.offset - 1:]
                            try:
                                data = codec.loads(bytes.fromhex(blob)) if blob else None
                            except codec.CodecError:
                                raise RuntimeError(
                                    f"{self.wal_path}: WAL entry is not in the "
                                    "current (codec) format — this walDir was "
                                    "written by an incompatible build; move it "
                                    "aside to start fresh") from None
                            self.core.entries.append(Entry(term, data))
                    elif rec[0] == "commit":
                        idx = min(rec[1], self.core.last_index)
                        self.core.commit = max(self.core.commit, idx)
            # replay committed entries into the SM (config changes re-apply to
            # the core so the recovered membership matches pre-crash)
            for idx in range(self.core.offset + 1, self.core.commit + 1):
                ent = self.core.entry_at(idx)
                if ent.data is None:
                    continue
                if (isinstance(ent.data, tuple) and len(ent.data) == 3
                        and ent.data[0] == "__config_change__"):
                    self.core.apply_config(ent.data[1], ent.data[2])
                    continue
                self.sm.apply(ent.data, idx)
            self.core.applied = self.core.commit

    def persist(self, hard_state_changed: bool, new_entries: list[tuple[int, Entry]], commit: int):
        """Batched WAL append: the whole drained batch lands as ONE "entb"
        record in ONE write + ONE flush (group commit's durability half —
        the per-entry write/flush was most of the unbatched commit cost)."""
        if not self.wal:
            return
        recs = []
        if hard_state_changed:
            recs.append(json.dumps(["hs", self.core.term, self.core.voted_for]))
        if new_entries:
            recs.append(json.dumps(["entb", [
                [idx, ent.term, _ent_blob(ent)] for idx, ent in new_entries]]))
        recs.append(json.dumps(["commit", commit]))
        self.wal.write("\n".join(recs) + "\n")
        self.wal.flush()

    def take_snapshot(self):
        """Snapshot the SM at applied index and compact the log."""
        if not self.wal_path:
            self.core.compact(self.core.applied, self.core.term_at(self.core.applied))
            return
        idx = self.core.applied
        term = self.core.term_at(idx)
        payload = self.sm.snapshot()
        # membership travels with the snapshot: config entries before the
        # compaction point are gone from the log
        meta = json.dumps({"index": idx, "term": term,
                           "peers": list(self.core.peers)}).encode()
        tmp = self.wal_path + ".snap.tmp"
        with open(tmp, "wb") as f:
            f.write(len(meta).to_bytes(4, "little") + meta + payload)
        os.replace(tmp, self.wal_path + ".snap")
        self.core.compact(idx, term)
        self.wal.close()
        self.wal = open(self.wal_path, "w")
        self.wal.write(json.dumps(["hs", self.core.term, self.core.voted_for]) + "\n")
        for i in range(self.core.offset + 1, self.core.last_index + 1):
            ent = self.core.entry_at(i)
            self.wal.write(json.dumps(["ent", i, ent.term, _ent_blob(ent)]) + "\n")
        self.wal.write(json.dumps(["commit", self.core.commit]) + "\n")
        self.wal.flush()


class MultiRaft:
    """All raft groups of one node + the tick/apply pump."""

    # gather window armed once drains start batching: proposals arriving
    # inside it ride the same commit round (group commit); zero while the
    # node sees only sequential proposers, so their latency stays handoff-only
    GROUP_WINDOW = float(os.environ.get("CFS_RAFT_GROUP_WINDOW_MS", "0.5")) / 1e3

    def __init__(self, node_id: int, net: InProcNet, wal_dir: str | None = None,
                 snapshot_every: int = 0):
        self.node_id = node_id
        self.net = net
        self.wal_dir = wal_dir
        self.snapshot_every = snapshot_every
        self.groups: dict[int, _Group] = {}
        # per-node name: tick/deliver must NEVER hold two node locks at once
        # (delivery acquires the destination's), and distinct names let the
        # sanitizer prove it — a nodeA->nodeB + nodeB->nodeA edge pair is the
        # deadlock this file's send-outside-the-lock discipline prevents
        self._lock = SanitizedRLock(name=f"raft.node{node_id}")
        # proposal pump: proposers enqueue + wake; the pump drains (the
        # reference's propose-channel/run-goroutine split). Lazy: nodes that
        # never see a proposal never spawn the thread.
        self._prop_wake = threading.Event()
        self._dirty: deque[_Group] = deque()
        self._pump_started = False
        self._pump_lock = SanitizedLock(name="raft.pumpstart")
        self.pump_dead = False  # a drain crash poisons the node: fail fast
        # group-commit observability. The role registry (cfs_raft_*) is the
        # primary surface — counters + a batch-occupancy histogram rendered
        # by every daemon's /metrics; drain_stats stays as the legacy dict
        # view (perfbench resets/reads it), updated only under _stats_lock
        # so readers can take a consistent snapshot.
        self.drain_stats = {"rounds": 0, "entries": 0, "max_batch": 0}
        self._stats_lock = SanitizedLock(name="raft.stats")
        net.register(self)

    # -- group lifecycle -----------------------------------------------------

    def create_group(self, group_id: int, peers: list[int], sm: StateMachine) -> None:
        with self._lock:
            core = RaftCore(group_id, self.node_id, peers)
            wal_path = None
            if self.wal_dir:
                os.makedirs(self.wal_dir, exist_ok=True)
                wal_path = os.path.join(self.wal_dir, f"g{group_id}.wal")
            g = _Group(core, sm, wal_path)

            def snap_fn():
                payload = sm.snapshot()
                return core.applied, core.term_at(core.applied), payload

            core.snapshot_fn = snap_fn
            self.groups[group_id] = g

    def remove_group(self, group_id: int) -> None:
        with self._lock:
            self.groups.pop(group_id, None)

    def is_leader(self, group_id: int) -> bool:
        g = self.groups.get(group_id)
        return g is not None and g.core.role == ROLE_LEADER

    def leader_of(self, group_id: int) -> int | None:
        g = self.groups.get(group_id)
        return g.core.leader if g else None

    # -- the pump ------------------------------------------------------------

    def tick(self):
        """One logical clock tick for every group; flush I/O.

        Quiescent leaders' liveness beats are MERGED: one group_hb message per
        peer carries every group's (term, commit) slice, so heartbeat traffic
        scales with peers, not partitions (tiglabs raft README:18).

        Outbound messages are sent AFTER the node lock is released: delivery
        acquires the destination node's lock, and holding two node locks at
        once would deadlock concurrent datanode/metanode handler threads."""
        out: list[Msg] = []
        merged: dict[int, list] = {}  # dst -> [[gid, term, commit], ...]
        with self._lock:
            for gid, g in self.groups.items():
                if not self.pump_dead and (g.core.pending or g.pending_futs):
                    # drain stragglers (and fail stranded futures after a
                    # leadership loss) — proposers normally drain on wakeup;
                    # a dead pump means a poisoned mid-round state, so the
                    # safety net must not keep committing on top of it
                    out += self._drain_pending(g)
                term0, vote0 = g.core.term, g.core.voted_for
                last0, commit0 = g.core.last_index, g.core.commit
                g.core.tick()
                for p in g.core.pending_hb:
                    merged.setdefault(p, []).append([gid, g.core.term, g.core.commit])
                g.core.pending_hb.clear()
                out += self._flush(g, term0, vote0, last0, commit0)
        for dst, slices in merged.items():
            out.append(Msg(type="group_hb", group=0, src=self.node_id, dst=dst,
                           term=0, hb=slices))
        if out:
            self.net.send(out)

    def deliver(self, msgs: list[Msg]):
        out: list[Msg] = []
        with self._lock:
            for m in msgs:
                if m.type == "group_hb":
                    out += self._on_group_hb(m)
                    continue
                if m.type == "group_hb_resp":
                    out += self._on_group_hb_resp(m)
                    continue
                g = self.groups.get(m.group)
                if g is None:
                    continue
                term0, vote0 = g.core.term, g.core.voted_for
                last0, commit0 = g.core.last_index, g.core.commit
                g.core.step(m)
                out += self._flush(g, term0, vote0, last0, commit0)
        if out:
            self.net.send(out)

    def _on_group_hb(self, m: Msg) -> list[Msg]:
        """Fan a merged heartbeat into each group; stale sender terms ride
        back in ONE merged response."""
        out: list[Msg] = []
        stale: list = []
        for gid, term, commit in m.hb:
            g = self.groups.get(gid)
            if g is None:
                continue
            term0, vote0 = g.core.term, g.core.voted_for
            last0, commit0 = g.core.last_index, g.core.commit
            ok = g.core.step_group_hb(m.src, term, commit)
            out += self._flush(g, term0, vote0, last0, commit0)
            if not ok:
                stale.append([gid, g.core.term])
        if stale:
            out.append(Msg(type="group_hb_resp", group=0, src=self.node_id,
                           dst=m.src, term=0, hb=stale))
        return out

    def _on_group_hb_resp(self, m: Msg) -> list[Msg]:
        """A peer saw a higher term for these groups: step down there."""
        out: list[Msg] = []
        for gid, term in m.hb:
            g = self.groups.get(gid)
            if g is None or term <= g.core.term:
                continue
            term0, vote0 = g.core.term, g.core.voted_for
            last0, commit0 = g.core.last_index, g.core.commit
            g.core._become_follower(term, None)
            out += self._flush(g, term0, vote0, last0, commit0)
        return out

    def _flush(self, g: _Group, term0: int, vote0, last0: int, commit0: int) -> list[Msg]:
        core = g.core
        msgs, committed = core.ready()
        # a conflicting append may have OVERWRITTEN entries below last0: the
        # rewritten span must reach the WAL too (its record truncates the
        # stale-term suffix at replay), or recovery replays the old entries
        start = max(last0, core.offset) + 1
        if core.log_rewind is not None:
            start = min(start, max(core.log_rewind, core.offset + 1))
            core.log_rewind = None
        new_entries = [
            (i, core.entry_at(i))
            for i in range(start, core.last_index + 1)
        ]
        hs_changed = core.term != term0 or core.voted_for != vote0
        if hs_changed or new_entries or core.commit != commit0:
            g.persist(hs_changed, new_entries, core.commit)
            if new_entries:
                # the crash window between the batched WAL append and the
                # apply pass below — chaos tests prove a restart here replays
                # every drained entry exactly once (no loss, no double apply)
                chaos.failpoint("raft.drain", node=self.node_id)
        for idx, ent in committed:
            if isinstance(ent.data, tuple) and len(ent.data) == 2 and ent.data[0] == "__install_snapshot__":
                g.sm.restore(ent.data[1])
                continue
            if (isinstance(ent.data, tuple) and len(ent.data) == 3
                    and ent.data[0] == "__config_change__"):
                g.core.apply_config(ent.data[1], ent.data[2])
                waiter = g.waiters.pop(idx, None)
                if waiter and ent.term == waiter[0]:
                    waiter[1].set_result(sorted(g.core.peers + [g.core.id]))
                continue
            result = g.sm.apply(ent.data, idx) if ent.data is not None else None
            waiter = g.waiters.pop(idx, None)
            if waiter:
                wterm, fut = waiter
                if ent.term == wterm:
                    fut.set_result(result)
                else:
                    fut.set_exception(NotLeaderError(core.leader))
        if g.last_leader != core.leader:
            g.last_leader = core.leader
            g.sm.on_leader_change(core.leader)
        if (
            self.snapshot_every
            and core.applied - core.offset >= self.snapshot_every
        ):
            g.take_snapshot()
        return msgs

    # -- client API ------------------------------------------------------------

    def propose_config(self, group_id: int, action: str, node_id: int) -> Future:
        """Single-server membership change ('add'/'remove' one node); the
        future resolves with the new peer set once the change commits."""
        assert action in ("add", "remove"), action
        return self.propose(group_id, ("__config_change__", action, node_id))

    def propose(self, group_id: int, data) -> Future:
        """Replicate one command; future resolves with sm.apply's result.
        Rides the group-commit path: concurrent proposers coalesce into one
        WAL flush + one replication round per drained batch."""
        return self.propose_batch(group_id, [data])[0]

    def propose_batch(self, group_id: int, datas: list) -> list[Future]:
        """Replicate a FIFO batch of commands; one future per command, each
        resolving with its own sm.apply result (an entry rejected by a
        leadership change fails only its own future). Raises NotLeaderError
        synchronously when this node is not the group's leader."""
        g = self.groups.get(group_id)
        if g is None:
            raise KeyError(f"no group {group_id} on node {self.node_id}")
        if self.pump_dead:
            raise RuntimeError(
                f"raft drain pump died on node {self.node_id} "
                "(see stderr traceback); restart the node to recover")
        futs: list[Future] = []
        with g.pending_lock:
            if g.core.role != ROLE_LEADER:
                raise NotLeaderError(g.core.leader)
            for data in datas:
                g.core.pending.append(data)
                fut: Future = Future()
                g.pending_futs.append(fut)
                futs.append(fut)
        # NOTE on tracing: the "raft:<ms>" track entry is appended by the
        # WAITER thread after future.result() (metanode submit_sync, the
        # datanode random-write handler) — a done-callback here would race
        # the waiter's span.finish()/reply construction and drop the entry
        # nondeterministically (Future runs callbacks after waking waiters).
        self._dirty.append(g)
        self._ensure_pump()
        self._prop_wake.set()
        return futs

    def _ensure_pump(self):
        if self._pump_started:
            return
        with self._pump_lock:
            if self._pump_started:
                return
            t = threading.Thread(target=self._pump, daemon=True,
                                 name=f"raft-drain-{self.node_id}")
            t.start()
            self._pump_started = True

    def _pump(self):
        """Drain pump: wake -> (gather window while batching) -> drain every
        dirty group -> send. One WAL flush + one fan-out per drained batch.

        A drain failure mid-round (WAL I/O error, SM apply bug) leaves
        applied-tracking ahead of the state machine — continuing would
        silently diverge replicas. Die LOUDLY instead: later proposals fail
        fast with RuntimeError and a restart recovers from the WAL."""
        try:
            self._pump_loop()
        except BaseException:
            self.pump_dead = True
            raise

    def _pump_loop(self):
        window = 0.0
        while True:
            self._prop_wake.wait()
            self._prop_wake.clear()
            if window:
                time.sleep(window)  # let concurrent proposers pile in
            out: list[Msg] = []
            biggest = 0
            seen: set[int] = set()
            with self._lock:
                while True:
                    try:
                        g = self._dirty.popleft()
                    except IndexError:
                        break
                    if id(g) in seen:
                        continue
                    seen.add(id(g))
                    biggest = max(biggest, len(g.core.pending))
                    out += self._drain_pending(g)
            if out:
                self.net.send(out)
            window = self.GROUP_WINDOW if biggest > 1 else 0.0

    def _record_drain(self, batch: int) -> None:
        """One drained batch: bump the legacy dict (under its lock) and the
        raft role registry (drain counters + batch-size histogram)."""
        with self._stats_lock:
            st = self.drain_stats
            st["rounds"] += 1
            st["entries"] += batch
            st["max_batch"] = max(st["max_batch"], batch)
        try:
            from chubaofs_tpu.utils.exporter import BATCH_BUCKETS, registry

            reg = registry("raft")
            reg.counter("drain_rounds_total").add()
            reg.counter("drain_entries_total").add(batch)
            reg.summary("drain_batch", buckets=BATCH_BUCKETS).observe(batch)
        except Exception:
            pass  # metrics must never poison the drain pump (pump_dead)

    def drain_stats_snapshot(self) -> dict:
        """Consistent copy of the legacy counters (no torn multi-field
        reads — rounds/entries/max_batch all from one instant)."""
        with self._stats_lock:
            return dict(self.drain_stats)

    def drain_stats_reset(self) -> None:
        """Zero the legacy counters under the lock (bench epochs); the
        registry counters stay cumulative, as counters must."""
        with self._stats_lock:
            self.drain_stats.update(rounds=0, entries=0, max_batch=0)

    def _drain_pending(self, g: _Group) -> list[Msg]:
        """Drain the group's pending proposals (held lock: self._lock). Each
        round is ONE core log-append of up to max_batch entries, ONE WAL
        write+flush, and ONE AppendEntries fan-out; the whole queue empties
        here, so a proposer blocked on the node lock usually finds its own
        entry already drained by whoever held it."""
        core = g.core
        out: list[Msg] = []
        while True:
            term0, vote0 = core.term, core.voted_for
            last0, commit0 = core.last_index, core.commit
            with g.pending_lock:
                if not core.pending and not g.pending_futs:
                    break
                try:
                    idxs = core.drain_proposals()
                except NotLeaderError as e:
                    core.pending.clear()
                    stranded = list(g.pending_futs)
                    g.pending_futs.clear()
                    for fut in stranded:
                        fut.set_exception(NotLeaderError(e.leader))
                    break
                if not idxs:
                    break  # queue raced empty: nothing left to drain
                self._record_drain(len(idxs))
                futs = [g.pending_futs.popleft() for _ in idxs]
                for idx, fut in zip(idxs, futs):
                    g.waiters[idx] = (core.term, fut)
            out += self._flush(g, term0, vote0, last0, commit0)
        return out


def run_until(net: InProcNet, cond, max_ticks: int = 300, sleep: float = 0.0) -> bool:
    """Drive every node's clock until cond() or tick budget exhausted (tests)."""
    for _ in range(max_ticks):
        for node in list(net.nodes.values()):
            node.tick()
        if cond():
            return True
        if sleep:
            time.sleep(sleep)
    return cond()


class TickLoop:
    """Background wall-clock pump for live deployments (100ms/tick default)."""

    def __init__(self, nodes: list[MultiRaft], interval: float = 0.1):
        self.nodes = nodes
        self.interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True, name="raft-tick")

    def start(self):
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            for n in self.nodes:
                n.tick()
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)
