"""TCP raft transport — the network twin of InProcNet.

Reference counterpart: depends/tiglabs/raft's dedicated TCP transports
(transport_heartbeat.go, transport_replicate.go) with merged heartbeats
across groups (depends/tiglabs/raft/README.md:18). Kept: per-destination
batching (every `send` groups all groups' messages to one peer into ONE
frame — the merged-heartbeat idea), fire-and-forget delivery (raft tolerates
loss; a dead peer's queue drops oldest first), background per-peer sender
threads so a slow peer never stalls the tick loop. Changed: one port instead
of two — heartbeats here are tiny Msg batches on the same framed stream, so
a separate heartbeat listener buys nothing.

Framing: [u32 length][32B HMAC-SHA256][codec-encoded list[Msg]]. The payload
is a safe tagged-binary encoding (raft.codec) that can only ever decode to
plain values — a hostile frame cannot make the decoder run code, so the HMAC
is an integrity/anti-spoof gate, not the last line of defense. Binding the
listener off-loopback REQUIRES an explicit cluster secret (refused at start
otherwise): with the well-known default secret any network peer could inject
raft traffic and corrupt consensus state.
"""

from __future__ import annotations

import hashlib
import hmac
import queue
import socket
import struct
import threading

from chubaofs_tpu import chaos
from chubaofs_tpu.raft import codec
from chubaofs_tpu.raft.core import Entry, Msg
from chubaofs_tpu.rpc.evloop import EvloopServer, evloop_enabled

_LEN = struct.Struct("<I")
MAX_FRAME = 256 << 20  # a snapshot install rides one frame
DEFAULT_SECRET = b"chubaofs-tpu-raft"

# Msg fields in wire order; entries ride separately as (term, data) pairs
_MSG_FIELDS = (
    "type", "group", "src", "dst", "term", "last_log_index", "last_log_term",
    "granted", "prev_index", "prev_term", "commit", "success", "match_index",
    "snap_index", "snap_term", "snap_data", "hb",
)


def _wire_msgs(msgs: list[Msg]) -> list:
    return [
        [[getattr(m, f) for f in _MSG_FIELDS],
         [(e.term, e.data) for e in m.entries]]
        for m in msgs
    ]


def _unwire_msgs(v) -> list[Msg]:
    if not isinstance(v, list):
        raise codec.CodecError("frame is not a message batch")
    out = []
    for item in v:
        fields, ents = item
        if len(fields) != len(_MSG_FIELDS):
            raise codec.CodecError("bad message field count")
        m = Msg(**dict(zip(_MSG_FIELDS, fields)))
        m.entries = [Entry(term, data) for term, data in ents]
        out.append(m)
    return out


def _pack(secret: bytes, msgs: list[Msg]) -> bytes:
    payload = codec.dumps(_wire_msgs(msgs))
    mac = hmac.new(secret, payload, hashlib.sha256).digest()
    return _LEN.pack(len(payload)) + mac + payload


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


class _FrameFramer:
    """Incremental reader for the [u32 len][32B MAC][payload] raft frame —
    the evloop per-connection state machine twin of the blocking _serve
    loop. Yields (mac, payload); oversized lengths raise and drop the
    connection before a byte of the body is bought."""

    def __init__(self):
        self._stage = "len"
        self._length = 0
        self._mac: bytes | None = None

    def need(self) -> int:
        if self._stage == "len":
            return _LEN.size
        if self._stage == "mac":
            return 32
        return self._length

    def feed(self, buf: bytearray):
        if self._stage == "len":
            (self._length,) = _LEN.unpack(buf)
            if self._length > MAX_FRAME:
                raise codec.CodecError("oversized frame")
            self._stage = "mac"
            return None
        if self._stage == "mac":
            self._mac = bytes(buf)
            self._stage = "payload"
            return None
        mac, self._mac, self._stage = self._mac, None, "len"
        return (mac, buf)


class _PeerLink:
    """One outbound connection + sender thread; reconnects lazily per frame."""

    def __init__(self, addr: str, secret: bytes):
        self.addr = addr
        self.secret = secret
        self.q: queue.Queue[list[Msg]] = queue.Queue(maxsize=256)
        self.sock: socket.socket | None = None
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def offer(self, msgs: list[Msg]) -> None:
        try:
            self.q.put_nowait(msgs)
        except queue.Full:  # drop oldest: newer raft state supersedes older
            try:
                self.q.get_nowait()
            except queue.Empty:
                pass
            try:
                self.q.put_nowait(msgs)
            except queue.Full:
                pass

    def _connect(self) -> socket.socket:
        host, port = self.addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=2.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _run(self):
        while not self._stop.is_set():
            try:
                msgs = self.q.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                if self.sock is None:
                    self.sock = self._connect()
                self.sock.sendall(_pack(self.secret, msgs))
            except OSError:
                if self.sock is not None:
                    try:
                        self.sock.close()
                    except OSError:
                        pass
                    self.sock = None
                # message dropped — raft retries via the next tick

    def close(self):
        self._stop.set()
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass


class TcpNet:
    """Network transport for one MultiRaft node.

    `peers` maps node_id -> "host:port" for every raft node including self;
    the local node's entry is the listen address. Implements the same
    send/register surface InProcNet does, so MultiRaft is transport-blind.
    """

    def __init__(self, node_id: int, peers: dict[int, str],
                 secret: bytes = DEFAULT_SECRET):
        self.node_id = node_id
        self.peers = dict(peers)
        self.secret = secret
        self.node = None  # the local MultiRaft, set by register()
        self.links: dict[int, _PeerLink] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

        host, port = self.peers[node_id].rsplit(":", 1)
        if secret == DEFAULT_SECRET and host not in ("127.0.0.1", "localhost", "::1"):
            raise ValueError(
                "raft transport bound off-loopback requires an explicit "
                "cluster secret (set 'raftSecret' in the daemon config); "
                "refusing to start with the well-known default")
        self.listener = socket.create_server((host, int(port)))
        self.listen_addr = f"{host}:{self.listener.getsockname()[1]}"
        self.peers[node_id] = self.listen_addr
        self._evloop: EvloopServer | None = None
        if evloop_enabled():
            # inbound raft frames ride the shared event-loop core: verify +
            # decode + deliver run on its worker pool (deliver takes node
            # locks), fire-and-forget so encode=None
            self._evloop = EvloopServer(self.listener, self._on_frame,
                                        name="raft",
                                        framer_factory=_FrameFramer,
                                        encode=None)
            self._evloop.start()
        else:
            self._accept_thread = threading.Thread(target=self._accept,
                                                   daemon=True)
            self._accept_thread.start()

    # -- InProcNet surface ----------------------------------------------------

    def register(self, node) -> None:
        self.node = node

    def send(self, msgs: list[Msg]) -> None:
        by_dst: dict[int, list[Msg]] = {}
        for m in msgs:
            by_dst.setdefault(m.dst, []).append(m)
        for dst, batch in by_dst.items():
            try:
                # injected link loss/flap: drop the batch on the floor —
                # raft re-sends via the next tick, exactly like real loss
                chaos.failpoint("raft.send", node=self.node_id)
            except chaos.FailpointError:
                continue
            if dst == self.node_id:
                if self.node is not None:
                    self.node.deliver(batch)
                continue
            link = self._link(dst)
            if link is not None:
                link.offer(batch)

    # -- plumbing -------------------------------------------------------------

    def _link(self, dst: int) -> _PeerLink | None:
        addr = self.peers.get(dst)
        if addr is None:
            return None
        with self._lock:
            link = self.links.get(dst)
            if link is None or link.addr != addr:
                if link is not None:
                    link.close()
                link = self.links[dst] = _PeerLink(addr, self.secret)
            return link

    def set_peer(self, node_id: int, addr: str) -> None:
        """Membership/address change: future sends dial the new address."""
        with self._lock:
            self.peers[node_id] = addr

    def _on_frame(self, msg) -> None:
        """Evloop handler: one (mac, payload) frame — authenticate, decode,
        deliver. Any failure raises, which drops THAT connection (the
        blocking _serve loop's `return` on the same conditions)."""
        mac, payload = msg
        want = hmac.new(self.secret, payload, hashlib.sha256).digest()
        if not hmac.compare_digest(mac, want):
            raise ConnectionError("unauthenticated frame")
        msgs = _unwire_msgs(codec.loads(payload))  # CodecError et al drop the conn
        if self.node is not None:
            self.node.deliver(msgs)

    def _accept(self):
        """CFS_EVLOOP=0 shim: the pre-evloop thread-per-connection path."""
        while not self._stop.is_set():
            try:
                conn, _ = self.listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(  # racelint: CFS_EVLOOP=0 rollback shim — evloop is the default serving path
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                (length,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                if length > MAX_FRAME:
                    return
                mac = _recv_exact(conn, 32)
                payload = _recv_exact(conn, length)
                want = hmac.new(self.secret, payload, hashlib.sha256).digest()
                if not hmac.compare_digest(mac, want):
                    return  # unauthenticated frame: drop the connection
                try:
                    msgs = _unwire_msgs(codec.loads(payload))
                except (codec.CodecError, TypeError, ValueError):
                    return  # malformed frame: hostile or corrupt — drop conn
                if self.node is not None:
                    self.node.deliver(msgs)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop.set()
        if self._evloop is not None:
            self._evloop.stop()
        try:
            self.listener.close()
        except OSError:
            pass
        with self._lock:
            for link in self.links.values():
                link.close()
            self.links.clear()
