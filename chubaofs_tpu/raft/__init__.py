"""Multi-raft consensus — equivalent of the reference's vendored tiglabs/raft
(depends/tiglabs/raft: etcd-style multi-raft with merged heartbeats across
groups, separate heartbeat/replicate transports) and blobstore's single-group
common/raftserver. One implementation serves both roles here."""

from chubaofs_tpu.raft.core import RaftCore, ROLE_FOLLOWER, ROLE_CANDIDATE, ROLE_LEADER, NotLeaderError
from chubaofs_tpu.raft.server import MultiRaft, StateMachine, InProcNet

__all__ = [
    "RaftCore",
    "MultiRaft",
    "StateMachine",
    "InProcNet",
    "NotLeaderError",
    "ROLE_FOLLOWER",
    "ROLE_CANDIDATE",
    "ROLE_LEADER",
]
