"""RaftCore — a deterministic, message-passing raft consensus core.

Reference counterpart: depends/tiglabs/raft (statemachine.go:23-30, server.go:65)
— the multi-raft engine under master, metanode, and datanode random-writes.
Design follows the etcd/tiglabs shape: a PURE state machine advanced by tick()
and step(msg), emitting messages and committed entries through ready(). No
threads, no clocks, no sockets in here — the server layer owns those — so every
consensus scenario (elections, splits, log repair, snapshot install) is unit
-testable deterministically, the way the reference tests multi-node logic with
in-process fakes (SURVEY §4).

Log model: 1-based indexes; entries list holds (term, payload) pairs starting at
`offset + 1` (offset = index of the last snapshot-compacted entry).

Group commit (the reference drain loop, raft.go:283-311): client proposals
accumulate in `pending` and `drain_proposals()` moves up to `max_batch` of
them into the log in ONE append pass followed by ONE AppendEntries fan-out —
so N concurrent proposers cost one replication round, not N. Replication is
pipelined per follower: `_send_append` advances next_index optimistically
(batch i+1 ships without waiting for ack i) under a bounded in-flight window;
NACKs and heartbeat probes rewind next_index, so message loss self-heals.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"
ROLE_LEADER = "leader"

ELECTION_TICKS = 10  # randomized per-node in [E, 2E)
HEARTBEAT_TICKS = 2
# group-commit drain width (reference parity: raft.go:283-311 drains up to 64
# pending proposals into one log-append/replication round) and the per-follower
# pipelined-replication window (entries in flight past the last verified match)
MAX_BATCH = 64
MAX_INFLIGHT = 4 * MAX_BATCH


class NotLeaderError(Exception):
    def __init__(self, leader: int | None):
        super().__init__(f"not leader; leader hint: {leader}")
        self.leader = leader


@dataclass
class Entry:
    term: int
    data: object  # opaque command; None for leader no-op barriers
    # cached codec-encoded payload for the WAL (filled by the first persist;
    # in-proc replicas share the Entry, so one encode serves all three logs)
    wal_hex: str | None = None


@dataclass
class Msg:
    type: str  # vote_req | vote_resp | append | append_resp | snap
    group: int
    src: int
    dst: int
    term: int
    # vote
    last_log_index: int = 0
    last_log_term: int = 0
    granted: bool = False
    # append
    prev_index: int = 0
    prev_term: int = 0
    entries: list[Entry] = field(default_factory=list)
    commit: int = 0
    success: bool = False
    match_index: int = 0
    # snapshot
    snap_index: int = 0
    snap_term: int = 0
    snap_data: bytes = b""
    # merged cross-group heartbeat (group_hb/group_hb_resp): [gid, term,
    # commit] triples / [gid, term] stale pairs — ONE message per peer pair
    # per tick regardless of group count (tiglabs raft README:18)
    hb: list = field(default_factory=list)


class RaftCore:
    def __init__(self, group: int, node_id: int, peers: list[int],
                 rng: random.Random | None = None, max_batch: int = MAX_BATCH):
        self.group = group
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.rng = rng or random.Random(node_id * 7919 + group)
        self.max_batch = max_batch
        # group commit: queued client proposals awaiting a drain round; the
        # server owns the matching futures (FIFO, same enqueue order)
        self.pending: list = []

        # persistent state
        self.term = 0
        self.voted_for: int | None = None
        self.offset = 0  # last compacted index
        self.offset_term = 0
        self.entries: list[Entry] = []

        # volatile
        # lowest index whose entry was overwritten since the last WAL flush:
        # the server must re-persist from here, or recovery replays the
        # stale-term suffix a conflicting append truncated in memory
        self.log_rewind: int | None = None
        self.role = ROLE_FOLLOWER
        self.leader: int | None = None
        self.commit = 0
        self.applied = 0
        self.votes: set[int] = set()
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}
        self.elapsed = 0
        self.election_timeout = self._rand_timeout()

        self._outbox: list[Msg] = []
        self._committed: list[tuple[int, Entry]] = []
        # set by the server when the sm can produce a snapshot for laggards
        self.snapshot_fn = None  # () -> (index, term, bytes)
        # peers due a liveness heartbeat this tick; the SERVER merges these
        # across groups into one group_hb per peer (tiglabs README:18)
        self.pending_hb: list[int] = []

    # -- helpers ------------------------------------------------------------

    def _rand_timeout(self) -> int:
        return ELECTION_TICKS + self.rng.randrange(ELECTION_TICKS)

    @property
    def last_index(self) -> int:
        return self.offset + len(self.entries)

    def term_at(self, index: int) -> int:
        if index == self.offset:
            return self.offset_term
        if index < self.offset or index > self.last_index:
            return -1
        return self.entries[index - self.offset - 1].term

    def entry_at(self, index: int) -> Entry:
        return self.entries[index - self.offset - 1]

    def _send(self, **kw):
        self._outbox.append(Msg(group=self.group, src=self.id, **kw))

    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    # -- public: server drives these ----------------------------------------

    def tick(self):
        self.elapsed += 1
        if self.role == ROLE_LEADER:
            if self.elapsed >= HEARTBEAT_TICKS:
                self.elapsed = 0
                for p in self.peers:
                    # merged path only for peers whose match is VERIFIED (an
                    # append_resp proved the prefix); next_index alone can be
                    # optimistic (fresh members, post-election defaults)
                    if self.match_index.get(p, 0) < self.last_index:
                        # laggard/unverified: real replication traffic
                        self._send_append(p)
                    else:
                        # quiescent: liveness only — merged across groups by
                        # the server so 1,000 partitions != 1,000 messages
                        self.pending_hb.append(p)
        elif self.elapsed >= self.election_timeout:
            self._campaign()

    def step_group_hb(self, src: int, term: int, commit: int) -> bool:
        """One group's slice of a merged heartbeat. Returns False when the
        sender's term is stale (the server reports it back so the old leader
        steps down). Safe without a log-prefix check: a leader only puts a
        peer on the merged path once match_index == last_index, which an
        append_resp verified; any divergence since implies a higher term,
        caught here."""
        if term < self.term:
            return False
        if term > self.term:
            self._become_follower(term, src)
        self.role = ROLE_FOLLOWER
        self.leader = src
        self.elapsed = 0
        if commit > self.commit:
            self.commit = min(commit, self.last_index)
            self._emit_committed()
        return True

    def propose(self, data) -> int:
        return self.propose_batch([data])[-1]

    def propose_batch(self, datas: list) -> list[int]:
        """Queue + drain in one call: the whole batch lands in the log as one
        append pass and one AppendEntries fan-out (multiple drain rounds only
        past max_batch). Returns the assigned indexes, FIFO."""
        if self.role != ROLE_LEADER:
            raise NotLeaderError(self.leader)
        self.pending.extend(datas)
        out: list[int] = []
        while self.pending:
            out += self.drain_proposals()
        return out[-len(datas):]

    def queue_proposal(self, data) -> None:
        """Enqueue one proposal for the next drain round (group commit)."""
        if self.role != ROLE_LEADER:
            raise NotLeaderError(self.leader)
        self.pending.append(data)

    def drain_proposals(self) -> list[int]:
        """Move up to max_batch pending proposals into the log: ONE append
        pass, ONE replication fan-out (the raft.go:283-311 drain loop analog).
        Raises NotLeaderError — pending intact for the caller to fail — when
        leadership was lost between enqueue and drain."""
        if self.role != ROLE_LEADER:
            raise NotLeaderError(self.leader)
        batch = self.pending[: self.max_batch]
        if not batch:
            return []
        del self.pending[: len(batch)]
        first = self.last_index + 1
        for data in batch:
            self.entries.append(Entry(self.term, data))
        self.match_index[self.id] = self.last_index
        if not self.peers:  # single-node group commits immediately
            self._advance_commit()
        else:
            self._broadcast_append()
        return list(range(first, self.last_index + 1))

    # -- membership (single-server change: one add/remove per entry keeps any
    # two quorums overlapping, the standard safe reconfiguration) -------------

    def apply_config(self, action: str, node_id: int) -> None:
        """Run when a __config_change__ entry COMMITS, on every replica."""
        if action == "add" and node_id != self.id and node_id not in self.peers:
            self.peers.append(node_id)
            if self.role == ROLE_LEADER:
                self.next_index[node_id] = self.last_index + 1
                self.match_index[node_id] = 0
        elif action == "remove":
            if node_id == self.id:
                # removed from the group: stop campaigning/serving
                self.peers = []
                self.role = ROLE_FOLLOWER
                self.leader = None
                return
            if node_id in self.peers:  # racelint: RaftCore state is only touched under the owning MultiRaft node lock
                self.peers.remove(node_id)
                self.next_index.pop(node_id, None)
                self.match_index.pop(node_id, None)
                if self.role == ROLE_LEADER:
                    self._advance_commit()  # quorum may shrink past pending

    def step(self, m: Msg):
        if m.term > self.term:
            self._become_follower(m.term, m.src if m.type == "append" else None)
        handler = getattr(self, "_on_" + m.type)
        handler(m)

    def ready(self) -> tuple[list[Msg], list[tuple[int, Entry]]]:
        """Drain outgoing messages and newly committed entries."""
        out, self._outbox = self._outbox, []
        committed, self._committed = self._committed, []
        return out, committed

    def compact(self, index: int, term: int):
        """Drop log entries <= index (after the server snapshots the SM)."""
        if index <= self.offset:
            return
        keep = self.entries[index - self.offset :]
        self.offset, self.offset_term, self.entries = index, term, keep

    # -- roles ---------------------------------------------------------------

    def _become_follower(self, term: int, leader: int | None):
        self.term = term
        self.role = ROLE_FOLLOWER
        self.voted_for = None
        self.leader = leader
        self.votes.clear()
        self.elapsed = 0
        self.election_timeout = self._rand_timeout()

    def _campaign(self):
        self.role = ROLE_CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.votes = {self.id}
        self.leader = None
        self.elapsed = 0
        self.election_timeout = self._rand_timeout()
        if not self.peers:
            self._become_leader()
            return
        for p in self.peers:
            self._send(
                type="vote_req",
                dst=p,
                term=self.term,
                last_log_index=self.last_index,
                last_log_term=self.term_at(self.last_index),
            )

    def _become_leader(self):
        self.role = ROLE_LEADER
        self.leader = self.id
        # one timeline record per election, emitted by the WINNER (followers
        # learning the leader would triple-report every election). emit()
        # never raises, so the tick path stays safe.
        from chubaofs_tpu.utils import events

        events.emit("raft_leader", entity=f"g{self.group}",
                    detail={"group": self.group, "node": self.id,
                            "term": self.term})
        self.elapsed = 0
        self.next_index = {p: self.last_index + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        self.match_index[self.id] = self.last_index
        # no-op barrier commits entries from prior terms (raft §5.4.2)
        self.entries.append(Entry(self.term, None))
        self.match_index[self.id] = self.last_index
        if not self.peers:
            self._advance_commit()
        else:
            self._broadcast_append()

    # -- vote flow -----------------------------------------------------------

    def _on_vote_req(self, m: Msg):
        if m.term < self.term:
            self._send(type="vote_resp", dst=m.src, term=self.term, granted=False)
            return
        up_to_date = (m.last_log_term, m.last_log_index) >= (
            self.term_at(self.last_index),
            self.last_index,
        )
        grant = up_to_date and self.voted_for in (None, m.src)
        if grant:
            self.voted_for = m.src
            self.elapsed = 0
        self._send(type="vote_resp", dst=m.src, term=self.term, granted=grant)

    def _on_vote_resp(self, m: Msg):
        if self.role != ROLE_CANDIDATE or m.term != self.term:
            return
        if m.granted:
            self.votes.add(m.src)
            if len(self.votes) >= self.quorum():
                self._become_leader()

    # -- replication ----------------------------------------------------------

    def _broadcast_append(self):
        for p in self.peers:
            self._send_append(p)

    def _send_append(self, peer: int):
        next_i = self.next_index.get(peer, self.last_index + 1)
        if next_i <= self.offset:
            self._send_snapshot(peer)
            return
        prev = next_i - 1
        # pipelined replication: ship at most max_batch entries per message
        # and advance next_index OPTIMISTICALLY, so batch i+1 goes out without
        # waiting for ack i. The window bounds entries in flight past the last
        # verified match; when it is full (or next_i is already past the tail)
        # this degrades to an empty probe carrying prev/commit — the probe's
        # ACK advances match, its NACK rewinds next_index, so both lost
        # appends and lost acks self-heal on the heartbeat cadence.
        ents: list[Entry] = []
        if prev - self.match_index.get(peer, 0) < MAX_INFLIGHT:
            end = min(self.last_index, next_i + self.max_batch - 1)
            ents = [self.entry_at(i) for i in range(next_i, end + 1)]
        self._send(
            type="append",
            dst=peer,
            term=self.term,
            prev_index=prev,
            prev_term=self.term_at(prev),
            entries=ents,
            commit=self.commit,
        )
        if ents:
            self.next_index[peer] = next_i + len(ents)

    def _send_snapshot(self, peer: int):
        if self.snapshot_fn is None:
            return
        idx, term, data = self.snapshot_fn()
        self._send(
            type="snap", dst=peer, term=self.term, snap_index=idx, snap_term=term, snap_data=data
        )

    def _on_append(self, m: Msg):
        if m.term < self.term:
            self._send(type="append_resp", dst=m.src, term=self.term, success=False)
            return
        self.role = ROLE_FOLLOWER
        self.leader = m.src
        self.elapsed = 0
        if m.prev_index > self.last_index or self.term_at(m.prev_index) != m.prev_term:
            self._send(
                type="append_resp",
                dst=m.src,
                term=self.term,
                success=False,
                match_index=min(self.last_index, max(self.offset, m.prev_index - 1)),
            )
            return
        # append, truncating conflicts IN PLACE — the common fresh-tail case
        # must not copy the whole log per entry (O(batch x log) per append)
        for i, ent in enumerate(m.entries):
            idx = m.prev_index + 1 + i
            if idx <= self.offset:
                continue  # already compacted into a snapshot
            if idx <= self.last_index:
                if self.term_at(idx) == ent.term:
                    continue
                del self.entries[idx - self.offset - 1:]
                if self.log_rewind is None or idx < self.log_rewind:
                    self.log_rewind = idx
            self.entries.append(ent)
        if m.commit > self.commit:
            self.commit = min(m.commit, self.last_index)
            self._emit_committed()
        self._send(
            type="append_resp",
            dst=m.src,
            term=self.term,
            success=True,
            match_index=m.prev_index + len(m.entries),
        )

    def _on_append_resp(self, m: Msg):
        if self.role != ROLE_LEADER or m.term != self.term:
            return
        if m.success:
            self.match_index[m.src] = max(self.match_index.get(m.src, 0), m.match_index)
            # never rewind a pipelined next_index on an (older) ack
            self.next_index[m.src] = max(
                self.next_index.get(m.src, 0), self.match_index[m.src] + 1)
            self._advance_commit()
            if self.next_index[m.src] <= self.last_index:
                # window freed / next chunk of a laggard catch-up
                self._send_append(m.src)
        else:
            # prefix mismatch: the follower's match_index hint is always
            # genuine here (a stale-term NACK carries a higher term, which
            # dethroned us in step() before reaching this branch), so jump
            # next_index straight to it — a next_index-relative backoff would
            # fight the pipelined optimistic advance and never converge
            self.next_index[m.src] = max(1, min(m.match_index + 1, self.last_index + 1))
            self._send_append(m.src)

    def _advance_commit(self):
        for idx in range(self.last_index, self.commit, -1):
            if self.term_at(idx) != self.term:
                break  # only commit entries of the current term by counting (§5.4.2)
            votes = sum(
                1 for p in [self.id, *self.peers] if self.match_index.get(p, 0) >= idx
            )
            if votes >= self.quorum():
                self.commit = idx
                self._emit_committed()
                break

    def _emit_committed(self):
        while self.applied < self.commit:
            self.applied += 1
            if self.applied <= self.offset:
                continue  # folded into a snapshot already
            self._committed.append((self.applied, self.entry_at(self.applied)))

    # -- snapshot install ------------------------------------------------------

    def _on_snap(self, m: Msg):
        if m.term < self.term:
            return
        self.role = ROLE_FOLLOWER
        self.leader = m.src
        self.elapsed = 0
        if m.snap_index <= self.commit:
            return  # stale snapshot
        self.offset, self.offset_term = m.snap_index, m.snap_term
        self.entries = []
        self.commit = self.applied = m.snap_index
        self._committed.append((m.snap_index, Entry(m.snap_term, ("__install_snapshot__", m.snap_data))))
        self._send(
            type="append_resp", dst=m.src, term=self.term, success=True, match_index=m.snap_index
        )
