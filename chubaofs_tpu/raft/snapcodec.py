"""Sectioned, CRC-framed state snapshots — the pickle-snapshot replacement.

Reference counterpart: metanode/partition_store.go:57-1033 — snapshots are
per-type files (inode/dentry/extend/multipart/txn), each carrying its own
CRC32, loaded type-by-type on recovery; clustermgr streams RocksDB checkpoint
files the same way. Here a snapshot is one byte stream of framed sections:

    magic "CFSS1\\n"
    repeat:  [u16 name_len][name utf8][u32 crc32(payload)][u64 payload_len][payload]

Payloads are raft.codec values (safe tagged binary — no pickle anywhere on
the raft path). Large collections are emitted as REPEATED sections of bounded
batch size, and `read_sections` yields them lazily from the buffer, so restore
applies a 100k-inode namespace batch-by-batch instead of materializing a
second full-size decoded image. CRC mismatches raise SnapshotError — a
corrupt section never half-applies silently.
"""

from __future__ import annotations

import struct
import zlib
from typing import Iterator

from chubaofs_tpu.raft import codec

MAGIC = b"CFSS1\n"
_HDR = struct.Struct("<HIQ")  # name_len, crc32, payload_len

BATCH = 1024  # items per repeated section


class SnapshotError(ValueError):
    pass


class SnapshotWriter:
    def __init__(self):
        self._parts: list[bytes] = [MAGIC]

    def add(self, name: str, value) -> None:
        """Append one section holding a codec-encoded value."""
        raw_name = name.encode("utf-8")
        payload = codec.dumps(value)
        self._parts.append(
            _HDR.pack(len(raw_name), zlib.crc32(payload) & 0xFFFFFFFF, len(payload)))
        self._parts.append(raw_name)
        self._parts.append(payload)

    def add_batched(self, name: str, items, batch: int = BATCH) -> None:
        """Emit a list/iterable as repeated bounded-size sections."""
        buf = []
        for item in items:
            buf.append(item)
            if len(buf) >= batch:
                self.add(name, buf)
                buf = []
        if buf:
            self.add(name, buf)

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


def read_sections(payload: bytes) -> Iterator[tuple[str, object]]:
    """Yield (name, decoded value) per section, verifying CRCs lazily."""
    view = memoryview(payload)
    if bytes(view[: len(MAGIC)]) != MAGIC:
        raise SnapshotError("bad snapshot magic")
    pos = len(MAGIC)
    total = len(payload)
    while pos < total:
        if pos + _HDR.size > total:
            raise SnapshotError("truncated section header")
        name_len, crc, plen = _HDR.unpack_from(view, pos)
        pos += _HDR.size
        if pos + name_len + plen > total:
            raise SnapshotError("truncated section body")
        name = bytes(view[pos : pos + name_len]).decode("utf-8")
        pos += name_len
        body = bytes(view[pos : pos + plen])
        pos += plen
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise SnapshotError(f"section {name!r} CRC mismatch")
        try:
            yield name, codec.loads(body)
        except codec.CodecError as e:
            raise SnapshotError(f"section {name!r}: {e}") from None


def restore_sections(payload: bytes, handlers: dict) -> None:
    """Dispatch each section to handlers[name]; unknown names error out
    (an unknown section means a version/trust mismatch, not data to skip)."""
    for name, value in read_sections(payload):
        h = handlers.get(name)
        if h is None:
            raise SnapshotError(f"unknown snapshot section {name!r}")
        h(value)
