"""Product-matrix MSR regenerating code over GF(2^8) (repair-traffic codes).

The product-matrix MSR construction (Rashmi-Shah-Kumar, arXiv:1412.3022's
regenerating-code family) at the minimum-storage point: n nodes, k data
nodes, d = 2k-2 repair helpers, alpha = k-1 sub-units per node, beta = 1
sub-unit shipped per helper. A lost shard is rebuilt from d helpers who each
send shard_size/(d-k+1) = shard_size/alpha bytes — d*beta total instead of
the k full shards an RS repair downloads. For the shipped RG6P6 mode
(n=12, k=6, d=10, alpha=5) that is 10/5 = 2 shard-equivalents of download
per repaired shard vs RS(12,4)'s 12 — a 6x cut in repair traffic at the
cost of rate 1/2 storage (vs RS(12,4)'s 3/4).

Construction (all math in GF(2^8), POLY 0x11D):

  * message: the blob's k*alpha sub-units arranged as two symmetric
    alpha x alpha matrices S1, S2 (k*alpha = alpha*(alpha+1) distinct
    symbols = twice an upper triangle);
  * encoding matrix Psi (n x d): row i is the plain Vandermonde row
    (1, x_i, ..., x_i^(d-1)) with x_i = g^i, which factors as
    [phi_i | lambda_i * phi_i] for phi_i = (1, x_i, ..., x_i^(alpha-1))
    and lambda_i = x_i^alpha. Node i stores psi_i^T [S1; S2] — alpha
    symbols per byte column;
  * repair of node f: helper i ships the single symbol phi_f^T w_i
    (its alpha stored symbols combined by the FAILED node's phi row —
    the beta-combine). Stacking d helper symbols gives
    Psi_H [S1 phi_f; S2 phi_f]; Psi_H is d Vandermonde rows, hence
    invertible, and w_f = S1 phi_f + lambda_f S2 phi_f by symmetry. The
    whole decode is ONE (alpha, d) @ (d, L) matmul — window-sized, and
    shaped exactly like the matmul jobs CodecService already drains;
  * any k nodes decode the message (the MSR/MDS property), so the code is
    made SYSTEMATIC by the standard precode: with G_raw the raw
    (n*alpha, k*alpha) generator over the symbol vector,
    G = G_raw @ inv(G_raw[:k*alpha]) stores the blob bytes verbatim on the
    first k nodes — bit-exact with RsEncoder's data layout — while repair
    math is untouched (stored shards are still a product-matrix codeword,
    just of the precoded message).

Distinctness requirements: x_i pairwise distinct (any n <= 255) and
lambda_i = g^(i*alpha) pairwise distinct (n <= 255/gcd(alpha, 255);
51 for alpha=5). Both checked at construction.

Why helpers COMBINE instead of shipping a raw byte range: uncoded-access
(help-by-transfer) MSR at this tiny sub-packetization is impossible —
optimal-access constructions need alpha ~ r^(n/r) sub-units. The product-
matrix code trades a cheap GF combine on the helper (reads its whole local
shard, ships beta bytes) for the bandwidth win; disk reads are unchanged,
NETWORK bytes drop, which is the cost the repair plane actually pays for
cross-node rebuilds.

This module is pure host-side numpy: it builds the tiny generator/repair
matrices (<= 60x30) and provides oracle encode/repair/decode used by tests
and the blobnode beta-combine. The data-plane path submits these matrices
as CodecService matmul jobs so the byte work runs on the device batched
with everything else.
"""

from __future__ import annotations

import functools
import math

import numpy as np

from chubaofs_tpu.ops import gf256


class PMKernel:
    """One (n, k) product-matrix MSR code instance; matrices built once."""

    def __init__(self, n: int, k: int):
        if k < 3:
            raise ValueError(f"PM-MSR needs k >= 3, got k={k}")
        self.n = n
        self.k = k
        self.alpha = k - 1  # sub-units per shard
        self.d = 2 * k - 2  # helpers per repair
        if n <= self.d:
            raise ValueError(
                f"PM-MSR(n={n}, k={k}) needs n > d={self.d} so a single "
                f"loss leaves d helpers")
        if n > gf256.ORDER // math.gcd(self.alpha, gf256.ORDER):
            raise ValueError(
                f"n={n} too large: lambda_i = g^(i*alpha) collide beyond "
                f"{gf256.ORDER // math.gcd(self.alpha, gf256.ORDER)} nodes")
        a = self.alpha
        # x_i = g^i; phi_i = (1, x_i, .., x_i^(a-1)); lambda_i = x_i^a;
        # psi_i = (1, x_i, .., x_i^(d-1)) = [phi_i | lambda_i*phi_i]
        self.x = np.array([gf256.gf_pow(2, i) for i in range(n)], np.uint8)
        self.phi = np.array(
            [[gf256.gf_pow(int(x), j) for j in range(a)] for x in self.x],
            np.uint8)
        self.lam = np.array(
            [gf256.gf_pow(int(x), a) for x in self.x], np.uint8)
        self.psi = np.array(
            [[gf256.gf_pow(int(x), j) for j in range(self.d)] for x in self.x],
            np.uint8)
        assert len(set(self.lam.tolist())) == n, "lambda_i must be distinct"

        # raw generator over the alpha*(alpha+1) = k*alpha distinct symbols
        # of [S1; S2]: stored symbol (i, c) = sum_r psi_i[r] * M[r][c]
        nsym = k * a
        g_raw = np.zeros((n * a, nsym), np.uint8)
        for i in range(n):
            for c in range(a):
                row = i * a + c
                for r in range(self.d):
                    g_raw[row, self._sym(r, c)] ^= self.psi[i, r]
        # systematic precode: first k nodes store the message verbatim
        t = g_raw[: k * a]
        self.G = gf256.gf_matmul(g_raw, gf256.gf_inv_matrix(t))
        assert np.array_equal(self.G[: k * a], np.eye(k * a, dtype=np.uint8))
        self.parity_mat = np.ascontiguousarray(self.G[k * a:])

    def _sym(self, r: int, c: int) -> int:
        """Column index of symbol M[r][c]: S1 upper triangle then S2's."""
        a = self.alpha
        half = a * (a + 1) // 2
        off = 0
        if r >= a:  # S2 block
            r -= a
            off = half
        lo, hi = (r, c) if r <= c else (c, r)
        return off + lo * a - lo * (lo - 1) // 2 + (hi - lo)

    # -- repair-plane matrices (host-built, device-applied) -----------------

    def helper_coeffs(self, fail: int) -> np.ndarray:
        """phi_f (alpha,) — the combine coefficients a helper applies to its
        alpha sub-units to produce the beta payload for failed node f."""
        return np.array(self.phi[fail], np.uint8)

    def repair_matrix(self, fail: int, helpers: list[int]) -> np.ndarray:
        """(alpha, d) decode matrix R: failed shard (alpha, L) = R @ P with
        P the (d, L) stacked helper payloads in `helpers` order."""
        if len(helpers) != self.d or fail in helpers:
            raise ValueError(f"need {self.d} helpers != failed {fail}")
        inv = gf256.gf_inv_matrix(self.psi[np.asarray(helpers)])
        a = self.alpha
        # R = [I_a | lambda_f * I_a] @ inv(Psi_H)
        return inv[:a] ^ gf256.gf_mul(self.lam[fail], inv[a: 2 * a])

    def decode_matrix(self, survivors: list[int],
                      want: list[int]) -> np.ndarray:
        """Generic any-k decode (the multi-loss fallback): given k survivor
        NODES' full shards stacked as (k*alpha, L) sub-unit rows, the
        (len(want)*alpha, k*alpha) matrix rebuilding the wanted nodes."""
        if len(survivors) != self.k:
            raise ValueError(f"need exactly k={self.k} survivors")
        a = self.alpha
        rows = np.concatenate([self.G[i * a: (i + 1) * a] for i in survivors])
        inv = gf256.gf_inv_matrix(rows)  # MSR any-k property: invertible
        wrows = np.concatenate([self.G[i * a: (i + 1) * a] for i in want])
        return gf256.gf_matmul(wrows, inv)

    # -- numpy oracle verbs (tests, blobnode combine, host fallback) --------

    def split_shard(self, shard: bytes | np.ndarray) -> np.ndarray:
        """One shard's bytes as its (alpha, L) sub-unit matrix."""
        buf = np.frombuffer(memoryview(shard), np.uint8) \
            if not isinstance(shard, np.ndarray) else shard
        if buf.size % self.alpha:
            raise ValueError(
                f"shard size {buf.size} not a multiple of alpha={self.alpha}")
        return buf.reshape(self.alpha, -1)

    def helper_payload(self, fail: int, shard: bytes | np.ndarray) -> bytes:
        """The beta = shard/alpha bytes helper ships for failed node f:
        phi_f combined over the helper's own sub-units."""
        sub = self.split_shard(shard)
        return gf256.gf_matmul(self.phi[fail][None, :], sub).tobytes()

    def repair(self, fail: int, helpers: list[int],
               payloads: np.ndarray) -> np.ndarray:
        """payloads (d, L) in `helpers` order -> the failed shard's bytes
        (alpha*L,)."""
        mat = self.repair_matrix(fail, helpers)
        return gf256.gf_matmul(mat, np.asarray(payloads, np.uint8)).reshape(-1)

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data (k, S) node-major shards -> full (n, S) stripe (oracle)."""
        k, size = data.shape
        if k != self.k:
            raise ValueError(f"want {self.k} data shards, got {k}")
        msg = np.asarray(data, np.uint8).reshape(self.k * self.alpha, -1)
        parity = gf256.gf_matmul(self.parity_mat, msg)
        return np.concatenate(
            [np.asarray(data, np.uint8),
             parity.reshape(self.n - self.k, size)])

    def reconstruct(self, shards: np.ndarray, bad_idx: list[int],
                    data_only: bool = False) -> np.ndarray:
        """Full-stripe oracle rebuild from any k intact nodes (the fallback
        path's math): shards (n, S) with garbage rows at bad_idx."""
        bad = sorted(set(int(i) for i in bad_idx))
        if not bad:
            return np.array(shards, copy=True)
        alive = [i for i in range(self.n) if i not in bad]
        if len(alive) < self.k:
            raise ValueError(f"{len(bad)} losses > n-k={self.n - self.k}")
        want = [i for i in bad if i < self.k] if data_only else bad
        out = np.array(shards, np.uint8, copy=True)
        if not want:
            return out
        srv = alive[: self.k]
        mat = self.decode_matrix(srv, want)
        stacked = np.concatenate([self.split_shard(out[i]) for i in srv])
        fixed = gf256.gf_matmul(mat, stacked)
        size = out.shape[1]
        out[np.asarray(want)] = fixed.reshape(len(want), size)
        return out

    def verify(self, shards: np.ndarray) -> bool:
        """Parity check: recompute parity rows from the data rows."""
        mat = np.asarray(shards, np.uint8)
        return np.array_equal(self.encode(mat[: self.k]), mat)


@functools.lru_cache(maxsize=16)
def get_kernel(n: int, k: int) -> PMKernel:
    return PMKernel(n, k)
