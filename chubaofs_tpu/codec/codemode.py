"""EC code modes: declarative N+M+L layouts with AZ-aware stripe geometry.

Equivalent of reference blobstore/common/codemode/codemode.go:26-160. A CodeMode
names a fixed Tactic: N data shards, M global parity, L local (per-AZ) parity,
the AZ count, the put/get quorums, and the minimum shard size used when splitting
small blobs. Stripe-layout helpers (global stripe, per-AZ local stripes, shard->AZ
assignment) mirror the reference's GlobalStripe/GetECLayoutByAZ semantics
(codemode.go:119-126): data shards are dealt to AZs contiguously N/AZCount each,
then parity M/AZCount each, then locals L/AZCount each.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

ALIGN_0B = 0
ALIGN_512B = 512
ALIGN_2KB = 2048


class CodeMode(enum.IntEnum):
    EC15P12 = 1
    EC6P6 = 2
    EC16P20L2 = 3
    EC6P10L2 = 4
    EC6P3L3 = 5
    EC6P6Align0 = 6
    EC6P6Align512 = 7
    EC4P4L2 = 8
    EC12P4 = 9
    EC16P4 = 10
    EC3P3 = 11
    EC10P4 = 12
    EC6P3 = 13
    EC12P9 = 14
    # the hot-tier redundancy engine (ISSUE 12): a systematic RS(1,2)
    # stripe IS the codec-native 3-replica layout — shard 0 is the blob
    # bytes verbatim (one direct read serves a GET), shards 1-2 are GF
    # scalar images recoverable through the ordinary reconstruct path.
    # Never size-selected: blobs enter only via tier promotion.
    Replica3 = 15
    # repair-traffic regenerating code (ISSUE 19): product-matrix MSR
    # RG(n=12, k=6, d=10, alpha=5) — single-shard repair downloads
    # d*(shard/alpha) = 2 shard-equivalents instead of RS's 12. Systematic
    # (data shards are raw blob bytes), rate 1/2.
    RG6P6 = 16
    # test-only modes (kept for parity with the reference's table)
    EC6P6L9 = 200
    EC6P8L10 = 201
    # BASELINE.json archive config (EC(20,4)+LRC local parity, 2 AZ) — shared
    # by bench.py and the multichip dryrun so the two can never drift
    EC20P4L2 = 202
    # BASELINE.json unit-bench config (plain RS 4+2, single AZ)
    EC4P2 = 203
    # small regenerating mode for fast tests: RG(n=8, k=4, d=6, alpha=3)
    RG4P4 = 204


@dataclass(frozen=True)
class Tactic:
    """Immutable strategy of one CodeMode (codemode.go:129-160)."""

    N: int
    M: int
    L: int
    az_count: int
    put_quorum: int
    get_quorum: int = 0
    min_shard_size: int = ALIGN_2KB
    # regenerating-code geometry (0/1 = plain RS/LRC): sub_units is the
    # per-shard sub-unit count alpha (a shard is alpha equal slices; the
    # repair beta-payload is ONE slice), helpers is d, the helper count a
    # single-loss repair reads from. Product-matrix MSR fixes alpha = N-1,
    # helpers = 2N-2 (codec/pm.py).
    sub_units: int = 1
    helpers: int = 0

    @property
    def total(self) -> int:
        return self.N + self.M + self.L

    @property
    def read_hedge(self) -> int:
        """How many shard reads a degraded GET keeps in flight at once:
        N would-be-sufficient reads plus speculative extras, so one slow or
        dead blobnode never sets the GET latency floor (the reference hedges
        the same way — getDataShardOnly fans out, reconstruct fallback races
        the stragglers, stream_get.go:427-530). `get_quorum` is the explicit
        per-mode bound; 0 (unset) defaults to N + ceil(M/2), capped at N+M."""
        hedge = self.get_quorum or self.N + (self.M + 1) // 2
        return min(hedge, self.N + self.M)

    @property
    def global_count(self) -> int:
        return self.N + self.M

    @property
    def is_regenerating(self) -> bool:
        """True for product-matrix regenerating modes (beta-fetch repair)."""
        return self.sub_units > 1

    def beta_size(self, shard_size: int) -> int:
        """Bytes ONE helper ships for a single-shard repair: shard/alpha."""
        if shard_size % self.sub_units:
            raise ValueError(
                f"shard size {shard_size} not divisible by "
                f"sub_units={self.sub_units}")
        return shard_size // self.sub_units

    def is_valid(self) -> bool:
        if self.N <= 0 or self.M <= 0 or self.L < 0 or self.az_count <= 0:
            return False
        if self.N % self.az_count or self.M % self.az_count or self.L % self.az_count:
            return False
        if self.is_regenerating:
            # PM-MSR geometry: alpha = N-1, d = 2N-2, a single loss must
            # leave d helpers, and LRC locals don't compose with beta-fetch
            if self.L or self.sub_units != self.N - 1:
                return False
            if self.helpers != 2 * self.N - 2 or self.total - 1 < self.helpers:
                return False
        # quorum bound: (N+M)/AZCount + N <= PutQuorum <= M+N (codemode.go:137-140)
        return self.put_quorum <= self.N + self.M

    def global_stripe(self) -> list[int]:
        """Indexes of the N+M global-stripe shards (data then parity)."""
        return list(range(self.N + self.M))

    def az_of_shard(self, idx: int) -> int:
        """AZ owning shard idx under contiguous N/M/L dealing."""
        if idx < self.N:
            return idx // (self.N // self.az_count)
        if idx < self.N + self.M:
            return (idx - self.N) // (self.M // self.az_count)
        if idx < self.total:
            return (idx - self.N - self.M) // (self.L // self.az_count) if self.L else 0
        raise IndexError(idx)

    def shards_in_az(self, az: int) -> list[int]:
        """All shard indexes (data, global parity, local parity) living in one AZ."""
        if not 0 <= az < self.az_count:
            raise IndexError(az)
        dn, pn = self.N // self.az_count, self.M // self.az_count
        out = list(range(az * dn, (az + 1) * dn))
        out += list(range(self.N + az * pn, self.N + (az + 1) * pn))
        if self.L:
            ln = self.L // self.az_count
            base = self.N + self.M
            out += list(range(base + az * ln, base + (az + 1) * ln))
        return out

    def local_stripes(self) -> list[tuple[list[int], int, int]]:
        """[(shard_indexes, local_n, local_m)] per AZ — the LRC repair stripes.

        Matches the layout comment at codemode.go:119-126: each AZ's local stripe is
        its data + global-parity shards (local_n of them) protected by its local
        parities (local_m). Empty when L == 0.
        """
        if not self.L:
            return []
        local_n = (self.N + self.M) // self.az_count
        local_m = self.L // self.az_count
        return [(self.shards_in_az(az), local_n, local_m) for az in range(self.az_count)]

    def shard_size(self, blob_size: int) -> int:
        """Per-shard byte size when splitting a blob (codemode.go:142-158).

        Regenerating modes round up to a multiple of sub_units so every
        shard slices into alpha equal sub-units (the beta-payload unit).
        """
        if blob_size <= 0:
            raise ValueError(f"blob_size {blob_size}")
        size = -(-blob_size // self.N)  # ceil div
        size = max(size, self.min_shard_size)
        if self.sub_units > 1:
            size = -(-size // self.sub_units) * self.sub_units
        return size

    def helper_set(self, fail: int, alive: list[int]) -> list[int]:
        """The layout-aware helper pick for a single-shard beta-fetch repair:
        which d survivors ship their beta payload for failed shard `fail`.

        Policy: prefer helpers in the failed shard's own AZ (repair traffic
        stays local), then ring-distance-closest AZs, index order within an
        AZ for determinism. Returns [] when the survivors can't cover d —
        the caller then falls back to the full-stripe gather.
        """
        if not self.is_regenerating:
            return []
        cand = [i for i in alive if i != fail and i < self.global_count]
        if len(cand) < self.helpers:
            return []
        az_f = self.az_of_shard(fail)
        ring = self.az_count

        def rank(i: int) -> tuple[int, int]:
            dist = abs(self.az_of_shard(i) - az_f)
            return (min(dist, ring - dist), i)

        return sorted(cand, key=rank)[: self.helpers]


_TACTICS: dict[CodeMode, Tactic] = {
    # three AZ
    CodeMode.EC15P12: Tactic(15, 12, 0, 3, put_quorum=24),
    CodeMode.EC6P6: Tactic(6, 6, 0, 3, put_quorum=11),
    CodeMode.EC12P9: Tactic(12, 9, 0, 3, put_quorum=20),
    # two AZ (LRC)
    CodeMode.EC16P20L2: Tactic(16, 20, 2, 2, put_quorum=34),
    CodeMode.EC6P10L2: Tactic(6, 10, 2, 2, put_quorum=14),
    # single AZ
    CodeMode.EC12P4: Tactic(12, 4, 0, 1, put_quorum=15),
    CodeMode.EC16P4: Tactic(16, 4, 0, 1, put_quorum=19),
    CodeMode.EC3P3: Tactic(3, 3, 0, 1, put_quorum=5),
    CodeMode.EC10P4: Tactic(10, 4, 0, 1, put_quorum=13),
    CodeMode.EC6P3: Tactic(6, 3, 0, 1, put_quorum=8),
    # hot tier: exact-size shards (ALIGN_0B) so replica shard 0 == blob
    CodeMode.Replica3: Tactic(1, 2, 0, 1, put_quorum=2,
                              min_shard_size=ALIGN_0B),
    # regenerating: PM-MSR n=12/k=6/d=10/alpha=5 — repair ships 10 beta
    # payloads (2 shard-equivalents) instead of 12 full shards
    CodeMode.RG6P6: Tactic(6, 6, 0, 1, put_quorum=11,
                           sub_units=5, helpers=10),
    # env/test modes
    CodeMode.EC6P3L3: Tactic(6, 3, 3, 3, put_quorum=9),
    CodeMode.EC6P6Align0: Tactic(6, 6, 0, 3, put_quorum=11, min_shard_size=ALIGN_0B),
    CodeMode.EC6P6Align512: Tactic(6, 6, 0, 3, put_quorum=11, min_shard_size=ALIGN_512B),
    CodeMode.EC4P4L2: Tactic(4, 4, 2, 2, put_quorum=6),
    CodeMode.EC6P6L9: Tactic(6, 6, 9, 3, put_quorum=11),
    CodeMode.EC6P8L10: Tactic(6, 8, 10, 2, put_quorum=13, min_shard_size=ALIGN_0B),
    CodeMode.EC20P4L2: Tactic(20, 4, 2, 2, put_quorum=22),
    CodeMode.EC4P2: Tactic(4, 2, 0, 1, put_quorum=5),
    CodeMode.RG4P4: Tactic(4, 4, 0, 1, put_quorum=7,
                           sub_units=3, helpers=6),
}


def get_tactic(mode: CodeMode | int | str) -> Tactic:
    try:
        if isinstance(mode, str):
            mode = CodeMode[mode]
        return _TACTICS[CodeMode(mode)]
    except (KeyError, ValueError):
        raise ValueError(
            f"unknown code mode {mode!r}; known: {[m.name for m in _TACTICS]}"
        ) from None


def all_modes() -> list[CodeMode]:
    return list(_TACTICS.keys())
