"""Encoder — the ec.Encoder-equivalent API over the TPU bit-matrix kernels.

Mirrors the capability surface of reference blobstore/common/ec/encoder.go:41-62
(Encode / Verify / Reconstruct / ReconstructData / Split / Join / GetDataShards /
GetParityShards / GetLocalShards / GetShardsInIdc) and the LRC variant
(lrcencoder.go): global RS(N, M) plus per-AZ local RS over each AZ's global shards.

Differences from the reference, by design:
  * the math runs as batched GF(2) bit-matmuls on the TPU MXU (ops/rs.py), not
    SIMD table gathers;
  * shards are numpy uint8 views stacked into one (total, k) array per call —
    the stacked form is what the device wants, and the blobstore access layer
    (chubaofs_tpu/blobstore) keeps blobs in that form end to end;
  * reconstruct accepts any repairable missing pattern; for LRC it prefers
    AZ-local stripes (the reference's recoverByLocalStripe,
    blobnode/work_shard_recover.go:517) and falls back to the global stripe.

The list-of-buffers API is kept for drop-in familiarity: a user of the reference's
`ec.Encoder` finds the same verbs here.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import IO, Sequence

import numpy as np

from chubaofs_tpu.codec.codemode import CodeMode, Tactic, get_tactic
from chubaofs_tpu.ops import gf256, rs

Shards = list[np.ndarray]


class ECError(Exception):
    pass


class ShortDataError(ECError):
    pass


class VerifyError(ECError):
    pass


class InvalidShardsError(ECError):
    pass


@dataclass(frozen=True)
class EncoderConfig:
    """Equivalent of ec.Config (encoder.go:66-71)."""

    code_mode: CodeMode | Tactic
    enable_verify: bool = False

    @property
    def tactic(self) -> Tactic:
        t = self.code_mode
        return t if isinstance(t, Tactic) else get_tactic(t)


def _as_matrix(shards: Sequence[np.ndarray | bytes | bytearray], total: int) -> np.ndarray:
    if len(shards) != total:
        raise InvalidShardsError(f"want {total} shards, got {len(shards)}")
    rows = [np.frombuffer(memoryview(s), dtype=np.uint8) if not isinstance(s, np.ndarray) else s for s in shards]
    k = len(rows[0])
    if k == 0 or any(len(r) != k for r in rows):
        raise InvalidShardsError("shards must be equal-sized and non-empty")
    return np.stack(rows)


def _check_writable(shards: Sequence, idx: Sequence[int]) -> None:
    """Reject read-only output buffers BEFORE any device work is spent."""
    for i in idx:
        dst = shards[i]
        ro = dst.flags.writeable is False if isinstance(dst, np.ndarray) else memoryview(dst).readonly
        if ro:
            raise InvalidShardsError(
                f"shard {i} is read-only; pass bytearray/ndarray for output shards"
            )


def _writeback(shards: Sequence, mat: np.ndarray, idx: Sequence[int]) -> None:
    """Copy repaired/encoded rows back into caller-owned buffers."""
    for i in idx:
        dst = shards[i]
        if isinstance(dst, np.ndarray):
            dst[:] = mat[i]
        else:
            memoryview(dst)[:] = mat[i].tobytes()


class RsEncoder:
    """Plain RS encoder for L == 0 code modes."""

    def __init__(self, cfg: EncoderConfig):
        self.cfg = cfg
        self.tactic = cfg.tactic
        if not self.tactic.is_valid():
            raise ValueError(f"invalid code-mode tactic {self.tactic}")
        if self.tactic.L:
            raise ValueError("use LrcEncoder for L != 0 modes")
        self.kernel = rs.get_kernel(self.tactic.N, self.tactic.M)

    # -- core verbs --------------------------------------------------------

    def encode(self, shards: Sequence) -> None:
        t = self.tactic
        _check_writable(shards, range(t.N, t.total))
        mat = _as_matrix(shards, t.total)
        full = np.asarray(self.kernel.encode(mat[: t.N]))
        if self.cfg.enable_verify and not bool(self.kernel.verify(full)):
            raise VerifyError("post-encode verify failed")
        _writeback(shards, full, range(t.N, t.total))

    def verify(self, shards: Sequence) -> bool:
        mat = _as_matrix(shards, self.tactic.total)
        return bool(self.kernel.verify(mat))

    def reconstruct(self, shards: Sequence, bad_idx: Sequence[int]) -> None:
        self._reconstruct(shards, bad_idx, data_only=False)

    def reconstruct_data(self, shards: Sequence, bad_idx: Sequence[int]) -> None:
        self._reconstruct(shards, bad_idx, data_only=True)

    def _reconstruct(self, shards, bad_idx, data_only: bool) -> None:
        if not bad_idx:
            return
        t = self.tactic
        target = [i for i in bad_idx if i < t.N] if data_only else list(bad_idx)
        _check_writable(shards, target)
        mat = _as_matrix(shards, t.total)
        fixed = np.asarray(self.kernel.reconstruct(mat, list(bad_idx), data_only=data_only))
        _writeback(shards, fixed, target)

    # -- shard bookkeeping (encoder.go:52-62) -------------------------------

    def split(self, data: bytes | bytearray | np.ndarray) -> Shards:
        """Split source data into a full zero-padded shard list (data + parity)."""
        t = self.tactic
        buf = np.frombuffer(memoryview(data), dtype=np.uint8)
        if buf.size == 0:
            raise ShortDataError("empty data")
        size = t.shard_size(buf.size)
        out = np.zeros((t.total, size), dtype=np.uint8)
        flat = out[: t.N].reshape(-1)
        flat[: buf.size] = buf
        return list(out)

    def join(self, dst: IO[bytes], shards: Sequence, out_size: int) -> None:
        """Write the first out_size bytes of the data region; accepts the full
        shard list or just the N data shards."""
        t = self.tactic
        if len(shards) < t.N:
            raise InvalidShardsError(f"join needs >= {t.N} shards")
        mat = _as_matrix(list(shards)[: t.N], t.N)
        data = mat.reshape(-1)
        if out_size > data.size:
            raise ShortDataError(f"join: want {out_size} bytes, have {data.size}")
        dst.write(data[:out_size].tobytes())

    def get_data_shards(self, shards: Sequence) -> list:
        return list(shards[: self.tactic.N])

    def get_parity_shards(self, shards: Sequence) -> list:
        return list(shards[self.tactic.N : self.tactic.N + self.tactic.M])

    def get_local_shards(self, shards: Sequence) -> list:
        return []

    def get_shards_in_idc(self, shards: Sequence, az: int) -> list:
        return [shards[i] for i in self.tactic.shards_in_az(az)]


class LrcEncoder(RsEncoder):
    """LRC: global RS(N, M) plus one local RS per AZ over that AZ's global shards.

    Layout (codemode.go:119-126): shards = N data | M global parity | L local
    parity; each AZ's local stripe is its (N+M)/AZCount global shards plus its
    L/AZCount local parities.
    """

    def __init__(self, cfg: EncoderConfig):
        self.cfg = cfg
        self.tactic = cfg.tactic
        t = self.tactic
        if not t.is_valid():
            raise ValueError(f"invalid code-mode tactic {t}")
        if not t.L:
            raise ValueError("LrcEncoder requires L != 0")
        self.kernel = rs.get_kernel(t.N, t.M)
        self.local_n = (t.N + t.M) // t.az_count
        self.local_m = t.L // t.az_count
        self.local_kernel = rs.get_kernel(self.local_n, self.local_m)

    def encode(self, shards: Sequence) -> None:
        t = self.tactic
        mat = _as_matrix(shards, t.total)
        full = np.asarray(self.kernel.encode(mat[: t.N]))  # (N+M, k)
        mat[: t.global_count] = full
        self._encode_locals(mat)
        if self.cfg.enable_verify and not self._verify_matrix(mat):
            raise VerifyError("post-encode verify failed")
        _writeback(shards, mat, range(t.N, t.total))

    def _encode_locals(self, mat: np.ndarray, azs: Sequence[int] | None = None) -> None:
        """Fill local-parity rows of mat from its global rows, batched per-AZ.

        azs restricts the recompute to the given AZ indexes (default: all).
        """
        t = self.tactic
        stripes = t.local_stripes()
        if azs is not None:
            stripes = [stripes[a] for a in sorted(set(azs))]
        if not stripes:
            return
        # selected AZ stripes share (local_n, local_m): batch into one kernel call
        src = np.stack([mat[idx[: self.local_n]] for idx, _, _ in stripes])
        parity = np.asarray(self.local_kernel.encode_parity(src))  # (az, local_m, k)
        for a, (idx, _, _) in enumerate(stripes):
            mat[idx[self.local_n :]] = parity[a]

    def _verify_matrix(self, mat: np.ndarray) -> bool:
        t = self.tactic
        if not bool(self.kernel.verify(mat[: t.global_count])):
            return False
        stripes = t.local_stripes()
        full = np.stack([mat[idx] for idx, _, _ in stripes])
        return bool(np.all(np.asarray(self.local_kernel.verify(full))))

    def verify(self, shards: Sequence) -> bool:
        return self._verify_matrix(_as_matrix(shards, self.tactic.total))

    def _reconstruct(self, shards, bad_idx, data_only: bool) -> None:
        if not bad_idx:
            return
        t = self.tactic
        target = [i for i in bad_idx if i < t.N] if data_only else list(bad_idx)
        _check_writable(shards, target)
        mat = _as_matrix(shards, t.total)
        bad = set(int(i) for i in bad_idx)

        # 1. local repair: any AZ whose missing count fits its local stripe
        #    (reference recoverByLocalStripe, work_shard_recover.go:517)
        for idx, local_n, local_m in t.local_stripes():
            az_bad = [i for i in idx if i in bad]
            if not az_bad or len(az_bad) > local_m:
                continue
            sub = mat[idx]  # (local_n+local_m, k)
            pos = {g: p for p, g in enumerate(idx)}
            fixed = np.asarray(
                self.local_kernel.reconstruct(sub, [pos[i] for i in az_bad])
            )
            mat[idx] = fixed
            bad -= set(az_bad)

        # 2. global repair for whatever remains in the global stripe
        global_bad = [i for i in bad if i < t.global_count]
        if global_bad:
            if len(global_bad) > t.M:
                raise InvalidShardsError(
                    f"{len(global_bad)} global shards missing > M={t.M}"
                )
            fixed = np.asarray(
                self.kernel.reconstruct(mat[: t.global_count], global_bad)
            )
            mat[: t.global_count] = fixed
            bad -= set(global_bad)

        # 3. any still-missing local parities: recompute from repaired globals,
        #    only in the AZs that actually lost one
        if bad and not data_only:
            locals_bad = [i for i in bad if i >= t.global_count]
            if locals_bad:
                self._encode_locals(mat, azs=[t.az_of_shard(i) for i in locals_bad])
            bad = {i for i in bad if i < t.global_count}
        if bad and any(i < t.N for i in bad):
            raise InvalidShardsError(f"unrecoverable shards: {sorted(bad)}")

        _writeback(shards, mat, target)

    def get_local_shards(self, shards: Sequence) -> list:
        t = self.tactic
        return list(shards[t.global_count : t.total])


class PmEncoder(RsEncoder):
    """Product-matrix MSR encoder for regenerating code modes (codec/pm.py).

    Same verb surface as RsEncoder — systematic, so split/join and the data
    shards are bit-identical to RsEncoder's at equal shard size. What
    changes is the repair math: reconstruct decodes from any N intact
    nodes via the PM generator, and the kernel additionally exposes the
    beta-fetch single-loss path (helper_payload/repair) the repair plane
    uses through the scheduler.
    """

    def __init__(self, cfg: EncoderConfig):
        self.cfg = cfg
        self.tactic = cfg.tactic
        t = self.tactic
        if not t.is_valid():
            raise ValueError(f"invalid code-mode tactic {t}")
        if not t.is_regenerating:
            raise ValueError("PmEncoder requires a regenerating tactic")
        from chubaofs_tpu.codec import pm

        self.kernel = pm.get_kernel(t.total, t.N)

    def encode(self, shards: Sequence) -> None:
        t = self.tactic
        _check_writable(shards, range(t.N, t.total))
        mat = _as_matrix(shards, t.total)
        if mat.shape[1] % t.sub_units:
            raise InvalidShardsError(
                f"shard size {mat.shape[1]} not a multiple of "
                f"sub_units={t.sub_units}")
        full = self.kernel.encode(mat[: t.N])
        if self.cfg.enable_verify and not self.kernel.verify(full):
            raise VerifyError("post-encode verify failed")
        _writeback(shards, full, range(t.N, t.total))

    def verify(self, shards: Sequence) -> bool:
        mat = _as_matrix(shards, self.tactic.total)
        return bool(self.kernel.verify(mat))

    def _reconstruct(self, shards, bad_idx, data_only: bool) -> None:
        if not bad_idx:
            return
        t = self.tactic
        target = [i for i in bad_idx if i < t.N] if data_only else list(bad_idx)
        _check_writable(shards, target)
        mat = _as_matrix(shards, t.total)
        fixed = self.kernel.reconstruct(mat, list(bad_idx),
                                        data_only=data_only)
        _writeback(shards, fixed, target)


@functools.lru_cache(maxsize=32)
def lrc_parity_matrix(t: Tactic) -> np.ndarray:
    """Composed (M+L, N) GF(2^8) generator: global parity rows plus every AZ's
    local parities expressed directly over the N data shards.

    Every LRC parity is linear over GF(2^8), so the reference's two-stage
    encode (global RS, then per-AZ local RS over data+parity rows —
    lrcencoder.go) composes into ONE matrix: an AZ's local input rows are
    A = [basis rows of its data shards; its global parity rows P_az], and its
    local parity rows are L @ A. One fused matmul then yields ALL parity of an
    LRC stripe — no intermediate stripe materialization, no second HBM pass.
    The result is bit-identical to the two-stage path (same generators).
    """
    if not t.L:
        raise ValueError("lrc_parity_matrix requires L != 0")
    g = gf256.systematic_generator(t.N, t.M)  # (N+M, N)
    P = g[t.N :]
    local_n = (t.N + t.M) // t.az_count
    local_m = t.L // t.az_count
    L = gf256.systematic_generator(local_n, local_m)[local_n:]  # (local_m, local_n)
    ident = np.eye(t.N, dtype=np.uint8)
    rows = [P]
    for idx, _, _ in t.local_stripes():
        A = np.stack(
            [ident[i] if i < t.N else P[i - t.N] for i in idx[:local_n]]
        )  # (local_n, N)
        rows.append(gf256.gf_matmul(L, A))
    return np.concatenate(rows, axis=0)


# the reference interface name, for drop-in reading of call sites
Encoder = RsEncoder | LrcEncoder | PmEncoder


def new_encoder(cfg: EncoderConfig | CodeMode | int | str, **kw) -> Encoder:
    """NewEncoder equivalent (encoder.go:78-112): picks RS vs LRC by
    tactic.L, and the product-matrix encoder for regenerating tactics."""
    if not isinstance(cfg, EncoderConfig):
        cfg = EncoderConfig(code_mode=get_tactic(cfg), **kw)
    if cfg.tactic.is_regenerating:
        return PmEncoder(cfg)
    return LrcEncoder(cfg) if cfg.tactic.L else RsEncoder(cfg)
