"""The erasure-codec API layer — equivalent of reference blobstore/common/ec + codemode."""

from chubaofs_tpu.codec.codemode import CodeMode, Tactic, get_tactic
from chubaofs_tpu.codec.encoder import (
    Encoder,
    LrcEncoder,
    RsEncoder,
    new_encoder,
    EncoderConfig,
)

__all__ = [
    "CodeMode",
    "Tactic",
    "get_tactic",
    "Encoder",
    "RsEncoder",
    "LrcEncoder",
    "new_encoder",
    "EncoderConfig",
]
