"""CodecService — the batching device sidecar for erasure-coding math.

Reference analog: the access layer encodes each blob inline on the CPU
(stream_put.go:143 `encoder.Encode`) and blobnode workers reconstruct per-task
(work_shard_recover.go:422). On TPU, per-blob dispatch would waste the chip:
each call pays host->device latency, and small stripes underfill the MXU. This
service is the TPU-native replacement:

  * callers submit encode/repair jobs (numpy matrices) and get futures back;
  * a dispatcher thread drains the queue, groups jobs by (layout, k-bucket),
    pads each shard length up to the bucket, stacks them into one (B, n, k)
    device batch, runs ONE fused-kernel call, then scatters results back;
  * shard lengths are bucketed to powers of two (>= 16 KiB) so the jit cache
    stays small and the MXU sees few distinct shapes;
  * with no accelerator (or in tests), the same code runs on the CPU backend —
    same numerics, same API.

Batching trades a bounded latency (max_wait_ms) for throughput, exactly like the
reference's proxy-side volume-allocation batching — but for math instead of
metadata.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field

import numpy as np

from chubaofs_tpu.ops import rs
from chubaofs_tpu.utils.locks import SanitizedLock

MIN_BUCKET = 16 * 1024


def bucket_len(k: int) -> int:
    """Round a shard length up to the service's shape bucket."""
    b = MIN_BUCKET
    while b < k:
        b *= 2
    return b


class _ChainFuture(Future):
    """Wrapper future whose cancel() propagates to the upstream codec job,
    so a caller holding only the composed LRC result (encode_tactic) can
    still drop the queued device work (access pipeline aborts)."""

    def __init__(self, upstream: Future):
        super().__init__()
        self._upstream = upstream

    def cancel(self) -> bool:
        self._upstream.cancel()  # best-effort: running jobs finish
        return super().cancel()


@dataclass
class _Job:
    kind: str  # "encode" | "matmul"
    n: int
    m: int
    data: np.ndarray  # (rows, kb) uint8 — PRE-PADDED to the shape bucket
    k: int  # true shard length (result is sliced back to it)
    kb: int  # bucket_len(k), computed at submission
    future: Future = field(default_factory=Future)
    # matmul jobs carry their GF matrix (repair rows x survivors)
    mat: np.ndarray | None = None
    # the SUBMITTER's trace span (if any): the dispatcher attributes its
    # batch's host/device time back onto it as named stages, so a PUT's
    # critical-path report splits encode wait into host-ms vs device-ms
    span: object | None = None


def _pad_to_bucket(data: np.ndarray, k: int, kb: int) -> np.ndarray:
    """Pad (rows, k) up to (rows, kb) on the SUBMITTING thread — the drain
    loop then only stacks, and padding cost parallelizes across callers
    instead of serializing on the dispatcher."""
    if k == kb:
        return np.ascontiguousarray(data, np.uint8)
    out = np.zeros((data.shape[0], kb), np.uint8)
    out[:, :k] = data
    return out


class CodecService:
    """Queue -> padded device batches -> futures. Thread-safe, one device stream."""

    def __init__(self, max_batch: int = 32, max_wait_ms: float = 2.0,
                 mesh=None, mesh_interpret: bool = False):
        """mesh: optional jax.sharding.Mesh (dp, sp) — drained batches then
        run through parallel.mesh.sharded_gf_matmul instead of the single-
        device path, which takes the whole blobstore data plane (access
        PUT/GET, scheduler bulk repair) multi-chip without any caller
        change (SURVEY §7 step 6). mesh_interpret forces the Pallas kernel
        in interpret mode on CPU meshes (the dryrun/test path)."""
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1e3
        self.mesh = mesh
        self._mesh_mm = None
        if mesh is not None:
            from chubaofs_tpu.parallel.mesh import sharded_gf_matmul

            self._mesh_mm = sharded_gf_matmul(mesh, interpret=mesh_interpret)
        self._q: queue.Queue[_Job | None] = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True, name="codec-svc")
        self._started = False
        self._closed = False
        self._lock = SanitizedLock(name="codec.lifecycle")
        # dispatcher observability: how well jobs coalesce into device batches
        # (same counter shape as MultiRaft.drain_stats for the raft drain).
        # The codec role registry (cfs_codec_*) is the primary surface; this
        # dict is the legacy view, mutated only under _stats_lock so readers
        # get consistent snapshots (stats_snapshot).
        self.stats = {"batches": 0, "jobs": 0, "max_batch": 0}
        self._stats_lock = SanitizedLock(name="codec.stats")

    def _ensure_started(self):
        with self._lock:
            if self._closed:
                raise RuntimeError("CodecService is closed")
            if not self._started:
                self._thread.start()
                self._started = True

    # -- public API --------------------------------------------------------

    def encode(self, n: int, m: int, data: np.ndarray) -> Future:
        """data (n, k) uint8 -> Future[(n+m, k) uint8 full stripe]."""
        if data.shape[0] != n:
            raise ValueError(f"want {n} data rows, got {data.shape}")
        k = data.shape[1]
        kb = bucket_len(k)
        job = _Job("encode", n, m, _pad_to_bucket(data, k, kb), k, kb)
        self._submit(job)
        return job.future

    def matmul(self, mat: np.ndarray, data: np.ndarray) -> Future:
        """Generic GF(2^8) matmul job: data (rows, k) uint8 ->
        Future[(mat.shape[0], k) uint8]. The raw entry the regenerating-code
        paths ride: PM parity blocks, beta-repair decodes, and any-k
        fallback decodes are all just content-keyed matrices, so they batch
        on the device exactly like RS repairs."""
        mat = np.ascontiguousarray(mat, np.uint8)
        data = np.asarray(data, np.uint8)
        if data.ndim != 2 or mat.ndim != 2 or data.shape[0] != mat.shape[1]:
            raise ValueError(
                f"matmul shape mismatch: mat {mat.shape} @ data {data.shape}")
        k = data.shape[1]
        kb = bucket_len(k)
        job = _Job("matmul", data.shape[0], mat.shape[0],
                   _pad_to_bucket(data, k, kb), k, kb, mat=mat)
        self._submit(job)
        return job.future

    def encode_tactic(self, t, data: np.ndarray) -> Future:
        """data (N, k) uint8 -> Future[(total, k) full stripe], local parities
        included for LRC tactics — computed in ONE composed-matrix matmul
        (encoder.lrc_parity_matrix), not a second device pass. Regenerating
        tactics run their PM parity block the same way: one matmul over the
        stripe's sub-unit rows."""
        if t.is_regenerating:
            return self._encode_pm(t, data)
        if not t.L:
            return self.encode(t.N, t.M, data)
        from chubaofs_tpu.codec.encoder import lrc_parity_matrix

        if data.shape[0] != t.N:
            raise ValueError(f"want {t.N} data rows, got {data.shape}")
        # snapshot ONCE (explicit copy) and build the result from the same
        # snapshot the job computed parity from — caller-side dtype changes or
        # post-submit mutation must never yield a stripe whose data rows don't
        # match its parity
        data = np.array(data, np.uint8, order="C")
        mat = lrc_parity_matrix(t)
        k = data.shape[1]
        kb = bucket_len(k)
        job = _Job("matmul", t.N, t.M + t.L, _pad_to_bucket(data, k, kb),
                   k, kb, mat=mat)
        self._submit(job)
        out = _ChainFuture(job.future)

        def _finish(f: Future):
            if f.cancelled() or out.cancelled():
                # cancelled upstream (drain handshake dropped the job) or
                # downstream (pipeline abort): nothing to deliver
                return
            try:
                if f.exception():
                    out.set_exception(f.exception())
                else:
                    out.set_result(
                        np.concatenate([data, f.result()], axis=0))
            except InvalidStateError:
                pass  # out.cancel() raced the delivery: outcome discarded

        job.future.add_done_callback(_finish)
        return out

    def _encode_pm(self, t, data: np.ndarray) -> Future:
        """Product-matrix encode: shard rows reshaped (free) to sub-unit
        rows, parity block applied as one matmul, parity rows reshaped back
        to shards. Same snapshot discipline as the LRC path."""
        from chubaofs_tpu.codec import pm

        if data.shape[0] != t.N:
            raise ValueError(f"want {t.N} data rows, got {data.shape}")
        size = data.shape[1]
        if size % t.sub_units:
            raise ValueError(
                f"shard size {size} not a multiple of sub_units={t.sub_units}")
        data = np.array(data, np.uint8, order="C")
        kernel = pm.get_kernel(t.total, t.N)
        f = self.matmul(kernel.parity_mat,
                        data.reshape(t.N * t.sub_units, -1))
        out = _ChainFuture(f)

        def _finish(fut: Future):
            if fut.cancelled() or out.cancelled():
                return
            try:
                if fut.exception():
                    out.set_exception(fut.exception())
                else:
                    parity = fut.result().reshape(t.M, size)
                    out.set_result(np.concatenate([data, parity], axis=0))
            except InvalidStateError:
                pass  # out.cancel() raced the delivery: outcome discarded

        f.add_done_callback(_finish)
        return out

    def reconstruct_tactic(self, t, shards: np.ndarray, bad_idx: list[int],
                           data_only: bool = False) -> Future:
        """Tactic-aware full-stripe rebuild: RS/LRC global stripes use the
        windowed RS repair matrix; regenerating stripes decode from any N
        intact nodes via the PM generator (the multi-loss fallback — the
        single-loss beta-fetch path lives in the scheduler)."""
        if not t.is_regenerating:
            return self.reconstruct(t.N, t.M, shards, bad_idx, data_only)
        from chubaofs_tpu.codec import pm

        kernel = pm.get_kernel(t.total, t.N)
        bad = sorted(set(int(i) for i in bad_idx))
        want = [i for i in bad if i < t.N] if data_only else bad
        if not want:
            f: Future = Future()
            f.set_result(np.array(shards, copy=True))
            return f
        alive = [i for i in range(t.total) if i not in bad]
        if len(alive) < t.N:
            f = Future()
            f.set_exception(ValueError(
                f"{len(bad)} losses > M={t.M} for regenerating stripe"))
            return f
        srv = alive[: t.N]
        mat = kernel.decode_matrix(srv, want)
        shards = np.asarray(shards, np.uint8)
        size = shards.shape[1]
        job_f = self.matmul(
            mat, shards[np.asarray(srv)].reshape(t.N * t.sub_units, -1))
        out_future: Future = Future()

        def _finish(fut: Future):
            if fut.exception():
                out_future.set_exception(fut.exception())
                return
            fixed = np.array(shards, copy=True)
            fixed[np.asarray(want)] = fut.result().reshape(len(want), size)
            out_future.set_result(fixed)

        job_f.add_done_callback(_finish)
        return out_future

    def reconstruct(
        self, n: int, m: int, shards: np.ndarray, bad_idx: list[int], data_only=False
    ) -> Future:
        """shards (n+m, k) with garbage rows at bad_idx -> Future[repaired copy]."""
        kernel = rs.get_kernel(n, m)
        mat, present, missing = kernel.repair_matrix(list(bad_idx), data_only)
        if not missing:
            f: Future = Future()
            f.set_result(np.array(shards, copy=True))
            return f
        k = shards.shape[1]
        kb = bucket_len(k)
        survivors = _pad_to_bucket(
            np.asarray(shards, np.uint8)[np.asarray(present)], k, kb)
        job = _Job("matmul", n, m, survivors, k, kb, mat=mat)
        self._submit(job)

        out_future: Future = Future()

        def _finish(f: Future):
            if f.exception():
                out_future.set_exception(f.exception())
                return
            rows = f.result()
            fixed = np.array(shards, copy=True)
            fixed[np.asarray(missing)] = rows
            out_future.set_result(fixed)

        job.future.add_done_callback(_finish)
        return out_future

    def decode_rows(self, n: int, m: int, present: list[int],
                    survivors: np.ndarray, want: list[int]) -> Future:
        """Range-scoped degraded decode: survivors (n, w) uint8 — the chosen
        n survivor shards' bytes over just the window's byte columns, row
        order matching `present` — -> Future[(len(want), w) uint8] holding
        ONLY the wanted shard rows over those columns.

        Never materializes the full stripe: the decode matrix is sliced to
        the wanted rows on the host (RSKernel.window_matrix), so the device
        pass is (len(want), n) @ (n, w) — window-sized both ways. Jobs with
        the identical (present, want) pattern batch on the device exactly
        like repairs (content-keyed matrix signature).
        """
        kernel = rs.get_kernel(n, m)
        mat = kernel.window_matrix(present, want)
        survivors = np.asarray(survivors, np.uint8)
        if survivors.ndim != 2 or survivors.shape[0] != n:
            raise ValueError(
                f"want ({n}, w) survivors, got {survivors.shape}")
        k = survivors.shape[1]
        kb = bucket_len(k)
        job = _Job("matmul", n, m, _pad_to_bucket(survivors, k, kb),
                   k, kb, mat=mat)
        self._submit(job)
        return job.future

    def close(self):
        """Idempotent shutdown; jobs enqueued after close() fail fast, jobs
        still queued when the sentinel lands get an exception (never a hang)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            started = self._started
        if started:
            self._q.put(None)
            self._thread.join(timeout=5)

    # -- dispatcher --------------------------------------------------------

    def _submit(self, job: _Job):
        from chubaofs_tpu.blobstore import trace

        job.span = trace.current_span()
        self._ensure_started()
        self._q.put(job)

    def _drain(self) -> list[_Job]:
        try:
            first = self._q.get(timeout=0.2)
        except queue.Empty:
            return []
        if first is None:
            raise StopIteration
        batch = [first]
        deadline = self.max_wait
        import time

        t0 = time.monotonic()
        while len(batch) < self.max_batch:
            remaining = deadline - (time.monotonic() - t0)
            try:
                job = self._q.get(timeout=max(0.0, remaining))
            except queue.Empty:
                break
            if job is None:
                self._q.put(None)  # re-post sentinel for the outer loop
                break
            batch.append(job)
        return batch

    def _run(self):
        while True:
            try:
                batch = self._drain()
            except StopIteration:
                # fail anything still queued so no caller blocks forever
                while True:
                    try:
                        job = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if job is not None and not job.future.done():
                        job.future.set_exception(RuntimeError("CodecService closed"))
                return
            if not batch:
                continue
            # honor caller-side cancellation (pipeline aborts drop their
            # encode-ahead jobs): a cancelled job is skipped before any
            # device work, and the running-handshake means a later cancel()
            # fails cleanly instead of racing set_result
            batch = [j for j in batch
                     if j.future.set_running_or_notify_cancel()]
            if not batch:
                continue
            # group by compatible shape signature (kb was bucketed at
            # submission; the drain loop never re-derives shapes)
            groups: dict[tuple, list[_Job]] = {}
            for j in batch:
                if j.kind == "encode":
                    sig = ("encode", j.n, j.m, j.kb)
                else:
                    # matrices are tiny (<= 36x36): key by CONTENT so only jobs
                    # with the identical repair matrix share a batch
                    sig = ("matmul", j.mat.tobytes(), j.data.shape[0], j.kb)
                groups.setdefault(sig, []).append(j)
            for sig, jobs in groups.items():
                try:
                    self._run_group(sig, jobs)
                except Exception as e:  # propagate to every waiter
                    for j in jobs:
                        if not j.future.done():
                            j.future.set_exception(e)

    def stats_snapshot(self) -> dict:
        """Consistent copy of the legacy counters (no torn reads)."""
        with self._stats_lock:
            return dict(self.stats)

    def _record_batch(self, jobs: int, elapsed_s: float,
                      kind: str = "") -> None:
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["jobs"] += jobs
            self.stats["max_batch"] = max(self.stats["max_batch"], jobs)
        from chubaofs_tpu.utils.exporter import BATCH_BUCKETS, registry

        reg = registry("codec")
        reg.counter("batches_total").add()
        reg.counter("jobs_total").add(jobs)
        if kind:
            # the encode/matmul split: proves repair DECODE really batches
            # on the device (bench_repair and the kill soak read this)
            reg.counter("kind_jobs_total", {"kind": kind}).add(jobs)
            reg.counter("kind_batches_total", {"kind": kind}).add()
        reg.summary("batch_jobs", buckets=BATCH_BUCKETS).observe(jobs)
        reg.summary("dispatch_seconds").observe(elapsed_s)

    def _run_group(self, sig: tuple, jobs: list[_Job]):
        import time as _time

        t0 = _time.perf_counter()
        # jobs arrive pre-padded to the bucket: stacking is the whole job here
        stack = np.stack([j.data for j in jobs])
        t_dev = _time.perf_counter()
        # both paths go through the host-boundary grouped entry: batches of
        # stripes are viewed (free numpy reshape) as MXU-row-filling groups
        # before they ever reach the device (rs.gf_matmul_hostbatch) — or,
        # with a mesh, fan out dp/sp-sharded across every device
        mm = self._mesh_mm or rs.gf_matmul_hostbatch
        if sig[0] == "encode":
            kernel = rs.get_kernel(jobs[0].n, jobs[0].m)
            parity = mm(kernel.parity_bits, stack)
            out = np.concatenate([stack, parity], axis=1)  # (B, n+m, kb)
        else:
            from chubaofs_tpu.ops import bitmatrix

            out = mm(bitmatrix.expand_matrix(jobs[0].mat).astype(np.int8), stack)
        t_done = _time.perf_counter()
        self._record_batch(len(jobs), t_done - t0, kind=str(sig[0]))
        for j in jobs:
            if j.span is not None:
                # the BATCH's wall intervals, attributed to every rider: the
                # job was on the host/device during exactly these windows
                # (shared across the batch — sums can exceed device seconds,
                # wall-clock union cannot)
                j.span.add_stage("codec.host", start=t0, dur=t_dev - t0)
                j.span.add_stage("codec.device", start=t_dev,
                                 dur=t_done - t_dev)
        for i, j in enumerate(jobs):
            j.future.set_result(out[i, :, : j.k])


_default: CodecService | None = None
_default_lock = SanitizedLock(name="codec.default")


def default_service() -> CodecService:
    global _default
    with _default_lock:
        if _default is None:
            _default = CodecService()
        return _default
