"""Mesh + sharding layer for the codec: the framework's DP/SP scale-out axes.

The reference scales erasure coding by fanning stripes out to goroutines on many
hosts (access stream_put.go:193-442; scheduler bulk repair). The TPU-native
equivalent is a jax.sharding.Mesh with two axes:

  * ``dp`` (data/stripe parallel) — independent stripes across devices; the analog
    of the reference's per-blob goroutines.
  * ``sp`` (shard-length / "sequence" parallel) — the byte axis *within* a stripe
    split across devices, so a single huge stripe (the long-context analog, SURVEY
    §5 "stripe batch size × shard count") exceeds one chip's HBM/compute. GF
    encoding is columnwise-independent, so sp sharding needs no collectives for
    encode; only verify's final reduction crosses devices (an AND via jnp.all,
    lowered to an XLA all-reduce over ICI).

The bit-generator matrices are tiny (<= 320x320 int8) and replicated.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chubaofs_tpu.ops import rs


def codec_mesh(devices=None, dp: int | None = None, sp: int | None = None) -> Mesh:
    """Build a (dp, sp) mesh over the given devices (default: all)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and sp is None:
        sp = 2 if n % 2 == 0 and n > 1 else 1
        dp = n // sp
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != {n} devices")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def shard_stripes(mesh: Mesh, stripes) -> jax.Array:
    """Place (B, n, k) stripes: B over dp, k over sp, shard axis replicated.

    Host data goes straight to the mesh's devices — no intermediate commit to
    the default backend (which may be a different platform than the mesh).
    """
    if not isinstance(stripes, jax.Array):
        stripes = np.asarray(stripes)
    return jax.device_put(stripes, NamedSharding(mesh, P("dp", None, "sp")))


def sharded_codec_step(mesh: Mesh, n: int, m: int):
    """Jitted full codec step over the mesh: encode -> verify -> repair.

    This is the flagship distributed 'step' (the training-step analog): one batch
    of stripes goes through the complete PUT+scrub+repair pipeline. Returns a
    function (data (B, n, k) uint8) -> (stripe (B, n+m, k), ok (B,), repaired (B, n+m, k)).
    """
    kernel = rs.get_kernel(n, m)
    out_spec = NamedSharding(mesh, P("dp", None, "sp"))
    ok_spec = NamedSharding(mesh, P("dp"))

    # a representative repair pattern: lose the first data and first parity shard
    plan = kernel.repair_plan([0, n])

    def step(data):
        # portable=True: the XLA einsum lowering partitions over the mesh; the
        # fused Pallas kernel has no GSPMD partitioning rule
        stripe = kernel.encode(data, portable=True)  # (B, n+m, k)
        ok = kernel.verify(stripe, portable=True)  # (B,) — all-reduce over sp
        repaired = kernel.apply_repair(plan, stripe, portable=True)
        return stripe, ok, repaired

    jitted = jax.jit(step, out_shardings=(out_spec, ok_spec, out_spec))

    def run(data):
        data = shard_stripes(mesh, data)
        with mesh:
            return jitted(data)

    return run
