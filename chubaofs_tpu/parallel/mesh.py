"""Mesh + sharding layer for the codec: the framework's DP/SP scale-out axes.

The reference scales erasure coding by fanning stripes out to goroutines on many
hosts (access stream_put.go:193-442; scheduler bulk repair). The TPU-native
equivalent is a jax.sharding.Mesh with two axes:

  * ``dp`` (data/stripe parallel) — independent stripes across devices; the analog
    of the reference's per-blob goroutines.
  * ``sp`` (shard-length / "sequence" parallel) — the byte axis *within* a stripe
    split across devices, so a single huge stripe (the long-context analog, SURVEY
    §5 "stripe batch size × shard count") exceeds one chip's HBM/compute. GF
    encoding is columnwise-independent, so sp sharding needs no collectives for
    encode; only verify's final reduction crosses devices (an AND via jnp.all,
    lowered to an XLA all-reduce over ICI).

The bit-generator matrices are tiny (<= 320x320 int8) and replicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chubaofs_tpu.ops import rs


def codec_mesh(devices=None, dp: int | None = None, sp: int | None = None) -> Mesh:
    """Build a (dp, sp) mesh over the given devices (default: all)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and sp is None:
        sp = 2 if n % 2 == 0 and n > 1 else 1
        dp = n // sp
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != {n} devices")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def shard_stripes(mesh: Mesh, stripes) -> jax.Array:
    """Place (B, n, k) stripes: B over dp, k over sp, shard axis replicated.

    Host data goes straight to the mesh's devices — no intermediate commit to
    the default backend (which may be a different platform than the mesh).
    """
    if not isinstance(stripes, jax.Array):
        stripes = np.asarray(stripes)
    return jax.device_put(stripes, NamedSharding(mesh, P("dp", None, "sp")))


def group_view(data: np.ndarray, g: int) -> np.ndarray:
    """Host-boundary group view: (B, n, k) -> (B/g, g*n, k). A free numpy
    reshape here; on device the same reshape physically rearranges the
    sublane-tiled buffer (PERF.md "group stacking")."""
    b, n, k = data.shape
    assert b % g == 0, (b, g)
    return data.reshape(b // g, g * n, k)


def ungroup_stripe(stripe: np.ndarray, g: int, n: int, m: int,
                   b: int | None = None) -> np.ndarray:
    """Host-boundary inverse for encoded stripes: grouped (B/g, g*n + g*m, k)
    -> per-stripe (B, n+m, k). The grouped layout keeps the g stripes' data
    rows first and their parity rows after (block order), so the split is two
    views plus one concatenate. Pass ``b`` (the original stripe count) to
    drop the zero-padding stripes an uneven batch leaves inside the final
    group — the device can't slice a partial group, so it happens here."""
    stripe = np.asarray(stripe)
    bg, rows, k = stripe.shape
    assert rows == g * (n + m), (stripe.shape, g, n, m)
    data = stripe[:, : g * n, :].reshape(bg * g, n, k)
    par = stripe[:, g * n :, :].reshape(bg * g, m, k)
    out = np.concatenate([data, par], axis=1)
    return out[:b] if b is not None else out


def _grouped_row(s: int, gi: int, g: int, n: int, m: int) -> int:
    """Stripe-local shard index s (0..n+m) of slab gi -> grouped stripe row."""
    return gi * n + s if s < n else g * n + gi * m + (s - n)


def _select_gf(mesh: Mesh, fused: bool | None, interpret: bool):
    """(gf, use_fused) for this mesh. Auto-select keys off the MESH's
    platform, not the default backend: under axon the default is a proxied
    TPU while the dryrun mesh is CPU devices — compiling the Mosaic kernel
    for a CPU mesh would crash. interpret=True forces the Pallas kernel in
    interpret mode (CPU-mesh tests of the real kernel)."""
    mesh_platform = next(iter(mesh.devices.flat)).platform
    use_fused = interpret or (
        fused if fused is not None else mesh_platform == "tpu"
    )

    def gf(mat_bits, x):
        if use_fused:
            from chubaofs_tpu.ops import pallas_gf

            # numpy matrices pass through unconverted so the plane-major
            # permutation runs in numpy at trace time; traced matrices pay
            # a tiny in-graph gather instead
            return pallas_gf.gf_matmul_bytes_fused(mat_bits, x,
                                                   interpret=interpret)
        return rs.gf_matmul_bytes(mat_bits, x)

    return gf, use_fused


def sharded_gf_matmul(mesh: Mesh, *, fused: bool | None = None,
                      interpret: bool = False):
    """Mesh-wide drop-in for ``rs.gf_matmul_hostbatch``: host (B, n, k)
    batches x a byte-major bit matrix -> host (B, r, k), sharded B over
    ``dp`` and k over ``sp``, with the MXU group-stacked layout taken at the
    host boundary (PERF.md). This is how CodecService — and therefore the
    whole blobstore data plane above it (access PUT/GET, scheduler bulk
    repair) — runs on more than one chip: the service stays a single queue,
    but every drained batch fans out across the mesh.

    The matrix rides as a RUNTIME argument (replicated), so every repair
    pattern of the same shape shares one compiled program — exactly the
    ``sharded_codec_step`` plan contract, applied to the service's generic
    matmul jobs."""
    gf, use_fused = _select_gf(mesh, fused, interpret)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P("dp", None, "sp")),
        out_specs=P("dp", None, "sp"),
        check_vma=False,
    )
    def mm(mat, data):
        return gf(mat, data)

    jitted = jax.jit(mm)
    replicated = NamedSharding(mesh, P())
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]

    def run(mat_bits: np.ndarray, batch: np.ndarray) -> np.ndarray:
        batch = np.asarray(batch, np.uint8)
        mat_bits = np.asarray(mat_bits, np.int8)
        b, n, k = batch.shape
        r = mat_bits.shape[0] // 8
        if b == 0 or r == 0 or k == 0:
            return np.zeros((b, r, k), np.uint8)
        if use_fused:
            from chubaofs_tpu.ops import pallas_gf

            # cap g so grouping never collapses the batch below dp (every
            # mesh row must keep real stripes, not padding)
            g = pallas_gf.pick_group(b, *mat_bits.shape, cap=max(1, b // dp))
        else:
            g = 1
        mat_s = np.kron(np.eye(g, dtype=np.int8), mat_bits) if g > 1 else mat_bits
        data = group_view(batch, g) if g > 1 else batch
        pad_rows = (-data.shape[0]) % dp
        if pad_rows:  # zero stripes encode trivially; sliced back out below
            data = np.concatenate(
                [data, np.zeros((pad_rows, g * n, k), np.uint8)])
        kpad = (-k) % (sp * 128)
        if kpad:
            data = np.pad(data, ((0, 0), (0, 0), (0, kpad)))
        with mesh:
            out = jitted(jax.device_put(mat_s, replicated),
                         shard_stripes(mesh, data))
        out = np.asarray(out)[: b // g, :, :k]
        return out.reshape(b, r, k)

    return run


def sharded_codec_step(
    mesh: Mesh, n: int, m: int, *, fused: bool | None = None,
    interpret: bool = False, group: int = 1
):
    """Jitted full codec step over the mesh: encode -> verify -> repair.

    This is the flagship distributed 'step' (the training-step analog): one batch
    of stripes goes through the complete PUT+scrub+repair pipeline. Returns
    ``run(data, bad_idx=(0, n))`` mapping (B, n, k) uint8 data stripes to
    (stripe, ok (B,), repaired).

    Sharding story: the step is a ``jax.shard_map`` over (dp, sp) — each device
    runs the FUSED Pallas kernel on its local block (GF math is
    columnwise-independent, so no collectives except verify's AND over sp,
    a psum on ICI). ``fused=None`` auto-selects: Pallas on TPU backends, the
    XLA einsum lowering elsewhere; ``interpret=True`` forces the Pallas kernel
    in interpret mode (CPU-mesh tests of the real kernel).

    ``group=g`` runs the MXU group-stacked layout per device (PERF.md: the
    single-chip 54 -> 122 GB/s step, carried to the sharded path): g stripes
    are viewed as one wide (g*n, k) stripe AT THE HOST BOUNDARY (free numpy
    reshape in ``run``) and all matrices — generator and runtime repair plans
    alike — are kron-stacked to fill the MXU rows. With group > 1:
      * pass HOST (numpy) batches — a device-resident input is staged through
        the host (D2H + re-upload), because only the host view is free;
      * the stripe and repaired outputs stay in the grouped device layout —
        convert with ``ungroup_stripe(out, g, n, m, b=B)``, which also drops
        the zero-pad stripes an uneven batch leaves inside the final group
        (the device cannot slice a partial group);
      * ``ok`` is always per-stripe and sliced to B.

    The repair pattern is RUNTIME data via ``repair_plan_padded`` — changing
    ``bad_idx`` between calls never recompiles (the kron stacking preserves
    static shapes). Batches that don't divide dp*group are zero-padded in and
    sliced out (zero stripes encode/verify trivially).
    """
    g = int(group)
    assert g >= 1
    kernel = rs.get_kernel(n, m)
    gn, gm = g * n, g * m
    if g == 1:
        parity_bits = kernel.parity_bits
    else:
        parity_bits = np.kron(np.eye(g, dtype=np.int8), kernel.parity_bits)
    gf, use_fused = _select_gf(mesh, fused, interpret)
    sp_size = mesh.shape["sp"]
    trace_count = [0]

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("dp", None, "sp"), P(), P(), P()),
        out_specs=(P("dp", None, "sp"), P("dp"), P("dp", None, "sp")),
        # pallas_call carries no varying-mesh-axes metadata; the out_specs
        # above are the replication contract, checked by the tests numerically
        check_vma=False,
    )
    def step(data, repair_bits, present, missing):
        trace_count[0] += 1  # trace-time only: counts compilations, not calls
        parity = gf(parity_bits, data)  # (B/(dp*g), g*m, k/sp) per device
        stripe = jnp.concatenate([data, parity], axis=-2)
        # verify: recompute parity from the stripe's data rows, AND over sp;
        # row-wise first so ok stays PER STRIPE even in the grouped layout
        expect = gf(parity_bits, stripe[..., :gn, :])
        eq_rows = jnp.all(expect == stripe[..., gn:, :], axis=-1)  # (b, g*m)
        ok_local = jnp.all(eq_rows.reshape(*eq_rows.shape[:-1], g, m), axis=-1)
        ok = jax.lax.psum(ok_local.astype(jnp.int32), "sp") == sp_size
        ok = ok.reshape(-1)  # (b*g,): per original stripe
        # repair: survivors -> missing rows via the runtime plan
        survivors = jnp.take(stripe, present, axis=-2)
        rows = gf(repair_bits, survivors)
        repaired = stripe.at[..., missing, :].set(rows)
        return stripe, ok, repaired

    jitted = jax.jit(step)
    replicated = NamedSharding(mesh, P())

    @functools.lru_cache(maxsize=64)
    def plan_for(bad: tuple) -> tuple:
        # once per pattern: the O(n^3) host-side inversion AND the replicated
        # broadcast to every mesh device (repeat steps transfer nothing).
        # With group > 1 the plan is kron-stacked and its survivor/missing
        # coordinates expanded to grouped stripe rows — shapes stay static,
        # so changing patterns still never recompiles.
        mat, present, missing = kernel.repair_plan_padded(list(bad))
        if g > 1:
            mat = np.kron(np.eye(g, dtype=np.int8), mat)
            present = np.asarray(
                [_grouped_row(int(s), gi, g, n, m)
                 for gi in range(g) for s in present], np.int32)
            missing = np.asarray(
                [_grouped_row(int(s), gi, g, n, m)
                 for gi in range(g) for s in missing], np.int32)
        plan = (mat, present, missing)
        return tuple(jax.device_put(a, replicated) for a in plan)

    def run(data, bad_idx=(0, n)):
        args = plan_for(tuple(sorted(set(int(i) for i in bad_idx))))
        if isinstance(data, jax.Array) and g > 1:
            # the group view is only free at the host boundary: device inputs
            # pay a D2H + re-upload here (see docstring — pass numpy batches)
            data = np.asarray(data)
        if not isinstance(data, jax.Array):
            data = np.asarray(data)
        b = data.shape[0]
        pad = (-b) % (mesh.shape["dp"] * g)
        if pad:
            # pad in the input's own space: device arrays stay on device
            xp = jnp if isinstance(data, jax.Array) else np
            data = xp.concatenate(
                [data, xp.zeros((pad, *data.shape[1:]), xp.uint8)], axis=0
            )
        if g > 1:
            data = group_view(data, g)
        data = shard_stripes(mesh, data)
        with mesh:
            stripe, ok, repaired = jitted(data, *args)
        if pad:
            nb = b // g + (1 if b % g else 0) if g > 1 else b
            stripe = stripe[:nb]
            repaired = repaired[:nb]
            ok = ok[:b]
        return stripe, ok, repaired

    run.trace_count = trace_count
    run.group = g
    return run
