"""Mesh + sharding layer for the codec: the framework's DP/SP scale-out axes.

The reference scales erasure coding by fanning stripes out to goroutines on many
hosts (access stream_put.go:193-442; scheduler bulk repair). The TPU-native
equivalent is a jax.sharding.Mesh with two axes:

  * ``dp`` (data/stripe parallel) — independent stripes across devices; the analog
    of the reference's per-blob goroutines.
  * ``sp`` (shard-length / "sequence" parallel) — the byte axis *within* a stripe
    split across devices, so a single huge stripe (the long-context analog, SURVEY
    §5 "stripe batch size × shard count") exceeds one chip's HBM/compute. GF
    encoding is columnwise-independent, so sp sharding needs no collectives for
    encode; only verify's final reduction crosses devices (an AND via jnp.all,
    lowered to an XLA all-reduce over ICI).

The bit-generator matrices are tiny (<= 320x320 int8) and replicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chubaofs_tpu.ops import rs


def codec_mesh(devices=None, dp: int | None = None, sp: int | None = None) -> Mesh:
    """Build a (dp, sp) mesh over the given devices (default: all)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and sp is None:
        sp = 2 if n % 2 == 0 and n > 1 else 1
        dp = n // sp
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp*sp = {dp}*{sp} != {n} devices")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def shard_stripes(mesh: Mesh, stripes) -> jax.Array:
    """Place (B, n, k) stripes: B over dp, k over sp, shard axis replicated.

    Host data goes straight to the mesh's devices — no intermediate commit to
    the default backend (which may be a different platform than the mesh).
    """
    if not isinstance(stripes, jax.Array):
        stripes = np.asarray(stripes)
    return jax.device_put(stripes, NamedSharding(mesh, P("dp", None, "sp")))


def sharded_codec_step(
    mesh: Mesh, n: int, m: int, *, fused: bool | None = None, interpret: bool = False
):
    """Jitted full codec step over the mesh: encode -> verify -> repair.

    This is the flagship distributed 'step' (the training-step analog): one batch
    of stripes goes through the complete PUT+scrub+repair pipeline. Returns
    ``run(data, bad_idx=(0, n))`` mapping (B, n, k) uint8 data stripes to
    (stripe (B, n+m, k), ok (B,), repaired (B, n+m, k)).

    Sharding story: the step is a ``jax.shard_map`` over (dp, sp) — each device
    runs the FUSED Pallas kernel on its local (B/dp, n, k/sp) block (GF math is
    columnwise-independent, so no collectives except verify's AND over sp,
    a psum on ICI). ``fused=None`` auto-selects: Pallas on TPU backends, the
    XLA einsum lowering elsewhere; ``interpret=True`` forces the Pallas kernel
    in interpret mode (CPU-mesh tests of the real kernel).

    The repair pattern is RUNTIME data via ``repair_plan_padded`` — changing
    ``bad_idx`` between calls never recompiles. Batches that don't divide dp
    are zero-padded in and sliced out (zero stripes encode/verify trivially).
    """
    kernel = rs.get_kernel(n, m)
    # auto-select keys off the MESH's platform, not the default backend: under
    # axon the default is a proxied TPU while the dryrun mesh is CPU devices —
    # compiling the Mosaic kernel for a CPU mesh would crash the dryrun
    mesh_platform = next(iter(mesh.devices.flat)).platform
    use_fused = interpret or (
        fused if fused is not None else mesh_platform == "tpu"
    )

    def gf(mat_bits, x):
        if use_fused:
            from chubaofs_tpu.ops import pallas_gf

            # numpy matrices (the generator) pass through unconverted so the
            # plane-major permutation runs in numpy at trace time; group
            # stacking does NOT apply here — the per-device layout is still
            # per-stripe (PERF.md "remaining headroom" item 3)
            return pallas_gf.gf_matmul_bytes_fused(mat_bits, x, interpret=interpret)
        return rs.gf_matmul_bytes(jnp.asarray(mat_bits), x)

    sp_size = mesh.shape["sp"]
    trace_count = [0]

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("dp", None, "sp"), P(), P(), P()),
        out_specs=(P("dp", None, "sp"), P("dp"), P("dp", None, "sp")),
        # pallas_call carries no varying-mesh-axes metadata; the out_specs
        # above are the replication contract, checked by the tests numerically
        check_vma=False,
    )
    def step(data, repair_bits, present, missing):
        trace_count[0] += 1  # trace-time only: counts compilations, not calls
        parity = gf(kernel.parity_bits, data)  # (B/dp, m, k/sp) per device
        stripe = jnp.concatenate([data, parity], axis=-2)
        # verify: recompute parity from the stripe's data rows, AND over sp
        expect = gf(kernel.parity_bits, stripe[..., :n, :])
        ok_local = jnp.all(expect == stripe[..., n:, :], axis=(-2, -1))
        ok = jax.lax.psum(ok_local.astype(jnp.int32), "sp") == sp_size
        # repair: survivors -> missing rows via the runtime plan
        survivors = jnp.take(stripe, present, axis=-2)
        rows = gf(repair_bits, survivors)
        repaired = stripe.at[..., missing, :].set(rows)
        return stripe, ok, repaired

    jitted = jax.jit(step)
    replicated = NamedSharding(mesh, P())

    @functools.lru_cache(maxsize=64)
    def plan_for(bad: tuple) -> tuple:
        # once per pattern: the O(n^3) host-side inversion AND the replicated
        # broadcast to every mesh device (repeat steps transfer nothing)
        plan = kernel.repair_plan_padded(list(bad))
        return tuple(jax.device_put(a, replicated) for a in plan)

    def run(data, bad_idx=(0, n)):
        args = plan_for(tuple(sorted(set(int(i) for i in bad_idx))))
        if not isinstance(data, jax.Array):
            data = np.asarray(data)
        b = data.shape[0]
        pad = (-b) % mesh.shape["dp"]
        if pad:
            # pad in the input's own space: device arrays stay on device
            xp = jnp if isinstance(data, jax.Array) else np
            data = xp.concatenate(
                [data, xp.zeros((pad, *data.shape[1:]), xp.uint8)], axis=0
            )
        data = shard_stripes(mesh, data)
        with mesh:
            out = jitted(data, *args)
        if pad:
            out = jax.tree.map(lambda x: x[:b], out)
        return out

    run.trace_count = trace_count
    return run
