"""Device meshes and sharded codec dispatch (multi-chip scale-out)."""

from chubaofs_tpu.parallel.mesh import (
    codec_mesh,
    shard_stripes,
    sharded_codec_step,
)

__all__ = ["codec_mesh", "shard_stripes", "sharded_codec_step"]
