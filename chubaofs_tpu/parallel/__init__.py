"""Device meshes and sharded codec dispatch (multi-chip scale-out)."""

from chubaofs_tpu.parallel.mesh import (
    codec_mesh,
    group_view,
    shard_stripes,
    sharded_codec_step,
    sharded_gf_matmul,
    ungroup_stripe,
)

__all__ = [
    "codec_mesh",
    "group_view",
    "shard_stripes",
    "sharded_codec_step",
    "sharded_gf_matmul",
    "ungroup_stripe",
]
