"""Metadata plane — range-sharded, raft-replicated inode/dentry partitions
(reference metanode/ equivalent)."""

from chubaofs_tpu.meta.partition import (
    Dentry,
    ExtentKey,
    Inode,
    MetaPartitionSM,
    MetaError,
    ROOT_INO,
)
from chubaofs_tpu.meta.metanode import MetaNode

__all__ = [
    "Inode",
    "Dentry",
    "ExtentKey",
    "MetaPartitionSM",
    "MetaNode",
    "MetaError",
    "ROOT_INO",
]
