"""MetaNode — hosts meta partitions and dispatches metadata ops.

Reference counterpart: metanode/metanode.go + manager.go:103 (op dispatch) +
partition_free_list.go (async deletion of orphaned inodes' data). Partitions are
raft groups on the shared MultiRaft server (group id = partition id); mutations
are proposed to the partition's leader, reads served from leader state.
"""

from __future__ import annotations

from concurrent.futures import Future

from chubaofs_tpu.meta.partition import (MetaError, MetaPartitionSM,
                                         WrongPartition)
from chubaofs_tpu.raft.server import MultiRaft, NotLeaderError
from chubaofs_tpu.utils.locks import SanitizedLock


class OpError(Exception):
    def __init__(self, code: str, msg: str):
        super().__init__(f"{code}: {msg}")
        self.code = code


# every pid ever hosted in this process, feeding the bounded-label guard for
# cfs_metanode_partition_ops{pid}: the VALUE set is declared (closed over the
# partitions the master actually created — bounded by cluster state, unlike
# an arbitrary wire string), so obslint rule 1's invariant holds at runtime.
# Process-wide because declare_label_values is keyed by label name and an
# in-process cluster hosts several MetaNodes.
_KNOWN_PIDS: set[str] = set()
_known_pids_lock = SanitizedLock(name="metanode.pids")

# ops that are the CURE or the plumbing, not client load: counting them would
# make the meta rebalancer/splitter chase its own moves (DataNode's
# REPAIR_CLASS rationale applied to the metadata plane)
_MAINTENANCE_OPS = frozenset({
    "freeze_range", "unfreeze_range", "import_entries", "complete_split",
    "set_range_end",
    "drain_freelist", "purge_ack", "drain_del_extents", "del_extents_ack",
    "tx_sweep", "set_quota_def", "set_quota_flag", "delete_quota_def",
})


def _declare_pid(pid: int) -> None:
    from chubaofs_tpu.utils.exporter import declare_label_values

    with _known_pids_lock:
        _KNOWN_PIDS.add(str(pid))
        declare_label_values("pid", _KNOWN_PIDS)


class MetaNode:
    def __init__(self, node_id: int, raft: MultiRaft):
        self.node_id = node_id
        self.raft = raft
        self.partitions: dict[int, MetaPartitionSM] = {}
        self._lock = SanitizedLock(name="metanode.partitions")
        # per-partition op tally since the last take_loads() — the heartbeat
        # payload the master's split/rebalance accounting reads (the
        # DataNode.take_loads shape on the metadata plane). A plain dict
        # PLUS a declared-pid metric: partition ids here are bounded by the
        # master's own creations, so the label guard admits them.
        self._loads_lock = SanitizedLock(name="metanode.loads")
        self._op_loads: dict[int, int] = {}
        from chubaofs_tpu.utils.exporter import registry

        self._reg = registry("metanode")  # bound once: _note_load is per-op
        self._partitions_g = self._reg.gauge("partitions")
        # pid -> bound counter series, populated at create_partition so the
        # hot path pays one dict lookup, not a registry+labels resolution
        self._load_counters: dict[int, object] = {}
        # injected by the deployment: called with (inode) to purge file data;
        # must RAISE on failure so the orphan stays queued and is retried
        self.data_purge_hook = None
        # called with ({"extents": [...], "obj_extents": [...]}) for spans
        # dropped by truncate; same raise-to-retry contract
        self.extent_purge_hook = None

    # -- partition lifecycle (master drives this) ----------------------------

    def create_partition(self, partition_id: int, start: int, end: int, peers: list[int]) -> None:
        with self._lock:
            sm = MetaPartitionSM(partition_id, start, end)
            self.partitions[partition_id] = sm
            self.raft.create_group(partition_id, peers, sm)
        _declare_pid(partition_id)
        self._load_counters[partition_id] = self._reg.counter(
            "partition_ops", {"pid": str(partition_id)})
        self._partitions_gauge()

    def _partitions_gauge(self) -> None:
        self._partitions_g.set(len(self.partitions))

    def is_leader(self, partition_id: int) -> bool:
        return self.raft.is_leader(partition_id)

    def remove_partition(self, partition_id: int) -> None:
        """Drop a retired replica (decommission tail step)."""
        with self._lock:
            self.raft.remove_group(partition_id)
            self.partitions.pop(partition_id, None)
        self._load_counters.pop(partition_id, None)
        with self._loads_lock:
            # the accrued window leaves with the partition: reporting it
            # after a migrate-off keeps this node "hot" for load it no
            # longer serves, and a back-to-back rebalance sweep would shed
            # a second, correctly-placed partition on that stale signal
            self._op_loads.pop(partition_id, None)
        self._partitions_gauge()

    # -- load accounting (the split/rebalance heartbeat feed) ------------------

    def _note_load(self, partition_id: int, op: str | None = None) -> None:
        if op is not None and op in _MAINTENANCE_OPS:
            return
        with self._loads_lock:
            self._op_loads[partition_id] = \
                self._op_loads.get(partition_id, 0) + 1
        c = self._load_counters.get(partition_id)
        if c is not None:
            c.add()

    def _unnote_load(self, partition_id: int) -> None:
        """Take back one _note_load from the heartbeat window: a read that
        bounced off the route guard (EWRONGPART) was not served load, and
        the freeze->swap retry storm must not re-trip the split threshold.
        The per-pid metric counter is NOT rolled back (counters only go up;
        it measures request pressure, while the window drives splits)."""
        with self._loads_lock:
            n = self._op_loads.get(partition_id, 0)
            if n > 1:
                self._op_loads[partition_id] = n - 1
            else:
                self._op_loads.pop(partition_id, None)

    def take_loads(self) -> dict[int, int]:
        """Per-partition ops served since the last call, then reset — each
        heartbeat reports one window's delta (DataNode.take_loads contract),
        so the master's NodeInfo.loads stays a recent-load snapshot."""
        with self._loads_lock:
            out, self._op_loads = self._op_loads, {}
        return out

    def refund_loads(self, loads: dict[int, int]) -> None:
        """Fold a taken-but-unreported window back in (heartbeat send
        failed) so a master hiccup never erases observed load."""
        with self._loads_lock:
            for pid, c in loads.items():
                self._op_loads[pid] = self._op_loads.get(pid, 0) + c

    def split_reports(self) -> dict[int, dict]:
        """pid -> replicated split_info for partitions mid-split — the
        heartbeat payload the master's resume sweep reads (any replica may
        report; the master dedupes against the volume view)."""
        out = {}
        for pid, sm in list(self.partitions.items()):
            info = sm.split_info  # single read: the raft apply thread may
            if info is not None:  # null it (complete/unfreeze) mid-sweep
                out[pid] = dict(info)
        return out

    def propose_raft_config(self, partition_id: int, action: str,
                            node_id: int, timeout: float = 10.0):
        """Single-server membership change; must run on the group leader."""
        if partition_id not in self.partitions:
            raise OpError("ENOPARTITION",
                          f"partition {partition_id} not on node {self.node_id}")
        return self.raft.propose_config(partition_id, action, node_id).result(timeout)

    # -- write ops: through raft ---------------------------------------------

    @staticmethod
    def _chain_result(fut: Future, unnote=None) -> Future:
        """Map a raft apply-result future onto the op-result/OpError shape.
        `unnote` refunds the submitter's load tally on an EWRONGPART
        outcome — a route-guard bounce is not served load (see submit) —
        and runs BEFORE the chained future resolves, so a waiter that
        checks take_loads right after result() sees the refund. Only the
        rare bounce pays it: the common path must add NO work on the raft
        apply thread (the commit pipeline's bottleneck)."""
        out: Future = Future()

        def _done(f: Future):
            if f.exception():
                out.set_exception(f.exception())
                return
            res = f.result()
            if res[0] == "err":
                if unnote is not None and res[1] == "EWRONGPART":
                    unnote()
                out.set_exception(OpError(res[1], res[2]))
            else:
                out.set_result(res[1])

        fut.add_done_callback(_done)
        return out

    def submit(self, partition_id: int, op: str, **args) -> Future:
        """Propose one fsm op; future resolves to the op result or raises.
        Rides raft group commit: concurrent submits against one partition
        coalesce into shared WAL-flush + replication rounds."""
        # propose FIRST: it raises NotLeaderError synchronously on a
        # follower, and a misdirected client probe (leader-hunt herd) must
        # not count as served load — a phantom tally here can cross
        # CFS_META_SPLIT_OPS and split a partition that served no traffic.
        # A route-guard bounce (stale client view mid-split) is refunded on
        # the commit outcome: the freeze->swap retry storm must not re-trip
        # the load threshold on the partition the split just relieved
        fut = self.raft.propose(partition_id, (op, dict(args)))
        self._note_load(partition_id, op)
        return self._chain_result(
            fut, unnote=lambda: self._unnote_load(partition_id))

    def submit_batch(self, partition_id: int, ops: list[tuple[str, dict]]) -> list[Future]:
        """Propose many fsm ops in one drained raft batch (one WAL flush, one
        AppendEntries fan-out). FIFO apply order; each op fails or resolves
        independently — errors are values through consensus, so one EEXIST in
        a batch never poisons its neighbors."""
        futs = self.raft.propose_batch(
            partition_id, [(op, dict(args)) for op, args in ops])
        return [self._chain_result(f) for f in futs]

    def submit_sync(self, partition_id: int, op: str, timeout: float = 5.0, **args):
        import time

        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        t0 = time.perf_counter()
        fut = self.submit(partition_id, op, **args)
        t_wait = time.perf_counter()
        result = fut.result(timeout)
        if span is not None:
            # appended HERE, by the waiter, after the commit resolved — a
            # raft-layer done-callback would race this thread's reply
            # construction/span.finish and lose the entry
            span.append_track_log("raft", start=t_wait)
            span.add_stage("raft", start=t_wait)  # group-commit wait
            # in-process callers get their "metanode" hop entry here; under
            # a MetaService handler the SERVICE span already appends one
            # covering the whole dispatch — one entry per hop either way
            if not span.operation.startswith("metanode."):
                span.append_track_log("metanode", start=t0)
        return result

    # -- read ops: leader-local ------------------------------------------------

    def _leader_sm(self, partition_id: int,
                   count: bool = True) -> MetaPartitionSM:
        sm = self.partitions.get(partition_id)
        if sm is None:
            # distinct from a namespace ENOENT: the SDK treats this as
            # try-the-next-replica, not file-not-found
            raise OpError("ENOPARTITION",
                          f"partition {partition_id} not on node {self.node_id}")
        if not self.raft.is_leader(partition_id):
            raise NotLeaderError(self.raft.leader_of(partition_id))
        if count:  # count=False: maintenance reads (export/dump/quota rolls)
            self._note_load(partition_id)
        return sm

    def get_inode(self, partition_id: int, ino: int):
        try:
            return self._leader_sm(partition_id).get_inode(ino)
        except WrongPartition as e:
            self._unnote_load(partition_id)
            raise OpError(e.code, str(e)) from None
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def lookup(self, partition_id: int, parent: int, name: str):
        try:
            return self._leader_sm(partition_id).lookup(parent, name)
        except WrongPartition as e:
            self._unnote_load(partition_id)
            raise OpError(e.code, str(e)) from None
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def read_dir(self, partition_id: int, parent: int):
        try:
            return self._leader_sm(partition_id).read_dir(parent)
        except WrongPartition as e:
            self._unnote_load(partition_id)
            raise OpError(e.code, str(e)) from None
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def multipart_get(self, partition_id: int, upload_id: str):
        try:
            return self._leader_sm(partition_id).multipart_get(upload_id)
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def multipart_list(self, partition_id: int):
        try:
            return self._leader_sm(partition_id).multipart_list()
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def quota_usage(self, partition_id: int):
        try:
            return self._leader_sm(partition_id, count=False).quota_usage()
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def tx_status(self, partition_id: int, tx_id: str) -> str:
        try:
            return self._leader_sm(partition_id, count=False).tx_status(tx_id)
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def split_point(self, partition_id: int) -> int:
        """Median live inode of a partition (the split_at candidate)."""
        try:
            return self._leader_sm(partition_id, count=False).split_point()
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def export_range(self, partition_id: int, after: int = 0,
                     limit: int = 0) -> dict:
        """One page of a FROZEN partition's moving sub-range (split copy)."""
        try:
            return self._leader_sm(partition_id, count=False).export_range(
                after=after, limit=limit)
        except MetaError as e:
            raise OpError(e.code, str(e)) from None

    def dump_namespace(self, partition_id: int):
        """Full inode+dentry dump of one partition (fsck's feed)."""
        try:
            sm = self._leader_sm(partition_id, count=False)
        except MetaError as e:
            raise OpError(e.code, str(e)) from None
        return {"inodes": list(sm.inodes.values()),
                "dentries": list(sm.dentries.values())}

    # injected by the deployment: (tm_pid, tx_id) -> "committed" |
    # "rolledback" | "prepared" | "unknown" — asks the TM partition's leader
    tx_resolver_hook = None

    def sweep_transactions(self) -> int:
        """Resolve expired prepared 2PC txns (tx GC, metanode/transaction.go
        timeouts). TM-anchored txns roll back in the sweep itself; participant
        txns roll FORWARD or BACK to match the TM's recorded decision."""
        import time

        swept = 0
        for pid in list(self.partitions):
            if not self.raft.is_leader(pid):
                continue
            if not self.partitions[pid].txns:
                continue
            try:
                unresolved = self.submit_sync(pid, "tx_sweep", now=time.time())
            except (NotLeaderError, OpError):
                continue
            swept += len(unresolved)
            for tx_id, tm_pid in unresolved:
                decision = "unknown"
                if self.tx_resolver_hook is not None:
                    try:
                        decision = self.tx_resolver_hook(tm_pid, tx_id)
                    except Exception:
                        continue  # TM unreachable: keep the txn for next sweep
                try:
                    if decision == "committed":
                        self.submit_sync(pid, "tx_commit", tx_id=tx_id)
                    elif decision in ("rolledback", "unknown"):
                        # unknown = the TM never saw the txn (coordinator died
                        # before preparing it there): nothing can commit it
                        self.submit_sync(pid, "tx_rollback", tx_id=tx_id)
                    # "prepared": TM undecided; its own sweep will decide first
                except (NotLeaderError, OpError):
                    continue
        return swept

    # -- freelist delete loop (partition_free_list.go:180,233 analog) ----------

    def drain_freelists(self) -> int:
        """Purge data of orphaned inodes + truncate-dropped extents on
        partitions this node leads. Two-phase: drain peeks, the purge runs,
        and only a successful purge acks the entry off the queue — so a
        datanode/blobstore hiccup leaves it queued for the next sweep
        (partition_free_list.go:180,233 retry discipline)."""
        purged = 0
        for pid in list(self.partitions):
            if not self.raft.is_leader(pid):
                continue
            try:
                # both peeks ride ONE drained raft batch (group commit):
                # half the consensus rounds per partition sweep
                drained_fut, extents_fut = self.submit_batch(
                    pid, [("drain_freelist", {}), ("drain_del_extents", {})])
                drained = drained_fut.result(5.0)
            except (NotLeaderError, OpError):
                continue
            done = []
            for inode in drained:
                if self.data_purge_hook:
                    try:
                        self.data_purge_hook(inode)
                    except Exception:
                        continue  # stays orphaned; retried next drain
                done.append(inode.ino)
            if done:
                try:
                    self.submit_sync(pid, "purge_ack", inos=done)
                except (NotLeaderError, OpError):
                    continue
                purged += len(done)

            try:
                entries = extents_fut.result(5.0)
            except (NotLeaderError, OpError):
                continue
            acked = []
            for seq, entry in entries:
                if self.extent_purge_hook:
                    try:
                        self.extent_purge_hook(entry)
                    except Exception:
                        continue
                acked.append(seq)
            if acked:
                try:
                    self.submit_sync(pid, "del_extents_ack", seqs=acked)
                except (NotLeaderError, OpError):
                    continue
                purged += len(acked)
        return purged
