"""Metanode wire service — metadata ops over the packet TCP protocol.

Reference counterpart: metanode/manager.go:103 (`HandleMetadataOperation`
dispatching OpMeta* packets from TCP conns) + sdk/meta/operation.go (the
client side of the same wire). Kept: request/response ride the shared binary
`Packet` (proto/packet.go), the partition id addresses the shard, a
not-leader reply carries the leader hint so clients re-aim
(sdk/meta retry/leader-switch), and op payloads are JSON. Changed: one
OP_META_OP opcode with the op name in the arg blob instead of ~40 distinct
opcodes — the partition state machine dispatches by name already.

`RemoteMetaNode` duck-types the in-process `MetaNode` surface the
`MetaWrapper` routes over (submit_sync / lookup / get_inode / read_dir /
multipart_*), so the SDK works unchanged against local objects or TCP.
"""

from __future__ import annotations

import json
import socket
import threading

import time

from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.meta.metanode import MetaNode, OpError
from chubaofs_tpu.meta.partition import MetaPartitionSM
from chubaofs_tpu.meta.wire import dec, enc
from chubaofs_tpu.proto.packet import (
    OP_META_OP,
    TRACE_ARG_KEY,
    Packet,
    RES_ERR,
    RES_NOT_LEADER,
    RES_OK,
    recv_packet,
    send_packet,
    trace_extract,
    trace_inject,
    trace_merge,
    trace_reply,
)
from chubaofs_tpu.raft.server import NotLeaderError
from chubaofs_tpu.rpc.evloop import EvloopServer, evloop_enabled
from chubaofs_tpu.utils.auditlog import record_slow_op
from chubaofs_tpu.utils.exporter import registry

# ops served from leader state without a raft round (metanode read path)
READ_OPS = {"lookup", "get_inode", "read_dir", "multipart_get",
            "multipart_list", "quota_usage", "tx_status", "dump_namespace",
            "split_point", "export_range"}

_ADMIN_OPS = {"admin_create_partition", "admin_remove_partition",
              "admin_raft_config", "admin_partitions",
              "admin_partition_leaders"}


def _op_label(op: str) -> str:
    """Metric label for an op name: the KNOWN op set verbatim, anything else
    collapsed to "other" — the op string arrives off the wire, and a label
    minted per arbitrary client string would grow the registry unboundedly
    (the invariant obslint enforces for literal keys)."""
    if op in READ_OPS or op in _ADMIN_OPS \
            or hasattr(MetaPartitionSM, "_op_" + op):
        return op
    return "other"


class MetaService:
    """TCP front of one MetaNode (manager.go dispatch analog)."""

    def __init__(self, metanode: MetaNode, host: str = "127.0.0.1", port: int = 0):
        self.metanode = metanode
        self._reg = registry("metanode")  # bound once: _handle is per-packet
        self.listener = socket.create_server((host, port))
        self.addr = f"{host}:{self.listener.getsockname()[1]}"
        self._stop = threading.Event()
        self._evloop: EvloopServer | None = None
        if evloop_enabled():
            # serving on the shared event-loop core: loop shards own the
            # sockets, _handle runs on the bounded worker pool (it blocks on
            # raft commits), per-connection order preserved
            self._evloop = EvloopServer(self.listener, self._handle,
                                        name="meta")
            self._evloop.start()
        else:
            self._thread = threading.Thread(target=self._accept, daemon=True)
            self._thread.start()

    def _accept(self):
        """CFS_EVLOOP=0 shim: the pre-evloop thread-per-connection path."""
        while not self._stop.is_set():
            try:
                conn, _ = self.listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(  # racelint: CFS_EVLOOP=0 rollback shim — evloop is the default serving path
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                pkt = recv_packet(conn)
                send_packet(conn, self._handle(pkt))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, pkt: Packet) -> Packet:
        """Dispatch wrapper: continues the packet's trace (span pushed so the
        partition/raft layers under the handler see it), counts per-op TP
        metrics into the metanode role registry (exporter.NewTPCnt at
        metanode/manager.go:109), sends the span's track log back in the
        reply arg, and audits over-threshold ops."""
        op = pkt.arg.get("op", "") if isinstance(pkt.arg, dict) else ""
        # reply carries the track log ONLY for requests that brought a trace
        # id (same guard as datanode dispatch): untraced callers on the
        # hottest metadata path pay zero extra reply bytes
        traced = isinstance(pkt.arg, dict) and TRACE_ARG_KEY in pkt.arg
        span = trace_extract(pkt, f"metanode.{op or 'packet'}")
        trace.push_span(span)
        t0 = time.perf_counter()
        try:
            with self._reg.tp("meta_op", {"op": _op_label(op)}):
                resp = self._handle_inner(pkt, op)
            span.append_track_log("metanode", start=t0)
            return trace_reply(resp, span) if traced else resp
        finally:
            span.finish()
            trace.pop_span()
            record_slow_op("metanode", _op_label(op) if op else "packet",
                           time.perf_counter() - t0, span=span)

    def _handle_inner(self, pkt: Packet, op: str) -> Packet:
        if pkt.opcode != OP_META_OP:
            return pkt.reply(RES_ERR, arg={"error": f"bad opcode {pkt.opcode:#x}"})
        args = dec(json.loads(pkt.data.decode())) if pkt.data else {}
        pid = pkt.partition_id
        try:
            if op == "admin_create_partition":
                # node-level admin task from the master (cluster_task.go
                # analog); raft_addrs lets this node's TcpNet dial peers
                raft_addrs = args.pop("raft_addrs", None) or {}
                if hasattr(self.metanode.raft.net, "set_peer"):
                    for nid, addr in raft_addrs.items():
                        self.metanode.raft.net.set_peer(int(nid), addr)
                if pid not in self.metanode.partitions:
                    self.metanode.create_partition(pid, **args)
                return pkt.reply(RES_OK, data=b"null")
            if op == "admin_remove_partition":
                self.metanode.remove_partition(pid)
                return pkt.reply(RES_OK, data=b"null")
            if op == "admin_raft_config":
                # the leader must be able to dial a freshly added member
                raft_addrs = args.get("raft_addrs") or {}
                if hasattr(self.metanode.raft.net, "set_peer"):
                    for nid, addr in raft_addrs.items():
                        self.metanode.raft.net.set_peer(int(nid), addr)
                out = self.metanode.propose_raft_config(
                    pid, args["action"], args["node_id"])
                return pkt.reply(RES_OK, data=json.dumps(enc(out)).encode())
            if op == "admin_partitions":
                out = sorted(self.metanode.partitions)
                return pkt.reply(RES_OK, data=json.dumps(out).encode())
            if op == "admin_partition_leaders":
                # pid -> whether THIS node currently leads its raft group
                # (the meta-scale bench's leader-spread evidence)
                out = {pid: self.metanode.is_leader(pid)
                       for pid in sorted(self.metanode.partitions)}
                return pkt.reply(RES_OK, data=json.dumps(out).encode())
            if op in READ_OPS:
                out = getattr(self.metanode, op)(pid, **args)
            else:
                out = self.metanode.submit_sync(pid, op, **args)
            return pkt.reply(RES_OK, data=json.dumps(enc(out)).encode())
        except NotLeaderError as e:
            return pkt.reply(RES_NOT_LEADER, arg={"leader": e.leader})
        except OpError as e:
            return pkt.reply(RES_ERR, arg={"code": e.code, "error": str(e)})
        except Exception as e:  # never kill the conn on a handler bug
            return pkt.reply(RES_ERR, arg={"code": "EIO",
                                           "error": f"{type(e).__name__}: {e}"})

    def close(self):
        self._stop.set()
        if self._evloop is not None:
            self._evloop.stop()
        try:
            self.listener.close()
        except OSError:
            pass


class RemoteMetaNode:
    """Client handle speaking MetaService's wire; MetaNode duck-type.

    One pooled connection per handle; MetaWrapper's leader-retry logic drives
    which node gets asked (sdk/meta/operation.go's sendToMetaPartition).
    """

    def __init__(self, addr: str, conn_pool=None, timeout: float = 10.0):
        self.addr = addr
        self.timeout = timeout
        self.pool = conn_pool
        self._local = threading.local()

    def _conn(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            host, port = self.addr.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
        return sock

    def _drop_conn(self):
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            self._local.sock = None

    def _call(self, pid: int, op: str, **args):
        pkt = trace_inject(Packet(opcode=OP_META_OP, partition_id=pid,
                                  arg={"op": op},
                                  data=json.dumps(enc(args)).encode()))
        # connect failures are ECONN (nothing was sent — always safe to retry
        # elsewhere); failures after send are EIO (the op may have applied, so
        # only idempotent ops retry — sdk/meta's same distinction)
        try:
            sock = self._conn()
        except (ConnectionError, OSError) as e:
            self._drop_conn()
            raise OpError("ECONN", f"metanode {self.addr}: {e}") from None
        try:
            send_packet(sock, pkt)
            resp = recv_packet(sock)
        except (ConnectionError, OSError) as e:
            self._drop_conn()
            raise OpError("EIO", f"metanode {self.addr}: {e}") from None
        trace_merge(resp)  # fold the metanode's track log into our span
        if resp.result == RES_NOT_LEADER:
            raise NotLeaderError(resp.arg.get("leader"))
        if resp.result != RES_OK:
            raise OpError(resp.arg.get("code", "EIO"), resp.arg.get("error", "error"))
        return dec(json.loads(resp.data.decode())) if resp.data else None

    # -- MetaNode surface ------------------------------------------------------

    def submit_sync(self, partition_id: int, op: str, timeout: float = 5.0, **args):
        return self._call(partition_id, op, **args)

    def lookup(self, partition_id: int, parent: int, name: str):
        return self._call(partition_id, "lookup", parent=parent, name=name)

    def get_inode(self, partition_id: int, ino: int):
        return self._call(partition_id, "get_inode", ino=ino)

    def read_dir(self, partition_id: int, parent: int):
        return self._call(partition_id, "read_dir", parent=parent)

    def multipart_get(self, partition_id: int, upload_id: str):
        return self._call(partition_id, "multipart_get", upload_id=upload_id)

    def multipart_list(self, partition_id: int):
        return self._call(partition_id, "multipart_list")

    def quota_usage(self, partition_id: int):
        out = self._call(partition_id, "quota_usage")
        return {int(k): v for k, v in out.items()}  # JSON stringifies int keys

    def tx_status(self, partition_id: int, tx_id: str) -> str:
        return self._call(partition_id, "tx_status", tx_id=tx_id)

    def dump_namespace(self, partition_id: int):
        return self._call(partition_id, "dump_namespace")

    def split_point(self, partition_id: int) -> int:
        return self._call(partition_id, "split_point")

    def export_range(self, partition_id: int, after: int = 0,
                     limit: int = 0) -> dict:
        return self._call(partition_id, "export_range", after=after,
                          limit=limit)

    def partition_leaders(self) -> dict[int, bool]:
        """pid -> is_leader on this node (admin; pid 0 addresses the node)."""
        out = self._call(0, "admin_partition_leaders")
        return {int(k): bool(v) for k, v in out.items()}

    def close(self):
        self._drop_conn()
