"""Tagged wire/state encoding for metanode values.

Results and snapshot records carry dataclasses (Inode/Dentry/ExtentKey) and
bytes; this tagged encoding round-trips them through JSON (the packet wire,
meta/service.py) and through raft.codec (snapshot sections) identically.
Reference counterpart: the request/response struct marshaling of
sdk/meta/operation.go + metanode inode/dentry binary marshal methods.
"""

from __future__ import annotations

import base64

from chubaofs_tpu.meta.partition import Dentry, ExtentKey, Inode


def enc(v):
    if isinstance(v, Inode):
        d = {k: enc(getattr(v, k)) for k in (
            "ino", "mode", "uid", "gid", "size", "nlink", "ctime", "mtime",
            "extents", "obj_extents", "xattrs")}
        return {"__inode__": d}
    if isinstance(v, Dentry):
        return {"__dentry__": {"parent": v.parent, "name": v.name,
                               "ino": v.ino, "mode": v.mode}}
    if isinstance(v, ExtentKey):
        return {"__ek__": {"file_offset": v.file_offset, "size": v.size,
                           "partition_id": v.partition_id,
                           "extent_id": v.extent_id,
                           "extent_offset": v.extent_offset}}
    if isinstance(v, (bytes, bytearray)):
        return {"__bytes__": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, tuple):
        return {"__tuple__": [enc(x) for x in v]}
    if isinstance(v, list):
        return [enc(x) for x in v]
    if isinstance(v, dict):
        return {k: enc(x) for k, x in v.items()}
    return v


def dec(v):
    if isinstance(v, dict):
        if "__inode__" in v:
            d = {k: dec(x) for k, x in v["__inode__"].items()}
            return Inode(**d)
        if "__dentry__" in v:
            return Dentry(**v["__dentry__"])
        if "__ek__" in v:
            return ExtentKey(**v["__ek__"])
        if "__bytes__" in v:
            return base64.b64decode(v["__bytes__"])
        if "__tuple__" in v:
            return tuple(dec(x) for x in v["__tuple__"])
        return {k: dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [dec(x) for x in v]
    return v
