"""MetaPartitionSM — one inode-range shard of a volume's namespace.

Reference counterpart: metanode/partition.go:69-244 (metaPartition with
start/end inode range), inode.go:57-75 (Inode with Extents + ObjExtents),
dentry.go:42-47, the fsm ops in partition_fsmop_inode.go and the snapshot logic
of partition_store.go. Differences by design: the store is plain dicts behind a
raft StateMachine (ops arrive ordered and single-threaded, so btree clones and
copy-on-write are unnecessary); snapshots are sectioned CRC-framed binary
streams (raft.snapcodec — the partition_store.go per-type-files-with-CRCs
analog) applied batch-by-batch on restore; the orphan freelist is a queue
drained by the metanode's delete loop (partition_free_list.go analog).

Every mutating verb is a pure (op, args) command applied through raft; reads go
through the leader's local state.
"""

from __future__ import annotations

import stat as stat_mod
import time
from dataclasses import dataclass, field

from chubaofs_tpu.raft import snapcodec
from chubaofs_tpu.raft.server import StateMachine

ROOT_INO = 1


class MetaError(Exception):
    code = "EIO"


class NoEntry(MetaError):
    code = "ENOENT"


class Exists(MetaError):
    code = "EEXIST"


class NotEmpty(MetaError):
    code = "ENOTEMPTY"


class NotDir(MetaError):
    code = "ENOTDIR"


class IsDir(MetaError):
    code = "EISDIR"


class CrossPartition(MetaError):
    """Combined op aborted: the child inode lives in another partition.
    A pure pre-check failure (nothing mutated) — the client falls back to
    the per-op flow."""

    code = "EXDEVPART"


class WrongPartition(MetaError):
    """The routed inode is outside this partition's live range — the
    client's volume view is stale (a mid-range split moved the sub-range)
    or the sub-range is frozen mid-split. Pure pre-check (nothing mutated):
    the client refreshes its view and re-routes instead of failing the op."""

    code = "EWRONGPART"


class TxConflict(MetaError):
    code = "ETXCONFLICT"


class QuotaExceeded(MetaError):
    code = "EDQUOT"


class OutOfRange(MetaError):
    code = "ERANGE"


class SplitRefused(MetaError):
    """A split-orchestration op refused by the SM's own state (freeze
    conflict, frozen range, shrink violation, unfrozen export): the
    ORCHESTRATOR must handle it — retrying the same op can never succeed,
    so the meta-op hooks must not classify it as a transport failure."""

    code = "ESPLIT"


@dataclass
class ExtentKey:
    """Where one contiguous span of file data lives (proto/extent_key.go:40-47).

    Hot volumes: (partition_id, extent_id, offset in extent). Cold volumes use
    ObjExtentKey-style blobstore locations instead (kept as opaque dicts)."""

    file_offset: int
    size: int
    partition_id: int = 0
    extent_id: int = 0
    extent_offset: int = 0


@dataclass
class Inode:
    ino: int
    mode: int  # stat-style type+perm bits
    uid: int = 0
    gid: int = 0
    size: int = 0
    nlink: int = 1
    ctime: float = field(default_factory=time.time)
    mtime: float = field(default_factory=time.time)
    extents: list[ExtentKey] = field(default_factory=list)
    obj_extents: list[dict] = field(default_factory=list)  # cold-tier locations
    xattrs: dict[str, bytes] = field(default_factory=dict)

    @property
    def is_dir(self) -> bool:
        return stat_mod.S_ISDIR(self.mode)


@dataclass
class Dentry:
    parent: int
    name: str
    ino: int
    mode: int


class MetaPartitionSM(StateMachine):
    """The replicated state of one meta partition (inode range [start, end))."""

    def __init__(self, partition_id: int, start: int, end: int):
        self.partition_id = partition_id
        self.start = start
        self.end = end
        self.cursor = start  # last allocated ino
        self.inodes: dict[int, Inode] = {}
        # dentries keyed (parent_ino, name); children index for readdir
        self.dentries: dict[tuple[int, str], Dentry] = {}
        self.children: dict[int, dict[str, Dentry]] = {}
        self.freelist: list[int] = []  # orphaned inos awaiting data cleanup
        # evicted inode payloads keyed by ino: the drain needs the extent maps
        # to purge data (partition_free_list.go keeps the inode until its
        # extents are deleted)
        self.orphans: dict[int, Inode] = {}
        # extents dropped by truncate, awaiting datanode/blobstore deletion
        # (the metanode EXTENT_DEL-file analog); entries are (seq, payload) and
        # are removed only by an explicit ack after the purge succeeds
        self.del_extents: list[tuple[int, dict]] = []
        self.del_seq = 0
        self.multipart: dict[str, dict] = {}  # S3 multipart sessions
        # client-op idempotence (metanode/uniq_checker.go): per client a
        # bounded window of uniq-id -> recorded result, replicated via raft so
        # every replica answers a retried op identically
        self.uniq_seen: dict[str, dict] = {}
        # two-phase transactions (metanode/transaction.go): prepared txns hold
        # intent locks until commit/rollback/expiry
        self.txns: dict[str, dict] = {}  # tx_id -> {ops, deadline}
        self.tx_locks: dict[tuple, str] = {}  # lock key -> tx_id
        # tx_id -> (decision, retain-until); decisions outlive the txn by
        # TX_DONE_RETAIN so late-resolving participants always find them
        self.tx_done: dict[str, tuple[str, float]] = {}
        # directory quotas (metanode quota + master_quota_manager):
        # qid -> {max_files, max_bytes, files, bytes, exceeded}
        self.quotas: dict[int, dict] = {}
        # mid-range load split (ISSUE 15): while a split is in flight the
        # sub-range [frozen_from, end) is FROZEN — reads and writes there
        # raise WrongPartition until the master swaps the volume view and
        # complete_split drops the moved entries. split_info carries the
        # replicated resume record {split_at, new_pid, new_peers} so a
        # crashed orchestrator (or a restarted replica) can finish the
        # split from the partition's own state.
        self.frozen_from: int | None = None
        self.split_info: dict | None = None
        self._apply_now = 0.0  # proposer-stamped wall clock of the last op
        if start == ROOT_INO:
            root = Inode(ino=ROOT_INO, mode=stat_mod.S_IFDIR | 0o755, nlink=2)
            self.inodes[ROOT_INO] = root
            self.cursor = ROOT_INO

    # -- raft StateMachine ---------------------------------------------------

    UNIQ_WINDOW = 128

    def apply(self, data, index: int):
        """One fsm op. Under raft group commit, entries arrive in drained
        BATCHES (one WAL flush + replication round for up to max_batch
        submits), but each entry still applies alone in log order: errors are
        values through consensus, so a failing op (EEXIST, EDQUOT, ...) never
        poisons the rest of its drained batch, and the proposer-stamped _now/
        _uniq semantics are untouched by who shared its commit round."""
        op, args = data
        uniq = args.get("_uniq")  # never mutate args: the tuple is shared
        if "_now" in args:
            # wall time rides the PROPOSAL: replicas and WAL replay must stamp
            # identical ctimes/mtimes, so apply never reads the local clock
            self._apply_now = args["_now"]
            args = {k: v for k, v in args.items() if k != "_now"}
        if uniq is not None:
            cid, uid = uniq
            hist = self.uniq_seen.get(cid)
            if hist is not None and uid in hist:
                return hist[uid]  # duplicate delivery: replay the answer
            args = {k: v for k, v in args.items() if k != "_uniq"}
        try:
            result = ("ok", getattr(self, "_op_" + op)(**args))
        except MetaError as e:
            # errors are VALUES through consensus: every replica must take the
            # same path, and the proposer gets the errno back
            result = ("err", e.code, str(e))
        if uniq is not None:
            hist = self.uniq_seen.setdefault(cid, {})
            hist[uid] = result
            if len(hist) > self.UNIQ_WINDOW:
                for k in sorted(hist)[: len(hist) - self.UNIQ_WINDOW]:
                    del hist[k]
            # recency order: re-inserting on every use makes eviction drop the
            # LEAST RECENTLY ACTIVE client, and dict order is identical on
            # every replica (same apply sequence), so it stays deterministic
            self.uniq_seen[cid] = self.uniq_seen.pop(cid)
            if len(self.uniq_seen) > 512:
                for k in list(self.uniq_seen)[:128]:
                    del self.uniq_seen[k]
        return result

    # Snapshots: sectioned CRC-framed stream (partition_store.go per-type
    # files analog). Inodes/dentries/orphans are REPEATED bounded-size
    # sections so a lagging follower applies a large namespace incrementally
    # instead of decoding one giant object.

    @staticmethod
    def _inode_wire(i: Inode) -> list:
        return [i.ino, i.mode, i.uid, i.gid, i.size, i.nlink, i.ctime, i.mtime,
                [[e.file_offset, e.size, e.partition_id, e.extent_id,
                  e.extent_offset] for e in i.extents],
                i.obj_extents, i.xattrs]

    @staticmethod
    def _inode_unwire(w: list) -> Inode:
        return Inode(ino=w[0], mode=w[1], uid=w[2], gid=w[3], size=w[4],
                     nlink=w[5], ctime=w[6], mtime=w[7],
                     extents=[ExtentKey(*e) for e in w[8]],
                     obj_extents=list(w[9]), xattrs=dict(w[10]))

    def snapshot(self) -> bytes:
        # wire enc/dec tags the dataclasses living inside op results
        # (uniq_seen replays) — import here: meta.wire imports this module
        from chubaofs_tpu.meta import wire

        w = snapcodec.SnapshotWriter()
        w.add("meta", {
            "partition_id": self.partition_id, "start": self.start,
            "end": self.end, "cursor": self.cursor, "del_seq": self.del_seq,
            "frozen_from": self.frozen_from, "split_info": self.split_info,
        })
        w.add_batched("inodes", (self._inode_wire(i) for i in self.inodes.values()))
        w.add_batched("dentries", ([d.parent, d.name, d.ino, d.mode]
                                   for d in self.dentries.values()))
        w.add_batched("orphans", (self._inode_wire(i) for i in self.orphans.values()))
        w.add("freelist", self.freelist)
        w.add("del_extents", self.del_extents)
        w.add("multipart", self.multipart)
        w.add("uniq_seen", wire.enc(self.uniq_seen))
        w.add("txns", self.txns)
        w.add("tx_locks", self.tx_locks)
        w.add("tx_done", self.tx_done)
        w.add("quotas", self.quotas)
        return w.getvalue()

    def restore(self, payload: bytes) -> None:
        from chubaofs_tpu.meta import wire

        self.inodes, self.dentries, self.children, self.orphans = {}, {}, {}, {}

        def load_meta(m):
            self.partition_id = m["partition_id"]
            self.start, self.end = m["start"], m["end"]
            self.cursor, self.del_seq = m["cursor"], m["del_seq"]
            # .get: snapshots from before mid-range splits existed
            self.frozen_from = m.get("frozen_from")
            self.split_info = m.get("split_info")

        def load_inodes(batch):
            for rec in batch:
                i = self._inode_unwire(rec)
                self.inodes[i.ino] = i

        def load_dentries(batch):
            for parent, name, ino, mode in batch:
                d = Dentry(parent, name, ino, mode)
                self.dentries[(parent, name)] = d
                self.children.setdefault(parent, {})[name] = d

        def load_orphans(batch):
            for rec in batch:
                i = self._inode_unwire(rec)
                self.orphans[i.ino] = i

        snapcodec.restore_sections(payload, {
            "meta": load_meta,
            "inodes": load_inodes,
            "dentries": load_dentries,
            "orphans": load_orphans,
            "freelist": lambda v: setattr(self, "freelist", list(v)),
            "del_extents": lambda v: setattr(
                self, "del_extents", [tuple(e) for e in v]),
            "multipart": lambda v: setattr(self, "multipart", dict(v)),
            "uniq_seen": lambda v: setattr(self, "uniq_seen", wire.dec(v)),
            "txns": lambda v: setattr(self, "txns", dict(v)),
            "tx_locks": lambda v: setattr(self, "tx_locks", dict(v)),
            "tx_done": lambda v: setattr(self, "tx_done", dict(v)),
            "quotas": lambda v: setattr(self, "quotas", dict(v)),
        })

    # -- routing ownership (mid-range split fencing) ---------------------------

    def owns_ino_live(self, ino: int) -> bool:
        """owns_ino minus the frozen sub-range: while a split is in flight
        the entries >= frozen_from are leaving this partition, and serving
        them here (reads included — the sibling starts serving writes the
        instant the view swaps, BEFORE complete_split shrinks `end`) would
        hand out stale state."""
        if self.frozen_from is not None and ino >= self.frozen_from:
            return False
        return self.start <= ino < self.end

    def _route_guard(self, ino: int) -> None:
        if not self.owns_ino_live(ino):
            live_end = self.frozen_from if self.frozen_from is not None \
                else self.end
            raise WrongPartition(
                f"ino {ino} not served by partition {self.partition_id} "
                f"[{self.start}, {live_end})")

    # -- fsm ops: inodes -------------------------------------------------------

    def _next_ino(self) -> int:
        bound = self.frozen_from if self.frozen_from is not None else self.end
        if self.cursor + 1 >= bound:
            if self.frozen_from is not None:
                # the free tail of the range is moving to the sibling (it
                # inherits the cursor): re-route, don't report exhaustion
                raise WrongPartition(
                    f"partition {self.partition_id} allocations moved by "
                    f"split at {self.frozen_from}")
            raise OutOfRange(f"partition {self.partition_id} inode range exhausted")
        self.cursor += 1
        return self.cursor

    QUOTA_XATTR = "__quota_ids__"

    def _op_create_inode(self, mode: int, uid: int = 0, gid: int = 0,
                         quota_ids: list[int] | None = None):
        ino = self._next_ino()
        inode = Inode(ino=ino, mode=mode, uid=uid, gid=gid,
                      ctime=self._apply_now, mtime=self._apply_now)
        if inode.is_dir:
            inode.nlink = 2
        if quota_ids:  # subtree quota ids stick to the inode for byte charges
            import json as _json

            inode.xattrs[self.QUOTA_XATTR] = _json.dumps(quota_ids).encode()
        self.inodes[ino] = inode
        return inode

    def _op_delete_dentry_unlink(self, parent: int, name: str,
                                 quota_ids: list[int] | None = None,
                                 want_dir: bool | None = None):
        """Combined remove: lookup + delete_dentry + unlink_inode in ONE
        raft commit when this partition owns BOTH the parent and the
        child's inode (the single-tail-MP common case) — the client also
        saves its pre-lookup round-trip. `want_dir` enforces the caller's
        rmdir/unlink type expectation inside the commit (no TOCTOU against
        a concurrent rename-over). Raises CrossPartition when the child
        inode lives elsewhere; the client falls back to the per-op flow."""
        self._route_guard(parent)
        d = self.dentries.get((parent, name))
        if d is None:
            raise NoEntry(f"{name!r} in {parent}")
        if want_dir is not None and stat_mod.S_ISDIR(d.mode) != want_dir:
            raise (NotDir if want_dir else IsDir)(f"{name!r}")
        if not self.owns_ino_live(d.ino):
            # owns_ino_live: a child in the FROZEN sub-range must not be
            # mutated here either — the client falls back to the per-op
            # flow, whose unlink re-routes once the view swaps
            raise CrossPartition(f"ino {d.ino} outside [{self.start},{self.end})")
        self._op_delete_dentry(parent, name, quota_ids=quota_ids)
        inode = self._op_unlink_inode(d.ino)
        return d.ino, inode.nlink

    def _op_create_inode_dentry(self, parent: int, name: str, mode: int,
                                uid: int = 0, gid: int = 0,
                                quota_ids: list[int] | None = None):
        """Combined create: inode + dentry in ONE raft commit when the
        parent lives in this partition (the common single-tail-MP case).
        Halves the per-create consensus round-trips vs the two-op flow
        (create_inode then create_dentry) while keeping its invariants:
        every check — name conflict, parent type, locks, file-count quota —
        runs BEFORE the inode allocates, so a failed create leaves nothing
        behind to undo and burns no inode-range slot."""
        key = (parent, name)
        self._route_guard(parent)
        self._check_lock(("d", parent, name), None)
        self._check_lock(("c", parent), None)
        if key in self.dentries:
            raise Exists(f"{name!r} exists in {parent}")
        pdir = self._get_inode(parent)
        if not pdir.is_dir:
            raise NotDir(f"parent {parent}")
        # quota charge is ALSO a pre-check (it raises EDQUOT before any
        # mutation): an EDQUOT-looping client never burns inode-range
        # slots on a full quota
        self._quota_charge_files(quota_ids, +1)
        try:
            inode = self._op_create_inode(mode, uid, gid, quota_ids)
        except MetaError:  # OutOfRange: refund the charge, nothing mutated
            self._quota_charge_files(quota_ids, -1)
            raise
        # _committing=True: locks checked and quota charged above
        self._op_create_dentry(parent, name, inode.ino, inode.mode,
                               quota_ids=quota_ids, _committing=True)
        return inode

    def _inode_quota_ids(self, inode: Inode) -> list[int]:
        raw = inode.xattrs.get(self.QUOTA_XATTR)
        if not raw:
            return []
        import json as _json

        return _json.loads(raw)

    def _op_unlink_inode(self, ino: int):
        self._route_guard(ino)
        inode = self._get_inode(ino)
        inode.nlink -= 1
        if inode.is_dir:
            inode.nlink = max(inode.nlink, 0)
        if inode.nlink <= 0 or (inode.is_dir and inode.nlink <= 1):
            pass  # survives until evict
        return inode

    def _op_evict_inode(self, ino: int):
        self._route_guard(ino)
        inode = self.inodes.get(ino)
        if inode is None:
            return None
        if inode.nlink <= 0 or (inode.is_dir and inode.nlink <= 1):
            del self.inodes[ino]
            if not inode.is_dir:
                if inode.size:  # the file's bytes leave the quota with it
                    self._quota_charge_bytes(
                        self._inode_quota_ids(inode), -inode.size)
                self.freelist.append(ino)
                self.orphans[ino] = inode
        return None

    def _op_update_inode(self, ino: int, size: int | None = None, mode: int | None = None,
                         uid: int | None = None, gid: int | None = None,
                         mtime: float | None = None):
        self._route_guard(ino)
        inode = self._get_inode(ino)
        if size is not None:
            inode.size = size
        if mode is not None:
            inode.mode = mode
        if uid is not None:
            inode.uid = uid
        if gid is not None:
            inode.gid = gid
        inode.mtime = mtime if mtime is not None else self._apply_now
        return inode

    def _op_append_extents(self, ino: int, extents: list[dict], size: int):
        """AppendExtentKey analog (sdk/meta/api.go:1137): extend the file map."""
        self._route_guard(ino)
        inode = self._get_inode(ino)
        grow = max(0, size - inode.size)
        if grow:
            self._quota_charge_bytes(self._inode_quota_ids(inode), grow)
        for e in extents:
            inode.extents.append(ExtentKey(**e))
        inode.size = max(inode.size, size)
        inode.mtime = self._apply_now
        return inode

    def _op_append_obj_extents(self, ino: int, locations: list[dict], size: int):
        """Cold tier: record blobstore locations (ObjExtents, inode.go:73-74)."""
        self._route_guard(ino)
        inode = self._get_inode(ino)
        grow = max(0, size - inode.size)
        if grow:
            self._quota_charge_bytes(self._inode_quota_ids(inode), grow)
        inode.obj_extents.extend(locations)
        inode.size = max(inode.size, size)
        inode.mtime = self._apply_now
        return inode

    def _op_truncate(self, ino: int, size: int):
        self._route_guard(ino)
        inode = self._get_inode(ino)
        shrink = max(0, inode.size - size)
        if shrink:  # credit the quota back for the cut-off span
            self._quota_charge_bytes(self._inode_quota_ids(inode), -shrink)
        dropped = [e for e in inode.extents if e.file_offset >= size]
        inode.extents = [e for e in inode.extents if e.file_offset < size]
        for e in inode.extents:
            if e.file_offset + e.size > size:
                e.size = size - e.file_offset
        # cold-tier map: obj extents are consecutive; keep those before the cut,
        # clip the one straddling it
        kept, dropped_obj, pos = [], [], 0
        for ext in inode.obj_extents:
            if pos >= size:
                dropped_obj.append(ext)
                pos += ext["size"]
                continue
            if pos + ext["size"] > size:
                ext = {**ext, "size": size - pos}
            kept.append(ext)
            pos += ext["size"]
        inode.obj_extents = kept
        if dropped or dropped_obj:
            self.del_seq += 1
            self.del_extents.append((self.del_seq, {
                "extents": [vars(e) for e in dropped],
                "obj_extents": dropped_obj,
            }))
        inode.size = size
        inode.mtime = self._apply_now
        return inode

    def _op_set_xattr(self, ino: int, key: str, value: bytes):
        self._route_guard(ino)
        self._get_inode(ino).xattrs[key] = value

    def _op_remove_xattr(self, ino: int, key: str):
        self._route_guard(ino)
        self._get_inode(ino).xattrs.pop(key, None)

    # -- fsm ops: dentries ------------------------------------------------------

    def _check_lock(self, key: tuple, tx_id: str | None = None):
        """A prepared transaction's intent lock blocks outside mutations."""
        holder = self.tx_locks.get(key)
        if holder is not None and holder != tx_id:
            raise TxConflict(f"{key} locked by txn {holder}")

    def _op_create_dentry(self, parent: int, name: str, ino: int, mode: int,
                          quota_ids: list[int] | None = None,
                          _tx: str | None = None, _committing: bool = False):
        """_committing=True is the 2PC commit replay: every check already ran
        (and quota was RESERVED) at prepare, so nothing here may fail — a
        failure after the TM decision would leave the txn half-applied."""
        key = (parent, name)
        if not _committing:
            # 2PC commit replays skip the guard: prepare already ran it, and
            # freeze_range refuses while prepared txns exist — a commit can
            # never land in a frozen sub-range, and commits cannot fail
            self._route_guard(parent)
            self._check_lock(("d", parent, name), _tx)
            self._check_lock(("c", parent), _tx)  # dir-delete freezes the child set
        if key in self.dentries:
            raise Exists(f"{name!r} exists in {parent}")
        pdir = self._get_inode(parent)
        if not pdir.is_dir:
            raise NotDir(f"parent {parent}")
        if not _committing:  # committed txns charged at prepare
            self._quota_charge_files(quota_ids, +1)
        d = Dentry(parent, name, ino, mode)
        self.dentries[key] = d
        self.children.setdefault(parent, {})[name] = d
        if stat_mod.S_ISDIR(mode):
            pdir.nlink += 1
        pdir.mtime = self._apply_now
        return d

    def _op_delete_dentry(self, parent: int, name: str,
                          quota_ids: list[int] | None = None,
                          _tx: str | None = None, _committing: bool = False):
        key = (parent, name)
        if not _committing:
            self._route_guard(parent)
            self._check_lock(("d", parent, name), _tx)
        d = self.dentries.get(key)
        if d is None:
            raise NoEntry(f"{name!r} in {parent}")
        if stat_mod.S_ISDIR(d.mode):
            if self.children.get(d.ino):
                raise NotEmpty(f"{name!r}")
            if not _committing:
                # a PREPARED create inside this directory holds ("d", d.ino, *):
                # deleting the dir now would make that txn's commit fail after
                # the TM decision — the commit-cannot-fail invariant's reverse
                # direction, so the plain rmdir path must conflict too
                for lk, holder in self.tx_locks.items():
                    if lk[0] == "d" and lk[1] == d.ino and holder != _tx:
                        raise TxConflict(f"dir {d.ino} has pending txn {holder}")
        self._quota_charge_files(quota_ids, -1)
        del self.dentries[key]
        self.children.get(parent, {}).pop(name, None)
        pdir = self.inodes.get(parent)
        if pdir:
            if stat_mod.S_ISDIR(d.mode):
                pdir.nlink -= 1
            pdir.mtime = self._apply_now
        return d

    def _op_rename_local(self, src_parent: int, src_name: str, dst_parent: int,
                         dst_name: str, src_quota_ids: list[int] | None = None,
                         dst_quota_ids: list[int] | None = None):
        """Atomic rename when both dentries live in this partition. The move
        leaves the source quota and enters the destination's.

        POSIX rename(2) REPLACE semantics: an existing destination is
        atomically displaced in the same commit — its dentry drops, and when
        this partition also owns its inode, the link drops too. Returns
        (new_dentry, displaced_ino, displaced_nlink, displaced_is_dir) where
        displaced_ino == 0 means nothing was displaced and displaced_nlink
        == -1 means the displaced inode lives in another partition (the
        client must unlink it via the per-op flow)."""
        self._route_guard(src_parent)
        self._route_guard(dst_parent)
        self._check_lock(("d", src_parent, src_name))
        self._check_lock(("d", dst_parent, dst_name))
        d = self.dentries.get((src_parent, src_name))
        if d is None:
            raise NoEntry(f"{src_name!r} in {src_parent}")
        displaced_ino, displaced_nlink = 0, -1
        displaced_is_dir = False
        displaced = self.dentries.get((dst_parent, dst_name))
        if displaced is not None:
            if displaced.ino == d.ino:
                # both names are links to ONE inode: rename(2) succeeds and
                # does nothing (POSIX "oldpath and newpath are hard links")
                return (d, 0, -1, False)
            src_is_dir = stat_mod.S_ISDIR(d.mode)
            displaced_is_dir = stat_mod.S_ISDIR(displaced.mode)
            if src_is_dir and not displaced_is_dir:
                raise NotDir(f"{dst_name!r} in {dst_parent}")
            if not src_is_dir and displaced_is_dir:
                raise IsDir(f"{dst_name!r} in {dst_parent}")
            if displaced_is_dir and self.children.get(displaced.ino):
                raise NotEmpty(f"{dst_name!r}")
            # drop the displaced dentry + its link inside THIS commit: no
            # window where dst is missing, no window with two dsts
            self._op_delete_dentry(dst_parent, dst_name,
                                   quota_ids=dst_quota_ids)
            displaced_ino = displaced.ino
            # owns_ino_live: a displaced inode in the FROZEN sub-range is
            # the sibling's to unlink (client per-op flow re-routes there)
            if self.owns_ino_live(displaced.ino) and displaced.ino in self.inodes:
                displaced_nlink = self._op_unlink_inode(displaced.ino).nlink
        self._op_create_dentry(dst_parent, dst_name, d.ino, d.mode,
                               quota_ids=dst_quota_ids)
        self._op_delete_dentry(src_parent, src_name, quota_ids=src_quota_ids)
        return (self.dentries[(dst_parent, dst_name)], displaced_ino,
                displaced_nlink, displaced_is_dir)

    def _op_link(self, parent: int, name: str, ino: int):
        self._route_guard(parent)
        if self.owns_ino(ino):
            # the nlink bump mutates the inode: fence it during a split
            # (a cross-partition link's nlink is the caller's contract)
            self._route_guard(ino)
        inode = self._get_inode(ino)
        if inode.is_dir:
            raise MetaError("hardlink to directory")
        d = self._op_create_dentry(parent, name, ino, inode.mode)
        inode.nlink += 1
        return d

    # -- fsm ops: freelist / multipart -----------------------------------------

    def _op_drain_freelist(self, max_items: int = 64):
        """Peek orphaned inodes for purging. The orphan stays until the purge
        acks (_op_purge_ack) — a failed purge is retried next drain."""
        return [self.orphans[i] for i in self.freelist[:max_items]
                if i in self.orphans]

    def _op_purge_ack(self, inos: list[int]):
        done = set(inos)
        self.freelist = [i for i in self.freelist if i not in done]
        for i in done:
            self.orphans.pop(i, None)
        return len(done)

    def _op_drain_del_extents(self, max_items: int = 64):
        return self.del_extents[:max_items]

    def _op_del_extents_ack(self, seqs: list[int]):
        done = set(seqs)
        self.del_extents = [(s, e) for s, e in self.del_extents if s not in done]
        return len(done)

    # -- fsm ops: transactions (metanode/transaction.go 2PC) --------------------
    #
    # prepare validates every sub-op and takes intent locks; commit replays the
    # sub-ops with the locks held (so they cannot fail); rollback/expiry drops
    # the intents. Deadlines ride the PROPOSAL (deterministic across replicas).
    #
    # Coordinator recovery (the reference's TM/RM split): every txn names a
    # TRANSACTION-MANAGER partition (tm_pid). The coordinator commits the TM
    # first — the TM's tx_done entry IS the durable decision. A participant
    # whose prepared txn expires does not abort unilaterally: the sweep hands
    # it to the metanode, which asks the TM partition and rolls the txn
    # forward (commit) or back to match. Only the TM's own expiry decides
    # "rolledback" (the coordinator died before any commit).

    TX_OPS = {"create_dentry", "delete_dentry"}

    @staticmethod
    def _tx_lock_keys(op: str, args: dict) -> list[tuple]:
        keys = [("d", args["parent"], args["name"])]
        if op == "delete_dentry" and args.get("_lock_children"):
            # deleting a DIRECTORY: freeze its child set too, or a create
            # inside it between prepare and commit breaks the "commit cannot
            # fail" invariant (the validation checked it was empty)
            keys.append(("c", args["_lock_children"]))
        return keys

    # a decision outlives its txn's deadline by this much, so a participant
    # resolving within TX_TTL + sweep slack ALWAYS finds it (the round-1
    # advisor showed count-based pruning could forget a commit inside that
    # window and roll a committed rename half back)
    TX_DONE_RETAIN = 120.0
    TX_DONE_HARD_CAP = 1 << 16  # memory backstop, far above any live window

    def _op_tx_prepare(self, tx_id: str, ops: list, deadline: float,
                       tm_pid: int = 0):
        if tx_id in self.tx_done:
            raise TxConflict(f"txn {tx_id} already {self.tx_done[tx_id][0]}")
        if tx_id in self.txns:
            return None  # idempotent re-prepare
        prepared_ops = []
        for op, args in ops:
            if op not in self.TX_OPS:
                raise MetaError(f"op {op!r} not transactable")
            args = dict(args)
            # a prepare landing in the frozen sub-range must conflict NOW:
            # freeze_range refuses while txns exist, so without this guard a
            # post-freeze prepare could commit into entries mid-copy
            self._route_guard(args["parent"])
            # dry-run validation so commit CANNOT fail later: every check the
            # commit replay would make must run (and conflict) here
            if op == "create_dentry":
                if (args["parent"], args["name"]) in self.dentries:
                    raise Exists(f"{args['name']!r} exists in {args['parent']}")
                pdir = self._get_inode(args["parent"])
                if not pdir.is_dir:
                    raise NotDir(f"parent {args['parent']}")
                # a prepared dir-delete of the parent must conflict NOW, not
                # at commit time
                self._check_lock(("c", args["parent"]))
            elif op == "delete_dentry":
                d = self.dentries.get((args["parent"], args["name"]))
                if d is None:
                    raise NoEntry(f"{args['name']!r} in {args['parent']}")
                if stat_mod.S_ISDIR(d.mode):
                    if self.children.get(d.ino):
                        raise NotEmpty(args["name"])
                    # a prepared create INSIDE this directory would repopulate
                    # it between our emptiness check and commit
                    for key, holder in self.tx_locks.items():
                        if key[0] == "d" and key[1] == d.ino and holder != tx_id:
                            raise TxConflict(
                                f"dir {d.ino} has pending txn {holder}")
                    args["_lock_children"] = d.ino
            for key in self._tx_lock_keys(op, args):
                self._check_lock(key)
            prepared_ops.append((op, args))
        # RESERVE quota at prepare (released on rollback): the commit replay
        # must never hit EDQUOT because the quota filled in between. A
        # mid-loop failure must undo the charges already made — prepare
        # failed, so no txn exists to roll them back later.
        charged = []
        try:
            for op, args in prepared_ops:
                if op == "create_dentry":
                    self._quota_charge_files(args.get("quota_ids"), +1)
                    charged.append(args.get("quota_ids"))
        except QuotaExceeded:
            for qids in charged:
                self._quota_charge_files(qids, -1)
            raise
        for op, args in prepared_ops:
            for key in self._tx_lock_keys(op, args):
                self.tx_locks[key] = tx_id
        self.txns[tx_id] = {"ops": prepared_ops, "deadline": deadline,
                            "tm_pid": tm_pid or self.partition_id}
        return None

    def _release_tx(self, tx_id: str, undo_reservations: bool):
        txn = self.txns.pop(tx_id, None)
        if txn is not None and undo_reservations:
            for op, args in txn["ops"]:
                if op == "create_dentry":
                    self._quota_charge_files(args.get("quota_ids"), -1)
        self.tx_locks = {k: t for k, t in self.tx_locks.items() if t != tx_id}
        if len(self.tx_done) > self.TX_DONE_HARD_CAP:
            for k in list(self.tx_done)[: self.TX_DONE_HARD_CAP // 2]:
                del self.tx_done[k]

    def _done_stamp(self, txn: dict) -> float:
        """Decision retention deadline, derived from the txn's own deadline so
        every replica computes the identical value (no wall clock in apply)."""
        return txn["deadline"] + self.TX_DONE_RETAIN

    def _op_tx_commit(self, tx_id: str):
        if tx_id in self.tx_done and self.tx_done[tx_id][0] == "committed":
            return None  # idempotent re-commit
        txn = self.txns.get(tx_id)
        if txn is None:
            raise TxConflict(
                f"txn {tx_id} not prepared "
                f"({self.tx_done.get(tx_id, ('unknown',))[0]})")
        for op, args in txn["ops"]:
            run_args = {k: v for k, v in args.items() if k != "_lock_children"}
            getattr(self, "_op_" + op)(**run_args, _tx=tx_id, _committing=True)
        self.tx_done[tx_id] = ("committed", self._done_stamp(txn))
        self._release_tx(tx_id, undo_reservations=False)
        return None

    def _op_tx_rollback(self, tx_id: str):
        txn = self.txns.get(tx_id)
        if txn is not None:
            self.tx_done[tx_id] = ("rolledback", self._done_stamp(txn))
            self._release_tx(tx_id, undo_reservations=True)
        return None

    def _op_tx_sweep(self, now: float):
        """Resolve expired prepared txns. TM-anchored txns roll back here (no
        commit decision was ever recorded); participant txns are RETURNED for
        the metanode to resolve against their TM partition. Also prunes
        decisions whose retention window has lapsed — never earlier."""
        unresolved = []
        for t, txn in list(self.txns.items()):
            if txn["deadline"] >= now:
                continue
            if txn["tm_pid"] == self.partition_id:
                self.tx_done[t] = ("rolledback", self._done_stamp(txn))
                self._release_tx(t, undo_reservations=True)
            else:
                unresolved.append((t, txn["tm_pid"]))
        for t, (_, expire) in list(self.tx_done.items()):
            if now > expire:
                del self.tx_done[t]
        return unresolved

    def tx_status(self, tx_id: str) -> str:
        """TM-side decision lookup: committed | rolledback | prepared | unknown."""
        if tx_id in self.tx_done:
            return self.tx_done[tx_id][0]
        if tx_id in self.txns:
            return "prepared"
        return "unknown"

    # -- fsm ops: mid-range load split (ISSUE 15) -------------------------------
    #
    # Master-orchestrated: freeze_range fences the sub-range (every op routed
    # there raises WrongPartition), export_range pages a CONSISTENT snapshot
    # of the frozen entries (frozen = immutable by construction),
    # import_entries loads them into the sibling raft group, the master then
    # swaps the volume view in ONE master-raft commit (the atomicity point:
    # before it the sub-range is owned — frozen — by this partition, after it
    # by the sibling; never by zero or two), and complete_split drops the
    # moved entries + shrinks `end`. Every step is idempotent, and split_info
    # is REPLICATED state reported via heartbeats — a crashed orchestrator or
    # restarted replica resumes the split from the partition's own record.

    EXPORT_BATCH = 256

    def split_point(self) -> int:
        """Median live inode — the split_at candidate (leader read). 0 when
        the partition cannot split: fewer than two live inodes, or a median
        that would leave one side empty."""
        inos = sorted(self.inodes)
        if len(inos) < 2:
            return 0
        m = inos[len(inos) // 2]
        if m <= inos[0] or m <= self.start or m >= self.end:
            return 0
        return m

    def _op_freeze_range(self, split_at: int, new_pid: int,
                         new_peers: list[int] | None = None):
        if self.frozen_from is not None:
            if self.frozen_from == split_at and self.split_info \
                    and self.split_info.get("new_pid") == int(new_pid):
                return dict(self.split_info)  # idempotent re-freeze (resume)
            raise SplitRefused(
                f"partition {self.partition_id} already splitting at "
                f"{self.frozen_from}")
        if not (self.start < split_at < self.end):
            raise SplitRefused(
                f"split_at {split_at} outside ({self.start}, {self.end})")
        if self.txns:
            # a prepared 2PC txn may commit into the moving sub-range, and
            # commits can NEVER fail — refuse; the sweep retries after the
            # txns resolve (seconds, bounded by TX_TTL)
            raise TxConflict(
                f"{len(self.txns)} prepared txn(s) in flight; retry split")
        self.frozen_from = split_at
        self.split_info = {"split_at": int(split_at), "new_pid": int(new_pid),
                           "new_peers": [int(p) for p in (new_peers or [])]}
        return dict(self.split_info)

    def _op_unfreeze_range(self):
        """Abort path: lift the fence without moving anything."""
        self.frozen_from, self.split_info = None, None
        return None

    def _op_set_range_end(self, end: int):
        """Shrink this partition's range end (the SM half of a CURSOR split:
        the master's view commit caps the old tail at split_at, and without
        this the SM would keep end=INF and allocate inodes BEYOND its view
        range — unroutable files). Never below the allocation cursor (live
        inos <= cursor by construction), refused mid-split. A request at or
        above the current end returns the EXISTING cap unchanged: a sweep
        retrying a cursor split whose view commit failed recomputes
        split_at from a cursor that has since advanced, so the recomputed
        cap overshoots the committed one — the caller must complete the
        view swap at the cap this op RETURNS, or the tail could never
        split again once the cursor fills the headroom."""
        if self.frozen_from is not None:
            raise SplitRefused(
                f"partition {self.partition_id} mid-split; range is frozen")
        if end >= self.end:
            return self.end
        if end <= self.start or end <= self.cursor:
            raise SplitRefused(
                f"range end {end} would cut live inos "
                f"(start {self.start}, cursor {self.cursor})")
        self.end = end
        return end

    def export_range(self, after: int = 0, limit: int = 0) -> dict:
        """One page of the frozen sub-range (leader read): inode wires plus
        each inode's child dentries, ino-ordered. The first page (after=0)
        also carries the allocation cursor and quota definitions the sibling
        inherits. Every dentry's parent inode lives in this partition
        (create_dentry routes by parent), so paging by parent ino covers
        the dentry set exactly."""
        if self.frozen_from is None:
            raise SplitRefused(f"partition {self.partition_id} not frozen")
        limit = limit or self.EXPORT_BATCH
        inos = sorted(i for i in self.inodes
                      if i >= self.frozen_from and i > after)
        page = inos[:limit]
        dentries = []
        for ino in page:
            dentries += [[d.parent, d.name, d.ino, d.mode]
                         for d in self.children.get(ino, {}).values()]
        out = {"inodes": [self._inode_wire(self.inodes[i]) for i in page],
               "dentries": dentries,
               "next": page[-1] if page else after,
               "done": len(inos) <= limit}
        if not after:
            out["cursor"] = self.cursor
            out["quotas"] = {qid: {"max_files": q.get("max_files", 0),
                                   "max_bytes": q.get("max_bytes", 0)}
                             for qid, q in self.quotas.items()}
        return out

    def _op_import_entries(self, inodes: list, dentries: list,
                           cursor: int | None = None,
                           quotas: dict | None = None,
                           final: bool = True):
        """Load one exported page into the sibling (keyed upserts, so a
        resumed orchestrator may replay pages). Quota usage is RECOUNTED
        from the imported entries on the FINAL page only (the sibling does
        not serve its range until the view swap, so intermediate counts are
        unobservable, and a per-page recount would make the copy
        O(n^2/batch) on the apply thread); the recount is idempotent, so
        replays can't double-charge, and the source sheds the moved usage
        the same way in complete_split, so volume aggregates conserve."""
        for rec in inodes:
            i = self._inode_unwire(rec)
            if not self.owns_ino(i.ino):
                raise SplitRefused(
                    f"import ino {i.ino} outside [{self.start}, {self.end})")
            self.inodes[i.ino] = i
        for parent, name, ino, mode in dentries:
            d = Dentry(parent, name, ino, mode)
            self.dentries[(parent, name)] = d
            self.children.setdefault(parent, {})[name] = d
        if cursor is not None:
            # inherit the source's allocation cursor: live inos <= cursor,
            # and the free tail (cursor, end) now allocates HERE
            self.cursor = max(self.cursor, int(cursor))
        for qid, q in (quotas or {}).items():
            dst = self.quotas.setdefault(
                int(qid), {"files": 0, "bytes": 0, "exceeded": False})
            dst["max_files"] = q.get("max_files", 0)
            dst["max_bytes"] = q.get("max_bytes", 0)
        if final:
            self._recount_quotas()
        return len(inodes)

    def _op_complete_split(self):
        """Cleanup tail step, AFTER the master's view swap: drop the moved
        entries and shrink `end` to the split point. Idempotent — completing
        an unfrozen partition is a no-op (resume may retry). Orphans and
        del_extents keep draining here: their inodes already left the
        namespace and the purge is location-addressed."""
        if self.frozen_from is None:
            return 0
        cut = self.frozen_from
        dropped = [i for i in self.inodes if i >= cut]
        for i in dropped:
            del self.inodes[i]
            self.children.pop(i, None)
        for k in [k for k, d in self.dentries.items() if d.parent >= cut]:
            del self.dentries[k]
        self.end = cut
        self.frozen_from, self.split_info = None, None
        # shed the moved entries' quota usage: without this their later
        # deletion debits the SIBLING (which the import recounted), the
        # max(0,..) clamp eats the debit there, and this side's stale
        # charge never releases — headroom leaks every split+delete cycle
        self._recount_quotas()
        return len(dropped)

    def _recount_quotas(self) -> None:
        """Rebuild quota usage counters from live entries (split paths only:
        import pages and complete). Deterministic over replicated SM state
        and idempotent, so page replays by a resumed orchestrator are safe.
        The derivation matches how charges/debits are attributed at op time:
        files per dentry under the PARENT dir inode's __quota_ids__ xattr
        (dentries live on the parent's partition, and the client resolves
        quota_ids from that same xattr), bytes per non-dir inode's own
        xattr times its size (released at evict, so un-evicted orphans
        stay counted — matching the charge they still hold)."""
        if not self.quotas:
            return
        for q in self.quotas.values():
            q["files"] = 0
            q["bytes"] = 0
        for d in self.dentries.values():
            parent = self.inodes.get(d.parent)
            if parent is None:
                continue
            for qid in self._inode_quota_ids(parent):
                q = self.quotas.get(qid)
                if q is not None:
                    q["files"] += 1
        for inode in self.inodes.values():
            if inode.is_dir or not inode.size:
                continue
            for qid in self._inode_quota_ids(inode):
                q = self.quotas.get(qid)
                if q is not None:
                    q["bytes"] += inode.size

    # -- fsm ops: quotas (metanode quota + master_quota_manager) ----------------
    #
    # A quota id names a directory subtree. Definitions are fanned out to every
    # partition of the volume; usage is counted where the charged op applies
    # (files at the dentry's partition — exact, because one directory's
    # dentries live on one partition; bytes at the inode's partition). The
    # aggregator (MetaWrapper.quota_usage) sums partitions and pushes the
    # `exceeded` flag back down, the reference's master-report loop shape.

    def _op_set_quota_def(self, quota_id: int, max_files: int = 0,
                          max_bytes: int = 0):
        q = self.quotas.setdefault(
            quota_id, {"files": 0, "bytes": 0, "exceeded": False})
        q["max_files"] = max_files
        q["max_bytes"] = max_bytes
        return None

    def _op_delete_quota_def(self, quota_id: int):
        self.quotas.pop(quota_id, None)
        return None

    def _op_set_quota_flag(self, quota_id: int, exceeded: bool):
        q = self.quotas.get(quota_id)
        if q is not None:
            q["exceeded"] = exceeded
        return None

    def _quota_check_files(self, quota_ids):
        for qid in quota_ids or ():
            q = self.quotas.get(qid)
            if q is None:
                continue
            if q["exceeded"] or (q.get("max_files") and
                                 q["files"] >= q["max_files"]):
                raise QuotaExceeded(f"quota {qid}: file limit")

    def _quota_charge_files(self, quota_ids, delta: int):
        if delta > 0:
            self._quota_check_files(quota_ids)
        for qid in quota_ids or ():
            q = self.quotas.get(qid)
            if q is not None:
                q["files"] = max(0, q["files"] + delta)

    def _quota_charge_bytes(self, quota_ids, delta: int):
        for qid in quota_ids or ():  # validate every quota BEFORE charging any
            q = self.quotas.get(qid)
            if q is None:
                continue
            if delta > 0 and (q["exceeded"] or (
                    q.get("max_bytes")
                    and q["bytes"] + delta > q["max_bytes"])):
                raise QuotaExceeded(f"quota {qid}: byte limit")
        for qid in quota_ids or ():
            q = self.quotas.get(qid)
            if q is not None:
                q["bytes"] = max(0, q["bytes"] + delta)

    def quota_usage(self) -> dict[int, dict]:
        return {qid: dict(q) for qid, q in self.quotas.items()}

    def _op_multipart_create(self, key: str, upload_id: str):
        self.multipart[upload_id] = {"key": key, "parts": {}}
        return upload_id

    def _op_multipart_put_part(self, upload_id: str, part_num: int, location: dict):
        """Returns the replaced part's location (None for a fresh part) so the
        caller can reclaim the superseded data (S3 UploadPart retry semantics)."""
        mp = self.multipart.get(upload_id)
        if mp is None:
            raise NoEntry(f"upload {upload_id}")
        old = mp["parts"].get(part_num)
        mp["parts"][part_num] = location
        return old

    def _op_multipart_complete(self, upload_id: str):
        mp = self.multipart.pop(upload_id, None)
        if mp is None:
            raise NoEntry(f"upload {upload_id}")
        return mp

    def _op_multipart_abort(self, upload_id: str):
        """Same pop as complete; the caller deletes the part data instead of
        linking it (objectnode AbortMultipartUpload path)."""
        mp = self.multipart.pop(upload_id, None)
        if mp is None:
            raise NoEntry(f"upload {upload_id}")
        return mp

    # -- reads (leader-local, not through the log) ------------------------------

    def _get_inode(self, ino: int) -> Inode:
        inode = self.inodes.get(ino)
        if inode is None:
            raise NoEntry(f"inode {ino}")
        return inode

    def get_inode(self, ino: int) -> Inode:
        self._route_guard(ino)
        return self._get_inode(ino)

    def lookup(self, parent: int, name: str) -> Dentry:
        self._route_guard(parent)
        d = self.dentries.get((parent, name))
        if d is None:
            raise NoEntry(f"{name!r} in {parent}")
        return d

    def read_dir(self, parent: int) -> list[Dentry]:
        self._route_guard(parent)
        self._get_inode(parent)
        return sorted(self.children.get(parent, {}).values(), key=lambda d: d.name)

    def owns_ino(self, ino: int) -> bool:
        return self.start <= ino < self.end

    def multipart_get(self, upload_id: str) -> dict:
        mp = self.multipart.get(upload_id)
        if mp is None:
            raise NoEntry(f"upload {upload_id}")
        return mp

    def multipart_list(self) -> dict[str, dict]:
        return dict(self.multipart)
