"""Kernel FUSE wire — a low-level /dev/fuse protocol server over the SDK.

Reference counterpart: client/fuse.go:470,670 — the reference mounts a volume
through a vendored bazil.org/fuse, whose fs.Serve loop reads fuse_kernel.h
request frames from /dev/fuse and dispatches them to the Super/Node layer.
Here the same wire is spoken directly: struct layouts from fuse_kernel.h
(protocol 7.x), a mount(2) of fstype "fuse" with the /dev/fuse fd, and a
dispatch loop driving MetaWrapper/FsClient inode verbs. With this, UNMODIFIED
external programs (ls, cp, a shell, an LTP-style battery) operate on a
chubaofs-tpu volume through the kernel VFS — the last user-facing capability
gap against the reference client.

Design notes vs the reference:
  * The protocol layer is inode(nodeid)-based, exactly like bazil's Node API —
    and our MetaWrapper is already inode-based (lookup/get_inode/read_dir/
    create_dentry/...), so nodeid == ino with no translation table
    (ROOT_INO == FUSE_ROOT_ID == 1).
  * Orphan-inode contract (client/fs file.go + Mount): UNLINK drops the
    dentry + link; the inode stays readable for open handles, and the LAST
    RELEASE evicts it (the kernel keeps unlinked-but-open inodes alive and
    only FORGETs them after release, so this maps 1:1). Mount implements the
    same contract for its in-process fd table; here the handle table mirrors
    the KERNEL's open-file state (fh from OPEN/CREATE, dropped at RELEASE),
    which Mount's path/fd surface cannot represent — the duplication is the
    two tables, the eviction rule itself is identical in both.
  * default_permissions: the kernel does uid/gid/mode permission checks from
    GETATTR results, so the server never needs an ACCESS handler (the
    reference relies on bazil's equivalent DefaultPermissions behavior).
  * Single dispatch thread: request frames are handled in arrival order.
    The reference serves concurrently via goroutines; here correctness and
    hermetic teardown win — the data plane below is already concurrent, and
    the POSIX battery is latency-insensitive.

Gated: callers should check `fuse_available()` (needs /dev/fuse + privilege);
tests skip cleanly where the device is absent (CI containers).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno as errno_mod
import os
import stat as stat_mod
import struct
import threading
import time

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.meta.metanode import OpError
from chubaofs_tpu.sdk.fs import FsClient, FsError
from chubaofs_tpu.utils.auditlog import record_slow_op

# -- fuse_kernel.h: opcodes ----------------------------------------------------

FUSE_LOOKUP = 1
FUSE_FORGET = 2
FUSE_GETATTR = 3
FUSE_SETATTR = 4
FUSE_MKNOD = 8
FUSE_MKDIR = 9
FUSE_UNLINK = 10
FUSE_RMDIR = 11
FUSE_RENAME = 12
FUSE_LINK = 13
FUSE_OPEN = 14
FUSE_READ = 15
FUSE_WRITE = 16
FUSE_STATFS = 17
FUSE_RELEASE = 18
FUSE_FSYNC = 20
FUSE_SETXATTR = 21
FUSE_GETXATTR = 22
FUSE_LISTXATTR = 23
FUSE_REMOVEXATTR = 24
FUSE_FLUSH = 25
FUSE_INIT = 26
FUSE_OPENDIR = 27
FUSE_READDIR = 28
FUSE_RELEASEDIR = 29
FUSE_FSYNCDIR = 30
FUSE_GETLK = 31
FUSE_SETLK = 32
FUSE_SETLKW = 33
FUSE_ACCESS = 34
FUSE_CREATE = 35
FUSE_INTERRUPT = 36
FUSE_BMAP = 37
FUSE_DESTROY = 38
FUSE_BATCH_FORGET = 42
FUSE_FALLOCATE = 43
FUSE_READDIRPLUS = 44
FUSE_RENAME2 = 45
FUSE_LSEEK = 46

# -- struct layouts (x86_64 / little-endian, protocol 7.23..7.31) --------------

IN_HEADER = struct.Struct("<IIQQIIII")  # len opcode unique nodeid uid gid pid pad
OUT_HEADER = struct.Struct("<IiQ")      # len error unique
# ino size blocks atime mtime ctime atimensec mtimensec ctimensec
# mode nlink uid gid rdev blksize padding
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")            # 88 bytes
ENTRY_OUT = struct.Struct("<QQQQII")                 # + ATTR = 128
ATTR_OUT = struct.Struct("<QII")                     # + ATTR = 104
OPEN_OUT = struct.Struct("<QII")                     # fh open_flags padding
WRITE_OUT = struct.Struct("<II")
INIT_OUT = struct.Struct("<IIIIHHIIHH8I")            # 64 bytes (7.23+)
GETATTR_IN = struct.Struct("<IIQ")
SETATTR_IN = struct.Struct("<IIQQQQQQIIIIIIII")      # 88 bytes
MKNOD_IN = struct.Struct("<IIII")
MKDIR_IN = struct.Struct("<II")
RENAME_IN = struct.Struct("<Q")
RENAME2_IN = struct.Struct("<QII")
LINK_IN = struct.Struct("<Q")
OPEN_IN = struct.Struct("<II")
CREATE_IN = struct.Struct("<IIII")
READ_IN = struct.Struct("<QQIIQII")
WRITE_IN = struct.Struct("<QQIIQII")
RELEASE_IN = struct.Struct("<QIIQ")
FSYNC_IN = struct.Struct("<QII")
GETXATTR_IN = struct.Struct("<II")
SETXATTR_IN = struct.Struct("<II")
GETXATTR_OUT = struct.Struct("<II")
KSTATFS = struct.Struct("<QQQQQIIII6I")              # 80 bytes
DIRENT = struct.Struct("<QQII")                      # + name, 8-aligned
FORGET_IN = struct.Struct("<Q")
BATCH_FORGET_IN = struct.Struct("<II")

FUSE_ROOT_ID = 1
FUSE_BIG_WRITES = 1 << 5
FATTR_MODE, FATTR_UID, FATTR_GID, FATTR_SIZE = 1 << 0, 1 << 1, 1 << 2, 1 << 3
FATTR_ATIME, FATTR_MTIME = 1 << 4, 1 << 5
MAX_WRITE = 128 * 1024
ATTR_TTL_S = 1  # client/fs/icache.go attr validity window (Mount.ATTR_TTL)

MS_NOSUID, MS_NODEV = 2, 4
MNT_DETACH = 2

_libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6", use_errno=True)


def fuse_available() -> bool:
    """Mounting needs /dev/fuse AND mount(2) privilege: this server calls
    mount(2) directly (no fusermount setuid dance), which requires
    CAP_SYS_ADMIN — /dev/fuse alone is world-writable on most distros, so
    an rw-access check would pass for users whose mount would EPERM."""
    return (os.path.exists("/dev/fuse")
            and os.access("/dev/fuse", os.R_OK | os.W_OK)
            and os.geteuid() == 0)


def _errno_of(exc: Exception) -> int:
    code = getattr(exc, "code", "")
    n = getattr(errno_mod, str(code), 0)
    return n if n else errno_mod.EIO


class _Handle:
    __slots__ = ("ino", "flags")

    def __init__(self, ino: int, flags: int):
        self.ino = ino
        self.flags = flags


class FuseServer:
    """One kernel mount of one volume: /dev/fuse fd + dispatch loop."""

    def __init__(self, fs: FsClient, mountpoint: str, volume: str = "",
                 audit_dir: str | None = None):
        from chubaofs_tpu.utils.auditlog import AuditLog

        self.fs = fs
        self.meta = fs.meta
        self.mountpoint = os.path.abspath(mountpoint)
        self.volume = volume or "chubaofs"
        # kernel-mounted access joins the same audit trail as the Mount
        # path (util/auditlog contract): one line per namespace-mutating op
        self.audit = AuditLog(audit_dir) if audit_dir else None
        self.client_id = f"fuse:pid{os.getpid()}"
        self.devfd = -1
        self._next_fh = 1
        self._fhs: dict[int, _Handle] = {}
        # OPENDIR snapshots: fh -> [(name, ino, mode)]. READDIR offsets index
        # the snapshot, so a directory mutated between two READDIR batches
        # never skips or repeats entries within one open handle
        self._dirhs: dict[int, list[tuple[str, int, int]]] = {}
        self._open_count: dict[int, int] = {}
        self._orphans: set[int] = set()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._mounted = False

    # -- mount / serve / unmount ----------------------------------------------

    @staticmethod
    def _disable_vfork_subprocess() -> None:
        """An in-process FUSE mount makes CPython's vfork fast path a
        process-wide deadlock trap: vfork suspends the forking thread —
        WITH the GIL held — until the child execs, and the child's pre-exec
        work can touch THIS process's own mount (chdir to a cwd under it,
        close() of an inherited writable fd sending FLUSH). The kernel then
        waits for the mount's userspace daemon, which is a Python thread
        that needs the very GIL the suspended forker holds: child waits on
        the daemon, daemon waits on the GIL, forker waits on the child.
        Plain fork() has no such window — the parent resumes immediately
        and the daemon serves the child's requests normally — so any
        process that hosts a kernel mount drops the vfork optimization.
        (Observed live: subprocess.run(cwd=<mountpoint>) under the mount's
        own process wedged in kernel_clone with every other thread parked
        on the GIL futex.)"""
        import subprocess

        if hasattr(subprocess, "_USE_VFORK"):
            subprocess._USE_VFORK = False

    def mount(self) -> None:
        self._disable_vfork_subprocess()
        self.devfd = os.open("/dev/fuse", os.O_RDWR)
        try:
            opts = (f"fd={self.devfd},rootmode=40000,user_id={os.getuid()},"
                    f"group_id={os.getgid()},default_permissions,allow_other")
            rc = _libc.mount(self.volume.encode(), self.mountpoint.encode(),
                             b"fuse.chubaofs_tpu", MS_NOSUID | MS_NODEV,
                             opts.encode())
            if rc != 0:
                e = ctypes.get_errno()
                raise OSError(e, f"mount(2): {os.strerror(e)}")
            self._mounted = True
        except BaseException:
            os.close(self.devfd)
            self.devfd = -1
            raise

    def serve_background(self) -> None:
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name=f"fuse:{self.mountpoint}")
        self._thread.start()

    def unmount(self) -> None:
        if self._mounted:
            # lazy detach: the serve loop's read() returns ENODEV and exits
            _libc.umount2(self.mountpoint.encode(), MNT_DETACH)
            self._mounted = False
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self.devfd >= 0:
            os.close(self.devfd)
            self.devfd = -1

    def serve(self) -> None:
        bufsize = MAX_WRITE + 0x1000
        while True:
            try:
                req = os.read(self.devfd, bufsize)
            except OSError as e:
                if e.errno == errno_mod.EINTR:
                    continue
                # ENODEV = unmounted; EBADF = fd closed during teardown
                return
            if not req:
                return
            (length, opcode, unique, nodeid, uid, gid, pid,
             _pad) = IN_HEADER.unpack_from(req)
            body = req[IN_HEADER.size:length]
            if opcode in (FUSE_FORGET, FUSE_BATCH_FORGET):
                continue  # reply-less by protocol; we hold no per-ino state
            if opcode == FUSE_INTERRUPT:
                continue  # ops are synchronous; nothing in flight to cancel
            t0 = time.perf_counter()
            err = ""
            # root span per kernel request: SDK/metanode/raft hops below
            # attach their track entries, so one slow VFS call explains
            # itself hop by hop in the slow-op log
            op_label = self._AUDITED.get(opcode, f"op{opcode}")
            span = trace.Span(f"fuse.{op_label}")
            trace.push_span(span)
            try:
                handler = self._DISPATCH.get(opcode)
                if handler is None:
                    err = "ENOSYS"
                    self._reply_err(unique, errno_mod.ENOSYS)
                    continue
                # injected faults surface as errno to the UNMODIFIED program
                # above the VFS (error -> EIO, delay/hang -> a stalled call)
                chaos.failpoint("fuse.dispatch")
                payload = handler(self, nodeid, body, uid, gid)
                self._reply(unique, payload or b"")
            except (FsError, OpError) as e:
                err = str(getattr(e, "code", "EIO"))
                self._reply_err(unique, _errno_of(e))
            except OSError as e:
                err = errno_mod.errorcode.get(e.errno or 0, "EIO")
                self._reply_err(unique, e.errno or errno_mod.EIO)
            except Exception:
                err = "EIO"
                self._reply_err(unique, errno_mod.EIO)
            finally:
                span.append_track_log("fuse", start=t0)
                span.finish()
                trace.pop_span()
                elapsed = time.perf_counter() - t0
                record_slow_op("fuse", op_label, elapsed, span=span, err=err)
                if self.audit is not None and opcode in self._AUDITED:
                    self.audit.log_fs_op(
                        self.client_id, self.volume, self._AUDITED[opcode],
                        f"ino{nodeid}", err=err, latency_us=int(elapsed * 1e6))
            if opcode == FUSE_DESTROY:
                return

    def _reply(self, unique: int, payload: bytes) -> None:
        hdr = OUT_HEADER.pack(OUT_HEADER.size + len(payload), 0, unique)
        os.write(self.devfd, hdr + payload)

    def _reply_err(self, unique: int, err: int) -> None:
        try:
            os.write(self.devfd, OUT_HEADER.pack(OUT_HEADER.size, -err, unique))
        except OSError:
            pass  # unmount raced the reply

    # -- attr helpers ----------------------------------------------------------

    def _inode(self, ino: int):
        try:
            return self.meta.get_inode(ino)
        except OpError as e:
            raise FsError(e.code, f"ino {ino}") from None

    def _attr_bytes(self, inode) -> bytes:
        t = int(inode.mtime)
        tn = int((inode.mtime - t) * 1e9)
        blocks = (inode.size + 511) // 512
        return ATTR.pack(inode.ino, inode.size, blocks, t, t, int(inode.ctime),
                         tn, tn, 0, inode.mode, inode.nlink, inode.uid,
                         inode.gid, 0, 4096, 0)

    def _entry_out(self, inode) -> bytes:
        return ENTRY_OUT.pack(inode.ino, 0, ATTR_TTL_S, ATTR_TTL_S, 0, 0) + \
            self._attr_bytes(inode)

    def _attr_out(self, inode) -> bytes:
        return ATTR_OUT.pack(ATTR_TTL_S, 0, 0) + self._attr_bytes(inode)

    @staticmethod
    def _name(body: bytes) -> str:
        return body.split(b"\0", 1)[0].decode()

    # -- handlers --------------------------------------------------------------

    def _do_init(self, nodeid, body, uid, gid) -> bytes:
        major, minor = struct.unpack_from("<II", body)
        if major != 7:  # kernel re-sends INIT after a bare-version reply
            return INIT_OUT.pack(7, 31, 0, 0, 0, 0, 0, 0, 0, 0, *([0] * 8))
        return INIT_OUT.pack(7, min(minor, 31), 0x20000, FUSE_BIG_WRITES,
                             12, 9, MAX_WRITE, 1, 0, 0, *([0] * 8))

    def _do_lookup(self, nodeid, body, uid, gid) -> bytes:
        try:
            d = self.meta.lookup(nodeid, self._name(body))
        except OpError as e:
            raise FsError(e.code) from None
        return self._entry_out(self._inode(d.ino))

    def _do_getattr(self, nodeid, body, uid, gid) -> bytes:
        return self._attr_out(self._inode(nodeid))

    def _do_setattr(self, nodeid, body, uid, gid) -> bytes:
        (valid, _pad, _fh, size, _lock, _atime, mtime, _ctime, _an, mtn,
         *_rest) = SETATTR_IN.unpack_from(body)
        mode = SETATTR_IN.unpack_from(body)[11]
        kw: dict = {}
        if valid & FATTR_SIZE:
            self.meta.truncate(nodeid, size)
        if valid & FATTR_MODE:
            old = self._inode(nodeid)
            kw["mode"] = (old.mode & ~0o7777) | (mode & 0o7777)
        if valid & FATTR_UID:
            kw["uid"] = SETATTR_IN.unpack_from(body)[13]
        if valid & FATTR_GID:
            kw["gid"] = SETATTR_IN.unpack_from(body)[14]
        if valid & FATTR_MTIME:
            kw["mtime"] = mtime + mtn / 1e9
        if kw:
            self.meta.update_inode(nodeid, **kw)
        return self._attr_out(self._inode(nodeid))

    def _create_child(self, parent: int, name: str, mode: int):
        """Delegates to the ONE create implementation (combined commit or
        two-op fallback with undo, FsClient._create_node)."""
        qids = self.fs._parent_quota_ids(parent)
        return self.fs._create_node(parent, name, mode, qids, name)

    def _do_mknod(self, nodeid, body, uid, gid) -> bytes:
        mode, rdev, _umask, _pad = MKNOD_IN.unpack_from(body)
        if not stat_mod.S_ISREG(mode):
            raise FsError("EPERM", "only regular files")
        name = self._name(body[MKNOD_IN.size:])
        return self._entry_out(self._create_child(nodeid, name, mode))

    def _do_mkdir(self, nodeid, body, uid, gid) -> bytes:
        mode, _umask = MKDIR_IN.unpack_from(body)
        name = self._name(body[MKDIR_IN.size:])
        inode = self._create_child(nodeid, name,
                                   stat_mod.S_IFDIR | (mode & 0o7777))
        return self._entry_out(inode)

    def _do_unlink(self, nodeid, body, uid, gid) -> None:
        name = self._name(body)
        ino, nlink = self.fs._remove_node(nodeid, name, want_dir=False,
                                          path=name)
        if nlink <= 0:
            with self._lock:
                still_open = self._open_count.get(ino, 0) > 0
                if still_open:
                    self._orphans.add(ino)
            if not still_open:
                self.fs.evict_ino(ino)

    def _do_rmdir(self, nodeid, body, uid, gid) -> None:
        name = self._name(body)
        ino, _ = self.fs._remove_node(nodeid, name, want_dir=True, path=name)
        self.meta.evict_inode(ino)

    RENAME_NOREPLACE = 1  # linux/fs.h RENAME_* flags

    def _rename(self, nodeid: int, newdir: int, rest: bytes,
                noreplace: bool = False) -> None:
        src, dst = rest.split(b"\0")[:2]
        try:
            if noreplace and self._exists(newdir, dst.decode()):
                raise FsError("EEXIST", dst.decode())
            displaced = self.meta.rename(
                nodeid, src.decode(), newdir, dst.decode(),
                src_quota_ids=self.fs._parent_quota_ids(nodeid),
                dst_quota_ids=self.fs._parent_quota_ids(newdir))
        except OpError as e:
            raise FsError(e.code) from None
        if not displaced:
            return
        # same contract as _do_unlink: an inode displaced while open joins
        # _orphans and its LAST RELEASE evicts it; otherwise evict now
        ino, nlink, is_dir = displaced
        if not ino:
            return
        if is_dir:
            self.meta.evict_inode(ino)  # empty dir: no open-handle grace
            return
        if nlink <= 0:
            with self._lock:
                still_open = self._open_count.get(ino, 0) > 0
                if still_open:
                    self._orphans.add(ino)
            if not still_open:
                self.fs.evict_ino(ino)

    def _exists(self, parent: int, name: str) -> bool:
        try:
            self.meta.lookup(parent, name)
            return True
        except OpError as e:
            if e.code == "ENOENT":
                return False
            # a transient lookup failure must NOT read as "absent": that
            # would let NOREPLACE clobber the very file it protects
            raise FsError(e.code, name) from None

    def _do_rename(self, nodeid, body, uid, gid) -> None:
        (newdir,) = RENAME_IN.unpack_from(body)
        self._rename(nodeid, newdir, body[RENAME_IN.size:])

    def _do_rename2(self, nodeid, body, uid, gid) -> None:
        newdir, flags, _pad = RENAME2_IN.unpack_from(body)
        if flags & ~self.RENAME_NOREPLACE:  # EXCHANGE/WHITEOUT unsupported
            raise FsError("EINVAL", f"rename2 flags {flags:#x}")
        self._rename(nodeid, newdir, body[RENAME2_IN.size:],
                     noreplace=bool(flags & self.RENAME_NOREPLACE))

    def _do_link(self, nodeid, body, uid, gid) -> bytes:
        (oldnode,) = LINK_IN.unpack_from(body)
        name = self._name(body[LINK_IN.size:])
        try:
            self.meta.link(nodeid, name, oldnode)
        except OpError as e:
            raise FsError(e.code, name) from None
        return self._entry_out(self._inode(oldnode))

    def _open_common(self, ino: int, flags: int) -> bytes:
        inode = self._inode(ino)
        if flags & os.O_TRUNC and not inode.is_dir:
            self.meta.truncate(ino, 0)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._fhs[fh] = _Handle(ino, flags)
            self._open_count[ino] = self._open_count.get(ino, 0) + 1
        return OPEN_OUT.pack(fh, 0, 0)

    def _do_open(self, nodeid, body, uid, gid) -> bytes:
        flags, _ = OPEN_IN.unpack_from(body)
        return self._open_common(nodeid, flags)

    def _do_create(self, nodeid, body, uid, gid) -> bytes:
        flags, mode, _umask, _pad = CREATE_IN.unpack_from(body)
        name = self._name(body[CREATE_IN.size:])
        try:
            inode = self._create_child(
                nodeid, name, stat_mod.S_IFREG | (mode & 0o7777))
        except FsError as e:
            # O_CREAT without O_EXCL: losing the race opens the winner's file
            if e.code != "EEXIST" or flags & os.O_EXCL:
                raise
            inode = self._inode(self.meta.lookup(nodeid, name).ino)
        return self._entry_out(inode) + self._open_common(inode.ino, flags)

    def _do_read(self, nodeid, body, uid, gid) -> bytes:
        fh, offset, size, *_ = READ_IN.unpack_from(body)
        h = self._fhs.get(fh)
        if h is None:
            raise FsError("EBADF", str(fh))
        return self.fs.read_at(h.ino, offset, size)

    def _do_write(self, nodeid, body, uid, gid) -> bytes:
        fh, offset, size, *_ = WRITE_IN.unpack_from(body)
        h = self._fhs.get(fh)
        if h is None:
            raise FsError("EBADF", str(fh))
        data = body[WRITE_IN.size:WRITE_IN.size + size]
        self.fs.write_at(h.ino, offset, data)
        return WRITE_OUT.pack(len(data), 0)

    def _do_release(self, nodeid, body, uid, gid) -> None:
        fh, *_ = RELEASE_IN.unpack_from(body)
        with self._lock:
            h = self._fhs.pop(fh, None)
            if h is None:
                return
            n = self._open_count.get(h.ino, 1) - 1
            evict = False
            if n <= 0:
                self._open_count.pop(h.ino, None)
                evict = h.ino in self._orphans
                self._orphans.discard(h.ino)
            else:
                self._open_count[h.ino] = n
        if evict:  # last close of an unlinked file releases it
            self.fs.evict_ino(h.ino)

    def _do_flush(self, nodeid, body, uid, gid) -> None:
        return None  # writes are synchronous end-to-end (Mount.fsync contract)

    def _do_fsync(self, nodeid, body, uid, gid) -> None:
        return None

    def _list_dir(self, nodeid) -> list[tuple[str, int, int]]:
        try:
            dentries = self.meta.read_dir(nodeid)
        except OpError as e:
            raise FsError(e.code) from None
        entries = [(".", nodeid, stat_mod.S_IFDIR),
                   ("..", nodeid, stat_mod.S_IFDIR)]
        entries += [(d.name, d.ino, d.mode) for d in dentries]
        return entries

    def _do_opendir(self, nodeid, body, uid, gid) -> bytes:
        # snapshot the listing into a REAL fh: READDIR resumes by positional
        # offset, and re-fetching on every batch would skip/duplicate entries
        # whenever the directory mutates between batches of a large listing
        entries = self._list_dir(nodeid)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._dirhs[fh] = entries
        return OPEN_OUT.pack(fh, 0, 0)

    def _do_readdir(self, nodeid, body, uid, gid) -> bytes:
        fh, offset, size, *_ = READ_IN.unpack_from(body)
        entries = self._dirhs.get(fh)
        if entries is None:  # unknown fh (e.g. server restart): best effort
            entries = self._list_dir(nodeid)
        out = bytearray()
        for i, (name, ino, mode) in enumerate(entries):
            if i < offset:
                continue
            nb = name.encode()
            ent = DIRENT.pack(ino, i + 1, len(nb), (mode >> 12) & 0xF) + nb
            ent += b"\0" * (-len(ent) % 8)
            if len(out) + len(ent) > size:
                break
            out += ent
        return bytes(out)

    def _do_releasedir(self, nodeid, body, uid, gid) -> None:
        fh, *_ = RELEASE_IN.unpack_from(body)
        with self._lock:
            self._dirhs.pop(fh, None)
        return None

    def _do_statfs(self, nodeid, body, uid, gid) -> bytes:
        # capacity numbers are advisory here (master owns real accounting);
        # report a roomy filesystem so tools don't refuse to write
        blocks = 1 << 30
        return KSTATFS.pack(blocks, blocks // 2, blocks // 2, 1 << 20,
                            1 << 20, 4096, 255, 4096, 0, *([0] * 6))

    def _do_setxattr(self, nodeid, body, uid, gid) -> None:
        size, _flags = SETXATTR_IN.unpack_from(body)
        rest = body[SETXATTR_IN.size:]
        name, rest = rest.split(b"\0", 1)
        try:
            self.meta.set_xattr(nodeid, name.decode(), rest[:size])
        except OpError as e:
            raise FsError(e.code) from None

    def _do_getxattr(self, nodeid, body, uid, gid) -> bytes:
        size, _pad = GETXATTR_IN.unpack_from(body)
        name = self._name(body[GETXATTR_IN.size:])
        inode = self._inode(nodeid)
        if name not in inode.xattrs:
            raise FsError("ENODATA", name)
        value = inode.xattrs[name]
        if size == 0:
            return GETXATTR_OUT.pack(len(value), 0)
        if len(value) > size:
            raise FsError("ERANGE", name)
        return value

    def _do_listxattr(self, nodeid, body, uid, gid) -> bytes:
        size, _pad = GETXATTR_IN.unpack_from(body)
        names = b"".join(k.encode() + b"\0"
                         for k in sorted(self._inode(nodeid).xattrs))
        if size == 0:
            return GETXATTR_OUT.pack(len(names), 0)
        if len(names) > size:
            raise FsError("ERANGE")
        return names

    def _do_removexattr(self, nodeid, body, uid, gid) -> None:
        try:
            self.meta.remove_xattr(nodeid, self._name(body))
        except OpError as e:
            raise FsError(e.code) from None

    def _do_destroy(self, nodeid, body, uid, gid) -> None:
        return None

    # namespace-mutating ops carry an audit line (Mount logs the same set);
    # READ/GETATTR/LOOKUP are deliberately unaudited — per-page logging
    # would swamp the trail the way the reference's auditlog never does
    _AUDITED = {
        FUSE_MKNOD: "create", FUSE_CREATE: "create", FUSE_MKDIR: "mkdir",
        FUSE_UNLINK: "unlink", FUSE_RMDIR: "rmdir", FUSE_RENAME: "rename",
        FUSE_RENAME2: "rename", FUSE_LINK: "link", FUSE_SETATTR: "setattr",
        FUSE_SETXATTR: "setxattr", FUSE_REMOVEXATTR: "removexattr",
    }

    _DISPATCH = {
        FUSE_INIT: _do_init,
        FUSE_LOOKUP: _do_lookup,
        FUSE_GETATTR: _do_getattr,
        FUSE_SETATTR: _do_setattr,
        FUSE_MKNOD: _do_mknod,
        FUSE_MKDIR: _do_mkdir,
        FUSE_UNLINK: _do_unlink,
        FUSE_RMDIR: _do_rmdir,
        FUSE_RENAME: _do_rename,
        FUSE_RENAME2: _do_rename2,
        FUSE_LINK: _do_link,
        FUSE_OPEN: _do_open,
        FUSE_CREATE: _do_create,
        FUSE_READ: _do_read,
        FUSE_WRITE: _do_write,
        FUSE_RELEASE: _do_release,
        FUSE_FLUSH: _do_flush,
        FUSE_FSYNC: _do_fsync,
        FUSE_OPENDIR: _do_opendir,
        FUSE_READDIR: _do_readdir,
        FUSE_RELEASEDIR: _do_releasedir,
        FUSE_FSYNCDIR: _do_fsync,
        FUSE_STATFS: _do_statfs,
        FUSE_SETXATTR: _do_setxattr,
        FUSE_GETXATTR: _do_getxattr,
        FUSE_LISTXATTR: _do_listxattr,
        FUSE_REMOVEXATTR: _do_removexattr,
        FUSE_DESTROY: _do_destroy,
    }


def mount_volume(master_addrs: list[str], volume: str, mountpoint: str,
                 access_addrs: list[str] | None = None) -> FuseServer:
    """Dial the cluster, build the volume's FsClient, kernel-mount it.

    The `mount.fuse`-style composition: RemoteCluster -> FsClient ->
    FuseServer.mount() + serve_background(). Caller owns unmount()."""
    from chubaofs_tpu.sdk.cluster import RemoteCluster

    cluster = RemoteCluster(master_addrs, access_addrs=access_addrs)
    fs = cluster.client(volume)
    srv = FuseServer(fs, mountpoint, volume=volume)
    srv.mount()
    srv.serve_background()
    return srv


def main(argv=None) -> int:
    """cfs-fuse: mount a volume at PATH until SIGINT/SIGTERM (fuse.go main)."""
    import argparse

    p = argparse.ArgumentParser(prog="cfs-fuse")
    p.add_argument("--master", required=True, action="append",
                   help="master addr (repeatable)")
    p.add_argument("--volume", required=True)
    p.add_argument("--access", action="append", default=[],
                   help="blobstore access addr for cold volumes")
    p.add_argument("mountpoint")
    args = p.parse_args(argv)
    if not fuse_available():
        print("/dev/fuse unavailable", flush=True)  # obslint: cfs-fuse CLI entry; stdout is the interface
        return 1
    from chubaofs_tpu.utils.shutdown import await_shutdown, shutdown_event

    stop = shutdown_event()
    srv = mount_volume(args.master, args.volume, args.mountpoint,
                       access_addrs=args.access or None)
    print(f'{{"mounted": "{args.mountpoint}", "volume": "{args.volume}"}}',  # obslint: mount line IS the stdout protocol (scripts parse it)
          flush=True)
    await_shutdown(stop)
    srv.unmount()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
