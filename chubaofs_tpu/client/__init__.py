"""Client layer — the FUSE-facing mount (client/ analog)."""

from chubaofs_tpu.client.mount import Mount, MountError

__all__ = ["Mount", "MountError"]
