"""Mount — the client's POSIX handle layer over one volume.

Reference counterpart: client/ — the FUSE daemon's Super + fs node layer
(client/fuse.go:588 NewSuper; fs ops client/fs/file.go:316-439,
client/fs/dir.go; inode attr cache client/fs/icache.go; orphan inode list;
per-op audit log via util/auditlog, CHANGELOG.md:10). Kept: a file-descriptor
table with positional + streaming reads/writes, a TTL'd inode-attribute
cache and (parent, name) lookup cache invalidated on mutation, the orphan
list — an unlinked-but-open inode stays readable until its last close, which
evicts it — and one audit line per namespace op. This path-based surface
serves in-process consumers and libsdk's cfs_* C ABI (libsdk/libsdk.go:259).
The kernel FUSE wire lives in client/fuse_ll.py and deliberately does NOT
layer on Mount: the kernel protocol is nodeid(inode)-based with the fd
table living in the kernel, so fuse_ll drives MetaWrapper/FsClient inode
verbs directly; the one shared contract — orphan-inode eviction on last
close — is intentionally implemented in both (see fuse_ll's module doc).
"""

from __future__ import annotations

import os
import threading
import time

from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.sdk.fs import FsClient, FsError
from chubaofs_tpu.utils.auditlog import AuditLog, record_slow_op


class MountError(FsError):
    pass


O_RDONLY, O_WRONLY, O_RDWR = 0, 1, 2
O_CREAT, O_TRUNC, O_APPEND = 0o100, 0o1000, 0o2000


class _Handle:
    __slots__ = ("fd", "ino", "flags", "pos", "path")

    def __init__(self, fd: int, ino: int, flags: int, path: str):
        self.fd = fd
        self.ino = ino
        self.flags = flags
        self.pos = 0
        self.path = path


class Mount:
    """One mounted volume: fd table + caches + orphan list + audit."""

    ATTR_TTL = 1.0  # client/fs/icache.go's attr validity window
    LOOKUP_TTL = 1.0

    def __init__(self, fs: FsClient, volume: str = "", audit_dir: str | None = None,
                 client_id: str = ""):
        self.fs = fs
        self.volume = volume
        self.client_id = client_id or f"pid{os.getpid()}"
        self.audit = AuditLog(audit_dir) if audit_dir else None
        self._lock = threading.Lock()
        self._next_fd = 3
        self._fds: dict[int, _Handle] = {}
        self._open_count: dict[int, int] = {}  # ino -> open handles
        self._orphans: set[int] = set()  # unlinked while open
        self._attr: dict[int, tuple[float, dict]] = {}  # ino -> (expiry, stat)
        self._lookups: dict[str, tuple[float, int]] = {}  # path -> (expiry, ino)

    # -- audit -----------------------------------------------------------------

    def _op(self, op: str, path: str, fn):
        from chubaofs_tpu.meta.metanode import OpError

        t0 = time.perf_counter()
        err = ""
        # the fs-op root (or child, under a caller's span): every meta/data
        # hop below hangs its track entry off this one trace id — the
        # FUSE→SDK→metanode→raft chain reads as one track log
        span = trace.child_of(trace.current_span(), f"mount.{op}")
        trace.push_span(span)
        try:
            return fn()
        except FsError as e:
            err = e.code
            raise
        except OpError as e:
            # direct meta calls (stat/truncate caches) surface the same
            # FsError contract the fs verbs do
            err = e.code
            raise MountError(e.code, path) from None
        finally:
            span.append_track_log("fuse", start=t0)
            span.finish()
            trace.pop_span()
            elapsed = time.perf_counter() - t0
            record_slow_op("fuse", op, elapsed, span=span, err=err)
            if self.audit:
                self.audit.log_fs_op(self.client_id, self.volume, op, path,
                                     err=err, latency_us=int(elapsed * 1e6))

    # -- caches ----------------------------------------------------------------

    def _resolve(self, path: str) -> int:
        now = time.monotonic()  # cache TTLs are deltas, never wall stamps
        hit = self._lookups.get(path)
        if hit and now < hit[0]:
            return hit[1]
        ino = self.fs.resolve(path)
        self._lookups[path] = (now + self.LOOKUP_TTL, ino)
        return ino

    def _stat_ino(self, ino: int) -> dict:
        from chubaofs_tpu.meta.metanode import OpError

        now = time.monotonic()
        hit = self._attr.get(ino)
        if hit and now < hit[0]:
            return hit[1]
        try:
            inode = self.fs.meta.get_inode(ino)
        except OpError as e:
            raise MountError(e.code, f"ino {ino}") from None
        st = {"ino": inode.ino, "mode": inode.mode, "size": inode.size,
              "nlink": inode.nlink, "uid": inode.uid, "gid": inode.gid,
              "mtime": inode.mtime, "is_dir": inode.is_dir}
        self._attr[ino] = (now + self.ATTR_TTL, st)
        return st

    def _invalidate(self, *inos: int, paths: tuple[str, ...] = ()):
        for ino in inos:
            self._attr.pop(ino, None)
        for p in paths:
            self._lookups.pop(p, None)

    def _invalidate_prefix(self, path: str):
        """Rename/rmdir moves a subtree: drop every cached path under it."""
        self._lookups = {p: v for p, v in self._lookups.items()
                         if p != path and not p.startswith(path.rstrip("/") + "/")}

    # -- fd table --------------------------------------------------------------

    def open(self, path: str, flags: int = O_RDONLY, mode: int = 0o644) -> int:
        def run():
            try:
                ino = self._resolve(path)
            except FsError:
                if not flags & O_CREAT:
                    raise
                try:
                    ino = self.fs.create(path, mode)
                except FsError as e:
                    # O_CREAT without O_EXCL: losing a concurrent-create race
                    # opens the winner's file (POSIX)
                    if e.code != "EEXIST":
                        raise
                    self._lookups.pop(path, None)
                    ino = self.fs.resolve(path)
                self._invalidate(paths=(path,))
            st = self._stat_ino(ino)
            if st["is_dir"] and flags & (O_WRONLY | O_RDWR):
                raise MountError("EISDIR", path)
            if flags & O_TRUNC and not st["is_dir"]:
                self.fs.meta.truncate(ino, 0)
                self._invalidate(ino)
            with self._lock:
                fd = self._next_fd
                self._next_fd += 1
                h = _Handle(fd, ino, flags, path)
                if flags & O_APPEND:
                    h.pos = self._stat_ino(ino)["size"]
                self._fds[fd] = h
                self._open_count[ino] = self._open_count.get(ino, 0) + 1
            return fd

        return self._op("open", path, run)

    def _handle(self, fd: int) -> _Handle:
        h = self._fds.get(fd)
        if h is None:
            raise MountError("EBADF", str(fd))
        return h

    def close(self, fd: int) -> None:
        def run():
            with self._lock:
                h = self._handle(fd)
                del self._fds[fd]
                n = self._open_count.get(h.ino, 1) - 1
                if n <= 0:
                    self._open_count.pop(h.ino, None)
                    evict = h.ino in self._orphans
                    if evict:
                        self._orphans.discard(h.ino)
                else:
                    self._open_count[h.ino] = n
                    evict = False
            if evict:  # last close of an unlinked file releases it
                self.fs.evict_ino(h.ino)
                self._invalidate(h.ino)

        return self._op("close", self._fds.get(fd, _Handle(0, 0, 0, "?")).path, run)

    # -- io --------------------------------------------------------------------

    def read(self, fd: int, size: int, offset: int | None = None) -> bytes:
        """offset None = streaming read advancing the cursor; an explicit
        offset is pread — it must NOT move the cursor (POSIX)."""
        h = self._handle(fd)

        def run():
            at = h.pos if offset is None else offset
            data = self.fs.read_at(h.ino, at, size)
            if offset is None:
                h.pos = at + len(data)
            return data

        return self._op("read", h.path, run)

    def write(self, fd: int, data: bytes, offset: int | None = None) -> int:
        """offset None = streaming write (or append under O_APPEND); an
        explicit offset is pwrite and leaves the cursor alone."""
        h = self._handle(fd)

        def run():
            if not h.flags & (O_WRONLY | O_RDWR):
                raise MountError("EBADF", f"fd {fd} is read-only")
            if offset is None:
                at = (self._stat_ino(h.ino)["size"]
                      if h.flags & O_APPEND else h.pos)
            else:
                at = offset
            self.fs.write_at(h.ino, at, data)
            if offset is None:
                h.pos = at + len(data)
            self._invalidate(h.ino)
            return len(data)

        return self._op("write", h.path, run)

    def lseek(self, fd: int, offset: int, whence: int = 0) -> int:
        h = self._handle(fd)
        if whence == 0:
            h.pos = offset
        elif whence == 1:
            h.pos += offset
        elif whence == 2:
            h.pos = self._stat_ino(h.ino)["size"] + offset
        else:
            raise MountError("EINVAL", f"whence {whence}")
        return h.pos

    def fsync(self, fd: int) -> None:
        self._handle(fd)  # writes are synchronous end-to-end already

    def fstat(self, fd: int) -> dict:
        h = self._handle(fd)
        self._attr.pop(h.ino, None)  # fstat is the fresh-size call
        return self._stat_ino(h.ino)

    # -- namespace -------------------------------------------------------------

    def stat(self, path: str) -> dict:
        return self._op("stat", path, lambda: self._stat_ino(self._resolve(path)))

    def mkdir(self, path: str, mode: int = 0o755) -> int:
        def run():
            ino = self.fs.mkdir(path, mode)
            self._invalidate(paths=(path,))
            return ino

        return self._op("mkdir", path, run)

    def readdir(self, path: str) -> list[str]:
        return self._op("readdir", path, lambda: self.fs.readdir(path))

    def rmdir(self, path: str) -> None:
        def run():
            self.fs.rmdir(path)
            self._invalidate_prefix(path)

        return self._op("rmdir", path, run)

    def unlink(self, path: str) -> None:
        def run():
            # the unlinked inode's identity comes from the metanode, never a
            # cached lookup — a stale cache would orphan/evict the wrong inode
            ino = self.fs.unlink(path, evict=False)
            with self._lock:
                still_open = self._open_count.get(ino, 0) > 0
                if still_open:
                    self._orphans.add(ino)
            if not still_open:
                self.fs.evict_ino(ino)
            self._invalidate(ino, paths=(path,))

        return self._op("unlink", path, run)

    def rename(self, src: str, dst: str) -> None:
        def run():
            # same orphan contract as unlink(): an inode displaced by
            # rename-over while open stays readable until its last close
            displaced = self.fs.rename(src, dst, evict_displaced=False)
            if displaced:
                ino, nlink, is_dir = displaced
                if is_dir:
                    self.fs.evict_ino(ino)
                elif ino and nlink <= 0:
                    with self._lock:
                        still_open = self._open_count.get(ino, 0) > 0
                        if still_open:
                            self._orphans.add(ino)
                    if not still_open:
                        self.fs.evict_ino(ino)
            self._invalidate_prefix(src)
            self._invalidate_prefix(dst)

        return self._op("rename", src, run)

    def link(self, existing: str, new: str) -> None:
        def run():
            self.fs.link(existing, new)
            self._invalidate(self._resolve(existing), paths=(new,))

        return self._op("link", existing, run)

    def truncate(self, path: str, size: int) -> None:
        def run():
            ino = self._resolve(path)
            self.fs.meta.truncate(ino, size)
            self._invalidate(ino)

        return self._op("truncate", path, run)

    def setxattr(self, path: str, key: str, value: bytes) -> None:
        self._op("setxattr", path, lambda: self.fs.setxattr(path, key, value))
        self._invalidate(self._resolve(path))

    def getxattr(self, path: str, key: str) -> bytes:
        return self._op("getxattr", path, lambda: self.fs.getxattr(path, key))

    def listxattr(self, path: str) -> list[str]:
        return self._op("listxattr", path, lambda: self.fs.listxattr(path))

    def removexattr(self, path: str, key: str) -> None:
        self._op("removexattr", path, lambda: self.fs.removexattr(path, key))

    def statfs(self) -> dict:
        return {"volume": self.volume, "open_fds": len(self._fds),
                "orphans": len(self._orphans)}

    def umount(self) -> None:
        """Close every handle (evicting orphans) and the audit log."""
        for fd in list(self._fds):
            try:
                self.close(fd)
            except FsError:
                pass
        if self.audit:
            self.audit.close()
