"""cfs-cli — operator CLI against the master admin API (cli/ analog)."""

from chubaofs_tpu.cli.main import main

__all__ = ["main"]
