"""cfs-cli — cluster/volume/node/user administration from the terminal.

Reference counterpart: cli/ (the cobra `cfs-cli` binary; command tree in
cli/cmd/*.go — cluster.go, vol.go, metanode.go, datanode.go, user.go,
config.go). Kept: the same command tree and spellings (`cfs-cli cluster
info`, `vol create NAME OWNER`, `user info NAME`…), a config file holding
the master addresses (cli/cmd/config.go stores ~/.cfs-cli.json the same
way), table output for humans with a `--json` escape hatch for scripts, and
a `completion` command emitting bash completion (cobra generates these).
Changed: argparse instead of cobra; the reference's ~60 subcommands collapse
to the admin surface the rebuilt master exposes.

Usage: python -m chubaofs_tpu.cli [--addr host:port]... <noun> <verb> [args]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from chubaofs_tpu.master.api_service import MasterClient
from chubaofs_tpu.master.master import MasterError

CONFIG_PATH = os.path.expanduser("~/.cfs-cli.json")


def load_config() -> dict:
    try:
        with open(CONFIG_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_config(cfg: dict) -> None:
    with open(CONFIG_PATH, "w") as f:
        json.dump(cfg, f, indent=2)


def table(rows: list[dict], columns: list[str], out) -> None:
    """Fixed-width table (the reference CLI's aligned output style)."""
    if not rows:
        print("(none)", file=out)
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in columns}
    print("  ".join(c.upper().ljust(widths[c]) for c in columns), file=out)
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns),
              file=out)


class CLI:
    def __init__(self, addrs: list[str], out=None, as_json: bool = False,
                 ticket: str | None = None):
        self.addrs = list(addrs)
        self.mc = MasterClient(addrs, admin_ticket=ticket)
        self.out = out or sys.stdout
        self.as_json = as_json

    def _emit(self, data, rows=None, columns=None):
        if self.as_json or rows is None:
            print(json.dumps(data, indent=2, default=str), file=self.out)
        else:
            table(rows, columns, self.out)

    # -- cluster ---------------------------------------------------------------

    def cluster_info(self, args):
        c = self.mc.get_cluster()
        if self.as_json:
            return self._emit(c)
        print(f"Leader     : node {c['leader_id']}", file=self.out)
        print(f"Volumes    : {len(c['volumes'])}", file=self.out)
        print(f"Users      : {len(c['users'])}", file=self.out)
        rows = [{"id": n["node_id"], "kind": n["kind"], "addr": n["addr"],
                 "zone": n.get("zone", ""), "status": n.get("status", ""),
                 "partitions": n["partition_count"]} for n in c["nodes"]]
        table(rows, ["id", "kind", "addr", "zone", "status", "partitions"],
              self.out)

    def cluster_stat(self, args):
        st = self.mc.cluster_stat()
        if self.as_json:
            return self._emit(st)
        gib = 1 << 30
        d, m = st["data"], st["meta"]
        print(f"Data space : {d['used_space'] / gib:.1f} / "
              f"{d['total_space'] / gib:.1f} GiB used", file=self.out)
        print(f"Meta space : {m['used_space'] / gib:.1f} / "
              f"{m['total_space'] / gib:.1f} GiB used", file=self.out)
        print(f"Nodes      : {st['active']}/{st['nodes']} active", file=self.out)
        print(f"Volumes    : {st['volumes']} "
              f"(mp={st['meta_partitions']} dp={st['data_partitions']})",
              file=self.out)
        for zone, z in sorted(st["zones"].items()):
            print(f"  zone {zone or '-'}: {z['active']}/{z['nodes']} active, "
                  f"{z['used_space'] / gib:.1f}/{z['total_space'] / gib:.1f} GiB",
                  file=self.out)

    def cluster_domains(self, args):
        """zone -> fault domain assignments (domain mode when non-empty)."""
        doms = self.mc.get_zone_domains()
        if self.as_json:
            return self._emit(doms)
        if not doms:
            print("domain mode off (no assignments)", file=self.out)
            return
        table([{"zone": z, "domain": d} for z, d in sorted(doms.items())],
              ["zone", "domain"], self.out)

    def cluster_setdomain(self, args):
        res = self.mc.set_zone_domain(args.zone, args.domain)
        if self.as_json:
            return self._emit(res)
        if res.get("warning"):
            print(f"warning: {res['warning']}", file=self.out)
        print(f"{len(res['domains'])} assignment(s)", file=self.out)

    def cluster_topology(self, args):
        """Zones -> nodesets -> nodes, rendered from the master's own
        topology view (`cfs-cli zone list` analog)."""
        topo = self.mc.get_topology()
        if self.as_json:
            return self._emit(topo)
        by_id = {n["node_id"]: n for n in self.mc.get_cluster()["nodes"]}
        rows = []
        for zone in sorted(topo):
            for ns in sorted(topo[zone], key=int):
                for nid in topo[zone][ns]:
                    n = by_id.get(nid, {})
                    rows.append({"zone": zone or "(none)", "nodeset": ns,
                                 "id": nid, "kind": n.get("kind", "?"),
                                 "status": n.get("status", "")})
        table(rows, ["zone", "nodeset", "id", "kind", "status"], self.out)

    # -- volumes ---------------------------------------------------------------

    def vol_create(self, args):
        v = self.mc.create_volume(args.name, owner=args.owner,
                                  cold=args.cold, capacity=args.capacity,
                                  dp_count=args.dp_count)
        self._emit(v)

    def vol_update(self, args):
        fr = None if args.follower_read is None else args.follower_read == "true"
        v = self.mc.update_volume(
            args.name, capacity=args.capacity, follower_read=fr,
            qos_read_mbps=args.qos_read_mbps,
            qos_write_mbps=args.qos_write_mbps)
        self._emit(v)

    def vol_list(self, args):
        vols = self.mc.list_volumes()
        self._emit(vols, rows=vols,
                   columns=["name", "owner", "cold", "mp_count", "dp_count"])

    def vol_info(self, args):
        self._emit(self.mc.get_volume(args.name))

    def vol_delete(self, args):
        if not args.yes:
            print(f"refusing to delete {args.name!r} without --yes",
                  file=self.out)
            raise SystemExit(2)
        self.mc.delete_volume(args.name)
        print(f"volume {args.name} deleted", file=self.out)

    # -- nodes -----------------------------------------------------------------

    def _nodes(self, kind: str):
        nodes = [n for n in self.mc.get_cluster()["nodes"] if n["kind"] == kind]
        rows = [{"id": n["node_id"], "addr": n["addr"],
                 "raft": n["raft_addr"], "partitions": n["partition_count"],
                 "last_heartbeat": round(n["last_heartbeat"], 1)}
                for n in nodes]
        self._emit(nodes, rows=rows,
                   columns=["id", "addr", "raft", "partitions", "last_heartbeat"])

    def metanode_list(self, args):
        self._nodes("meta")

    def datanode_list(self, args):
        self._nodes("data")

    def metanode_decommission(self, args):
        self._emit(self.mc.decommission_node(args.id, "meta"))

    def metanode_rebalance(self, args):
        """One hot-meta-partition migration sweep (heartbeat-load driven)."""
        res = self.mc.rebalance_meta(factor=args.factor,
                                     max_moves=args.max_moves)
        if self.as_json:
            return self._emit(res)
        print(f"moved {res['moved']} replica(s)", file=self.out)
        rows = [{"id": nid, "window_ops": int(load)}
                for nid, load in sorted(res["loads"].items(),
                                        key=lambda kv: int(kv[0]))]
        table(rows, ["id", "window_ops"], self.out)

    def datanode_decommission(self, args):
        self._emit(self.mc.decommission_node(args.id, "data"))

    def datanode_rebalance(self, args):
        """One hot-volume spreading sweep (heartbeat-load driven)."""
        res = self.mc.rebalance_hot(factor=args.factor,
                                    max_moves=args.max_moves)
        if self.as_json:
            return self._emit(res)
        print(f"moved {res['moved']} replica(s)", file=self.out)
        rows = [{"id": nid, "window_ops": int(load)}
                for nid, load in sorted(res["loads"].items(),
                                        key=lambda kv: int(kv[0]))]
        table(rows, ["id", "window_ops"], self.out)

    # -- partitions ------------------------------------------------------------

    def mp_list(self, args):
        mps = self.mc.meta_partitions(args.volume)
        self._emit(mps, rows=mps,
                   columns=["partition_id", "start", "end", "peers", "leader"])

    def dp_list(self, args):
        dps = self.mc.data_partitions(args.volume)
        rows = [{"pid": d["pid"], "peers": d["peers"], "hosts": d["hosts"]}
                for d in dps]
        self._emit(dps, rows=rows, columns=["pid", "peers", "hosts"])

    def dp_create(self, args):
        self._emit(self.mc.create_data_partition(args.volume))

    # -- users -----------------------------------------------------------------

    def user_create(self, args):
        self._emit(self.mc.create_user(args.name, args.type))

    def user_delete(self, args):
        self.mc.delete_user(args.name)
        print(f"user {args.name} deleted", file=self.out)

    def user_info(self, args):
        self._emit(self.mc.user_info(args.name))

    def user_list(self, args):
        users = self.mc.list_users()
        rows = [{"user_id": u["user_id"], "type": u["user_type"],
                 "access_key": u["access_key"], "own_vols": u["own_vols"]}
                for u in users]
        self._emit(users, rows=rows,
                   columns=["user_id", "type", "access_key", "own_vols"])

    def user_perm(self, args):
        actions = [] if args.none else [f"perm:{args.perm}"]
        u = self.mc.update_user_policy(args.name, args.volume, actions,
                                       grant=not args.none)
        self._emit(u)

    # -- autopilot (ISSUE 20) --------------------------------------------------

    def _autopilot_call(self, op: str | None = None,
                        off: bool = False) -> dict:
        """The /autopilot side-door (plain JSON, not the admin envelope):
        first reachable configured master wins."""
        from chubaofs_tpu.tools.cfsstat import scrape

        path = "/autopilot"
        if op:
            path += f"?op={op}" + ("&off=1" if off else "")
        last_err: Exception | None = None
        for addr in self.addrs:
            try:
                return json.loads(scrape(addr, path, timeout=5))
            except Exception as e:
                last_err = e
        raise MasterError(f"no /autopilot endpoint reachable: {last_err}")

    def _autopilot_render(self, st: dict) -> None:
        if self.as_json:
            return self._emit(st)
        mode = "dry-run" if st.get("dry_run") else \
            ("enabled" if st.get("enabled") else "disabled")
        b = st.get("budget") or {}
        print(f"Autopilot : {mode}", file=self.out)
        print(f"Budget    : {b.get('remaining', 0)}/{b.get('per_hour', 0)} "
              "action(s) remaining this hour", file=self.out)
        cooldowns = st.get("cooldowns") or {}
        rows = [{"binding": x["name"], "rule": x["rule"],
                 "actuator": x["actuator"],
                 "armed": "yes" if x.get("armed") else "no",
                 "cooldown_s": cooldowns.get(x["actuator"], "")}
                for x in st.get("bindings", [])]
        table(rows, ["binding", "rule", "actuator", "armed", "cooldown_s"],
              self.out)
        decisions = st.get("decisions") or []
        if decisions:
            print("Recent decisions:", file=self.out)
            rows = [{"decision": d.get("decision"),
                     "binding": d.get("binding"),
                     "fingerprint": d.get("fingerprint")}
                    for d in decisions[-10:]]
            table(rows, ["decision", "binding", "fingerprint"], self.out)

    def autopilot_status(self, args):
        self._autopilot_render(self._autopilot_call())

    def autopilot_enable(self, args):
        self._autopilot_render(self._autopilot_call("enable"))

    def autopilot_disable(self, args):
        self._autopilot_render(self._autopilot_call("disable"))

    def autopilot_dry_run(self, args):
        self._autopilot_render(self._autopilot_call("dry-run", off=args.off))


COMPLETION = """# bash completion for cfs-cli
_cfs_cli() {
  local cur prev nouns verbs
  cur="${COMP_WORDS[COMP_CWORD]}"
  prev="${COMP_WORDS[COMP_CWORD-1]}"
  nouns="cluster vol metanode datanode metapartition datapartition user autopilot config completion"
  case "$prev" in
    cluster) verbs="info topology" ;;
    autopilot) verbs="status enable disable dry-run" ;;
    vol) verbs="create list info delete" ;;
    metanode|datanode) verbs="list decommission rebalance" ;;
    metapartition) verbs="list" ;;
    datapartition) verbs="list create" ;;
    user) verbs="create delete info list perm" ;;
    config) verbs="set show" ;;
    *) verbs="$nouns" ;;
  esac
  COMPREPLY=( $(compgen -W "$verbs" -- "$cur") )
}
complete -F _cfs_cli cfs-cli
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cfs-cli", description="chubaofs-tpu cluster admin CLI")
    p.add_argument("--addr", action="append", default=None,
                   help="master address host:port (repeatable); defaults to "
                        "the configured masters")
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument("--ticket", default=None,
                   help="authnode master:admin capability ticket (b64); "
                        "defaults to the configured adminTicket")
    sub = p.add_subparsers(dest="noun", required=True)

    cluster = sub.add_parser("cluster").add_subparsers(dest="verb", required=True)
    cluster.add_parser("info").set_defaults(fn="cluster_info")
    cluster.add_parser("topology").set_defaults(fn="cluster_topology")
    cluster.add_parser("stat").set_defaults(fn="cluster_stat")
    cluster.add_parser("domains").set_defaults(fn="cluster_domains")
    sd = cluster.add_parser("setdomain")
    sd.add_argument("zone")
    sd.add_argument("domain", help="empty string clears the assignment")
    sd.set_defaults(fn="cluster_setdomain")

    vol = sub.add_parser("vol", aliases=["volume"]).add_subparsers(
        dest="verb", required=True)
    c = vol.add_parser("create")
    c.add_argument("name")
    c.add_argument("owner", nargs="?", default="")
    c.add_argument("--cold", action="store_true",
                   help="erasure-coded blobstore tier")
    c.add_argument("--capacity", type=int, default=1 << 40)
    c.add_argument("--dp-count", type=int, default=3)
    c.set_defaults(fn="vol_create")
    u = vol.add_parser("update")
    u.add_argument("name")
    u.add_argument("--capacity", type=int, default=None)
    u.add_argument("--follower-read", choices=["true", "false"], default=None)
    u.add_argument("--qos-read-mbps", type=int, default=None)
    u.add_argument("--qos-write-mbps", type=int, default=None)
    u.set_defaults(fn="vol_update")
    vol.add_parser("list").set_defaults(fn="vol_list")
    i = vol.add_parser("info")
    i.add_argument("name")
    i.set_defaults(fn="vol_info")
    d = vol.add_parser("delete")
    d.add_argument("name")
    d.add_argument("--yes", action="store_true")
    d.set_defaults(fn="vol_delete")

    mn = sub.add_parser("metanode").add_subparsers(dest="verb", required=True)
    mn.add_parser("list").set_defaults(fn="metanode_list")
    md = mn.add_parser("decommission")
    md.add_argument("id", type=int)
    md.set_defaults(fn="metanode_decommission")
    mrb = mn.add_parser("rebalance")
    mrb.add_argument("--factor", type=float, default=1.5)
    mrb.add_argument("--max-moves", type=int, default=1)
    mrb.set_defaults(fn="metanode_rebalance")
    dn = sub.add_parser("datanode").add_subparsers(dest="verb", required=True)
    dn.add_parser("list").set_defaults(fn="datanode_list")
    rb = dn.add_parser("rebalance")
    rb.add_argument("--factor", type=float, default=1.5)
    rb.add_argument("--max-moves", type=int, default=2)
    rb.set_defaults(fn="datanode_rebalance")
    dd = dn.add_parser("decommission")
    dd.add_argument("id", type=int)
    dd.set_defaults(fn="datanode_decommission")

    mp = sub.add_parser("metapartition").add_subparsers(dest="verb", required=True)
    m = mp.add_parser("list")
    m.add_argument("volume")
    m.set_defaults(fn="mp_list")
    dp = sub.add_parser("datapartition").add_subparsers(dest="verb", required=True)
    dl = dp.add_parser("list")
    dl.add_argument("volume")
    dl.set_defaults(fn="dp_list")
    dc = dp.add_parser("create")
    dc.add_argument("volume")
    dc.set_defaults(fn="dp_create")

    user = sub.add_parser("user").add_subparsers(dest="verb", required=True)
    uc = user.add_parser("create")
    uc.add_argument("name")
    uc.add_argument("--type", default="normal", choices=["root", "admin", "normal"])
    uc.set_defaults(fn="user_create")
    ud = user.add_parser("delete")
    ud.add_argument("name")
    ud.set_defaults(fn="user_delete")
    ui = user.add_parser("info")
    ui.add_argument("name")
    ui.set_defaults(fn="user_info")
    user.add_parser("list").set_defaults(fn="user_list")
    up = user.add_parser("perm")
    up.add_argument("name")
    up.add_argument("volume")
    up.add_argument("perm", nargs="?", default="readonly",
                    choices=["readonly", "writable"])
    up.add_argument("--none", action="store_true", help="revoke")
    up.set_defaults(fn="user_perm")

    ap = sub.add_parser("autopilot").add_subparsers(dest="verb", required=True)
    ap.add_parser("status").set_defaults(fn="autopilot_status")
    ap.add_parser("enable").set_defaults(fn="autopilot_enable")
    ap.add_parser("disable").set_defaults(fn="autopilot_disable")
    adr = ap.add_parser("dry-run")
    adr.add_argument("--off", action="store_true",
                     help="leave shadow mode (actions run again)")
    adr.set_defaults(fn="autopilot_dry_run")

    cfg = sub.add_parser("config").add_subparsers(dest="verb", required=True)
    cs = cfg.add_parser("set")
    cs.add_argument("--addr", action="append", required=True)
    cs.set_defaults(fn="config_set")
    cfg.add_parser("show").set_defaults(fn="config_show")

    sub.add_parser("completion").set_defaults(fn="completion")
    return p


def main(argv: list[str] | None = None, out=None) -> int:
    out = out or sys.stdout
    args = build_parser().parse_args(argv)

    if args.fn == "completion":
        print(COMPLETION, file=out)
        return 0
    if args.fn == "config_set":
        save_config({"masterAddrs": args.addr})
        print(f"masters set: {args.addr}", file=out)
        return 0
    if args.fn == "config_show":
        print(json.dumps(load_config(), indent=2), file=out)
        return 0

    cfg = load_config()
    addrs = args.addr or cfg.get("masterAddrs")
    ticket = args.ticket or cfg.get("adminTicket")
    if not addrs:
        print("no master address: pass --addr or run "
              "`cfs-cli config set --addr host:port`", file=sys.stderr)
        return 2
    from chubaofs_tpu.rpc.errors import HTTPError

    cli = CLI(addrs, out=out, as_json=args.json, ticket=ticket)
    try:
        getattr(cli, args.fn)(args)
    except (MasterError, HTTPError, OSError) as e:
        # unreachable master / transport errors read as clean errors, not
        # tracebacks
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
