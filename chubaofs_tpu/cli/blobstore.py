"""blobstore-cli — admin CLI for the blobstore daemon (blobstore/cli analog).

Reference counterpart: blobstore/cli (the interactive admin shell over
clustermgr/scheduler/access APIs). Kept: the noun-verb command tree (stat,
disk ls, vol ls/info, task ls, switch ls/set, reload) plus an interactive
REPL when no command is given. Changed: one flat HTTP admin surface on the
access gateway instead of per-service endpoints — the rebuilt blobstore
composes its services into one daemon.

Usage:
    python -m chubaofs_tpu.cli.blobstore --addr host:port [cmd...]
    (no cmd -> interactive shell)
"""

from __future__ import annotations

import argparse
import json
import sys

from chubaofs_tpu.rpc.client import RPCClient


class BlobCli:
    def __init__(self, addr: str):
        self.rpc = RPCClient([addr], retries=2)

    # -- plumbing -------------------------------------------------------------

    def _req(self, method: str, path: str):
        status, _, body = self.rpc.do(method, path, b"")
        if status != 200:
            raise RuntimeError(body.decode() or f"HTTP {status}")
        return json.loads(body)

    def _get(self, path: str):
        return self._req("GET", path)

    def _post(self, path: str):
        return self._req("POST", path)

    @staticmethod
    def _table(rows: list[dict], cols: list[str]) -> str:
        if not rows:
            return "(none)"
        widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
                  for c in cols}
        head = "  ".join(c.upper().ljust(widths[c]) for c in cols)
        lines = [head] + [
            "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols)
            for r in rows
        ]
        return "\n".join(lines)

    # -- commands -------------------------------------------------------------

    def cmd_stat(self, *a) -> str:
        return json.dumps(self._get("/admin/stat"), indent=2)

    def cmd_disk(self, verb: str = "ls", *a) -> str:
        disks = self._get("/admin/disks")
        return self._table(disks, ["disk_id", "node_id", "az", "status",
                                   "chunk_count"])

    def cmd_vol(self, verb: str = "ls", vid: str = "", *a) -> str:
        if verb == "info":
            return json.dumps(self._get(f"/admin/volume?vid={int(vid)}"),
                              indent=2)
        return self._table(self._get("/admin/volumes"),
                           ["vid", "code_mode", "status", "units"])

    def cmd_task(self, verb: str = "ls", *a) -> str:
        return self._table(self._get("/admin/tasks"),
                           ["task_id", "kind", "state", "vid", "bid",
                            "disk_id", "retries"])

    def cmd_switch(self, verb: str = "ls", name: str = "", value: str = "", *a) -> str:
        if verb == "set":
            on = value in ("1", "on", "true")
            out = self._post(f"/admin/switch?name={name}&enabled={'1' if on else '0'}")
            return json.dumps(out)
        sw = self._get("/admin/switches")
        return self._table([{"switch": k, "enabled": v} for k, v in sw.items()],
                           ["switch", "enabled"])

    def cmd_forgive(self, *a) -> str:
        """Lift access punish windows after a confirmed host/AZ recovery."""
        self._post("/admin/forgive")
        return "punish windows cleared"

    def cmd_module(self, *a) -> str:
        return self._table(self._get("/admin/modules"), ["name", "running"])

    def cmd_reload(self, *a) -> str:
        return json.dumps(self._post("/admin/reload"))

    def cmd_help(self, *a) -> str:
        return ("commands: stat | disk ls | vol ls | vol info VID | task ls | "
                "switch ls | switch set NAME on|off | forgive | module ls | "
                "reload | help | exit")

    def dispatch(self, argv: list[str]) -> str:
        if not argv:
            return self.cmd_help()
        fn = getattr(self, "cmd_" + argv[0], None)
        if fn is None:
            return f"unknown command {argv[0]!r}\n{self.cmd_help()}"
        return fn(*argv[1:])

    def repl(self, stdin=None, stdout=None) -> None:
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        print("blobstore-cli (help for commands, exit to quit)", file=stdout)
        while True:
            print("bs> ", end="", file=stdout, flush=True)
            line = stdin.readline()
            if not line or line.strip() in ("exit", "quit"):
                return
            argv = line.split()
            if not argv:
                continue
            try:
                print(self.dispatch(argv), file=stdout)
            except Exception as e:
                print(f"error: {e}", file=stdout)


def main(argv: list[str] | None = None, stdout=None) -> int:
    p = argparse.ArgumentParser(prog="blobstore-cli")
    p.add_argument("--addr", required=True, help="blobstore daemon host:port")
    p.add_argument("cmd", nargs="*", help="command; omit for interactive shell")
    args = p.parse_args(argv)
    cli = BlobCli(args.addr)
    out = stdout or sys.stdout
    if not args.cmd:
        cli.repl(stdout=out)
        return 0
    try:
        print(cli.dispatch(args.cmd), file=out)
        return 0
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
