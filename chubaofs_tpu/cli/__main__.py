import sys

from chubaofs_tpu.cli.main import main

sys.exit(main())
