"""BlobNode — per-host chunk storage engine.

Reference counterpart: blobstore/blobnode (disks -> chunks -> shards; append-only
chunk datafiles with per-shard headers and crc32block framing,
core/storage/datafile.go:356,416; RocksDB shard metadb; punch-hole GC,
core/blobfile.go:83). Same on-disk contracts — append-only data files,
block-CRC framing, a persistent shard index, hole punching on delete — with
the shard index in the native libcfskv engine (utils/kvstore), exactly the
role RocksDB plays under the reference blobnode.

Layout on disk:
    <root>/superblock.json                 disk identity + chunk registry
    <root>/chunks/<chunk_id>.data          append-only shard records
    <root>/metadb/                         per-disk shard index (libcfskv — the
                                           native KV engine standing in for the
                                           reference's RocksDB metadb,
                                           blobnode/db/metadb.go); keys
                                           s/<chunk_id>/<bid> -> ShardMeta json.
                                           Legacy <chunk_id>.idx JSON-line WALs
                                           migrate into the metadb on open.

Shard record in a chunk datafile:
    [32B header: magic, bid, vuid, payload_len, header_crc]
    [crc32block-framed payload]
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass

from chubaofs_tpu.utils import crc32block
from chubaofs_tpu.utils.kvstore import open_kv

MAGIC = 0x73686472  # "shdr"
_HEADER = struct.Struct("<IQQQI")  # magic, bid, vuid, payload_len, crc-of-header
HEADER_LEN = _HEADER.size

# shard index states (metadb values)
STATUS_NORMAL = 1
STATUS_MARK_DELETE = 2
STATUS_DELETED = 3


def _punch_hole(fd: int, offset: int, length: int) -> None:
    """Release a byte range back to the filesystem (core/blobfile.go:83 analog).

    FALLOC_FL_PUNCH_HOLE | FALLOC_FL_KEEP_SIZE; best-effort — filesystems
    without hole support just keep the bytes until compaction."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        libc.fallocate(fd, 0x03, ctypes.c_long(offset), ctypes.c_long(length))
    except Exception:
        pass


class BlobNodeError(Exception):
    pass


class NoSuchShard(BlobNodeError):
    pass


class ChunkFull(BlobNodeError):
    pass


@dataclass
class ShardMeta:
    bid: int
    vuid: int
    offset: int  # offset of the record header in the datafile
    size: int  # payload length (unframed)
    status: int = STATUS_NORMAL


class Chunk:
    """One append-only chunk datafile + its shard index."""

    def __init__(self, path: str, chunk_id: str, max_size: int, metadb):
        self.chunk_id = chunk_id
        self.max_size = max_size
        self._data_path = path + ".data"
        self._idx_path = path + ".idx"  # legacy json-line WAL (migrated)
        self._db = metadb
        self._lock = threading.Lock()
        self.shards: dict[int, ShardMeta] = {}
        self._load()
        self._f = open(self._data_path, "r+b")
        self._size = os.path.getsize(self._data_path)

    def _key(self, bid: int) -> bytes:
        # fixed-width decimal keeps the metadb's byte order == bid order
        return f"s/{self.chunk_id}/{bid:020d}".encode()

    def _load(self):
        if not os.path.exists(self._data_path):
            open(self._data_path, "ab").close()
        if os.path.exists(self._idx_path):  # migrate a legacy index WAL
            with open(self._idx_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    meta = ShardMeta(**json.loads(line))
                    if meta.status == STATUS_DELETED:
                        self._db.delete(self._key(meta.bid))
                    else:
                        self._db.put(self._key(meta.bid),
                                     json.dumps(meta.__dict__).encode())
            os.replace(self._idx_path, self._idx_path + ".migrated")
        for _, v in self._db.scan(prefix=f"s/{self.chunk_id}/".encode()):
            meta = ShardMeta(**json.loads(v))
            self.shards[meta.bid] = meta

    def _log_idx(self, meta: ShardMeta):
        if meta.status == STATUS_DELETED:
            self._db.delete(self._key(meta.bid))
        else:
            self._db.put(self._key(meta.bid), json.dumps(meta.__dict__).encode())

    @property
    def used(self) -> int:
        return self._size

    def put(self, bid: int, vuid: int, payload: bytes) -> ShardMeta:
        framed = crc32block.encode(payload)
        with self._lock:
            if self._size + HEADER_LEN + len(framed) > self.max_size:
                raise ChunkFull(self.chunk_id)
            old = self.shards.get(bid)
            offset = self._size
            head = _HEADER.pack(MAGIC, bid, vuid, len(payload), 0)[:-4]
            self._f.seek(offset)
            self._f.write(head + struct.pack("<I", zlib.crc32(head)) + framed)
            self._f.flush()
            self._size = offset + HEADER_LEN + len(framed)
            meta = ShardMeta(bid=bid, vuid=vuid, offset=offset, size=len(payload))
            self.shards[bid] = meta
            self._log_idx(meta)
            if old is not None:
                # re-put (e.g. repeated repair): release the superseded record
                _punch_hole(
                    self._f.fileno(), old.offset, HEADER_LEN + crc32block.encoded_len(old.size)
                )
            return meta

    def get(self, bid: int, offset: int = 0, size: int | None = None) -> bytes:
        with self._lock:
            meta = self.shards.get(bid)
            if meta is None or meta.status != STATUS_NORMAL:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            if size is None:
                size = meta.size - offset
            if offset < 0 or size < 0 or offset + size > meta.size:
                raise BlobNodeError(f"range [{offset}, {offset+size}) outside shard of {meta.size}")
            fstart, fend = crc32block.block_range(offset, size)
            self._f.seek(meta.offset + HEADER_LEN + fstart)
            framed_total = crc32block.encoded_len(meta.size)
            framed = self._f.read(min(fend, framed_total) - fstart)
        blocks = crc32block.decode(framed)
        inner = offset - (fstart // (crc32block.BLOCK_SIZE + 4)) * crc32block.BLOCK_SIZE
        return blocks[inner : inner + size]

    def mark_delete(self, bid: int):
        with self._lock:
            meta = self.shards.get(bid)
            if meta is None:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            meta.status = STATUS_MARK_DELETE
            self._log_idx(meta)

    def delete(self, bid: int):
        """Punch-hole delete: release the record's bytes, drop the index entry."""
        with self._lock:
            meta = self.shards.get(bid)
            if meta is None:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            length = HEADER_LEN + crc32block.encoded_len(meta.size)
            _punch_hole(self._f.fileno(), meta.offset, length)
            meta.status = STATUS_DELETED
            self._log_idx(meta)
            del self.shards[meta.bid]

    def list_shards(self) -> list[ShardMeta]:
        with self._lock:
            return sorted(self.shards.values(), key=lambda m: m.bid)

    def close(self):
        self._f.close()


class Disk:
    """A directory of chunks with a superblock (core/disk/superblock.go analog)."""

    DEFAULT_CHUNK_SIZE = 1 << 30

    def __init__(self, root: str, disk_id: int, chunk_size: int | None = None):
        self.root = root
        self.disk_id = disk_id
        self.chunk_size = chunk_size or self.DEFAULT_CHUNK_SIZE
        os.makedirs(os.path.join(root, "chunks"), exist_ok=True)
        self._sb_path = os.path.join(root, "superblock.json")
        self.metadb = open_kv(os.path.join(root, "metadb"))
        self._lock = threading.Lock()
        self.chunks: dict[str, Chunk] = {}
        self._load()

    def _load(self):
        if os.path.exists(self._sb_path):
            with open(self._sb_path) as f:
                sb = json.load(f)
            self.disk_id = sb["disk_id"]
            self.chunk_size = sb["chunk_size"]
            for cid in sb["chunks"]:
                self.chunks[cid] = Chunk(
                    os.path.join(self.root, "chunks", cid), cid,
                    self.chunk_size, self.metadb
                )
        else:
            self._persist()

    def _persist(self):
        tmp = self._sb_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "disk_id": self.disk_id,
                    "chunk_size": self.chunk_size,
                    "chunks": list(self.chunks),
                },
                f,
            )
        os.replace(tmp, self._sb_path)

    def create_chunk(self, chunk_id: str) -> Chunk:
        with self._lock:
            if chunk_id in self.chunks:
                return self.chunks[chunk_id]
            c = Chunk(os.path.join(self.root, "chunks", chunk_id), chunk_id,
                      self.chunk_size, self.metadb)
            self.chunks[chunk_id] = c
            self._persist()
            return c

    def stats(self) -> dict:
        return {
            "disk_id": self.disk_id,
            "chunks": len(self.chunks),
            "used": sum(c.used for c in self.chunks.values()),
        }

    def close(self):
        for c in self.chunks.values():
            c.close()
        self.metadb.close()


class BlobNode:
    """Shard API over a set of disks (api/blobnode PutShard/GetShard analog).

    vuid (volume-unit id) identifies one stripe position of one volume; the
    clustermgr maps vuid -> (node, disk, chunk).
    """

    def __init__(self, node_id: int, disk_roots: list[str]):
        self.node_id = node_id
        self.disks: dict[int, Disk] = {}
        for i, root in enumerate(disk_roots):
            d = Disk(root, disk_id=node_id * 1000 + i)
            self.disks[d.disk_id] = d
        self._chunk_of_vuid: dict[int, tuple[int, str]] = {}
        self._lock = threading.Lock()
        # recover vuid->chunk mapping from chunk names ("vuid-<id>")
        for d in self.disks.values():
            for cid in d.chunks:
                if cid.startswith("vuid-"):
                    self._chunk_of_vuid[int(cid[5:])] = (d.disk_id, cid)

    # -- chunk lifecycle (clustermgr drives this) ---------------------------

    def create_vuid(self, vuid: int, disk_id: int | None = None) -> int:
        """Bind a volume unit to a fresh chunk; returns the disk id used."""
        with self._lock:
            if vuid in self._chunk_of_vuid:
                return self._chunk_of_vuid[vuid][0]
            if disk_id is None:
                disk_id = min(
                    self.disks, key=lambda d: self.disks[d].stats()["used"]
                )
            self.disks[disk_id].create_chunk(f"vuid-{vuid}")
            self._chunk_of_vuid[vuid] = (disk_id, f"vuid-{vuid}")
            return disk_id

    def _chunk(self, vuid: int) -> Chunk:
        loc = self._chunk_of_vuid.get(vuid)
        if loc is None:
            raise NoSuchShard(f"vuid {vuid} not on node {self.node_id}")
        disk_id, cid = loc
        return self.disks[disk_id].chunks[cid]

    # -- shard API ----------------------------------------------------------

    def put_shard(self, vuid: int, bid: int, payload: bytes) -> None:
        self._chunk(vuid).put(bid, vuid, payload)

    def get_shard(self, vuid: int, bid: int, offset: int = 0, size: int | None = None) -> bytes:
        return self._chunk(vuid).get(bid, offset, size)

    def mark_delete_shard(self, vuid: int, bid: int) -> None:
        self._chunk(vuid).mark_delete(bid)

    def delete_shard(self, vuid: int, bid: int) -> None:
        self._chunk(vuid).delete(bid)

    def list_shards(self, vuid: int) -> list[ShardMeta]:
        return self._chunk(vuid).list_shards()

    def stats(self) -> dict:
        return {
            "node_id": self.node_id,
            "disks": [d.stats() for d in self.disks.values()],
        }

    def close(self):
        for d in self.disks.values():
            d.close()
