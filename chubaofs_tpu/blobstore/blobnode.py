"""BlobNode — per-host chunk storage engine.

Reference counterpart: blobstore/blobnode (disks -> chunks -> shards; append-only
chunk datafiles with per-shard headers and crc32block framing,
core/storage/datafile.go:356,416; RocksDB shard metadb; punch-hole GC,
core/blobfile.go:83). Same on-disk contracts — append-only data files,
block-CRC framing, a persistent shard index, hole punching on delete — with
the shard index in the native libcfskv engine (utils/kvstore), exactly the
role RocksDB plays under the reference blobnode.

Layout on disk:
    <root>/superblock.json                 disk identity + chunk registry
    <root>/chunks/<chunk_id>.data          append-only shard records
    <root>/metadb/                         per-disk shard index (libcfskv — the
                                           native KV engine standing in for the
                                           reference's RocksDB metadb,
                                           blobnode/db/metadb.go); keys
                                           s/<chunk_id>/<bid> -> ShardMeta json.
                                           Legacy <chunk_id>.idx JSON-line WALs
                                           migrate into the metadb on open.

Shard record in a chunk datafile:
    [32B header: magic, bid, vuid, payload_len, header_crc]
    [crc32block-framed payload]
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import zlib
from dataclasses import dataclass

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore.clustermgr import DISK_BROKEN, DISK_NORMAL
from chubaofs_tpu.utils import crc32block
from chubaofs_tpu.utils.locks import SanitizedLock
from chubaofs_tpu.utils.kvstore import open_kv

MAGIC = 0x73686472  # "shdr"
_HEADER = struct.Struct("<IQQQI")  # magic, bid, vuid, payload_len, crc-of-header
HEADER_LEN = _HEADER.size

# shard index states (metadb values)
STATUS_NORMAL = 1
STATUS_MARK_DELETE = 2
STATUS_DELETED = 3


def _punch_hole(fd: int, offset: int, length: int) -> None:
    """Release a byte range back to the filesystem (core/blobfile.go:83 analog).

    FALLOC_FL_PUNCH_HOLE | FALLOC_FL_KEEP_SIZE; best-effort — filesystems
    without hole support just keep the bytes until compaction."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        libc.fallocate(fd, 0x03, ctypes.c_long(offset), ctypes.c_long(length))
    except Exception:
        pass


class BlobNodeError(Exception):
    pass


class NoSuchShard(BlobNodeError):
    pass


class ChunkFull(BlobNodeError):
    pass


def classify_io_error(e: BaseException) -> str:
    """Bucket a shard-IO failure for {reason}-labeled metrics: 'missing'
    (routine absence — the shard was never written or already lost),
    'timeout' (a silent hang that hit a deadline), 'io' (infrastructure:
    sockets, disks, injected faults), or 'error' (everything else — the
    bucket that should be a bug). The split is what makes a wedged node and
    a real defect distinguishable on a dashboard."""
    from concurrent.futures import TimeoutError as _FutTimeout

    from chubaofs_tpu.chaos.failpoints import Dropped, FailpointError

    if isinstance(e, NoSuchShard):
        return "missing"
    if isinstance(e, (TimeoutError, _FutTimeout)):
        return "timeout"
    if isinstance(e, (BlobNodeError, OSError, ConnectionError,
                      FailpointError, Dropped)):
        return "io"
    return "error"


@dataclass
class ShardMeta:
    bid: int
    vuid: int
    offset: int  # offset of the record header in the datafile
    size: int  # payload length (unframed)
    status: int = STATUS_NORMAL


class Chunk:
    """One append-only chunk datafile + its shard index.

    Compaction is generational (core/storage compaction analog): gen G lives
    in `<chunk>.data` (G=0) or `<chunk>.g<G>.data`; a compaction writes gen
    G+1 fully, then commits the gen bump AND every re-offset shard meta in ONE
    atomic metadb batch. A crash before the batch leaves gen G valid (the
    orphan G+1 file is swept on open); after it, gen G+1 is valid and stale
    files are swept on open.
    """

    def __init__(self, path: str, chunk_id: str, max_size: int, metadb):
        self.chunk_id = chunk_id
        self.max_size = max_size
        self._base_path = path
        self._idx_path = path + ".idx"  # legacy json-line WAL (migrated)
        self._db = metadb
        self._lock = SanitizedLock(name="blobnode.chunk")
        self.shards: dict[int, ShardMeta] = {}
        self.gen = int(self._db.get(self._gen_key()) or 0)
        self._data_path = self._gen_path(self.gen)
        self.tombstones: set[int] = set()  # deleted bids (metadb tombstones)
        self._check_committed_gen()
        self._sweep_stale_gens()
        self._load()
        self._f = open(self._data_path, "r+b")
        self._size = os.path.getsize(self._data_path)
        # garbage metric survives restarts: everything in the file that is not
        # a live record is punched/superseded space (compaction trigger)
        live = sum(HEADER_LEN + crc32block.encoded_len(m.size)
                   for m in self.shards.values())
        self.holes = max(0, self._size - live)

    def _check_committed_gen(self):
        """Never sweep while the committed generation's datafile is missing:
        deleting the survivors would turn a recoverable inconsistency into
        silent data loss. (compact() fsyncs the directory before the commit,
        so this only fires on external damage — fail loudly.)"""
        if os.path.exists(self._data_path):
            return
        d = os.path.dirname(self._base_path) or "."
        stem = os.path.basename(self._base_path)
        others = []
        for f in os.listdir(d):
            # same gen-suffix filter as _sweep_stale_gens: 'vuid-2560.data' is
            # NOT a generation of chunk 'vuid-256'
            if not f.startswith(stem) or not f.endswith(".data"):
                continue
            mid = f[len(stem):-len(".data")]
            if (mid == "" or (mid.startswith(".g") and mid[2:].isdigit())) \
                    and os.path.join(d, f) != self._data_path:
                others.append(f)
        if others:
            raise BlobNodeError(
                f"chunk {self.chunk_id}: committed gen {self.gen} datafile "
                f"missing but {others} exist — refusing to sweep")

    def _gen_key(self) -> bytes:
        return f"g/{self.chunk_id}".encode()

    def _gen_path(self, gen: int) -> str:
        return self._base_path + (".data" if gen == 0 else f".g{gen}.data")

    def _sweep_stale_gens(self):
        """Drop datafiles of any generation other than the committed one."""
        d = os.path.dirname(self._base_path) or "."
        stem = os.path.basename(self._base_path)
        for fname in os.listdir(d):
            if not fname.startswith(stem) or not fname.endswith(".data"):
                continue
            full = os.path.join(d, fname)
            if full != self._data_path:
                mid = fname[len(stem):-len(".data")]
                if mid == "" or (mid.startswith(".g") and mid[2:].isdigit()):
                    os.unlink(full)

    def _key(self, bid: int) -> bytes:
        # fixed-width decimal keeps the metadb's byte order == bid order
        return f"s/{self.chunk_id}/{bid:020d}".encode()

    def _load(self):
        if not os.path.exists(self._data_path):
            open(self._data_path, "ab").close()
        if os.path.exists(self._idx_path):  # migrate a legacy index WAL
            with open(self._idx_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    # DELETED entries become tombstones too: delete intent
                    # must survive the migration or the inspector could
                    # resurrect a partially-deleted blob
                    meta = ShardMeta(**json.loads(line))
                    self._db.put(self._key(meta.bid),
                                 json.dumps(meta.__dict__).encode())
            os.replace(self._idx_path, self._idx_path + ".migrated")
        for _, v in self._db.scan(prefix=f"s/{self.chunk_id}/".encode()):
            meta = ShardMeta(**json.loads(v))
            if meta.status == STATUS_DELETED:
                self.tombstones.add(meta.bid)  # deleted, not lost
            else:
                self.shards[meta.bid] = meta

    def _log_idx(self, meta: ShardMeta):
        # STATUS_DELETED stays in the metadb as a TOMBSTONE: the volume
        # inspector must be able to tell "deleted here" from "lost here", or a
        # partially-applied blob delete would be resurrected as a repair
        self._db.put(self._key(meta.bid), json.dumps(meta.__dict__).encode())

    @property
    def used(self) -> int:
        return self._size

    def put(self, bid: int, vuid: int, payload: bytes) -> ShardMeta:
        framed = crc32block.encode(payload)
        with self._lock:
            if self._size + HEADER_LEN + len(framed) > self.max_size:
                raise ChunkFull(self.chunk_id)
            old = self.shards.get(bid)
            offset = self._size
            head = _HEADER.pack(MAGIC, bid, vuid, len(payload), 0)[:-4]
            self._f.seek(offset)
            self._f.write(head + struct.pack("<I", zlib.crc32(head)) + framed)
            self._f.flush()
            self._size = offset + HEADER_LEN + len(framed)
            meta = ShardMeta(bid=bid, vuid=vuid, offset=offset, size=len(payload))
            self.shards[bid] = meta
            self.tombstones.discard(bid)  # re-put over a tombstone revives it
            self._log_idx(meta)
            if old is not None:
                # re-put (e.g. repeated repair): release the superseded record
                length = HEADER_LEN + crc32block.encoded_len(old.size)
                _punch_hole(self._f.fileno(), old.offset, length)
                self.holes += length
            return meta

    def get(self, bid: int, offset: int = 0, size: int | None = None) -> bytes:
        with self._lock:
            meta = self.shards.get(bid)
            if meta is None or meta.status != STATUS_NORMAL:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            if size is None:
                size = meta.size - offset
            if offset < 0 or size < 0 or offset + size > meta.size:
                raise BlobNodeError(f"range [{offset}, {offset+size}) outside shard of {meta.size}")
            fstart, fend = crc32block.block_range(offset, size)
            self._f.seek(meta.offset + HEADER_LEN + fstart)
            framed_total = crc32block.encoded_len(meta.size)
            framed = self._f.read(min(fend, framed_total) - fstart)
        blocks = crc32block.decode(framed)
        inner = offset - (fstart // (crc32block.BLOCK_SIZE + 4)) * crc32block.BLOCK_SIZE
        return blocks[inner : inner + size]

    def mark_delete(self, bid: int):
        with self._lock:
            meta = self.shards.get(bid)
            if meta is None:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            meta.status = STATUS_MARK_DELETE
            self._log_idx(meta)

    def delete(self, bid: int):
        """Punch-hole delete: release the record's bytes, drop the index entry."""
        with self._lock:
            meta = self.shards.get(bid)
            if meta is None:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            length = HEADER_LEN + crc32block.encoded_len(meta.size)
            _punch_hole(self._f.fileno(), meta.offset, length)
            self.holes += length
            meta.status = STATUS_DELETED
            self._log_idx(meta)
            self.tombstones.add(meta.bid)
            del self.shards[meta.bid]

    def compact(self) -> int:
        """Rewrite the datafile keeping only live records; returns bytes
        reclaimed. Crash-safe via the generational commit described on the
        class docstring."""
        with self._lock:
            new_gen = self.gen + 1
            new_path = self._gen_path(new_gen)
            new_metas: list[ShardMeta] = []
            with open(new_path, "wb") as out:
                for bid, meta in sorted(self.shards.items(),
                                        key=lambda kv: kv[1].offset):
                    length = HEADER_LEN + crc32block.encoded_len(meta.size)
                    self._f.seek(meta.offset)
                    record = self._f.read(length)
                    new_metas.append(ShardMeta(bid=bid, vuid=meta.vuid,
                                               offset=out.tell(),
                                               size=meta.size,
                                               status=meta.status))
                    out.write(record)
                out.flush()
                os.fsync(out.fileno())
            # the new file's DIRECTORY ENTRY must be durable before the gen
            # bump commits, or a crash could leave a committed gen with no file
            dfd = os.open(os.path.dirname(new_path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
            # commit point: gen bump + every re-offset meta, atomically.
            # Tombstones are RETAINED: they are cluster-level delete intent
            # ("deleted here, not lost"), not file-local garbage — purging them
            # would let the inspector resurrect a partially-deleted blob
            puts = [(self._gen_key(), str(new_gen).encode())]
            puts += [(self._key(m.bid), json.dumps(m.__dict__).encode())
                     for m in new_metas]
            self._db.write_batch(puts=puts)
            old_path, old_size = self._data_path, self._size
            self._f.close()
            self.gen = new_gen
            self._data_path = new_path
            self._f = open(new_path, "r+b")
            self._size = os.path.getsize(new_path)
            self.shards = {m.bid: m for m in new_metas}
            self.holes = 0
            if old_path != new_path:
                os.unlink(old_path)
            return old_size - self._size

    def tombstone(self, bid: int):
        """Record delete intent for a bid this chunk never stored (migrations
        carry tombstones with the unit). No-op when the bid is live here."""
        with self._lock:
            if bid in self.shards:
                return  # live here: a real delete must go through delete()
            meta = ShardMeta(bid=bid, vuid=0, offset=0, size=0,
                             status=STATUS_DELETED)
            self._log_idx(meta)
            self.tombstones.add(bid)

    def lose(self, bid: int):
        """Drop a record WITHOUT a tombstone — models media loss (a lost
        sector/file), as opposed to delete(), which records intent. The
        inspector repairs lost shards but finishes deleted ones."""
        with self._lock:
            meta = self.shards.pop(bid, None)
            if meta is None:
                raise NoSuchShard(f"chunk {self.chunk_id} bid {bid}")
            length = HEADER_LEN + crc32block.encoded_len(meta.size)
            _punch_hole(self._f.fileno(), meta.offset, length)
            self.holes += length
            self._db.delete(self._key(bid))

    def list_shards(self) -> list[ShardMeta]:
        with self._lock:
            return sorted(self.shards.values(), key=lambda m: m.bid)

    def destroy(self):
        """Delete the chunk outright: datafile, shard metas, tombstones, gen
        marker. Used when a volume unit is re-homed off this disk."""
        with self._lock:
            self._f.close()
            keys = [k for k, _ in self._db.scan(
                prefix=f"s/{self.chunk_id}/".encode())]
            keys.append(self._gen_key())
            self._db.write_batch(deletes=keys)
            try:
                os.unlink(self._data_path)
            except OSError:
                pass
            self.shards.clear()
            self.tombstones.clear()

    def close(self):
        self._f.close()


class Disk:
    """A directory of chunks with a superblock (core/disk/superblock.go analog)."""

    DEFAULT_CHUNK_SIZE = 1 << 30

    def __init__(self, root: str, disk_id: int, chunk_size: int | None = None):
        self.root = root
        self.disk_id = disk_id
        self.chunk_size = chunk_size or self.DEFAULT_CHUNK_SIZE
        os.makedirs(os.path.join(root, "chunks"), exist_ok=True)
        self._sb_path = os.path.join(root, "superblock.json")
        self.metadb = open_kv(os.path.join(root, "metadb"))
        self._lock = SanitizedLock(name="blobnode.disk")
        self.chunks: dict[str, Chunk] = {}
        self._load()

    def _load(self):
        if os.path.exists(self._sb_path):
            with open(self._sb_path) as f:
                sb = json.load(f)
            self.disk_id = sb["disk_id"]
            self.chunk_size = sb["chunk_size"]
            for cid in sb["chunks"]:
                self.chunks[cid] = Chunk(
                    os.path.join(self.root, "chunks", cid), cid,
                    self.chunk_size, self.metadb
                )
        else:
            self._persist()

    def _persist(self):
        tmp = self._sb_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "disk_id": self.disk_id,
                    "chunk_size": self.chunk_size,
                    "chunks": list(self.chunks),
                },
                f,
            )
        os.replace(tmp, self._sb_path)

    def create_chunk(self, chunk_id: str) -> Chunk:
        with self._lock:
            if chunk_id in self.chunks:
                return self.chunks[chunk_id]
            c = Chunk(os.path.join(self.root, "chunks", chunk_id), chunk_id,
                      self.chunk_size, self.metadb)
            self.chunks[chunk_id] = c
            self._persist()
            return c

    def stats(self) -> dict:
        return {
            "disk_id": self.disk_id,
            "chunks": len(self.chunks),
            "used": sum(c.used for c in self.chunks.values()),
        }

    def close(self):
        for c in self.chunks.values():
            c.close()
        self.metadb.close()


class BlobNode:
    """Shard API over a set of disks (api/blobnode PutShard/GetShard analog).

    vuid (volume-unit id) identifies one stripe position of one volume; the
    clustermgr maps vuid -> (node, disk, chunk).
    """

    def __init__(self, node_id: int, disk_roots: list[str],
                 iostat: bool = False, scrub_rate: float | None = None):
        self.node_id = node_id
        self.disks: dict[int, Disk] = {}
        for i, root in enumerate(disk_roots):
            d = Disk(root, disk_id=node_id * 1000 + i)
            self.disks[d.disk_id] = d
        self._chunk_of_vuid: dict[int, tuple[int, str]] = {}
        self._lock = SanitizedLock(name="blobnode.node")
        # shard-IO observability: per-node TP metrics in the blobnode role
        # registry; optionally the mmap'd iostat block node-side viewers read
        # (common/iostat) — off by default so test fleets don't litter shm
        from chubaofs_tpu.utils.exporter import registry as _registry

        self._reg = _registry("blobnode")
        self._iostat = None
        if iostat:
            from chubaofs_tpu.blobstore.iostat import IOStat

            self._iostat = IOStat(f"blobnode-{node_id}")
        # recover vuid->chunk mapping from chunk names ("vuid-<id>")
        for d in self.disks.values():
            for cid in d.chunks:
                if cid.startswith("vuid-"):
                    self._chunk_of_vuid[int(cid[5:])] = (d.disk_id, cid)
        # -- detection state (datainspect.go + disk-failure reporting) -------
        # scrub: token-bucket byte budget (CFS_SCRUB_RATE bytes/s; 0 =
        # unlimited) + a resumable (vuid, bid) cursor persisted in the first
        # disk's metadb, so a restarted node continues mid-sweep instead of
        # rescanning from shard zero
        if scrub_rate is None:
            scrub_rate = float(os.environ.get("CFS_SCRUB_RATE",
                                              str(64 << 20)))
        self._scrub_bucket = None
        if scrub_rate > 0:
            from chubaofs_tpu.utils.ratelimit import TokenBucket

            self._scrub_bucket = TokenBucket(scrub_rate)
        self._scrub_db = (self.disks[min(self.disks)].metadb
                          if self.disks else None)
        self._scrub_cursor: tuple[int, int] | None = None
        if self._scrub_db is not None:
            raw = self._scrub_db.get(b"scrub/cursor")
            if raw:
                try:
                    v, b = json.loads(raw)
                    self._scrub_cursor = (int(v), int(b))
                except (ValueError, TypeError):
                    # bad JSON raises ValueError, but valid-JSON garbage (a
                    # scalar, an object) fails the unpack with TypeError —
                    # either way: restart the sweep, lose nothing
                    pass
        # consecutive IO errors per disk: the heartbeat's disk-failure signal
        self._io_errors: dict[int, int] = {}
        self._closed = False

    # -- chunk lifecycle (clustermgr drives this) ---------------------------

    def create_vuid(self, vuid: int, disk_id: int | None = None) -> int:
        """Bind a volume unit to a fresh chunk; returns the disk id used."""
        with self._lock:
            if vuid in self._chunk_of_vuid:
                return self._chunk_of_vuid[vuid][0]
            if disk_id is None:
                disk_id = min(
                    self.disks, key=lambda d: self.disks[d].stats()["used"]
                )
            self.disks[disk_id].create_chunk(f"vuid-{vuid}")
            self._chunk_of_vuid[vuid] = (disk_id, f"vuid-{vuid}")
            return disk_id

    def _chunk(self, vuid: int) -> Chunk:
        loc = self._chunk_of_vuid.get(vuid)
        if loc is None:
            raise NoSuchShard(f"vuid {vuid} not on node {self.node_id}")
        disk_id, cid = loc
        return self.disks[disk_id].chunks[cid]

    def _disk_io(self, vuid: int, op):
        """Run one chunk op tracking CONSECUTIVE per-disk OSErrors — the
        disk-failure signal heartbeat() reports to clustermgr. Logical
        faults (NoSuchShard, CRC mismatches) don't count: a dying device
        shows up as the OS refusing IO, not as absent bids."""
        loc = self._chunk_of_vuid.get(vuid)
        before = self._io_errors.get(loc[0], 0) if loc is not None else 0
        try:
            out = op()
        except OSError:
            if loc is not None:
                # under the node lock: concurrent failing reads (access
                # fan-out, repair pool, scrub) must not lose increments of
                # the CONSECUTIVE count heartbeat's broken_after gates on
                with self._lock:
                    self._io_errors[loc[0]] = \
                        self._io_errors.get(loc[0], 0) + 1
                self._reg.counter("disk_io_errors").add()
            raise
        if loc is not None and before:
            with self._lock:
                # a success breaks the consecutive chain — but only reset if
                # the count is still the one we snapshotted: failures that
                # landed WHILE this op was in flight are newer information,
                # and zeroing them would lose increments the except path
                # took the lock to keep
                if self._io_errors.get(loc[0], 0) == before:
                    self._io_errors[loc[0]] = 0
        return out

    # -- shard API ----------------------------------------------------------

    def put_shard(self, vuid: int, bid: int, payload: bytes) -> None:
        import time as _time

        t0 = _time.perf_counter()
        if self._iostat is not None:
            self._iostat.write_begin()
        try:
            with self._reg.tp("shard_put"):
                chaos.failpoint("blobnode.put_shard", node=self.node_id)
                # corrupt-on-write models a bad controller: the framing CRCs
                # the already-flipped bytes, so only a later stripe-level
                # repair catches it
                payload = chaos.corrupt_bytes("blobnode.put_shard.payload",
                                              payload, node=self.node_id)
                self._disk_io(
                    vuid, lambda: self._chunk(vuid).put(bid, vuid, payload))
            self._reg.counter("shard_put_bytes_total").add(len(payload))
        finally:
            if self._iostat is not None:
                self._iostat.write_done(
                    len(payload), int((_time.perf_counter() - t0) * 1e6))

    def get_shard(self, vuid: int, bid: int, offset: int = 0, size: int | None = None) -> bytes:
        import time as _time

        t0 = _time.perf_counter()
        data = b""
        if self._iostat is not None:
            self._iostat.read_begin()
        try:
            with self._reg.tp("shard_get"):
                chaos.failpoint("blobnode.get_shard", node=self.node_id)
                data = self._disk_io(
                    vuid, lambda: self._chunk(vuid).get(bid, offset, size))
            self._reg.counter("shard_get_bytes_total").add(len(data))
            # corrupt-on-read models wire/DMA corruption past the CRC framing
            return chaos.corrupt_bytes("blobnode.get_shard.data", data,
                                       node=self.node_id)
        finally:
            if self._iostat is not None:
                self._iostat.read_done(
                    len(data), int((_time.perf_counter() - t0) * 1e6))

    def get_shard_combined(self, vuid: int, bid: int, coeffs: bytes) -> bytes:
        """Beta-combine helper read for regenerating-code repair: read the
        whole local shard, combine its len(coeffs) equal sub-units with the
        failed shard's GF(2^8) coefficients (codec/pm.py helper math), and
        return the single shard/len(coeffs)-byte payload. The disk still
        reads the full shard (iostat shows that truth); what shrinks is the
        bytes shipped to the repair worker — the cross-node cost repair
        bandwidth actually pays.
        """
        import time as _time

        import numpy as np

        from chubaofs_tpu.ops import gf256

        t0 = _time.perf_counter()
        data = b""
        if self._iostat is not None:
            self._iostat.read_begin()
        try:
            with self._reg.tp("shard_get"):
                # same failpoint as get_shard: wire-delay/error chaos regimes
                # apply to beta reads and full reads alike
                chaos.failpoint("blobnode.get_shard", node=self.node_id)
                data = self._disk_io(
                    vuid, lambda: self._chunk(vuid).get(bid, 0, None))
            buf = np.frombuffer(data, np.uint8)
            if not coeffs or buf.size % len(coeffs):
                raise BlobNodeError(
                    f"shard {len(data)}B not divisible into "
                    f"{len(coeffs)} sub-units")
            phi = np.frombuffer(coeffs, np.uint8)[None, :]
            out = gf256.gf_matmul(phi, buf.reshape(len(coeffs), -1)).tobytes()
            # count the SHIPPED bytes, like get_shard does — the beta win
            # must be visible in the node's own byte counters
            self._reg.counter("shard_get_bytes_total").add(len(out))
            self._reg.counter("shard_combine_bytes_total").add(len(out))
            return chaos.corrupt_bytes("blobnode.get_shard.data", out,
                                       node=self.node_id)
        finally:
            if self._iostat is not None:
                # the disk truly read the whole shard; iostat records that
                self._iostat.read_done(
                    len(data), int((_time.perf_counter() - t0) * 1e6))

    def mark_delete_shard(self, vuid: int, bid: int) -> None:
        self._chunk(vuid).mark_delete(bid)

    def delete_shard(self, vuid: int, bid: int) -> None:
        self._chunk(vuid).delete(bid)

    def list_shards(self, vuid: int) -> list[ShardMeta]:
        return self._chunk(vuid).list_shards()

    def lose_shard(self, vuid: int, bid: int) -> None:
        """Simulate media loss of one shard (no delete tombstone)."""
        self._chunk(vuid).lose(bid)

    def tombstone_shard(self, vuid: int, bid: int) -> None:
        """Record delete intent for a bid this chunk never stored — migrations
        carry tombstones WITH the unit, or a partially-deleted blob would be
        resurrected once the only tombstone-holding chunk moves."""
        self._chunk(vuid).tombstone(bid)

    def tombstones_of(self, vuid: int) -> set[int]:
        """All tombstoned bids of one unit (migrations enumerate these)."""
        return set(self._chunk(vuid).tombstones)

    def drop_vuid(self, vuid: int) -> None:
        """Release a re-homed volume unit's chunk: the space a balance/migrate
        moved away must actually free on the source disk. Idempotent."""
        with self._lock:
            loc = self._chunk_of_vuid.pop(vuid, None)
        if loc is None:
            return
        disk_id, cid = loc
        disk = self.disks[disk_id]
        with disk._lock:
            chunk = disk.chunks.pop(cid, None)
        if chunk is not None:
            chunk.destroy()
            disk._persist()

    def has_tombstone(self, vuid: int, bid: int) -> bool:
        """True when this bid was DELETED here (vs never written / lost)."""
        try:
            return bid in self._chunk(vuid).tombstones
        except NoSuchShard:
            return False

    def stats(self) -> dict:
        return {
            "node_id": self.node_id,
            "disks": [d.stats() for d in self.disks.values()],
        }

    # -- background hygiene (core compaction + datainspect.go analogs) -------

    def compact_once(self, min_hole_ratio: float = 0.25,
                     min_holes: int = 1 << 20) -> int:
        """Compact every chunk whose punched-hole share crosses the threshold;
        returns total bytes reclaimed."""
        reclaimed = 0
        for disk in self.disks.values():
            for chunk in list(disk.chunks.values()):
                if chunk.used and chunk.holes >= min_holes and \
                        chunk.holes / chunk.used >= min_hole_ratio:
                    reclaimed += chunk.compact()
        return reclaimed

    def inspect_once(self) -> list[tuple[int, int]]:
        """CRC scrub (blobnode/datainspect.go): re-read every live shard
        through the crc32block framing; returns [(vuid, bid)] that fail.
        The one-shot full sweep; the production loop is scrub_once()."""
        bad: list[tuple[int, int]] = []
        for vuid, (disk_id, cid) in list(self._chunk_of_vuid.items()):
            chunk = self.disks[disk_id].chunks.get(cid)
            if chunk is None:
                continue
            for meta in chunk.list_shards():
                if meta.status != STATUS_NORMAL:
                    continue
                try:
                    chunk.get(meta.bid)
                except Exception:
                    bad.append((vuid, meta.bid))
        return bad

    def _scrub_positions(self, cur: tuple[int, int] | None):
        """Live shard positions strictly AFTER the cursor, chunk by chunk
        in (vuid, bid) order — the batched-per-chunk iteration scrub_once
        resumes through."""
        for vuid in sorted(self._chunk_of_vuid):
            if cur is not None and vuid < cur[0]:
                continue
            loc = self._chunk_of_vuid.get(vuid)
            if loc is None:
                continue
            chunk = self.disks[loc[0]].chunks.get(loc[1])
            if chunk is None:
                continue
            for meta in chunk.list_shards():
                if cur is not None and vuid == cur[0] and meta.bid <= cur[1]:
                    continue
                if meta.status == STATUS_NORMAL:
                    yield vuid, meta.bid, chunk, meta

    def _save_scrub_cursor(self) -> None:
        if self._scrub_db is None:
            return
        try:
            if self._scrub_cursor is None:
                self._scrub_db.delete(b"scrub/cursor")
            else:
                self._scrub_db.put(b"scrub/cursor",
                                   json.dumps(list(self._scrub_cursor)).encode())
        except Exception:
            pass  # a cursor that fails to persist restarts the sweep, no worse

    def scrub_once(self, max_shards: int = 256) -> dict:
        """One budgeted tick of the background CRC scrub loop: re-read up to
        max_shards live shards through their crc32block framing, resuming
        from the persisted cursor, spending at most the CFS_SCRUB_RATE
        token-bucket byte budget. Returns {"scanned", "bad": [(vuid, bid)],
        "complete"} — complete=True means the sweep wrapped (the cursor
        reset) and everything currently live was verified this cycle."""
        scanned = 0
        bad: list[tuple[int, int]] = []
        complete = False
        exhausted = True  # ran off the end of the shard list (vs budget)
        for vuid, bid, chunk, meta in self._scrub_positions(self._scrub_cursor):
            if scanned >= max_shards:
                exhausted = False
                break
            cost = HEADER_LEN + crc32block.encoded_len(meta.size)
            if self._scrub_bucket is not None and not \
                    self._scrub_bucket.try_acquire(
                        min(cost, self._scrub_bucket.burst)):
                exhausted = False  # byte budget dry: resume here next tick
                break
            try:
                self._disk_io(vuid, lambda: chunk.get(bid))
            except OSError:
                # the OS refusing IO is a DISK failure (heartbeat's
                # consecutive-error signal, counted by _disk_io), not
                # bitrot — repairing shard-by-shard off a dying device
                # would fight the disk-repair migration
                pass
            except Exception:
                bad.append((vuid, bid))
            scanned += 1
            self._scrub_cursor = (vuid, bid)
        if exhausted:
            # wrapped: a full pass over every live shard finished
            if self._scrub_cursor is not None:
                self._reg.counter("scrub_sweeps").add()
            complete = True
            self._scrub_cursor = None
        self._save_scrub_cursor()
        if scanned:
            self._reg.counter("scrub_scanned_shards").add(scanned)
        if bad:
            self._reg.counter("scrub_bad_shards").add(len(bad))
            # a finding is a TRANSITION (healthy bytes -> detected bitrot):
            # one timeline record per tick, the shard ids in the detail —
            # never a metric label (obslint rule 1)
            from chubaofs_tpu.utils import events

            events.emit("scrub_finding", events.SEV_WARNING,
                        entity=f"node{self.node_id}",
                        detail={"node_id": self.node_id,
                                "bad": [[v, b] for v, b in bad],
                                "scanned": scanned})
        return {"scanned": scanned, "bad": bad, "complete": complete}

    def heartbeat(self, cm, broken_after: int = 3) -> None:
        """Report per-disk liveness + chunk counts to clustermgr, flagging
        any disk whose consecutive IO-error count crossed broken_after as
        BROKEN (the disk-failure half of detection; heartbeats going SILENT
        — a dead process — is caught by the clustermgr-side expiry)."""
        if self._closed:
            # a dead engine must go SILENT: heartbeat itself touches no disk
            # IO, so without this gate a crashed-but-still-routed node (the
            # chaos crash plan closes the engine in place) would keep
            # beating and the expiry path could never detect it
            return
        for disk_id, disk in self.disks.items():
            if self._io_errors.get(disk_id, 0) >= broken_after:
                try:
                    # only flip a NORMAL disk: re-reporting a DROPPED disk
                    # (repair done, error count never reset) as broken would
                    # mint an endless broken->repair->dropped->broken cycle
                    if cm.disk_status(disk_id) == DISK_NORMAL:
                        cm.set_disk_status(disk_id, DISK_BROKEN,
                                           reason="io_errors")
                except Exception:
                    pass  # control plane unreachable: retried next beat
                continue  # a broken disk stops heartbeating as healthy
            try:
                # no chunk_count: clustermgr's unit accounting is
                # authoritative (physical chunks lag volume creation)
                cm.heartbeat_disk(disk_id)
            except Exception:
                pass

    def close(self):
        self._closed = True
        for d in self.disks.values():
            d.close()
        if self._iostat is not None:
            self._iostat.close()
