"""Blobstore service composition: module registry, graceful drain-and-reload,
and the admin API surface.

Reference counterpart: blobstore/cmd/cmd.go:63-80 — services RegisterModule
their setup/teardown with the runner, and a graceful restart tears the stack
down in reverse order, draining in-flight work, then brings it back up (the
reference hands sockets across an exec; here the listener rebinds the same
address, which the composed single-process daemon makes equivalent). The admin
routes are the HTTP face the blobstore CLI (blobstore/cli analog,
chubaofs_tpu/cli/blobstore.py) drives.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class Module:
    """One registered service (RegisterModule analog)."""

    name: str
    setup: Callable[[dict, dict], object]  # (cfg, handles) -> handle
    teardown: Callable[[object], None] = lambda h: None


@dataclass
class ModuleRunner:
    """Ordered service lifecycle with graceful reload.

    Modules start in registration order and tear down in reverse (consumers
    before providers). reload() is the graceful restart: drain + teardown the
    whole stack, then set it back up from (possibly updated) config — state
    survives because every service persists (kvstore/WAL/chunk files)."""

    cfg: dict = field(default_factory=dict)
    modules: list[Module] = field(default_factory=list)
    handles: dict[str, object] = field(default_factory=dict)

    def __post_init__(self):
        self._lock = threading.Lock()
        self.reloads = 0
        self.last_error = ""

    def register(self, name: str, setup, teardown=None) -> None:
        if any(m.name == name for m in self.modules):
            raise ValueError(f"module {name!r} already registered")
        self.modules.append(Module(name, setup, teardown or (lambda h: None)))

    def start(self) -> None:
        with self._lock:
            self._start_locked()

    def _start_locked(self) -> None:
        started: list[Module] = []
        try:
            for m in self.modules:
                self.handles[m.name] = m.setup(self.cfg, self.handles)
                started.append(m)
        except Exception:
            # partial start: unwind what came up so no service leaks
            for m in reversed(started):
                self._teardown_one(m)
            raise

    def _teardown_one(self, m: Module) -> None:
        h = self.handles.pop(m.name, None)
        if h is not None:
            try:
                m.teardown(h)
            except Exception:
                pass  # teardown is best-effort during drain

    def reload(self, cfg: dict | None = None) -> None:
        """Graceful restart: teardown in reverse, bring everything back up.
        A failed restart is RECORDED (last_error) so operators can see why the
        stack is down via status(), not just a lost daemon-thread traceback."""
        with self._lock:
            for m in reversed(self.modules):
                self._teardown_one(m)
            if cfg is not None:
                self.cfg = cfg
            try:
                self._start_locked()
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                raise
            self.last_error = ""
            self.reloads += 1

    def call_with(self, name: str, fn):
        """Run fn(handle) UNDER the runner lock — callers (background ticks)
        never race a concurrent reload's teardown. Returns None when the
        module isn't up."""
        with self._lock:
            h = self.handles.get(name)
            if h is None:
                return None
            return fn(h)

    def stop(self) -> None:
        with self._lock:
            for m in reversed(self.modules):
                self._teardown_one(m)

    def status(self) -> list[dict]:
        with self._lock:
            return [{"name": m.name, "running": m.name in self.handles}
                    for m in self.modules]


def add_admin_routes(router, cluster, runner: ModuleRunner | None = None):
    """Admin surface over a MiniCluster (clustermgr/scheduler views + task
    switches + graceful reload) — what the blobstore CLI drives."""
    import json

    from chubaofs_tpu.blobstore.taskswitch import ALL_SWITCHES
    from chubaofs_tpu.rpc.router import Response

    def _json(data, status=200):
        return Response(status, {"Content-Type": "application/json"},
                        json.dumps(data).encode())

    def stat(req):
        cm = cluster.cm
        return _json({
            "disks": len(cm.disks),
            "broken_disks": [d.disk_id for d in cm.broken_disks()],
            "volumes": len(cm.volumes),
            "nodes": sorted(cluster.nodes),
            "services": {k: v for k, v in cm.services.items()},
            "reloads": runner.reloads if runner else 0,
            "reload_error": runner.last_error if runner else "",
        })

    def disks(req):
        return _json([d.__dict__ for d in cluster.cm.disks.values()])

    def volumes(req):
        return _json([
            {"vid": v.vid, "code_mode": v.code_mode, "status": v.status,
             "units": len(v.units)}
            for v in cluster.cm.volumes.values()
        ])

    def volume(req):
        try:
            vol = cluster.cm.get_volume(int(req.q("vid")))
        except Exception as e:
            return _json({"error": str(e)}, 404)
        return _json({"vid": vol.vid, "code_mode": vol.code_mode,
                      "status": vol.status,
                      "units": [u.__dict__ for u in vol.units]})

    def tasks(req):
        return _json([t.__dict__ for t in cluster.scheduler.tasks()])

    def switches(req):
        sw = cluster.scheduler.switches
        return _json({n: sw.enabled(n) for n in ALL_SWITCHES})

    def set_switch(req):
        name = req.q("name")
        if name not in ALL_SWITCHES:
            return _json({"error": f"unknown switch {name!r}"}, 400)
        enabled = req.q("enabled") in ("1", "true", "on")
        cluster.scheduler.switches.set(name, enabled)
        return _json({name: enabled})

    def forgive(req):
        """Lift all access punish windows after a CONFIRMED recovery
        (Access.clear_punishments — else writes treat a healed AZ/host as
        dark until punish_secs expires, and a second failure inside the
        window leaves blobs missing two AZs' worth of shards)."""
        cluster.access.clear_punishments()
        return _json({"forgiven": True})

    def modules(req):
        return _json(runner.status() if runner else [])

    def reload(req):
        if runner is None:
            return _json({"error": "no module runner"}, 400)

        # reload from a background thread: tearing down the gateway from
        # inside one of its own handler threads would deadlock the drain.
        # Failures land in runner.last_error (surfaced by /admin/stat).
        def _reload():
            try:
                runner.reload()
            except Exception:
                pass  # recorded in runner.last_error

        threading.Thread(target=_reload, daemon=True,
                         name="blobstore-reload").start()
        return _json({"reloading": True})

    router.get("/admin/stat", stat)
    router.get("/admin/disks", disks)
    router.get("/admin/volumes", volumes)
    router.get("/admin/volume", volume)
    router.get("/admin/tasks", tasks)
    router.get("/admin/switches", switches)
    router.post("/admin/switch", set_switch)
    router.post("/admin/forgive", forgive)
    router.get("/admin/modules", modules)
    router.post("/admin/reload", reload)
    return router
