"""Proxy — volume-allocation caching and the async message bus.

Reference counterpart: blobstore/proxy (allocator/volumemgr.go:348,512 caches
renewable volume grants from clustermgr; mq/ forwards shard-repair and
blob-delete messages to Kafka, service.go:57). Kafka is replaced by a durable
file-backed topic queue — same at-least-once contract, no external broker.
"""

from __future__ import annotations

import json
import os
import time

from chubaofs_tpu.blobstore.clustermgr import ClusterMgr, VolumeInfo
from chubaofs_tpu.utils.locks import SanitizedLock

TOPIC_SHARD_REPAIR = "shard_repair"
TOPIC_BLOB_DELETE = "blob_delete"
TOPIC_BLOB_HOT = "blob_hot"  # access-layer heat signals -> tier promoter


class TopicQueue:
    """Durable append-only topic with consumer offsets (the Kafka stand-in)."""

    def __init__(self, path: str | None = None):
        self._lock = SanitizedLock(name="proxy.topic")
        self._msgs: list[dict] = []
        self._offsets: dict[str, int] = {}
        self._path = path
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            if os.path.exists(path):
                with open(path) as f:
                    for line in f:
                        if line.strip():
                            self._msgs.append(json.loads(line))
            self._f = open(path, "a")
        else:
            self._f = None

    def produce(self, msg: dict) -> None:
        with self._lock:
            self._msgs.append(msg)
            if self._f:
                self._f.write(json.dumps(msg) + "\n")
                self._f.flush()

    def consume(self, group: str, max_msgs: int = 64) -> list[dict]:
        with self._lock:
            off = self._offsets.get(group, 0)
            batch = self._msgs[off : off + max_msgs]
            return batch

    def commit(self, group: str, count: int) -> None:
        with self._lock:
            self._offsets[group] = self._offsets.get(group, 0) + count

    def lag(self, group: str) -> int:
        with self._lock:
            return len(self._msgs) - self._offsets.get(group, 0)


class Proxy:
    """Per-IDC stateless proxy: cached volume grants + message production.

    Grants EXPIRE (alloc_ttl): like the reference allocator's renewal loop
    (proxy/allocator/volumemgr.go:348,512), a cached volume is re-validated
    against clustermgr after the TTL so a long-running proxy never keeps
    serving a volume that was retired, locked, or filled behind its back."""

    def __init__(self, cm: ClusterMgr, data_dir: str | None = None,
                 alloc_ttl: float = 30.0, active_vols: int | None = None):
        self.cm = cm
        self.alloc_ttl = alloc_ttl
        # grants rotate round-robin over a SET of active volumes (the
        # reference allocator keeps several volumes per mode in flight):
        # consecutive blobs of one windowed PUT then land on different
        # chunks/disks instead of serializing on one chunk's append lock
        if active_vols is None:
            active_vols = int(os.environ.get("CFS_PROXY_ACTIVE_VOLS", "2"))
        self.active_vols = max(1, active_vols)
        self._lock = SanitizedLock(name="proxy.alloc")
        # code_mode -> (volume grants, monotonic expiry)
        self._cached: dict[int, tuple[list[VolumeInfo], float]] = {}
        self._rr: dict[int, int] = {}
        d = data_dir
        self.topics = {
            TOPIC_SHARD_REPAIR: TopicQueue(os.path.join(d, "repair.jsonl") if d else None),
            TOPIC_BLOB_DELETE: TopicQueue(os.path.join(d, "delete.jsonl") if d else None),
            TOPIC_BLOB_HOT: TopicQueue(os.path.join(d, "hot.jsonl") if d else None),
        }

    # -- allocator (volumemgr.go:348 Alloc analog) ---------------------------

    def alloc_volume(self, code_mode: int) -> VolumeInfo:
        now = time.monotonic()
        with self._lock:
            granted, expires = self._cached.get(code_mode, ([], 0.0))
            vols = [v for v in granted if v.status == "active"]
            # renew on TTL expiry AND whenever a granted volume was retired
            # behind our back (len shrank): a thinned set would serialize
            # the PUT window on one chunk for the rest of the TTL — the
            # exact contention the rotating grant exists to prevent
            if not vols or now >= expires or len(vols) < len(granted):
                vols = self.cm.alloc_volumes(code_mode, self.active_vols)
                self._cached[code_mode] = (vols, now + self.alloc_ttl)
            i = self._rr.get(code_mode, 0)
            self._rr[code_mode] = i + 1
            return vols[i % len(vols)]

    def alloc_bids(self, count: int) -> tuple[int, int]:
        return self.cm.alloc_scope("bid", count)

    def invalidate(self, code_mode: int) -> None:
        with self._lock:
            self._cached.pop(code_mode, None)

    # -- message bus (mq analog) ---------------------------------------------

    def send_shard_repair(self, vid: int, bid: int, bad_idx: list[int], reason: str) -> None:
        self.topics[TOPIC_SHARD_REPAIR].produce(
            {"vid": vid, "bid": bid, "bad_idx": bad_idx, "reason": reason}
        )

    def send_blob_delete(self, vid: int, bid: int) -> None:
        self.topics[TOPIC_BLOB_DELETE].produce({"vid": vid, "bid": bid})

    def send_blob_hot(self, vid: int, bid: int, size: int) -> None:
        """Heat signal from the cache plane: this blob crossed the promote
        threshold — the scheduler's tier sweep turns it into a task. `size`
        is the blob's true byte length (shards alone can't recover it past
        the stripe padding; the promoter trims the replica copy with it)."""
        self.topics[TOPIC_BLOB_HOT].produce(
            {"vid": vid, "bid": bid, "size": size})
