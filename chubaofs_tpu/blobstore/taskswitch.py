"""Runtime task switches (blobstore/common/taskswitch analog).

Reference counterpart: common/taskswitch/task_switch.go:26,102 — background
task kinds (balance, disk_repair, disk_drop, blob_delete, shard_repair,
vol_inspect) each get an on/off switch persisted in the clustermgr config KV
and polled by the scheduler; flipping a switch pauses the task fleet without
restarts. Kept: named switches backed by a pluggable config accessor
(clustermgr KV here too), a polling refresher, and WaitEnable for task loops.
"""

from __future__ import annotations

import threading

SWITCH_BALANCE = "balance"
SWITCH_DISK_REPAIR = "disk_repair"
SWITCH_DISK_DROP = "disk_drop"
SWITCH_BLOB_DELETE = "blob_delete"
SWITCH_SHARD_REPAIR = "shard_repair"
SWITCH_VOL_INSPECT = "vol_inspect"
SWITCH_TIER_MIGRATE = "tier_migrate"

ALL_SWITCHES = (SWITCH_BALANCE, SWITCH_DISK_REPAIR, SWITCH_DISK_DROP,
                SWITCH_BLOB_DELETE, SWITCH_SHARD_REPAIR, SWITCH_VOL_INSPECT,
                SWITCH_TIER_MIGRATE)


class TaskSwitch:
    def __init__(self, name: str, enabled: bool = True):
        self.name = name
        self._enabled = enabled
        self._cond = threading.Condition()

    def enabled(self) -> bool:
        return self._enabled

    def set(self, enabled: bool):
        with self._cond:
            self._enabled = enabled
            if enabled:
                self._cond.notify_all()

    def wait_enable(self, timeout: float | None = None) -> bool:
        """Block a task loop while its switch is off (task_switch.go:102)."""
        with self._cond:
            return self._cond.wait_for(lambda: self._enabled, timeout)


class SwitchMgr:
    """Named switch registry; syncs from a config-KV getter when given one."""

    CONFIG_PREFIX = "task_switch/"

    def __init__(self, config_get=None, config_set=None):
        self._switches = {n: TaskSwitch(n) for n in ALL_SWITCHES}
        self._config_get = config_get
        self._config_set = config_set

    def switch(self, name: str) -> TaskSwitch:
        sw = self._switches.get(name)
        if sw is None:
            sw = self._switches[name] = TaskSwitch(name)
        return sw

    def enabled(self, name: str) -> bool:
        return self.switch(name).enabled()

    def set(self, name: str, enabled: bool):
        self.switch(name).set(enabled)
        if self._config_set is not None:
            self._config_set(self.CONFIG_PREFIX + name, "true" if enabled else "false")

    def refresh(self):
        """Pull persisted values (the scheduler's periodic sync loop body)."""
        if self._config_get is None:
            return
        for name, sw in self._switches.items():
            v = self._config_get(self.CONFIG_PREFIX + name)
            if v is not None:
                sw.set(str(v).lower() != "false")
