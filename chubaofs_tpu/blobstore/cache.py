"""BlobCache — the blobstore read-cache plane (ISSUE 12 tentpole).

Reference counterpart: blockcache/ + preload/ (PAPER.md layer map) — the
reference puts a node-local cache daemon and a preload pipeline in front of
the erasure-coded cold path because million-user GET traffic is zipfian: a
small hot head absorbs most reads, and serving it from an EC shard gather +
device decode per read is the online-EC read penalty arxiv 1709.05365
measures. Here the cache is in-process with the access gateway (the SDK and
S3 objectnode both read through `Access`, so one cache covers both GET
surfaces) and rides the grown `blockcache.BcacheManager`: TinyLFU admission
(counting sketch + ghost list) in front of a two-tier (memory overlay +
disk file) LRU with separate byte budgets.

Correctness contract — entries are keyed `(vid, bid, version)`:

  * blobs are immutable per bid on the write path (an overwrite allocates
    fresh bids), so a hit can only go stale through DELETE punch-out or a
    tier rewrite — both call `invalidate(vid, bid)`, which evicts the bytes
    AND bumps the blob's version;
  * `fill()` captures the version BEFORE the backend read and commits only
    if it still matches — a fill racing an invalidation lands under a dead
    version (unreachable) instead of resurrecting punched bytes;
  * the `cache.invalidate` failpoint sits in front of the punch-out so
    chaos runs can delay it and prove read-after-overwrite/-delete stays
    byte-correct (tests/test_cache_plane.py, chaos/soak.run_cache_soak).

Heat accounting for tier promotion also lives here: every lookup feeds a
bounded per-(vid, bid) counter, and `promote_signal()` fires once per blob
per aging epoch when CFS_PROMOTE_HITS accesses accumulate — the access
layer forwards the signal to the proxy's hot-blob topic, where the
scheduler turns it into a lease-driven promote task.

Knobs: CFS_CACHE_MB (memory-tier budget; 0/unset = cache plane off),
CFS_CACHE_DISK_MB (disk-tier budget, default 4x memory),
CFS_CACHE_ADMIT ("tinylfu" | "always"), CFS_PROMOTE_HITS (promotion
threshold, 0 = never signal).
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict

from chubaofs_tpu import chaos
from chubaofs_tpu.blockcache.bcache import BcacheManager
from chubaofs_tpu.utils.exporter import declare_label_values, registry
from chubaofs_tpu.utils.locks import SanitizedLock

# version-map bound: past _VER_MAX entries the prune pops oldest-first,
# but never an entry younger than the minimum-age floor — comfortably
# above any backend read's lifetime (write_deadline is 10s), so the
# fill-race window the version map exists to close stays closed. The map
# is then bounded by _VER_MIN_AGE_S worth of delete churn.
_VER_MAX = 65536
_VER_MIN_AGE_S = 30.0

# heat table bound: zipfian traffic concentrates heat, so a few thousand
# slots hold every plausible promotion candidate; on overflow the coldest
# half of the table is dropped (never the hot head)
_HEAT_MAX = 4096


class BlobCache:
    """In-process read cache for blobstore blobs, keyed (vid, bid, version)."""

    def __init__(self, cache_dir: str, mem_mb: int | None = None,
                 disk_mb: int | None = None, admit: str | None = None,
                 promote_hits: int | None = None):
        if mem_mb is None:
            mem_mb = int(os.environ.get("CFS_CACHE_MB", "") or 64)
        if disk_mb is None:
            disk_mb = int(os.environ.get("CFS_CACHE_DISK_MB", "") or 0)
            if disk_mb <= 0:
                disk_mb = 4 * mem_mb
        if admit is None:
            admit = os.environ.get("CFS_CACHE_ADMIT", "tinylfu")
        if promote_hits is None:
            promote_hits = int(os.environ.get("CFS_PROMOTE_HITS", "32") or 32)
        self.promote_hits = promote_hits
        self.mgr = BcacheManager(cache_dir, capacity_bytes=disk_mb << 20,
                                 mem_capacity_bytes=mem_mb << 20,
                                 admit=admit)
        self._lock = SanitizedLock(name="cache.ver")
        # (vid, bid) -> (version, monotonic stamp of the bump), kept in
        # bump order (move_to_end on re-bump) so pruning pops oldest-first
        # without ever sorting under the lock every GET also takes
        self._ver: OrderedDict[tuple[int, int], tuple[int, float]] = \
            OrderedDict()
        # (vid, bid) -> access count since the last signal/aging/invalidate
        self._heat: dict[tuple[int, int], int] = {}
        self._heat_total = 0
        self._reg = registry("cache")
        declare_label_values("tier", ("mem", "disk"))

    @classmethod
    def from_env(cls, cache_dir: str) -> "BlobCache | None":
        """The deployment wiring: a cache only when CFS_CACHE_MB is set to a
        positive budget — unset keeps every existing path byte-identical."""
        try:
            mem_mb = int(os.environ.get("CFS_CACHE_MB", "0") or 0)
        except ValueError:
            mem_mb = 0
        if mem_mb <= 0:
            return None
        return cls(cache_dir, mem_mb=mem_mb)

    # -- keying ----------------------------------------------------------------

    def _version(self, vid: int, bid: int) -> int:
        ver = self._ver.get((vid, bid))
        return 0 if ver is None else ver[0]

    @staticmethod
    def _key(vid: int, bid: int, ver: int) -> str:
        return f"b_{vid}_{bid}_{ver}"

    # -- read path -------------------------------------------------------------

    def get(self, vid: int, bid: int, offset: int = 0,
            size: int | None = None) -> bytes | None:
        """Ranged lookup; every call (hit or miss) is a heat sample."""
        self._reg.counter("lookups").add()
        with self._lock:
            ver = self._version(vid, bid)
            self._note_heat_locked(vid, bid)
        data = self.mgr.get(self._key(vid, bid, ver), offset, size)
        # hit/miss tallies ride the manager's cfs_bcache_* counters too;
        # cfs_cache_* is the plane-level family SLOs and cfs-top consume
        if data is None:
            self._reg.counter("misses").add()
        else:
            self._reg.counter("hits").add()
        return data

    def fill_version(self, vid: int, bid: int) -> int:
        """Capture the blob's version BEFORE reading the backend; pass it to
        fill() so a fill whose backend read straddled an invalidation can
        never land reachable bytes."""
        with self._lock:
            return self._version(vid, bid)

    def fill(self, vid: int, bid: int, ver: int, data: bytes) -> bool:
        with self._lock:
            if ver != self._version(vid, bid):
                self._reg.counter("stale_fills").add()
                return False
        ok = self.mgr.put(self._key(vid, bid, ver), data)
        # re-check AFTER the store write: an invalidate that raced the put
        # may have evicted this key before the bytes landed — its version
        # bump happens-before its evict, so a still-matching version here
        # proves the entry was not punched behind us, and a mismatch means
        # we must take our own bytes back out (an eventual version-map
        # prune would otherwise make them reachable again)
        with self._lock:
            landed_stale = ver != self._version(vid, bid)
        if landed_stale:
            self.mgr.evict(self._key(vid, bid, ver))
            self._reg.counter("stale_fills").add()
            return False
        self._reg.counter("fills" if ok else "fill_rejects").add()
        return ok

    # -- invalidation (write-through punch-out) --------------------------------

    def invalidate(self, vid: int, bid: int) -> None:
        """Punch the blob out: evict its bytes and bump its version. Callers
        invalidate BEFORE queueing the backend delete/punch, so by the time
        shards disappear no cached copy is reachable — the failpoint lets
        chaos stretch that window and prove the ordering carries it."""
        chaos.failpoint("cache.invalidate")
        with self._lock:
            cur, _ = self._ver.get((vid, bid), (0, 0.0))
            self._ver[(vid, bid)] = (cur + 1, time.monotonic())
            self._ver.move_to_end((vid, bid))
            self._heat.pop((vid, bid), None)
            self._prune_vers_locked()
        self.mgr.evict(self._key(vid, bid, cur))
        self._reg.counter("invalidations").add()

    def _prune_vers_locked(self) -> None:
        """Bound the version map: entries whose bump is older than the
        minimum-age floor can go — any fill that captured the pre-bump
        version has long since landed (unreachable, or self-evicted by the
        post-put re-check) or died, and the bytes were evicted at bump
        time, so forgetting the version cannot resurrect anything."""
        if len(self._ver) <= _VER_MAX:
            return
        # the map is in bump order, so the oldest entries sit at the front:
        # pop from there down to the cap, stopping at the minimum-age floor
        # (see _VER_MIN_AGE_S) — O(evicted), no scan or sort under the lock
        # every GET's version read also takes. In a storm where even the
        # front is younger than the floor the map temporarily exceeds the
        # cap, bounded by _VER_MIN_AGE_S worth of delete churn.
        floor = time.monotonic() - _VER_MIN_AGE_S
        while len(self._ver) > _VER_MAX:
            key, (_, ts) = next(iter(self._ver.items()))
            if ts > floor:
                break
            del self._ver[key]

    # -- heat / promotion signals ----------------------------------------------

    def _note_heat_locked(self, vid: int, bid: int) -> None:
        key = (vid, bid)
        self._heat[key] = self._heat.get(key, 0) + 1
        self._heat_total += 1
        if len(self._heat) > _HEAT_MAX:
            # keep the hot half; the dropped tail was never promotable
            keep = sorted(self._heat.items(), key=lambda kv: -kv[1])
            self._heat = dict(keep[: _HEAT_MAX // 2])
        if self._heat_total >= 16 * _HEAT_MAX:
            # aging: halve so the signal tracks SUSTAINED heat
            self._heat = {k: v >> 1 for k, v in self._heat.items() if v > 1}
            self._heat_total //= 2

    def promote_signal(self, vid: int, bid: int) -> bool:
        """True once per CFS_PROMOTE_HITS accesses (the counter resets on
        signal) — the caller forwards it to the hot-blob topic. A blob that
        STAYS hot keeps signalling every promote_hits accesses, which is
        what keeps the scheduler's idle-sweep demoter from evicting a
        still-hot blob out of the hot tier: signal silence really means
        'fewer than promote_hits accesses per demote window'."""
        if self.promote_hits <= 0:
            return False
        key = (vid, bid)
        with self._lock:
            if self._heat.get(key, 0) < self.promote_hits:
                return False
            self._heat[key] = 0
        self._reg.counter("promote_signals").add()
        return True

    def stats(self) -> dict:
        return self.mgr.stats()
