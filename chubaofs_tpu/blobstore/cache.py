"""BlobCache — the blobstore read-cache plane (ISSUE 12 tentpole).

Reference counterpart: blockcache/ + preload/ (PAPER.md layer map) — the
reference puts a node-local cache daemon and a preload pipeline in front of
the erasure-coded cold path because million-user GET traffic is zipfian: a
small hot head absorbs most reads, and serving it from an EC shard gather +
device decode per read is the online-EC read penalty arxiv 1709.05365
measures. Here the cache is in-process with the access gateway (the SDK and
S3 objectnode both read through `Access`, so one cache covers both GET
surfaces) and rides the grown `blockcache.BcacheManager`: TinyLFU admission
(counting sketch + ghost list) in front of a two-tier (memory overlay +
disk file) LRU with separate byte budgets.

Block granularity (ISSUE 17): entries are keyed
`(vid, bid, version, block_no)` with CFS_CACHE_BLOCK-sized blocks (default
256 KiB), so a ranged GET fills and hits ONLY the blocks its byte window
touches — a 4 KiB read of a 4 MiB blob caches one block, not the blob.
`get()` assembles its answer from the covering blocks and is a hit only
when every one is present; `fill()` accepts a (data, offset, total) window
and stores the fully-covered blocks (plus the tail block once `total`
proves it complete). The access layer rounds its backend fetch window out
to block boundaries, so fills always arrive block-aligned.

Correctness contract — versioning is unchanged from the blob-keyed plane:

  * blobs are immutable per bid on the write path (an overwrite allocates
    fresh bids), so a hit can only go stale through DELETE punch-out or a
    tier rewrite — both call `invalidate(vid, bid)`, which evicts every
    filled block AND bumps the blob's version;
  * `fill()` captures the version BEFORE the backend read and commits only
    if it still matches — a fill racing an invalidation lands under a dead
    version (unreachable) instead of resurrecting punched bytes;
  * blocks are only reachable while tracked: the fill ledger that
    invalidate punches from is pruned by EVICTING the blocks it forgets,
    so a version-map prune can never resurrect bytes;
  * the `cache.invalidate` failpoint sits in front of the punch-out so
    chaos runs can delay it and prove read-after-overwrite/-delete stays
    byte-correct (tests/test_cache_plane.py, chaos/soak.run_cache_soak).

Heat accounting for tier promotion also lives here: every lookup feeds a
bounded per-(vid, bid) counter, and `promote_signal()` fires once per blob
per aging epoch when CFS_PROMOTE_HITS accesses accumulate — the access
layer forwards the signal to the proxy's hot-blob topic, where the
scheduler turns it into a lease-driven promote task.

Knobs: CFS_CACHE_MB (memory-tier budget; 0/unset = cache plane off),
CFS_CACHE_DISK_MB (disk-tier budget, default 4x memory),
CFS_CACHE_BLOCK (cache block bytes, default 256 KiB),
CFS_CACHE_ADMIT ("tinylfu" | "always"), CFS_PROMOTE_HITS (promotion
threshold, 0 = never signal).
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict

from chubaofs_tpu import chaos
from chubaofs_tpu.blockcache.bcache import BcacheManager
from chubaofs_tpu.utils.exporter import declare_label_values, registry
from chubaofs_tpu.utils.locks import SanitizedLock

# version-map bound: past _VER_MAX entries the prune pops oldest-first,
# but never an entry younger than the minimum-age floor — comfortably
# above any backend read's lifetime (write_deadline is 10s), so the
# fill-race window the version map exists to close stays closed. The map
# is then bounded by _VER_MIN_AGE_S worth of delete churn.
_VER_MAX = 65536
_VER_MIN_AGE_S = 30.0

# heat table bound: zipfian traffic concentrates heat, so a few thousand
# slots hold every plausible promotion candidate; on overflow the coldest
# half of the table is dropped (never the hot head)
_HEAT_MAX = 4096

DEFAULT_BLOCK = 256 * 1024


class BlobCache:
    """In-process read cache for blobstore blobs, keyed
    (vid, bid, version, block_no)."""

    def __init__(self, cache_dir: str, mem_mb: int | None = None,
                 disk_mb: int | None = None, admit: str | None = None,
                 promote_hits: int | None = None,
                 block_bytes: int | None = None):
        if mem_mb is None:
            mem_mb = int(os.environ.get("CFS_CACHE_MB", "") or 64)
        if disk_mb is None:
            disk_mb = int(os.environ.get("CFS_CACHE_DISK_MB", "") or 0)
            if disk_mb <= 0:
                disk_mb = 4 * mem_mb
        if admit is None:
            admit = os.environ.get("CFS_CACHE_ADMIT", "tinylfu")
        if promote_hits is None:
            promote_hits = int(os.environ.get("CFS_PROMOTE_HITS", "32") or 32)
        if block_bytes is None:
            block_bytes = int(os.environ.get("CFS_CACHE_BLOCK", "")
                              or DEFAULT_BLOCK)
        # 4 KiB floor: a pathological env value must not mint a key per byte
        self.block = max(4096, int(block_bytes))
        self.promote_hits = promote_hits
        self.mgr = BcacheManager(cache_dir, capacity_bytes=disk_mb << 20,
                                 mem_capacity_bytes=mem_mb << 20,
                                 admit=admit)
        self._lock = SanitizedLock(name="cache.ver")
        # (vid, bid) -> (version, monotonic stamp of the bump), kept in
        # bump order (move_to_end on re-bump) so pruning pops oldest-first
        # without ever sorting under the lock every GET also takes
        self._ver: OrderedDict[tuple[int, int], tuple[int, float]] = \
            OrderedDict()
        # (vid, bid) -> blob size, learned on fill — what lets a
        # size=None lookup know which blocks a whole-blob read covers
        self._total: OrderedDict[tuple[int, int], int] = OrderedDict()
        # (vid, bid, ver) -> filled block numbers: the punch-out ledger.
        # invalidate() evicts exactly these; pruning EVICTS what it forgets
        # so an untracked block is never a reachable one.
        self._blocks: OrderedDict[tuple[int, int, int], set[int]] = \
            OrderedDict()
        # (vid, bid) -> access count since the last signal/aging/invalidate
        self._heat: dict[tuple[int, int], int] = {}
        self._heat_total = 0
        self._reg = registry("cache")
        declare_label_values("tier", ("mem", "disk"))

    @classmethod
    def from_env(cls, cache_dir: str) -> "BlobCache | None":
        """The deployment wiring: a cache only when CFS_CACHE_MB is set to a
        positive budget — unset keeps every existing path byte-identical."""
        try:
            mem_mb = int(os.environ.get("CFS_CACHE_MB", "0") or 0)
        except ValueError:
            mem_mb = 0
        if mem_mb <= 0:
            return None
        return cls(cache_dir, mem_mb=mem_mb)

    # -- keying ----------------------------------------------------------------

    def _version(self, vid: int, bid: int) -> int:
        ver = self._ver.get((vid, bid))
        return 0 if ver is None else ver[0]

    @staticmethod
    def _key(vid: int, bid: int, ver: int, blk: int) -> str:
        return f"b_{vid}_{bid}_{ver}_{blk}"

    # -- read path -------------------------------------------------------------

    def get(self, vid: int, bid: int, offset: int = 0,
            size: int | None = None) -> bytes | None:
        """Ranged lookup assembled from the covering blocks — a hit ONLY
        when every block the window touches is present (a torn answer is a
        miss, never a short read). One plane-level hit/miss per lookup;
        every call (hit or miss) is a heat sample."""
        self._reg.counter("lookups").add()
        with self._lock:
            ver = self._version(vid, bid)
            self._note_heat_locked(vid, bid)
            total = self._total.get((vid, bid))
        if size is None:
            if total is None:  # blob size never learned: can't enumerate
                self._reg.counter("misses").add()
                return None
            size = max(0, total - offset)
        B = self.block
        out = bytearray()
        for blk in range(offset // B, (offset + size - 1) // B + 1):
            lo = max(offset, blk * B) - blk * B
            hi = min(offset + size, (blk + 1) * B) - blk * B
            piece = self.mgr.get(self._key(vid, bid, ver, blk), lo, hi - lo)
            if piece is None or len(piece) != hi - lo:
                self._reg.counter("misses").add()
                return None
            out += piece
        self._reg.counter("hits").add()
        return bytes(out)

    def fill_version(self, vid: int, bid: int) -> int:
        """Capture the blob's version BEFORE reading the backend; pass it to
        fill() so a fill whose backend read straddled an invalidation can
        never land reachable bytes."""
        with self._lock:
            return self._version(vid, bid)

    def fill(self, vid: int, bid: int, ver: int, data: bytes,
             offset: int = 0, total: int | None = None) -> bool:
        """Store the blocks `data` (a window at `offset` of a `total`-byte
        blob) fully covers; the tail block is storable short once `total`
        proves it complete. A whole-blob fill (offset 0, no total) infers
        total=len(data). Returns True when every covered block landed."""
        if total is None and offset == 0:
            total = len(data)
        with self._lock:
            if ver != self._version(vid, bid):
                self._reg.counter("stale_fills").add()
                return False
        B = self.block
        end = offset + len(data)
        written: list[int] = []
        ok = True
        stored_any = False
        first_blk = (offset + B - 1) // B  # partial leading block: skipped
        for blk in range(first_blk, (end + B - 1) // B):
            b_lo = blk * B
            b_hi = min(b_lo + B, total) if total is not None else b_lo + B
            if b_hi <= b_lo or b_hi > end:
                continue  # block not fully covered by this window
            if self.mgr.put(self._key(vid, bid, ver, blk),
                            data[b_lo - offset: b_hi - offset]):
                written.append(blk)
                stored_any = True
            else:
                ok = False  # admission rejected this block
        # re-check AFTER the store writes: an invalidate that raced the puts
        # may have punched before the bytes landed — its version bump
        # happens-before its evict, so a still-matching version here proves
        # the blocks were not punched behind us, and a mismatch means we
        # must take our own bytes back out
        with self._lock:
            landed_stale = ver != self._version(vid, bid)
            if not landed_stale and written:
                blks = self._blocks.setdefault((vid, bid, ver), set())
                blks.update(written)
                self._blocks.move_to_end((vid, bid, ver))
                if total is not None:
                    self._total[(vid, bid)] = total
                    self._total.move_to_end((vid, bid))
                evictions = self._prune_ledgers_locked()
            else:
                evictions = []
        if landed_stale:
            for blk in written:
                self.mgr.evict(self._key(vid, bid, ver, blk))
            self._reg.counter("stale_fills").add()
            return False
        for key in evictions:  # ledger overflow: punch what it forgot
            self.mgr.evict(key)
        if not stored_any:
            self._reg.counter("fill_rejects").add()
            return False
        self._reg.counter("fills" if ok else "fill_rejects").add()
        return ok

    def _prune_ledgers_locked(self) -> list[str]:
        """Bound the fill/total ledgers; returns store keys the caller must
        evict (outside the lock) for ledger entries being forgotten — an
        untracked-but-reachable block would survive its invalidate."""
        evictions: list[str] = []
        while len(self._blocks) > _VER_MAX:
            (vid, bid, ver), blks = self._blocks.popitem(last=False)
            evictions.extend(self._key(vid, bid, ver, b) for b in blks)
        while len(self._total) > _VER_MAX:
            self._total.popitem(last=False)  # size=None lookups degrade
        return evictions

    # -- invalidation (write-through punch-out) --------------------------------

    def invalidate(self, vid: int, bid: int) -> None:
        """Punch the blob out: evict every filled block and bump its
        version. Callers invalidate BEFORE queueing the backend
        delete/punch, so by the time shards disappear no cached copy is
        reachable — the failpoint lets chaos stretch that window and prove
        the ordering carries it."""
        chaos.failpoint("cache.invalidate")
        with self._lock:
            cur, _ = self._ver.get((vid, bid), (0, 0.0))
            self._ver[(vid, bid)] = (cur + 1, time.monotonic())
            self._ver.move_to_end((vid, bid))
            self._heat.pop((vid, bid), None)
            self._total.pop((vid, bid), None)
            blks = self._blocks.pop((vid, bid, cur), set())
            self._prune_vers_locked()
        for blk in blks:
            self.mgr.evict(self._key(vid, bid, cur, blk))
        self._reg.counter("invalidations").add()

    def _prune_vers_locked(self) -> None:
        """Bound the version map: entries whose bump is older than the
        minimum-age floor can go — any fill that captured the pre-bump
        version has long since landed (unreachable, or self-evicted by the
        post-put re-check) or died, and the blocks were evicted at bump
        time, so forgetting the version cannot resurrect anything."""
        if len(self._ver) <= _VER_MAX:
            return
        # the map is in bump order, so the oldest entries sit at the front:
        # pop from there down to the cap, stopping at the minimum-age floor
        # (see _VER_MIN_AGE_S) — O(evicted), no scan or sort under the lock
        # every GET's version read also takes. In a storm where even the
        # front is younger than the floor the map temporarily exceeds the
        # cap, bounded by _VER_MIN_AGE_S worth of delete churn.
        floor = time.monotonic() - _VER_MIN_AGE_S
        while len(self._ver) > _VER_MAX:
            key, (_, ts) = next(iter(self._ver.items()))
            if ts > floor:
                break
            del self._ver[key]

    # -- heat / promotion signals ----------------------------------------------

    def _note_heat_locked(self, vid: int, bid: int) -> None:
        key = (vid, bid)
        self._heat[key] = self._heat.get(key, 0) + 1
        self._heat_total += 1
        if len(self._heat) > _HEAT_MAX:
            # keep the hot half; the dropped tail was never promotable
            keep = sorted(self._heat.items(), key=lambda kv: -kv[1])
            self._heat = dict(keep[: _HEAT_MAX // 2])
        if self._heat_total >= 16 * _HEAT_MAX:
            # aging: halve so the signal tracks SUSTAINED heat
            self._heat = {k: v >> 1 for k, v in self._heat.items() if v > 1}
            self._heat_total //= 2

    def promote_signal(self, vid: int, bid: int) -> bool:
        """True once per CFS_PROMOTE_HITS accesses (the counter resets on
        signal) — the caller forwards it to the hot-blob topic. A blob that
        STAYS hot keeps signalling every promote_hits accesses, which is
        what keeps the scheduler's idle-sweep demoter from evicting a
        still-hot blob out of the hot tier: signal silence really means
        'fewer than promote_hits accesses per demote window'."""
        if self.promote_hits <= 0:
            return False
        key = (vid, bid)
        with self._lock:
            if self._heat.get(key, 0) < self.promote_hits:
                return False
            self._heat[key] = 0
        self._reg.counter("promote_signals").add()
        return True

    def stats(self) -> dict:
        return self.mgr.stats()
