"""Distributed tracing — spans with in-band propagation + RPC track logs.

Reference counterpart: blobstore/common/trace (tracer.go:34 opentracing
aliases, span.go:25-35) — every blobstore ctx carries a span; services append
"track log" entries (module:latency/result) that ride response headers so the
access gateway can log one line covering the whole fan-out (used at
access/stream_put.go:47,100). Kept: trace-id propagation, child spans, track
logs appended bottom-up. The carrier is a plain dict standing in for HTTP
headers (inject/extract), so both in-process and HTTP hops propagate the same
way; the packet TCP wire carries the same two fields in its arg blob
(proto/packet.py trace_inject/trace_reply).

Track logs are BOUNDED: at most TRACK_MAX entries per span (a failpoint-looped
fan-out must not blow the response-header budget), and module names are
sanitized (`;`/newlines/`:` would corrupt the ';'-joined wire form).

Beyond the wire-form track log, every span is a STRUCTURED record: a span id,
its parent (in-process parent span, or the remote caller's span id carried
next to the trace id), a wall-clock start stamp plus monotonic duration, and
named STAGES — (name, offset, duration) attributions inside the span
(encode device time, raft commit wait, pool checkout...) that the
critical-path analyzer (tools/cfstrace.py) projects onto the request's wall
time. `finish()` hands the span to the trace sink (utils/tracesink.py) when
one is installed; with no sink the hook is a single None check.
"""

from __future__ import annotations

import threading
import time
import uuid

TRACE_ID_KEY = "Trace-Id"
TRACK_LOG_KEY = "Trace-Tracklog"
SPAN_ID_KEY = "Trace-Span-Id"

# hard cap on track entries per span: deep fan-outs degrade to a truncated
# track log, never to an unbounded response header
TRACK_MAX = 64
# stage attributions are richer than track entries but just as bounded: a
# retry-looped hop must not grow a span record without limit
STAGE_MAX = 128
_ENTRY_MAX = 128  # one hostile module name must not be the whole header

# sink hook installed by utils/tracesink (None = tracing-only, zero
# persistence work); called with the finished span, must never raise
_finish_hook = None


def set_finish_hook(fn) -> None:
    """Install (or clear, with None) the span-finish hook the trace sink
    rides. Process-global, like the span machinery itself."""
    global _finish_hook
    _finish_hook = fn


def finish_hook():
    """The currently installed span-finish hook (None if none) — a caller
    that temporarily swaps its own hook in must save this and CHAIN to it,
    or an active trace sink silently loses every span it swallows."""
    return _finish_hook


def union_len(intervals) -> float:
    """Total length of the union of [s, e) intervals (overlap counts once).
    THE sweep-line both overlap consumers share — the scheduler's
    repair-span overlap ratio and cfs-trace's critical-path/stage-overlap
    analyzers must agree on this math or their reported ratios drift."""
    total = 0.0
    end = float("-inf")
    for s, e in sorted(intervals):
        if e <= end:
            continue
        total += e - max(s, end)
        end = e
    return total


def intersect_len(a, b) -> float:
    """Length of the intersection of two interval unions (inclusion-
    exclusion over union_len): how long BOTH families were active at once."""
    if not a or not b:
        return 0.0
    return union_len(a) + union_len(b) - union_len(list(a) + list(b))


def overlap_ratio(a, b) -> float | None:
    """Intersection of two interval-union families over the SMALLER union —
    1.0 means the lesser family ran entirely inside the greater (perfect
    pipelining), 0.0 means strictly back-to-back, None means either side
    never happened. THE ratio definition shared by the scheduler's
    repair-span metric and cfs-trace's --overlap report: one implementation
    so the dashboard number and the CLI report can never drift apart."""
    if not a or not b:
        return None
    floor = min(union_len(a), union_len(b))
    return (intersect_len(a, b) / floor) if floor > 0 else 0.0

_local = threading.local()

_SANITIZE = str.maketrans({";": "_", ":": "_", "\n": "_", "\r": "_"})
# a whole entry keeps its own "module:ms" colon; only the separators that
# would corrupt the ';'-joined wire form are rewritten
_SANITIZE_ENTRY = str.maketrans({";": "_", "\n": "_", "\r": "_"})


def sanitize_module(module: str) -> str:
    """Track-log entries are ';'-joined and ':'-split downstream; a module
    name carrying either (or newlines, which break log lines) is rewritten."""
    return str(module).translate(_SANITIZE)[:_ENTRY_MAX]


class Span:
    def __init__(self, operation: str, trace_id: str | None = None,
                 parent: "Span | None" = None):
        self.operation = operation
        # lazy: the id mints on first READ. Dispatch loops create a span per
        # packet/VFS op unconditionally; an untraced op whose id nobody asks
        # for must not pay os.urandom entropy on the hot path.
        self._trace_id = trace_id or (parent.trace_id if parent else None)
        self.parent = parent
        self.start = time.perf_counter()
        # wall stamp pairs records from different processes onto one
        # timeline (same-host skew only); NEVER used for durations — those
        # stay on the monotonic clock
        self.start_wall = time.time()
        self.tags: dict[str, object] = {}
        self.logs: list[tuple[float, str]] = []
        self.track: list[str] = []  # track-log entries, e.g. "blobnode:12"
        self.track_dropped = 0  # entries the TRACK_MAX cap swallowed
        # named in-span attributions: (name, offset_s from start, dur_s)
        self.stages: list[tuple[str, float, float]] = []
        self.stage_dropped = 0
        # span id of the remote CALLER's span when this span continued a
        # carrier that named one (the cross-process parent edge)
        self.remote_parent: str | None = None
        self._span_id: str | None = None
        self.finished_us: int | None = None

    @property
    def trace_id(self) -> str:
        if self._trace_id is None:
            self._trace_id = uuid.uuid4().hex[:16]
        return self._trace_id

    @property
    def span_id(self) -> str:
        # lazy like trace_id: minted only when someone records/propagates it
        if self._span_id is None:
            self._span_id = uuid.uuid4().hex[:16]
        return self._span_id

    # -- opentracing-style surface ---------------------------------------------
    def set_tag(self, k: str, v) -> "Span":
        self.tags[k] = v
        return self

    def log(self, msg: str):
        self.logs.append((time.perf_counter() - self.start, msg))

    def _push_track(self, entry: str):
        if len(self.track) >= TRACK_MAX:
            if self.track_dropped == 0:
                # first drop on this span: count it (cold path — truncation
                # is the anomaly the counter exists to surface)
                try:
                    from chubaofs_tpu.utils.exporter import registry

                    registry("trace").counter("track_truncated").add()
                except Exception:
                    pass
            self.track_dropped += 1
            return
        self.track.append(entry)

    def add_stage(self, name: str, start: float, dur: float | None = None):
        """Attribute a named stage of this span: `start` is a
        time.perf_counter() stamp (any thread — one global clock), `dur`
        seconds (elapsed-since-start when omitted). Bounded by STAGE_MAX."""
        if dur is None:
            dur = time.perf_counter() - start
        if len(self.stages) >= STAGE_MAX:
            self.stage_dropped += 1
            return
        self.stages.append((sanitize_module(name), start - self.start, dur))

    def append_track_log(self, module: str, start: float | None = None,
                         err: Exception | None = None):
        """stream_put.go:100-style: module + elapsed ms + error class."""
        ms = int(((time.perf_counter() - (start or self.start)) * 1000))
        entry = f"{sanitize_module(module)}:{ms}"
        if err is not None:
            entry += f"/{sanitize_module(type(err).__name__)}"
        self._push_track(entry)

    def merge_track(self, entries):
        """Fold a remote hop's track entries (list or ';'-joined string) into
        this span, sanitized and bounded — the client side of a reply that
        carried a track log back."""
        if not entries:
            return
        if isinstance(entries, str):
            entries = entries.split(";")
        for e in entries:
            e = str(e).translate(_SANITIZE_ENTRY)[:_ENTRY_MAX]
            if e:
                self._push_track(e)

    def finish(self):
        if self.finished_us is None:
            self.finished_us = int((time.perf_counter() - self.start) * 1e6)
            if self.parent is not None:
                for e in self.track:
                    self.parent._push_track(e)
                self.parent.track_dropped += self.track_dropped
            hook = _finish_hook
            if hook is not None:
                try:
                    hook(self)
                except Exception:
                    pass  # a sink failure must never fail the traced op

    def __enter__(self):
        push_span(self)
        return self

    def __exit__(self, et, ev, tb):
        self.finish()
        pop_span()
        return False

    # -- propagation -----------------------------------------------------------
    def track_entries(self) -> list[str]:
        """Track entries as they go on the wire (always a fresh list — a
        caller may attach it to a reply that outlives this span's next
        append): a dropped-entry count is no longer silent — the
        `...truncated:<n>` sentinel rides in-band so a reader knows the log
        is a prefix, not the whole story."""
        if self.track_dropped:
            return self.track + [f"...truncated:{self.track_dropped}"]
        return list(self.track)

    def inject(self, carrier: dict):
        carrier[TRACE_ID_KEY] = self.trace_id
        carrier[SPAN_ID_KEY] = self.span_id
        if self.track:
            carrier[TRACK_LOG_KEY] = ";".join(self.track_entries())

    def track_log_string(self) -> str:
        return ";".join(self.track_entries())

    def modules(self) -> set[str]:
        """Distinct module names present in the track log."""
        return {e.split(":", 1)[0] for e in self.track if e}

    def to_record(self) -> dict:
        """The span as a JSON-able SpanRecord — what the trace sink persists
        and /traces serves; tools/cfstrace.py reassembles trees from these."""
        dur = self.finished_us
        if dur is None:  # unfinished span recorded early (best effort)
            dur = int((time.perf_counter() - self.start) * 1e6)
        rec: dict = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": (self.parent.span_id if self.parent is not None
                               else self.remote_parent),
            "op": self.operation,
            "start": round(self.start_wall, 6),
            "dur_us": dur,
        }
        if self.stages:
            rec["stages"] = [[n, int(off * 1e6), int(d * 1e6)]
                             for n, off, d in self.stages]
        if self.stage_dropped:
            rec["stages_dropped"] = self.stage_dropped
        if self.tags:
            rec["tags"] = dict(self.tags)
        if self.track:
            rec["track"] = self.track_log_string()
        return rec


def extract_trace_id(carrier: dict | None) -> str | None:
    """Trace id from a carrier dict, tolerant of lower-cased header keys
    (rpc Request lower-cases everything)."""
    if not carrier:
        return None
    return carrier.get(TRACE_ID_KEY) or carrier.get(TRACE_ID_KEY.lower())


def extract_span_id(carrier: dict | None) -> str | None:
    """The remote caller's span id, same lower-case tolerance."""
    if not carrier:
        return None
    return carrier.get(SPAN_ID_KEY) or carrier.get(SPAN_ID_KEY.lower())


def start_span(operation: str, carrier: dict | None = None) -> Span:
    """New root (or remote-continued, when carrier holds a trace id) span."""
    span = Span(operation, trace_id=extract_trace_id(carrier))
    if carrier:
        span.remote_parent = extract_span_id(carrier)
        tl = carrier.get(TRACK_LOG_KEY) or carrier.get(TRACK_LOG_KEY.lower())
        if tl:
            span.merge_track(tl)
    return span


def child_of(parent: Span | None, operation: str) -> Span:
    return Span(operation, parent=parent) if parent else Span(operation)


def push_span(span: Span):
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(span)


def pop_span():
    stack = getattr(_local, "stack", None)
    if stack:
        stack.pop()


def current_span() -> Span | None:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None
