"""Distributed tracing — spans with in-band propagation + RPC track logs.

Reference counterpart: blobstore/common/trace (tracer.go:34 opentracing
aliases, span.go:25-35) — every blobstore ctx carries a span; services append
"track log" entries (module:latency/result) that ride response headers so the
access gateway can log one line covering the whole fan-out (used at
access/stream_put.go:47,100). Kept: trace-id propagation, child spans, track
logs appended bottom-up. The carrier is a plain dict standing in for HTTP
headers (inject/extract), so both in-process and HTTP hops propagate the same
way; the packet TCP wire carries the same two fields in its arg blob
(proto/packet.py trace_inject/trace_reply).

Track logs are BOUNDED: at most TRACK_MAX entries per span (a failpoint-looped
fan-out must not blow the response-header budget), and module names are
sanitized (`;`/newlines/`:` would corrupt the ';'-joined wire form).
"""

from __future__ import annotations

import threading
import time
import uuid

TRACE_ID_KEY = "Trace-Id"
TRACK_LOG_KEY = "Trace-Tracklog"

# hard cap on track entries per span: deep fan-outs degrade to a truncated
# track log, never to an unbounded response header
TRACK_MAX = 64
_ENTRY_MAX = 128  # one hostile module name must not be the whole header

_local = threading.local()

_SANITIZE = str.maketrans({";": "_", ":": "_", "\n": "_", "\r": "_"})
# a whole entry keeps its own "module:ms" colon; only the separators that
# would corrupt the ';'-joined wire form are rewritten
_SANITIZE_ENTRY = str.maketrans({";": "_", "\n": "_", "\r": "_"})


def sanitize_module(module: str) -> str:
    """Track-log entries are ';'-joined and ':'-split downstream; a module
    name carrying either (or newlines, which break log lines) is rewritten."""
    return str(module).translate(_SANITIZE)[:_ENTRY_MAX]


class Span:
    def __init__(self, operation: str, trace_id: str | None = None,
                 parent: "Span | None" = None):
        self.operation = operation
        # lazy: the id mints on first READ. Dispatch loops create a span per
        # packet/VFS op unconditionally; an untraced op whose id nobody asks
        # for must not pay os.urandom entropy on the hot path.
        self._trace_id = trace_id or (parent.trace_id if parent else None)
        self.parent = parent
        self.start = time.perf_counter()
        self.tags: dict[str, object] = {}
        self.logs: list[tuple[float, str]] = []
        self.track: list[str] = []  # track-log entries, e.g. "blobnode:12"
        self.track_dropped = 0  # entries the TRACK_MAX cap swallowed
        self.finished_us: int | None = None

    @property
    def trace_id(self) -> str:
        if self._trace_id is None:
            self._trace_id = uuid.uuid4().hex[:16]
        return self._trace_id

    # -- opentracing-style surface ---------------------------------------------
    def set_tag(self, k: str, v) -> "Span":
        self.tags[k] = v
        return self

    def log(self, msg: str):
        self.logs.append((time.perf_counter() - self.start, msg))

    def _push_track(self, entry: str):
        if len(self.track) >= TRACK_MAX:
            self.track_dropped += 1
            return
        self.track.append(entry)

    def append_track_log(self, module: str, start: float | None = None,
                         err: Exception | None = None):
        """stream_put.go:100-style: module + elapsed ms + error class."""
        ms = int(((time.perf_counter() - (start or self.start)) * 1000))
        entry = f"{sanitize_module(module)}:{ms}"
        if err is not None:
            entry += f"/{sanitize_module(type(err).__name__)}"
        self._push_track(entry)

    def merge_track(self, entries):
        """Fold a remote hop's track entries (list or ';'-joined string) into
        this span, sanitized and bounded — the client side of a reply that
        carried a track log back."""
        if not entries:
            return
        if isinstance(entries, str):
            entries = entries.split(";")
        for e in entries:
            e = str(e).translate(_SANITIZE_ENTRY)[:_ENTRY_MAX]
            if e:
                self._push_track(e)

    def finish(self):
        if self.finished_us is None:
            self.finished_us = int((time.perf_counter() - self.start) * 1e6)
            if self.parent is not None:
                for e in self.track:
                    self.parent._push_track(e)
                self.parent.track_dropped += self.track_dropped

    def __enter__(self):
        push_span(self)
        return self

    def __exit__(self, et, ev, tb):
        self.finish()
        pop_span()
        return False

    # -- propagation -----------------------------------------------------------
    def inject(self, carrier: dict):
        carrier[TRACE_ID_KEY] = self.trace_id
        if self.track:
            carrier[TRACK_LOG_KEY] = ";".join(self.track)

    def track_log_string(self) -> str:
        return ";".join(self.track)

    def modules(self) -> set[str]:
        """Distinct module names present in the track log."""
        return {e.split(":", 1)[0] for e in self.track if e}


def extract_trace_id(carrier: dict | None) -> str | None:
    """Trace id from a carrier dict, tolerant of lower-cased header keys
    (rpc Request lower-cases everything)."""
    if not carrier:
        return None
    return carrier.get(TRACE_ID_KEY) or carrier.get(TRACE_ID_KEY.lower())


def start_span(operation: str, carrier: dict | None = None) -> Span:
    """New root (or remote-continued, when carrier holds a trace id) span."""
    span = Span(operation, trace_id=extract_trace_id(carrier))
    if carrier:
        tl = carrier.get(TRACK_LOG_KEY) or carrier.get(TRACK_LOG_KEY.lower())
        if tl:
            span.merge_track(tl)
    return span


def child_of(parent: Span | None, operation: str) -> Span:
    return Span(operation, parent=parent) if parent else Span(operation)


def push_span(span: Span):
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(span)


def pop_span():
    stack = getattr(_local, "stack", None)
    if stack:
        stack.pop()


def current_span() -> Span | None:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None
