"""Distributed tracing — spans with in-band propagation + RPC track logs.

Reference counterpart: blobstore/common/trace (tracer.go:34 opentracing
aliases, span.go:25-35) — every blobstore ctx carries a span; services append
"track log" entries (module:latency/result) that ride response headers so the
access gateway can log one line covering the whole fan-out (used at
access/stream_put.go:47,100). Kept: trace-id propagation, child spans, track
logs appended bottom-up. The carrier is a plain dict standing in for HTTP
headers (inject/extract), so both in-process and HTTP hops propagate the same
way.
"""

from __future__ import annotations

import threading
import time
import uuid

TRACE_ID_KEY = "Trace-Id"
TRACK_LOG_KEY = "Trace-Tracklog"

_local = threading.local()


class Span:
    def __init__(self, operation: str, trace_id: str | None = None,
                 parent: "Span | None" = None):
        self.operation = operation
        self.trace_id = trace_id or (parent.trace_id if parent else uuid.uuid4().hex[:16])
        self.parent = parent
        self.start = time.perf_counter()
        self.tags: dict[str, object] = {}
        self.logs: list[tuple[float, str]] = []
        self.track: list[str] = []  # track-log entries, e.g. "blobnode:12ms"
        self.finished_us: int | None = None

    # -- opentracing-style surface ---------------------------------------------
    def set_tag(self, k: str, v) -> "Span":
        self.tags[k] = v
        return self

    def log(self, msg: str):
        self.logs.append((time.perf_counter() - self.start, msg))

    def append_track_log(self, module: str, start: float | None = None,
                         err: Exception | None = None):
        """stream_put.go:100-style: module + elapsed + error class."""
        ms = int(((time.perf_counter() - (start or self.start)) * 1000))
        entry = f"{module}:{ms}"
        if err is not None:
            entry += f"/{type(err).__name__}"
        self.track.append(entry)

    def finish(self):
        if self.finished_us is None:
            self.finished_us = int((time.perf_counter() - self.start) * 1e6)
            if self.parent is not None:
                self.parent.track.extend(self.track)

    def __enter__(self):
        push_span(self)
        return self

    def __exit__(self, et, ev, tb):
        self.finish()
        pop_span()
        return False

    # -- propagation -----------------------------------------------------------
    def inject(self, carrier: dict):
        carrier[TRACE_ID_KEY] = self.trace_id
        if self.track:
            carrier[TRACK_LOG_KEY] = ";".join(self.track)

    def track_log_string(self) -> str:
        return ";".join(self.track)


def start_span(operation: str, carrier: dict | None = None) -> Span:
    """New root (or remote-continued, when carrier holds a trace id) span."""
    tid = carrier.get(TRACE_ID_KEY) if carrier else None
    span = Span(operation, trace_id=tid)
    if carrier and TRACK_LOG_KEY in carrier:
        span.track.extend(carrier[TRACK_LOG_KEY].split(";"))
    return span


def child_of(parent: Span | None, operation: str) -> Span:
    return Span(operation, parent=parent) if parent else Span(operation)


def push_span(span: Span):
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(span)


def pop_span():
    stack = getattr(_local, "stack", None)
    if stack:
        stack.pop()


def current_span() -> Span | None:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None
