"""Tiered buffer pools (blobstore/common/resourcepool analog).

Reference counterpart: common/resourcepool — sized-class []byte pools behind
ec.Buffer allocation (common/ec/buf.go) with a process memory cap; misses fall
through to plain allocation. Kept: power-of-two-ish size classes, per-class
free lists, a capacity limit that makes Alloc fail loudly when the cap would
be exceeded (the reference returns ErrPoolLimit), and zero-fill on reuse for
the EC write path (parity buffers must start clean).
"""

from __future__ import annotations

import threading

DEFAULT_CLASSES = (1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22, 1 << 24)


class PoolLimitError(MemoryError):
    pass


class MemPool:
    def __init__(self, classes=DEFAULT_CLASSES, capacity_bytes: int = 1 << 30):
        self.classes = tuple(sorted(classes))
        self.capacity = capacity_bytes
        self.in_use = 0
        self._free: dict[int, list[bytearray]] = {c: [] for c in self.classes}
        self._lock = threading.Lock()

    def _class_of(self, size: int) -> int:
        for c in self.classes:
            if size <= c:
                return c
        return size  # oversized: exact allocation, still capacity-accounted

    def alloc(self, size: int, zero: bool = True) -> bytearray:
        c = self._class_of(size)
        with self._lock:
            if self.in_use + c > self.capacity:
                raise PoolLimitError(f"pool capacity {self.capacity} exceeded")
            self.in_use += c
            bucket = self._free.get(c)
            buf = bucket.pop() if bucket else None
        if buf is None:
            return bytearray(c)
        if zero:
            buf[:] = bytes(c)
        return buf

    def put(self, buf: bytearray):
        c = len(buf)
        with self._lock:
            self.in_use = max(0, self.in_use - c)
            if c in self._free:
                self._free[c].append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {"in_use": self.in_use, "capacity": self.capacity,
                    "free": {c: len(v) for c, v in self._free.items()}}
