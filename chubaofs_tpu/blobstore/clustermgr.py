"""ClusterMgr — the blobstore control plane.

Reference counterpart: blobstore/clustermgr (raft-replicated managers:
DiskMgr/VolumeMgr/ScopeMgr/ServiceMgr/ConfigMgr, svr.go:123-138; volume creation
places chunks across AZs/racks, volumemgr/createvolume.go; bid/vid scopes,
scopemgr). This single-node engine keeps the same responsibilities and a
WAL+snapshot persistence contract; the consensus layer (chubaofs_tpu/raft) wraps
it for replication.

State model (all mutations go through apply() so a replicated log can drive it):
  * disks: disk_id -> {node_id, az, status, heartbeat}
  * volumes: vid -> {codemode, units: [vuid...], health}; vuid -> (node, disk)
  * scopes: named monotonic id ranges (vid space, bid space)
  * services / config KV
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

from chubaofs_tpu.codec.codemode import CodeMode, get_tactic
from chubaofs_tpu.utils import events
from chubaofs_tpu.utils.locks import SanitizedRLock

DISK_NORMAL = "normal"
DISK_BROKEN = "broken"
DISK_DROPPED = "dropped"

VOL_IDLE = "idle"
VOL_ACTIVE = "active"
VOL_LOCK = "lock"


class ClusterError(Exception):
    pass


@dataclass
class DiskInfo:
    disk_id: int
    node_id: int
    az: int = 0
    rack: str = ""
    status: str = DISK_NORMAL
    last_heartbeat: float = 0.0
    chunk_count: int = 0


@dataclass
class VolumeUnit:
    vuid: int
    index: int  # stripe position 0..total-1
    disk_id: int
    node_id: int
    epoch: int = 1


@dataclass
class VolumeInfo:
    vid: int
    code_mode: int
    units: list[VolumeUnit] = field(default_factory=list)
    status: str = VOL_IDLE
    used: int = 0
    capacity: int = 1 << 30

    def tactic(self):
        return get_tactic(self.code_mode)


def make_vuid(vid: int, index: int, epoch: int = 1) -> int:
    """vuid encodes (vid, stripe index, epoch) in one integer."""
    return (vid << 24) | (index << 8) | epoch


def parse_vuid(vuid: int) -> tuple[int, int, int]:
    return vuid >> 24, (vuid >> 8) & 0xFFFF, vuid & 0xFF


class ClusterMgr:
    """Single-group state machine; every mutation is an (op, args) apply."""

    def __init__(self, data_dir: str | None = None):
        self._lock = SanitizedRLock(name="clustermgr")
        self.disks: dict[int, DiskInfo] = {}
        self.volumes: dict[int, VolumeInfo] = {}
        self.scopes: dict[str, int] = {}
        self.services: dict[str, list[str]] = {}
        self.config: dict[str, str] = {}
        # tier residency map (ISSUE 12): (vid, bid) -> (hot_vid, hot_bid)
        # for blobs the promoter copied into the Replica3 hot engine. The
        # ORIGINAL EC copy stays authoritative (Location tokens keep
        # working); the map is a read-path redirect, replicated like every
        # other mutation so a restarted gateway keeps serving hot reads.
        self.tiermap: dict[tuple[int, int], tuple[int, int]] = {}
        # monotonic heartbeat observations, THIS process only (never
        # persisted — a wall-clock stamp would be meaningless arithmetic
        # across restarts, and expiry is a liveness judgment about what this
        # clustermgr has itself observed). Restored disks stamp "now" so a
        # freshly-loaded cluster gets a full grace window before any expiry.
        self._hb_mono: dict[int, float] = {}
        self._data_dir = data_dir
        self._db = None
        self._seq = 0  # last applied wal sequence
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            from chubaofs_tpu.utils.kvstore import open_kv

            self._db = open_kv(os.path.join(data_dir, "kv"))
            self._load()
        self._refresh_disk_gauges()

    # -- persistence (state in the native kvstore, the RocksDB role of
    # blobstore/common/kvstore under clustermgr) ----------------------------
    #
    # Keys: "snap" (json state) + "snap_seq" written atomically in one batch,
    # "w/<seq>" for WAL entries after the snapshot. A crash anywhere leaves
    # either the old snapshot + its WAL tail or the new snapshot with the
    # old WAL keys deleted in the same atomic batch — never a double replay.

    @staticmethod
    def _wal_key(seq: int) -> bytes:
        return b"w/%020d" % seq

    def _load(self):
        self._migrate_legacy()
        snap = self._db.get(b"snap")
        if snap is not None:
            self._seq = int(self._db.get(b"snap_seq") or b"0")
            self._restore(json.loads(snap))
        for k, v in self._db.scan(prefix=b"w/", start=self._wal_key(self._seq + 1)):
            op, args = json.loads(v)
            self._apply(op, args, replay=True)
            self._seq = int(k[2:])

    def _migrate_legacy(self):
        """One-time import of the earlier snapshot.json + wal-N.jsonl files."""
        snap = os.path.join(self._data_dir, "snapshot.json")
        legacy_wals = sorted(
            f for f in os.listdir(self._data_dir)
            if f.startswith("wal-") and f.endswith(".jsonl"))
        if not os.path.exists(snap) and not legacy_wals:
            return
        wal_id = 0
        if os.path.exists(snap):
            with open(snap) as f:
                payload = json.load(f)
            wal_id = payload.get("wal_id", 0)
            self._restore(payload["state"])
        wal = os.path.join(self._data_dir, f"wal-{wal_id}.jsonl")
        if os.path.exists(wal):
            with open(wal) as f:
                for line in f:
                    if line.strip():
                        op, args = json.loads(line)
                        self._apply(op, args, replay=True)
        self._db.write_batch(puts=[(b"snap", json.dumps(self.snapshot()).encode()),
                                   (b"snap_seq", b"0")])
        for f in legacy_wals + (["snapshot.json"] if os.path.exists(snap) else []):
            os.replace(os.path.join(self._data_dir, f),
                       os.path.join(self._data_dir, f + ".migrated"))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "disks": {i: d.__dict__ for i, d in self.disks.items()},
                "volumes": {
                    v: {**info.__dict__, "units": [u.__dict__ for u in info.units]}
                    for v, info in self.volumes.items()
                },
                "scopes": dict(self.scopes),
                "services": {k: list(v) for k, v in self.services.items()},
                "config": dict(self.config),
                "tiermap": [[v, b, hv, hb]
                            for (v, b), (hv, hb) in self.tiermap.items()],
            }

    def _restore(self, snap: dict):
        self.disks = {int(i): DiskInfo(**d) for i, d in snap["disks"].items()}
        now = time.monotonic()
        self._hb_mono = {i: now for i in self.disks}
        self.volumes = {}
        for v, info in snap["volumes"].items():
            units = [VolumeUnit(**u) for u in info.pop("units")]
            self.volumes[int(v)] = VolumeInfo(**{**info, "units": units})
        self.scopes = dict(snap["scopes"])
        self.services = {k: list(v) for k, v in snap["services"].items()}
        self.config = dict(snap["config"])
        # .get: snapshots from before the tier map existed
        self.tiermap = {(v, b): (hv, hb)
                        for v, b, hv, hb in snap.get("tiermap", [])}

    def checkpoint(self):
        """Fold the WAL into a fresh snapshot in ONE atomic kv batch: the new
        snapshot, its sequence floor, and the deletion of every folded WAL
        entry land together or not at all (RocksDB checkpoint discipline)."""
        if not self._db:
            return
        with self._lock:
            wal_keys = [k for k, _ in self._db.scan(prefix=b"w/")]
            self._db.write_batch(
                puts=[(b"snap", json.dumps(self.snapshot()).encode()),
                      (b"snap_seq", str(self._seq).encode())],
                deletes=wal_keys)

    def _apply(self, op: str, args: dict, replay: bool = False):
        handler = getattr(self, "_op_" + op)
        out = handler(**args)
        if self._db and not replay:
            self._seq += 1
            self._db.put(self._wal_key(self._seq), json.dumps([op, args]).encode())
        return out

    def _apply_batch(self, ops: list[tuple[str, dict]]) -> list:
        """Apply many ops with ONE durable kv write batch — the raft
        group-commit analog at this store's WAL layer (lock held by caller).
        Ops already applied before a mid-batch failure still reach the WAL."""
        out, puts = [], []
        try:
            for op, args in ops:
                out.append(getattr(self, "_op_" + op)(**args))
                if self._db:
                    self._seq += 1
                    puts.append((self._wal_key(self._seq),
                                 json.dumps([op, args]).encode()))
        finally:
            if self._db and puts:
                self._db.write_batch(puts=puts)
        return out

    def close(self):
        if self._db is not None:
            self._db.close()
            self._db = None

    def apply(self, op: str, args: dict):
        with self._lock:
            return self._apply(op, args)

    # -- scope mgr ----------------------------------------------------------

    def alloc_scope(self, name: str, count: int = 1) -> tuple[int, int]:
        """Allocate [first, last] inclusive monotonic ids from a named scope."""
        return self.apply("alloc_scope", {"name": name, "count": count})

    def _op_alloc_scope(self, name: str, count: int):
        cur = self.scopes.get(name, 0)
        self.scopes[name] = cur + count
        return (cur + 1, cur + count)

    # -- disk mgr -----------------------------------------------------------

    def register_disk(self, disk_id: int, node_id: int, az: int = 0, rack: str = "") -> None:
        self.apply("register_disk", {"disk_id": disk_id, "node_id": node_id, "az": az, "rack": rack})
        self._refresh_disk_gauges()

    def register_disks(self, specs: list[dict]) -> None:
        """Register many disks in ONE batched WAL commit (cluster bring-up:
        a node's whole disk set lands as a single kv write batch)."""
        with self._lock:
            self._apply_batch([
                ("register_disk", {"az": 0, "rack": "", **s}) for s in specs])
        self._refresh_disk_gauges()

    def _op_register_disk(self, disk_id: int, node_id: int, az: int, rack: str):
        if disk_id not in self.disks:  # racelint: _op_* appliers only run under self._lock (apply/_apply_batch take it)
            self.disks[disk_id] = DiskInfo(disk_id, node_id, az, rack)
        self.disks[disk_id].last_heartbeat = time.time()
        self._hb_mono[disk_id] = time.monotonic()  # racelint: _op_* appliers only run under self._lock (apply/_apply_batch take it)

    def heartbeat_disk(self, disk_id: int,
                       chunk_count: int | None = None) -> None:
        """Liveness beat. NOT an apply(): heartbeats are observations, not
        replicated state transitions — a WAL entry per beat per disk would
        bloat the log for zero recovery value (the reference batches them
        in memory the same way). chunk_count=None leaves the placement
        bookkeeping alone: clustermgr's own unit accounting is
        authoritative, and a node's physical chunk count legitimately lags
        volume creation (chunks materialize at first write)."""
        with self._lock:
            d = self.disks.get(disk_id)
            if d is None:
                raise ClusterError(f"unknown disk {disk_id}")
            d.last_heartbeat = time.time()
            self._hb_mono[disk_id] = time.monotonic()
            if chunk_count is not None:
                d.chunk_count = chunk_count

    def _op_heartbeat_disk(self, disk_id: int, chunk_count: int):
        # retained for WAL replay of pre-heartbeat-rework logs
        d = self.disks.get(disk_id)
        if d is None:
            raise ClusterError(f"unknown disk {disk_id}")
        d.last_heartbeat = time.time()
        self._hb_mono[disk_id] = time.monotonic()  # racelint: _op_* appliers only run under self._lock (apply/_apply_batch take it)
        d.chunk_count = chunk_count

    def disk_status(self, disk_id: int) -> str | None:
        """Current status of one disk (None if unknown) — the read half of
        the report-broken handshake: a reporter must not flip a disk that
        already left NORMAL (broken is being repaired, dropped IS repaired)."""
        with self._lock:
            d = self.disks.get(disk_id)
            return None if d is None else d.status

    def set_disk_status(self, disk_id: int, status: str,
                        reason: str = "report") -> None:
        """The ONE public disk-status transition (the error-count path:
        blobnode heartbeats report broken disks through here; repair
        completion drops them through here too). The transition lands on
        the event timeline — a WAL replay does not (it re-applies state,
        it is not a fresh transition)."""
        with self._lock:
            d = self.disks.get(disk_id)
            old = d.status if d is not None else None
            self._apply("set_disk_status",
                        {"disk_id": disk_id, "status": status})
            # gauge + timeline record land INSIDE the (re-entrant) lock:
            # the lock serializes every transition, so the timeline's order
            # matches the state machine's — a repair lease observed after
            # this broken-flip can never carry an earlier stamp (the same
            # contract the scheduler's lease emitters keep)
            self._refresh_disk_gauges()
            if old != status:
                self._emit_disk_event(disk_id, old, status, reason)

    def _emit_disk_event(self, disk_id: int, old: str | None, status: str,
                         reason: str) -> None:
        with self._lock:
            node_id = self.disks[disk_id].node_id \
                if disk_id in self.disks else -1
        events.emit(
            "disk_status",
            events.SEV_CRITICAL if status == DISK_BROKEN else events.SEV_INFO,
            entity=f"disk{disk_id}",
            detail={"disk_id": disk_id, "node_id": node_id,
                    "from": old, "to": status, "reason": reason})

    def _refresh_disk_gauges(self) -> None:
        """cfs_clustermgr_disks{status} gauges — the broken-disk count the
        alert plane evaluates (bounded label: the three status literals)."""
        from chubaofs_tpu.utils.exporter import registry

        with self._lock:
            counts = {DISK_NORMAL: 0, DISK_BROKEN: 0, DISK_DROPPED: 0}
            for d in self.disks.values():
                counts[d.status] = counts.get(d.status, 0) + 1
        reg = registry("clustermgr")
        for status, n in counts.items():
            reg.gauge("disks", {"status": status}).set(n)

    def _op_set_disk_status(self, disk_id: int, status: str):
        if disk_id not in self.disks:
            raise ClusterError(f"unknown disk {disk_id}")
        self.disks[disk_id].status = status

    # -- volume mgr ---------------------------------------------------------

    def create_volume(self, code_mode: CodeMode | int) -> VolumeInfo:
        """Place one chunk per stripe position on distinct disks, AZ-aware.

        Reference: volumemgr/createvolume.go — data/parity/local shards of one
        AZ land on that AZ's disks, no two units of a volume share a disk."""
        mode = int(code_mode)
        t = get_tactic(mode)
        with self._lock:
            healthy = [d for d in self.disks.values() if d.status == DISK_NORMAL]
            by_az: dict[int, list[DiskInfo]] = {}
            for d in healthy:
                by_az.setdefault(d.az, []).append(d)
            azs = sorted(by_az)
            if len(azs) < t.az_count:
                raise ClusterError(
                    f"codemode needs {t.az_count} AZs, cluster has {len(azs)}"
                )
            # check capacity per AZ
            per_az = t.total // t.az_count
            placements: list[int] = [0] * t.total
            for az_pos, az in enumerate(azs[: t.az_count]):
                pool = sorted(by_az[az], key=lambda d: d.chunk_count)
                need = [i for i in range(t.total) if t.az_of_shard(i) == az_pos]
                if len(pool) < len(need):
                    raise ClusterError(
                        f"AZ {az} has {len(pool)} disks, needs {len(need)}"
                    )
                for slot, d in zip(need, pool):
                    placements[slot] = d.disk_id
            (vid, _) = self._apply("alloc_scope", {"name": "vid", "count": 1})
            return self._apply(
                "create_volume", {"vid": vid, "code_mode": mode, "placements": placements}
            )

    def _op_create_volume(self, vid: int, code_mode: int, placements: list[int]):
        units = []
        for idx, disk_id in enumerate(placements):
            d = self.disks[disk_id]
            units.append(VolumeUnit(make_vuid(vid, idx), idx, disk_id, d.node_id))
            d.chunk_count += 1
        vol = VolumeInfo(vid=vid, code_mode=code_mode, units=units, status=VOL_ACTIVE)
        self.volumes[vid] = vol
        return vol

    def get_volume(self, vid: int) -> VolumeInfo:
        with self._lock:
            vol = self.volumes.get(vid)
            if vol is None:
                raise ClusterError(f"unknown volume {vid}")
            return vol

    def alloc_volume(self, code_mode: CodeMode | int, count_hint: int = 1) -> VolumeInfo:
        """Return an active volume of the mode, creating one if none exists."""
        mode = int(code_mode)
        with self._lock:
            for vol in self.volumes.values():
                if vol.code_mode == mode and vol.status == VOL_ACTIVE:
                    return vol
            return self.create_volume(mode)

    def alloc_volumes(self, code_mode: CodeMode | int,
                      count: int = 1) -> list[VolumeInfo]:
        """Up to `count` DISTINCT active volumes of the mode, creating the
        shortfall (volumemgr's multi-volume grant): a pipelined PUT spreads
        consecutive blobs across them so one chunk file's append lock never
        serializes the whole window. Returns fewer when the cluster can't
        place more volumes — never fails while at least one is allocatable."""
        mode = int(code_mode)
        # check + create under one (re-entrant) lock hold, like the singular
        # alloc_volume: concurrent grantees must not both see the same
        # shortfall and over-create volumes
        with self._lock:
            act = [v for v in self.volumes.values()
                   if v.code_mode == mode and v.status == VOL_ACTIVE]
            while len(act) < count:
                try:
                    act.append(self.create_volume(mode))
                except ClusterError:
                    if act:
                        break
                    raise
            return act[:count]

    def set_volume_status(self, vid: int, status: str) -> None:
        """Retire full volumes (VOL_IDLE) so alloc_volume rotates to a new one."""
        self.apply("set_volume_status", {"vid": vid, "status": status})

    def _op_set_volume_status(self, vid: int, status: str):
        vol = self.volumes.get(vid)
        if vol is None:
            raise ClusterError(f"unknown volume {vid}")
        vol.status = status

    def update_volume_unit(self, vid: int, index: int, new_disk_id: int) -> VolumeUnit:
        """Re-home a stripe position after repair/migration (epoch bump)."""
        return self.apply(
            "update_volume_unit", {"vid": vid, "index": index, "new_disk_id": new_disk_id}
        )

    def _op_update_volume_unit(self, vid: int, index: int, new_disk_id: int):
        vol = self.volumes.get(vid)
        if vol is None:
            raise ClusterError(f"unknown volume {vid}")
        unit = vol.units[index]
        d = self.disks[new_disk_id]
        old = self.disks.get(unit.disk_id)
        if old is not None and old.chunk_count > 0:
            old.chunk_count -= 1  # the chunk moved WITH the unit
        d.chunk_count += 1
        unit.epoch += 1
        unit.disk_id = new_disk_id
        unit.node_id = d.node_id
        unit.vuid = make_vuid(vid, index, unit.epoch)
        return unit

    # -- tier residency (hot Replica3 copies of sustained-hot EC blobs) ------

    def promote_blob(self, vid: int, bid: int, hot_vid: int,
                     hot_bid: int) -> tuple[int, int]:
        """Install the redirect iff absent (first committer wins); returns
        the WINNING residence — a promoter that lost the race frees its
        own replica set instead of overwriting (and leaking) the winner's."""
        return self.apply("promote_blob", {"vid": vid, "bid": bid,
                                           "hot_vid": hot_vid,
                                           "hot_bid": hot_bid})

    def _op_promote_blob(self, vid: int, bid: int, hot_vid: int, hot_bid: int):
        return self.tiermap.setdefault((vid, bid), (hot_vid, hot_bid))

    def demote_blob(self, vid: int, bid: int) -> tuple[int, int] | None:
        """Drop the redirect FIRST (readers fall back to the authoritative EC
        copy immediately); returns the hot residence so the caller can free
        its replica shards afterwards."""
        return self.apply("demote_blob", {"vid": vid, "bid": bid})

    def _op_demote_blob(self, vid: int, bid: int):
        return self.tiermap.pop((vid, bid), None)

    def hot_location(self, vid: int, bid: int) -> tuple[int, int] | None:
        with self._lock:
            return self.tiermap.get((vid, bid))

    def hot_blobs(self) -> dict[tuple[int, int], tuple[int, int]]:
        with self._lock:
            return dict(self.tiermap)

    # -- service + config mgr ----------------------------------------------

    def register_service(self, name: str, addr: str) -> None:
        self.apply("register_service", {"name": name, "addr": addr})

    def _op_register_service(self, name: str, addr: str):
        lst = self.services.setdefault(name, [])
        if addr not in lst:
            lst.append(addr)

    def get_service(self, name: str) -> list[str]:
        with self._lock:
            return list(self.services.get(name, []))

    def set_config(self, key: str, value: str) -> None:
        self.apply("set_config", {"key": key, "value": value})

    def _op_set_config(self, key: str, value: str):
        self.config[key] = value

    def del_config(self, key: str) -> None:
        self.apply("del_config", {"key": key})

    def _op_del_config(self, key: str):
        self.config.pop(key, None)

    def get_config(self, key: str, default: str | None = None) -> str | None:
        with self._lock:
            return self.config.get(key, default)

    def config_items(self, prefix: str = "") -> list[tuple[str, str]]:
        """Locked snapshot of config entries under a key prefix."""
        with self._lock:
            return [(k, v) for k, v in self.config.items() if k.startswith(prefix)]

    # -- health views --------------------------------------------------------

    def broken_disks(self) -> list[DiskInfo]:
        with self._lock:
            return [d for d in self.disks.values() if d.status == DISK_BROKEN]

    def expire_heartbeats(self, timeout_s: float) -> list[int]:
        """Mark NORMAL disks whose heartbeat this process hasn't observed in
        timeout_s as BROKEN (the kill-a-blobnode detection path: a dead
        engine stops beating and its disks become disk-repair work). The
        judgment clock is monotonic and process-local — a restarted
        clustermgr grants every disk a fresh grace window rather than
        condemning the fleet off stale wall-clock stamps. Returns the disk
        ids newly marked broken (the status change IS replicated)."""
        now = time.monotonic()
        with self._lock:
            stale = [
                d.disk_id for d in self.disks.values()
                if d.status == DISK_NORMAL
                and now - self._hb_mono.get(d.disk_id, now) > timeout_s
            ]
            for disk_id in stale:
                self._apply("set_disk_status",
                            {"disk_id": disk_id, "status": DISK_BROKEN})
            if stale:
                # under the lock, like set_disk_status: detection events
                # must stamp before any repair reaction can (causal order)
                self._refresh_disk_gauges()
                for disk_id in stale:
                    # the heartbeat-silence detection path, distinguished
                    # from the error-count report path on the timeline
                    self._emit_disk_event(disk_id, DISK_NORMAL, DISK_BROKEN,
                                          "heartbeat_silence")
        return stale

    def volumes_on_disk(self, disk_id: int) -> list[tuple[VolumeInfo, VolumeUnit]]:
        with self._lock:
            out = []
            for vol in self.volumes.values():
                for u in vol.units:
                    if u.disk_id == disk_id:
                        out.append((vol, u))
            return out
