"""Scheduler — the background task brain: shard repair, disk repair/drop,
balance, blob delete.

Reference counterpart: blobstore/scheduler (migrate state machines with
prepare/work/finish queues, migrate.go:322-347; Kafka consumers feeding
ShardRepairMgr shard_repairer.go:103 and blob_deleter.go; workers PULL tasks
via HTTPTaskAcquire, service.go:84, repair tasks served first). Shapes kept:

  * tasks move through PREPARED -> WORKING -> FINISHED and survive restarts by
    reloading from the clustermgr-persisted task table;
  * workers acquire tasks (repair before balance) and report completion;
  * the repair math itself is a batched TPU reconstruct through CodecService:
    a disk-repair task covers every (volume, bid) on the dead disk, and the
    worker stacks thousands of stripes into the same device batches
    (SURVEY §3.5's 10k-stripe bulk-repair config).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass, field

import numpy as np

from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.blobstore.blobnode import BlobNode, classify_io_error
from chubaofs_tpu.blobstore.clustermgr import (
    DISK_DROPPED,
    DISK_NORMAL,
    ClusterMgr,
    VolumeInfo,
    parse_vuid,
)
from chubaofs_tpu.blobstore.proxy import (
    TOPIC_BLOB_DELETE,
    TOPIC_BLOB_HOT,
    TOPIC_SHARD_REPAIR,
    Proxy,
)
from chubaofs_tpu.codec.service import CodecService, default_service
from chubaofs_tpu.utils.exporter import BATCH_BUCKETS, RATIO_BUCKETS, registry

TASK_PREPARED = "prepared"
TASK_WORKING = "working"
TASK_FINISHED = "finished"
TASK_FAILED = "failed"  # exhausted retries; eligible for re-creation

KIND_SHARD_REPAIR = "shard_repair"
KIND_DISK_REPAIR = "disk_repair"
KIND_DISK_DROP = "disk_drop"
KIND_BALANCE = "balance"
KIND_TIER_PROMOTE = "tier_promote"
KIND_TIER_DEMOTE = "tier_demote"

# acquisition priority (service.go:84: repair first; tier migration is an
# optimization, so it yields to every durability task)
_PRIORITY = [KIND_SHARD_REPAIR, KIND_DISK_REPAIR, KIND_DISK_DROP,
             KIND_BALANCE, KIND_TIER_PROMOTE, KIND_TIER_DEMOTE]

_TASK_STATES = (TASK_PREPARED, TASK_WORKING, TASK_FINISHED, TASK_FAILED)


def stage_overlap_ratio(stages) -> float | None:
    """Download/decode overlap of one repair span's stages: intersection of
    the 'download' interval union with the codec.* interval union, over the
    SMALLER of the two — 0 means the pipeline degenerated to serial, >0 means
    survivor downloads really ran while the device decoded. None when either
    side never happened (nothing to overlap)."""
    dl = [(off, off + dur) for name, off, dur in stages if name == "download"]
    dec = [(off, off + dur) for name, off, dur in stages
           if name.startswith("codec.")]
    return trace.overlap_ratio(dl, dec)


@dataclass
class Task:
    task_id: str
    kind: str
    state: str = TASK_PREPARED
    vid: int = 0
    bid: int = 0
    bad_idx: list[int] = field(default_factory=list)
    disk_id: int = 0
    dest_disk_id: int | None = None  # None = pick at execution
    size: int = 0  # tier_promote: the blob's true byte length
    created: float = field(default_factory=time.time)
    retries: int = 0
    error: str = ""
    # current lease number (0 = never leased). Monotonic across the
    # scheduler's lifetime; a report carrying an older lease is STALE — the
    # reaper requeued and re-leased the task after that worker went quiet.
    lease: int = 0


class Scheduler:
    """Leader-elected background brain (single leader here; raft wraps later)."""

    def __init__(self, cm: ClusterMgr, proxy: Proxy, nodes: dict[int, BlobNode],
                 codec: CodecService | None = None, record_log=None,
                 cache=None):
        from chubaofs_tpu.blobstore.taskswitch import SwitchMgr

        self.cm = cm
        self.proxy = proxy
        self.nodes = nodes
        self.codec = codec or default_service()
        # the gateway's BlobCache when co-located (MiniCluster): the deleter
        # punches blobs out of it before shards disappear
        self.cache = cache
        # switches persist in the clustermgr config KV (task_switch.go:26);
        # pull persisted state so a restarted scheduler honors prior settings
        self.switches = SwitchMgr(config_get=cm.get_config,
                                  config_set=cm.set_config)
        self.switches.refresh()
        self.record_log = record_log  # common/recordlog: finished-task audit
        self._lock = threading.Lock()
        self._tasks: dict[str, Task] = {}
        self._seq = 0
        self._inspect_cursor = 0  # round-robin position over volume ids
        # leased scheduling (the task_runner.go lease/renewal analog): every
        # acquire hands out a monotonic deadline; the reaper requeues expired
        # WORKING tasks with backoff so a dead worker can never strand one.
        self.lease_ms = float(os.environ.get("CFS_REPAIR_LEASE_MS", "30000"))
        self.requeue_backoff_s = 0.5  # doubled per expiry, capped below
        self.requeue_backoff_cap_s = 30.0
        # expiries before a WORKING task goes terminal FAILED (reap_expired)
        self.max_lease_expiries = 5
        # heartbeat-silence window after which a disk counts as dead (the
        # kill-a-blobnode detection path; generous default so slow test
        # phases never false-positive — the kill soak tightens it)
        self.hb_timeout_s = float(os.environ.get("CFS_HB_TIMEOUT_S", "60"))
        # tier demotion: a promoted blob that produces NO heat signal for
        # this many tier sweeps has gone cold — its replica copy is freed
        # and reads fall back to EC
        self.demote_sweeps = int(os.environ.get("CFS_DEMOTE_SWEEPS", "8"))
        self._tier_idle: dict[tuple[int, int], int] = {}  # under self._lock
        # recently-deleted (vid, bid)s, noted BEFORE the deleter touches
        # tier/cache state: an in-flight promote re-checks this after
        # committing its redirect, closing the promote-vs-delete race in
        # daemon deployments where the two run on different threads.
        # Bounded LRU; entries only need to outlive the concurrency window
        # (a promote for a long-gone blob fails on the punched EC read).
        self._deleted_recent: OrderedDict[tuple[int, int], None] = \
            OrderedDict()  # under self._lock
        self._lease_seq = 0
        self._lease_deadline: dict[str, float] = {}  # task_id -> monotonic
        self._not_before: dict[str, float] = {}      # requeue backoff gate
        self._expiries: dict[str, int] = {}          # per-task expiry count
        self._load_tasks()
        with self._lock:
            self._update_gauges_locked()

    # -- task table (persisted in the clustermgr config KV, the reference's
    # migrate-task tables in clustermgr: migrate.go:346-347) -------------------

    _TASK_PREFIX = "task/"
    _TASK_SEQ_KEY = "task_seq"

    # in-memory history cap: terminal tasks already left the KV
    # (_persist_task) and the recordlog holds the durable audit; keeping a
    # bounded tail serves `task ls` without letting a long outage — where
    # FAILED tasks are re-created per fresh damage report — grow the table,
    # and with it task ids and memory, without bound
    TERMINAL_KEEP = 256

    def _prune_terminal_locked(self) -> None:
        terminal = [t for t in self._tasks.values()
                    if t.state in (TASK_FINISHED, TASK_FAILED)]
        if len(terminal) <= self.TERMINAL_KEEP:
            return
        terminal.sort(key=lambda t: int(t.task_id.lstrip("t") or 0))
        for t in terminal[: len(terminal) - self.TERMINAL_KEEP]:
            del self._tasks[t.task_id]

    def _has_tombstone(self, node_id: int, vuid: int, bid: int) -> bool:
        """Tombstone probe that tolerates dark hosts: an unreachable node
        simply cannot attest a tombstone (the sweep retries next round)."""
        node = self.nodes.get(node_id)
        if node is None:
            return False
        try:
            return bool(node.has_tombstone(vuid, bid))
        except Exception:
            return False

    def _load_tasks(self):
        """Reload open tasks after a restart; WORKING tasks re-queue (their
        worker died with us — the reference's junk-task cleanup re-drives).
        The id counter persists separately so completed tasks' ids are never
        reissued (the recordlog audit keys on them)."""
        self._seq = int(self.cm.get_config(self._TASK_SEQ_KEY) or 0)
        for key, raw in self.cm.config_items(self._TASK_PREFIX):
            if not raw:
                continue
            t = Task(**json.loads(raw))
            if t.state == TASK_WORKING:
                t.state = TASK_PREPARED
            self._tasks[t.task_id] = t
            # lease numbers stay monotonic across reloads so a pre-crash
            # worker's report can never alias a fresh lease
            self._lease_seq = max(self._lease_seq, t.lease)

    def _persist_task(self, t: Task):
        key = self._TASK_PREFIX + t.task_id
        if t.state in (TASK_FINISHED, TASK_FAILED):
            # terminal states LEAVE the table (the recordlog keeps the audit);
            # a real delete, so the config KV never grows with task history
            self.cm.del_config(key)
            return
        self.cm.set_config(key, json.dumps(t.__dict__))

    def _new_task(self, **kw) -> Task:
        with self._lock:
            self._seq += 1
            self.cm.set_config(self._TASK_SEQ_KEY, str(self._seq))
            t = Task(task_id=f"t{self._seq}", **kw)
            self._tasks[t.task_id] = t
            self._persist_task(t)
            self._update_gauges_locked()
            return t

    def tasks(self, kind: str | None = None, state: str | None = None) -> list[Task]:
        with self._lock:
            return [
                t
                for t in self._tasks.values()
                if (kind is None or t.kind == kind)
                and (state is None or t.state == state)
            ]

    # -- producers -----------------------------------------------------------

    def poll_repair_topic(self, max_msgs: int = 64) -> int:
        """Drain the shard-repair topic into repair tasks (shard_repairer.go:103).

        Deduped by (vid, bid): every degraded GET emits a message, but one open
        task repairs the whole stripe."""
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_SHARD_REPAIR

        if not self.switches.enabled(SWITCH_SHARD_REPAIR):
            return 0
        topic = self.proxy.topics[TOPIC_SHARD_REPAIR]
        msgs = topic.consume("scheduler", max_msgs)
        with self._lock:
            # terminal tasks don't block a fresh attempt: a FAILED task means
            # retries ran out under the conditions of the time (e.g. a dark
            # AZ); the damage persisting past that deserves a new task, not
            # permanent abandonment (TASK_FAILED is "eligible for re-creation")
            open_keys = {
                (t.vid, t.bid)
                for t in self._tasks.values()
                if t.kind == KIND_SHARD_REPAIR
                and t.state not in (TASK_FINISHED, TASK_FAILED)
            }
        for m in msgs:
            key = (m["vid"], m["bid"])
            if key in open_keys:
                continue
            open_keys.add(key)
            self._new_task(
                kind=KIND_SHARD_REPAIR, vid=m["vid"], bid=m["bid"], bad_idx=m["bad_idx"]
            )
        topic.commit("scheduler", len(msgs))
        return len(msgs)

    def check_disks(self) -> list[Task]:
        """Turn broken disks into disk-repair tasks (disk_repairer analog).

        Destination disks are picked per-volume at execution time so the
        no-two-units-of-a-volume-per-disk invariant holds."""
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_DISK_REPAIR

        if not self.switches.enabled(SWITCH_DISK_REPAIR):
            return []
        out = []
        for disk in self.cm.broken_disks():
            # an open (prepared/working) task blocks re-creation; a FAILED one
            # does not — the disk is still broken and must be retried
            existing = [
                t
                for t in self.tasks(KIND_DISK_REPAIR)
                if t.disk_id == disk.disk_id and t.state in (TASK_PREPARED, TASK_WORKING)
            ]
            if existing:
                continue
            out.append(self._new_task(kind=KIND_DISK_REPAIR, disk_id=disk.disk_id))
        return out

    def inspect_volumes(self, max_volumes: int = 4) -> int:
        """Proactive integrity sweep (scheduler/volume_inspector.go): walk a
        cursor-bounded batch of volumes, verify every stripe position of every
        bid is present AND passes its crc32block framing, and feed anything
        broken to the repair topic — discovery without waiting for a client GET.
        Gated by SWITCH_VOL_INSPECT. Returns repair messages produced."""
        from chubaofs_tpu.blobstore.blobnode import STATUS_MARK_DELETE
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_VOL_INSPECT

        if not self.switches.enabled(SWITCH_VOL_INSPECT):
            return 0
        with self._lock:
            vids = sorted(self.cm.volumes)
            if not vids:
                return 0
            start = self._inspect_cursor % len(vids)
            batch = (vids[start:] + vids[:start])[:max_volumes]
            self._inspect_cursor = (start + len(batch)) % len(vids)
        produced = 0
        for vid in batch:
            vol = self.cm.get_volume(vid)
            t = vol.tactic()
            # bid -> stripe positions holding it, with index status
            seen: dict[int, dict[int, int]] = {}
            for u in vol.units:
                node = self.nodes.get(u.node_id)
                if node is None:
                    continue
                try:
                    metas = node.list_shards(u.vuid)
                except Exception:
                    continue
                for m in metas:
                    seen.setdefault(m.bid, {})[u.index] = m.status
            for bid, have in sorted(seen.items()):
                # a tombstone ANYWHERE means this bid was deleted: finish the
                # partial delete (idempotent, retried every sweep) instead of
                # resurrecting it — checked BEFORE the mark-delete skip so a
                # half-marked straggler can't wedge forever
                tombstoned = any(
                    self._has_tombstone(u.node_id, u.vuid, bid)
                    for u in vol.units
                )
                if tombstoned:
                    for idx in have:
                        unit = vol.units[idx]
                        node = self.nodes.get(unit.node_id)
                        if node is None:
                            continue
                        try:
                            node.delete_shard(unit.vuid, bid)
                        except Exception:
                            pass  # node down: retried on the next sweep
                    continue
                if any(st == STATUS_MARK_DELETE for st in have.values()):
                    continue  # delete in flight; the deleter owns this bid
                bad = []
                for idx in range(t.total):
                    unit = vol.units[idx]
                    node = self.nodes.get(unit.node_id)
                    if node is None or idx not in have:
                        bad.append(idx)
                        continue
                    try:
                        node.get_shard(unit.vuid, bid)  # full CRC-framed read
                    except Exception:
                        bad.append(idx)
                if bad:
                    self.proxy.send_shard_repair(vid, bid, bad, "inspect")
                    produced += 1
        if produced:
            registry("scheduler").counter("inspect_findings").add(produced)
        return produced

    def drop_disk(self, disk_id: int) -> Task:
        """Manual decommission -> migrate everything off (disk_drop analog)."""
        return self._new_task(kind=KIND_DISK_DROP, disk_id=disk_id)

    def check_balance(self, min_gap: int = 3) -> Task | None:
        """Even out chunk counts (scheduler/balancer.go): when the most-loaded
        normal disk leads the least-loaded same-AZ disk by >= min_gap chunks,
        create ONE balance task moving a single volume unit off it. Gated by
        SWITCH_BALANCE; one rebalance in flight at a time."""
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_BALANCE

        if not self.switches.enabled(SWITCH_BALANCE):
            return None
        if any(t.state in (TASK_PREPARED, TASK_WORKING)
               for t in self.tasks(KIND_BALANCE)):
            return None
        by_az: dict[int, list] = {}
        for d in self.cm.disks.values():
            if d.status == DISK_NORMAL:
                by_az.setdefault(d.az, []).append(d)
        # balance is intrinsically per-AZ (moves never cross AZs): evaluate
        # every AZ's own spread, not one global maximum
        for az, disks in sorted(by_az.items()):
            if len(disks) < 2:
                continue
            src = max(disks, key=lambda d: d.chunk_count)
            low = min(d.chunk_count for d in disks if d.disk_id != src.disk_id)
            if src.chunk_count - low < min_gap:
                continue
            for vol, unit in self.cm.volumes_on_disk(src.disk_id):
                try:
                    dest = self.pick_dest_disk(
                        exclude={u.disk_id for u in vol.units}, az=az)
                except RuntimeError:
                    continue
                # the move must CONVERGE: a destination nearly as loaded as
                # the source would just ping-pong units back and forth
                if self.cm.disks[dest].chunk_count + min_gap > src.chunk_count:
                    continue
                registry("scheduler").counter("balance_tasks").add()
                return self._new_task(kind=KIND_BALANCE, vid=vol.vid,
                                      disk_id=src.disk_id,
                                      dest_disk_id=dest)
        return None

    def pick_dest_disk(self, exclude: set[int], az: int) -> int:
        """Least-loaded normal disk in the AZ, outside the exclusion set
        (source disk + every disk already hosting a unit of the volume)."""
        candidates = [
            d
            for d in self.cm.disks.values()
            if d.status == DISK_NORMAL and d.disk_id not in exclude and d.az == az
        ]
        if not candidates:
            raise RuntimeError(f"no destination disk available in AZ {az}")
        return min(candidates, key=lambda d: d.chunk_count).disk_id

    # -- worker pull API (HTTPTaskAcquire analog) -----------------------------

    def acquire_task(self) -> Task | None:
        """Hand out the highest-priority PREPARED task under a LEASE: the
        returned task carries a fresh lease number and a monotonic deadline;
        a worker that never reports is reaped by reap_expired() and the task
        requeues with backoff. Capture task.lease IMMEDIATELY — the shared
        Task object's lease advances if the task is ever re-leased."""
        now = time.monotonic()
        got: Task | None = None
        with self._lock:
            for kind in _PRIORITY:
                if got is not None:
                    break
                for t in self._tasks.values():
                    if t.kind != kind or t.state != TASK_PREPARED:
                        continue
                    if self._not_before.get(t.task_id, 0.0) > now:
                        continue  # requeue backoff still cooling
                    t.state = TASK_WORKING
                    self._lease_seq += 1
                    t.lease = self._lease_seq
                    # persisted not for the WORKING state (reload demotes it
                    # back to PREPARED regardless) but for the LEASE number:
                    # _load_tasks restores _lease_seq from the stored maximum,
                    # so a worker that outlives a scheduler crash can never
                    # find its old lease number reissued to someone else
                    self._persist_task(t)
                    self._lease_deadline[t.task_id] = \
                        now + self.lease_ms / 1e3
                    self._update_gauges_locked()
                    got = t
                    # emit UNDER the lock: the lock serializes every lease
                    # transition, so stamping here keeps the timeline's
                    # order identical to the state machine's (an expiry's
                    # event can never trail its re-acquisition's), and the
                    # mutable lease field is captured before it can advance
                    from chubaofs_tpu.utils import events

                    events.emit("lease_acquired", entity=t.task_id,
                                detail={"kind": t.kind, "lease": t.lease,
                                        "disk_id": t.disk_id, "vid": t.vid,
                                        "bid": t.bid})
                    break
        return got

    def reap_expired(self) -> int:
        """Requeue WORKING tasks whose lease deadline passed (the junk-task
        cleanup loop the reference runs against dead workers): state back to
        PREPARED behind an exponential requeue backoff, counted by
        cfs_scheduler_lease_expired. The late worker's eventual report is
        dropped as stale (its lease no longer matches). A task that expires
        max_lease_expiries times goes terminal FAILED instead — workers
        renew mid-task (renew_lease), so repeated expiry means every
        execution dies, and re-executing forever is not an error path."""
        from chubaofs_tpu.utils import events

        now = time.monotonic()
        reaped = 0
        failed = 0
        with self._lock:
            for t in self._tasks.values():
                if t.state != TASK_WORKING:
                    continue
                deadline = self._lease_deadline.get(t.task_id)
                if deadline is not None and now < deadline:
                    continue
                self._lease_deadline.pop(t.task_id, None)
                n = self._expiries.get(t.task_id, 0) + 1
                self._expiries[t.task_id] = n
                if n >= self.max_lease_expiries:
                    t.state = TASK_FAILED
                    t.error = f"lease expired {n}x with no report"
                    self._persist_task(t)
                    self._not_before.pop(t.task_id, None)
                    self._expiries.pop(t.task_id, None)
                    failed += 1
                else:
                    t.state = TASK_PREPARED
                    self._not_before[t.task_id] = now + min(
                        self.requeue_backoff_cap_s,
                        self.requeue_backoff_s * (2 ** (n - 1)))
                reaped += 1
                # emit UNDER the lock (same rationale as acquire_task's):
                # the expiry's timeline stamp must precede any
                # re-acquisition's, and only the lock guarantees that
                terminal = t.state == TASK_FAILED
                events.emit("lease_expired", events.SEV_WARNING,
                            entity=t.task_id,
                            detail={"kind": t.kind, "expiries": n,
                                    "terminal": terminal})
                if terminal:
                    events.emit("task_failed", events.SEV_CRITICAL,
                                entity=t.task_id,
                                detail={"kind": t.kind, "error": t.error})
            if failed:
                self._prune_terminal_locked()
            if reaped:
                self._update_gauges_locked()
        if reaped:
            registry("scheduler").counter("lease_expired").add(reaped)
        if failed:
            registry("scheduler").counter("lease_expired_failed").add(failed)
        return reaped

    def renew_lease(self, task_id: str, lease: int) -> bool:
        """Extend a WORKING task's lease deadline by a full lease_ms (the
        reference task runner's renewal tick). A long disk migrate renews
        between units so a healthy slow worker never loses a race against
        the reaper; False means the lease is gone (task pruned, reaped, or
        re-leased) and the caller must abandon the task."""
        with self._lock:
            t = self._tasks.get(task_id)
            if t is None or t.state != TASK_WORKING or t.lease != lease:
                return False
            self._lease_deadline[task_id] = \
                time.monotonic() + self.lease_ms / 1e3
        registry("scheduler").counter("lease_renewed").add()
        return True

    def report_task(self, task_id: str, ok: bool, error: str = "",
                    lease: int | None = None) -> bool:
        """Worker completion report. Tolerant by contract: an unknown id
        (terminal-task pruning, scheduler reload), a task no longer WORKING
        (the reaper requeued it), or a mismatched lease (it was re-leased to
        another worker) is DROPPED with cfs_scheduler_stale_report — never a
        crash in the worker thread, and never a double state transition.
        Returns True when the report was accepted."""
        with self._lock:
            t = self._tasks.get(task_id)
            stale = (t is None or t.state != TASK_WORKING
                     or (lease is not None and lease != t.lease))
            if stale:
                reason = ("pruned" if t is None else
                          "not_working" if t.state != TASK_WORKING
                          else "lease")
            else:
                self._lease_deadline.pop(task_id, None)
                if ok:
                    t.state = TASK_FINISHED
                else:
                    t.retries += 1
                    t.error = error
                    t.state = TASK_PREPARED if t.retries < 3 else TASK_FAILED
                self._persist_task(t)
                if t.state in (TASK_FINISHED, TASK_FAILED):
                    self._prune_terminal_locked()
                    self._not_before.pop(task_id, None)
                    self._expiries.pop(task_id, None)
                self._update_gauges_locked()
            record = None
            if not stale and self.record_log is not None \
                    and t.state in (TASK_FINISHED, TASK_FAILED):
                record = {
                    "task_id": t.task_id, "kind": t.kind, "state": t.state,
                    "vid": t.vid, "bid": t.bid, "disk_id": t.disk_id,
                    "retries": t.retries, "error": t.error,
                }
        if stale:
            registry("scheduler").counter(
                "stale_report", {"reason": reason}).add()
            return False
        # record outside the lock; the audit trail must never alter task state
        if record is not None:
            try:
                self.record_log.encode(record)
            except OSError:
                pass
        if t.state in (TASK_FINISHED, TASK_FAILED):
            # terminal transition -> timeline. Emitted from the WORKER'S
            # calling context, so a live repair span's trace id rides along
            # and `cfs-events --correlate <trace>` joins the rebuild-finished
            # event to its repair trace
            from chubaofs_tpu.utils import events

            if t.state == TASK_FINISHED:
                events.emit("task_finished", entity=t.task_id,
                            detail={"kind": t.kind, "vid": t.vid,
                                    "bid": t.bid, "disk_id": t.disk_id,
                                    "retries": t.retries})
            else:
                events.emit("task_failed", events.SEV_CRITICAL,
                            entity=t.task_id,
                            detail={"kind": t.kind, "vid": t.vid,
                                    "bid": t.bid, "disk_id": t.disk_id,
                                    "retries": t.retries, "error": t.error})
        return True

    def _update_gauges_locked(self) -> None:
        """cfs_scheduler_tasks{kind,state} gauges over the (bounded) table —
        the cfs-stat repair rollup's task inventory."""
        counts: dict[tuple[str, str], int] = {}
        for t in self._tasks.values():
            counts[(t.kind, t.state)] = counts.get((t.kind, t.state), 0) + 1
        reg = registry("scheduler")
        for kind in _PRIORITY:
            for state in _TASK_STATES:
                reg.gauge("tasks", {"kind": kind, "state": state}).set(
                    counts.get((kind, state), 0))

    # -- detection drivers (scrub + heartbeat expiry) -------------------------

    def run_scrub(self, max_shards: int = 256) -> int:
        """One budgeted scrub tick across every reachable blobnode: each
        node re-reads up to max_shards live shards through its crc32block
        framing (cursor-resumable, CFS_SCRUB_RATE-limited — see
        BlobNode.scrub_once) and every CRC failure feeds the repair topic.
        This is the datainspect.go half of detection: it finds bitrot
        without waiting for a client GET or a full inspector sweep."""
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_VOL_INSPECT

        if not self.switches.enabled(SWITCH_VOL_INSPECT):
            return 0
        produced = 0
        for node in list(self.nodes.values()):
            try:
                res = node.scrub_once(max_shards=max_shards)
            except Exception:
                continue  # dark/closed engine: its restart resumes the cursor
            for vuid, bid in res["bad"]:
                vid, idx, _ = parse_vuid(vuid)
                try:
                    self.proxy.send_shard_repair(vid, bid, [idx], "scrub")
                    produced += 1
                except Exception:
                    pass  # proxy down: the next sweep re-finds it
        if produced:
            registry("scheduler").counter("scrub_findings").add(produced)
        return produced

    def check_node_health(self, timeout_s: float | None = None) -> list[int]:
        """Mark disks whose heartbeats went silent as BROKEN (the
        kill-a-blobnode detection path): a dead engine stops heartbeating,
        its disks expire, and check_disks turns them into disk-repair tasks.
        Returns the disk ids newly marked broken."""
        timeout = self.hb_timeout_s if timeout_s is None else timeout_s
        if timeout <= 0:
            return []
        stale = self.cm.expire_heartbeats(timeout)
        if stale:
            registry("scheduler").counter("hb_expired_disks").add(len(stale))
        return stale

    # -- blob deleter ---------------------------------------------------------

    def run_deleter(self, max_msgs: int = 64) -> int:
        """Consume delete messages -> mark-delete then punch-hole on blobnodes
        (blob_deleter.go two-phase analog)."""
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_BLOB_DELETE

        if not self.switches.enabled(SWITCH_BLOB_DELETE):
            return 0
        topic = self.proxy.topics[TOPIC_BLOB_DELETE]
        msgs = topic.consume("deleter", max_msgs)
        for m in msgs:
            # a deleted blob leaves EVERY tier. Order matters on a daemon,
            # where GETs serve CONCURRENTLY with this loop: (1) note the
            # delete so an in-flight tier promote re-checks it, (2) drop
            # the hot replica copy, (3) punch the EC shards, (4) invalidate
            # the cache LAST — an invalidate-before-punch would let a GET
            # in the gap refill the cache from the still-readable shards
            # under the post-bump version, and nothing would ever evict
            # those bytes again (the gateway's own delete() already did the
            # pre-delete write-through invalidation for its clients)
            key = (m["vid"], m["bid"])
            with self._lock:
                self._deleted_recent[key] = None
                while len(self._deleted_recent) > 4096:
                    self._deleted_recent.popitem(last=False)
            self._drop_hot_copy(*key)
            vol = self.cm.get_volume(m["vid"])
            for unit in vol.units:
                node = self.nodes.get(unit.node_id)
                if node is None:
                    continue
                try:
                    node.mark_delete_shard(unit.vuid, m["bid"])
                    node.delete_shard(unit.vuid, m["bid"])
                except Exception:
                    pass  # already gone or never written; repair owns the rest
            if self.cache is not None:
                self.cache.invalidate(*key)
        topic.commit("deleter", len(msgs))
        return len(msgs)

    def _recently_deleted(self, vid: int, bid: int) -> bool:
        with self._lock:
            return (vid, bid) in self._deleted_recent

    # -- tier migration (the cache plane's promoter/demoter, ISSUE 12) --------

    def run_tier(self, max_msgs: int = 64) -> int:
        """One tier sweep: drain the hot-blob topic into promote tasks for
        blobs not yet resident in the hot engine, and create demote tasks
        for promoted blobs whose heat signal has been silent for
        demote_sweeps consecutive sweeps. Worker execution rides the same
        lease machinery as repair (acquire -> lease -> report)."""
        from chubaofs_tpu.blobstore.taskswitch import SWITCH_TIER_MIGRATE

        topic = self.proxy.topics[TOPIC_BLOB_HOT]
        # drain the topic FULLY: the idle-demote counter below reads "no
        # signal this sweep" as cooling, so a partial batch under signal
        # backlog would demote genuinely hot blobs whose messages merely
        # sat past the batch boundary (then re-promote them — churn)
        msgs: list[dict] = []
        while True:
            batch = topic.consume("tier", max_msgs)
            if not batch:
                break
            topic.commit("tier", len(batch))
            msgs.extend(batch)
        if not self.switches.enabled(SWITCH_TIER_MIGRATE):
            # consumed-and-DISCARDED: heat signals are advisory, and the
            # access layer keeps producing them while a cache is armed —
            # leaving them unconsumed would grow hot.jsonl without bound
            # and dump an hours-stale backlog on the sweep that re-enables
            return 0
        hot_now = {(m["vid"], m["bid"]): m.get("size", 0) for m in msgs}
        promoted = self.cm.hot_blobs()
        with self._lock:
            open_keys = {
                (t.vid, t.bid)
                for t in self._tasks.values()
                if t.kind in (KIND_TIER_PROMOTE, KIND_TIER_DEMOTE)
                and t.state not in (TASK_FINISHED, TASK_FAILED)
            }
        for (vid, bid), size in sorted(hot_now.items()):
            if (vid, bid) in promoted or (vid, bid) in open_keys:
                continue
            open_keys.add((vid, bid))
            self._new_task(kind=KIND_TIER_PROMOTE, vid=vid, bid=bid, size=size)
        demote: list[tuple[int, int]] = []
        with self._lock:
            # drop idle entries for blobs no longer promoted (demoted or
            # deleted behind our back) so the table tracks the tier map
            for key in [k for k in self._tier_idle if k not in promoted]:
                del self._tier_idle[key]
            for key in promoted:
                if key in hot_now:
                    self._tier_idle[key] = 0
                    continue
                n = self._tier_idle.get(key, 0) + 1
                self._tier_idle[key] = n
                if n >= self.demote_sweeps and key not in open_keys:
                    demote.append(key)
                    del self._tier_idle[key]
        for vid, bid in demote:
            self._new_task(kind=KIND_TIER_DEMOTE, vid=vid, bid=bid)
        return len(msgs)

    def _drop_hot_copy(self, vid: int, bid: int) -> None:
        """Demote-and-free: drop the tier-map redirect FIRST (readers fall
        back to the authoritative EC copy), then best-effort delete the
        replica shards — an unreachable hot node leaks bytes until its
        chunk is re-imaged, never correctness."""
        if self.cm.hot_location(vid, bid) is None:
            # the common case (never promoted): skip the demote apply —
            # it would mint a durable no-op WAL record per blob delete.
            # Race-safe vs an in-flight promote: the deleter notes the key
            # in _deleted_recent BEFORE calling here, and _tier_promote
            # re-checks that note after committing its redirect
            return
        hot = self.cm.demote_blob(vid, bid)
        if hot is None:
            return
        hot_vid, hot_bid = hot
        from chubaofs_tpu.utils import events

        events.emit("tier_demote", entity=f"blob({vid},{bid})",
                    detail={"vid": vid, "bid": bid, "hot_vid": hot_vid,
                            "hot_bid": hot_bid})
        try:
            vol = self.cm.get_volume(hot_vid)
        except Exception:
            return
        for unit in vol.units:
            node = self.nodes.get(unit.node_id)
            if node is None:
                continue
            try:
                node.mark_delete_shard(unit.vuid, hot_bid)
                node.delete_shard(unit.vuid, hot_bid)
            except Exception:
                pass
        registry("cache").counter("demotes").add()


class RepairWorker:
    """Executes repair/migrate tasks with batched TPU reconstructs.

    Reference: blobnode's embedded worker (task_runner.go:171,
    work_shard_recover.go:399-547). The TPU-native differences: one task's
    stripes are stacked into large (B, n, k) reconstruct batches instead of
    per-stripe loops, and bulk migrates run a WINDOWED pipeline — up to
    CFS_REPAIR_WINDOW stripes' survivor downloads in flight while earlier
    stripes decode on the device (the PUT pipeline's window pattern applied
    to repair-GET). Every task runs under a `scheduler.repair` span whose
    `download` stages and the codec's `codec.host`/`codec.device` stages let
    cfs-trace prove the overlap.
    """

    def __init__(self, sched: Scheduler, nodes: dict[int, BlobNode],
                 codec: CodecService | None = None,
                 read_deadline: float = 3.0,
                 repair_window: int | None = None):
        self.sched = sched
        self.cm = sched.cm
        self.nodes = nodes
        self.codec = codec or sched.codec
        # every survivor read races this deadline: a wedged blobnode turns
        # into a typed probe_fail{timeout}, never a silent stall
        self.read_deadline = read_deadline
        if repair_window is None:
            repair_window = int(os.environ.get("CFS_REPAIR_WINDOW", "4"))
        self.repair_window = repair_window  # 0/1 = serial gather
        # stripe-level window workers (one per in-flight gather) and the
        # shard-read fan-out pool they share; both bounded so one repair
        # task can't monopolize a host
        self._stripe_pool = ThreadPoolExecutor(
            max_workers=max(1, repair_window or 1),
            thread_name_prefix="repair-stripe")
        self._shard_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="repair-io")

    def set_repair_window(self, window: int) -> None:
        """Change the stripe window AND resize the pool that realizes it —
        assigning repair_window bare would leave a pool sized for the old
        window silently serializing (or over-parallelizing) the gathers."""
        if window == self.repair_window:
            return
        self.repair_window = window
        old = self._stripe_pool
        self._stripe_pool = ThreadPoolExecutor(
            max_workers=max(1, window or 1),
            thread_name_prefix="repair-stripe")
        old.shutdown(wait=False)

    def close(self) -> None:
        """Shut down the worker's executors (racelint: unjoined-thread).
        wait=False mirrors Access.close — a read wedged on a dead node must
        not stall teardown; it fails on its own deadline."""
        self._stripe_pool.shutdown(wait=False)
        self._shard_pool.shutdown(wait=False)

    def run_once(self) -> bool:
        """Process one task; failures are recorded on the task, never raised —
        one poisoned stripe must not stall the background plane. The whole
        task executes under a root span so repair traces are analyzable, and
        the report carries the ACQUIRE-time lease: if the lease expired and
        the reaper re-queued the task mid-flight, this report is dropped as
        stale (idempotent write-back makes the re-execution safe)."""
        task = self.sched.acquire_task()
        if task is None:
            return False
        lease = task.lease  # capture NOW: the field advances on re-lease
        reg = registry("scheduler")
        with trace.child_of(trace.current_span(), "scheduler.repair") as span:
            span.set_tag("task", task.task_id)
            span.set_tag("kind", task.kind)
            span.set_tag("window", self.repair_window)
            ok, err = True, ""
            try:
                if task.kind == KIND_SHARD_REPAIR:
                    self._repair_shards(task.vid, task.bid, task.bad_idx)
                elif task.kind == KIND_BALANCE:
                    self._balance_unit(task)
                elif task.kind in (KIND_DISK_REPAIR, KIND_DISK_DROP):
                    self._migrate_disk(task, lease)
                elif task.kind == KIND_TIER_PROMOTE:
                    self._tier_promote(task, lease)
                elif task.kind == KIND_TIER_DEMOTE:
                    self.sched._drop_hot_copy(task.vid, task.bid)
            except Exception as e:
                ok, err = False, f"{type(e).__name__}: {e}"
            ratio = stage_overlap_ratio(span.stages)
            if ratio is not None:
                span.set_tag("overlap_ratio", round(ratio, 3))
                reg.summary("repair_overlap_ratio",
                            buckets=RATIO_BUCKETS).observe(ratio)
            self.sched.report_task(task.task_id, ok, error=err, lease=lease)
        return True

    # -- tier promotion (EC cold copy -> Replica3 hot engine) ------------------

    def _tier_promote(self, task: Task, lease: int | None = None):
        """Copy one sustained-hot blob into the 3-replica hot engine: read
        its data region off the EC stripe (reconstructing around any damage
        — a hot blob deserves promotion even while degraded), trim to the
        blob's true size, encode the systematic RS(1,2) replica stripe, and
        land it on a Replica3 volume before committing the redirect.
        Idempotent: a re-executed task (lease expiry, crash) sees the
        redirect and returns; a half-written replica set is unreachable
        until promote_blob commits, and put_shard punch-and-append makes
        the rewrite safe."""
        from chubaofs_tpu.codec.codemode import CodeMode, get_tactic

        if self.cm.hot_location(task.vid, task.bid) is not None:
            return
        if self.sched._recently_deleted(task.vid, task.bid):
            return  # the blob is going/gone; don't resurrect it hot
        span = trace.current_span()
        vol = self.cm.get_volume(task.vid)
        t = vol.tactic()
        reads = self._probe(vol, task.bid, range(t.N), span=span)
        if len(reads) == t.N:
            payload = b"".join(reads[i] for i in range(t.N))
        else:
            stripe, present, _ = self._gather(vol, t, task.bid, span=span)
            missing = [i for i in range(t.N + t.M) if i not in present]
            if missing:
                stripe = self.codec.reconstruct_tactic(
                    t, stripe, missing, data_only=True).result()
            payload = stripe[: t.N].reshape(-1).tobytes()
        if task.size > 0:
            payload = payload[: task.size]  # strip the EC stripe padding
        # a big-blob promote on a degraded stripe (gather + reconstruct)
        # can outlive one lease: renew before the replica writes, like
        # _migrate_disk renews per unit — a lost lease means the reaper
        # may have re-leased this task, and the re-execution owns it now
        if lease is not None and \
                not self.sched.renew_lease(task.task_id, lease):
            raise RuntimeError(
                f"lease {lease} lost mid-promote of ({task.vid}, {task.bid})")
        rt = get_tactic(CodeMode.Replica3)
        mat = np.frombuffer(payload, np.uint8).reshape(1, -1)
        full = self.codec.encode_tactic(rt, mat).result()
        hot_vol = self.cm.alloc_volume(int(CodeMode.Replica3))
        hot_bid, _ = self.cm.alloc_scope("bid", 1)
        wrote: set[int] = set()
        for i, unit in enumerate(hot_vol.units):
            node = self.nodes.get(unit.node_id)
            if node is None:
                continue
            try:
                node.create_vuid(unit.vuid, unit.disk_id)
                node.put_shard(unit.vuid, hot_bid, full[i].tobytes())
                wrote.add(i)
            except Exception:
                continue
        # shard 0 is NOT optional: the hot read path serves only the data
        # shard, so a redirect whose data replica never landed would send
        # every GET through a failed hot read before the EC fallback —
        # worse than no promotion at all
        if len(wrote) < rt.put_quorum or 0 not in wrote:
            # take the landed shards back out before failing: no redirect
            # references them, so nothing else ever would — and every
            # retry allocs a FRESH hot_bid, so leaked sets would pile up
            for i in wrote:
                unit = hot_vol.units[i]
                node = self.nodes.get(unit.node_id)
                if node is None:
                    continue
                try:
                    node.mark_delete_shard(unit.vuid, hot_bid)
                    node.delete_shard(unit.vuid, hot_bid)
                except Exception:
                    pass  # best effort; the write just succeeded here
            raise RuntimeError(
                f"hot promote of ({task.vid}, {task.bid}): wrote "
                f"{sorted(wrote)}/{rt.total} replicas, quorum "
                f"{rt.put_quorum} incl. the data shard")
        winner = self.cm.promote_blob(task.vid, task.bid, hot_vol.vid,
                                      hot_bid)
        if winner != (hot_vol.vid, hot_bid):
            # first committer won (a re-leased execution of this task beat
            # us past the lease backstop): OUR replica set is the orphan —
            # free it; the winner's redirect stands untouched
            for i in wrote:
                unit = hot_vol.units[i]
                node = self.nodes.get(unit.node_id)
                if node is None:
                    continue
                try:
                    node.mark_delete_shard(unit.vuid, hot_bid)
                    node.delete_shard(unit.vuid, hot_bid)
                except Exception:
                    pass
            return
        # delete-race re-check AFTER the commit: the deleter notes the key
        # BEFORE its own _drop_hot_copy, so either it sees our redirect
        # (and removes it) or we see its note here (and remove it) — a
        # promote racing a delete can never leave a dangling hot copy
        # serving a deleted blob's bytes
        if self.sched._recently_deleted(task.vid, task.bid):
            self.sched._drop_hot_copy(task.vid, task.bid)
            raise RuntimeError(
                f"blob ({task.vid}, {task.bid}) deleted during promote")
        registry("cache").counter("promotes").add()
        registry("cache").counter("promote_bytes").add(len(payload))
        from chubaofs_tpu.utils import events

        events.emit("tier_promote", entity=f"blob({task.vid},{task.bid})",
                    detail={"vid": task.vid, "bid": task.bid,
                            "hot_vid": hot_vol.vid, "hot_bid": hot_bid,
                            "bytes": len(payload)})

    # -- single-stripe shard repair -------------------------------------------

    def _repair_shards(self, vid: int, bid: int, bad_idx: list[int]):
        vol = self.cm.get_volume(vid)
        t = vol.tactic()
        unhandled = sorted(set(bad_idx))
        if t.L:
            unhandled = self._repair_local_stripes(vol, t, bid, unhandled)
            if not unhandled:
                return
        if t.is_regenerating and len(unhandled) == 1:
            # the repair-traffic win: a single loss under a regenerating
            # mode downloads d beta payloads, not N full shards. Multi-loss
            # (or any helper failure) falls through to the generic gather.
            if self._repair_regenerating(vol, t, bid, unhandled[0]):
                return
        elif t.is_regenerating and len(unhandled) > 1:
            registry("scheduler").counter(
                "repair_beta_fallback", {"reason": "multi_loss"}).add()
        self._repair_global(vol, t, bid)

    def _repair_local_stripes(self, vol: VolumeInfo, t, bid: int,
                              bad_idx: list[int]) -> list[int]:
        """LRC local-stripe-first repair (work_shard_recover.go:517
        recoverByLocalStripe): for each AZ whose damage fits its local parity
        budget, repair reading ONLY that AZ's shards. Returns the reported bad
        indexes that still need the global path."""
        span = trace.current_span()
        leftover: list[int] = []
        for idx, local_n, local_m in t.local_stripes():
            az_reported = [i for i in bad_idx if i in idx]
            if not az_reported:
                continue
            reads = self._probe(vol, bid, idx, span=span)  # same-AZ reads only
            az_bad = [i for i in idx if i not in reads]
            if not az_bad:
                continue
            if len(az_bad) > local_m:
                leftover.extend(az_reported)  # beyond local budget
                continue
            shard_len = len(next(iter(reads.values())))
            sub = np.zeros((len(idx), shard_len), np.uint8)
            pos = {g: p for p, g in enumerate(idx)}
            for g, data in reads.items():
                sub[pos[g]] = np.frombuffer(data, np.uint8)
            fixed = self.codec.reconstruct(
                local_n, local_m, sub, [pos[i] for i in az_bad]
            ).result()
            for g in az_bad:
                self._write_back(vol, g, bid, fixed[pos[g]].tobytes())
            # the repair-traffic win the LRC layout buys: these shards were
            # healed reading ONE local group, not the global stripe
            registry("scheduler").counter(
                "repair_local_shards").add(len(az_bad))
        return leftover

    def _repair_global(self, vol: VolumeInfo, t, bid: int):
        """Global-stripe repair + recompute of any missing local parities."""
        span = trace.current_span()
        stripe, present, shard_len = self._gather(vol, t, bid, span=span)
        missing = [i for i in range(t.N + t.M) if i not in present]
        if missing:
            fixed = self.codec.reconstruct_tactic(t, stripe, missing).result()
            for idx in missing:
                self._write_back(vol, idx, bid, fixed[idx].tobytes())
            stripe = fixed
            registry("scheduler").counter(
                "repair_global_shards").add(len(missing))
        if t.L:
            # local parities live outside the global stripe: any missing one is
            # recomputed from its AZ's (now whole) global shards
            local_idx = list(range(t.global_count, t.total))
            have = self._probe(vol, bid, local_idx, span=span)
            lost_azs = {t.az_of_shard(i) for i in local_idx if i not in have}
            local_n = (t.N + t.M) // t.az_count
            local_m = t.L // t.az_count
            for idx, _, _ in t.local_stripes():
                az = t.az_of_shard(idx[0])
                if az not in lost_azs:
                    continue
                src = stripe[idx[:local_n]]
                full = self.codec.encode(local_n, local_m, src).result()
                for p, g in enumerate(idx[local_n:]):
                    if g not in have:
                        self._write_back(vol, g, bid, full[local_n + p].tobytes())

    def _write_back(self, vol: VolumeInfo, idx: int, bid: int, payload: bytes):
        """Idempotent by construction: put_shard over an existing bid punches
        the superseded record and appends the same bytes, so a re-executed
        task (lease expiry, crash-restart) can never corrupt the stripe."""
        unit = vol.units[idx]
        node = self.nodes[unit.node_id]
        node.create_vuid(unit.vuid, unit.disk_id)
        node.put_shard(unit.vuid, bid, payload)
        registry("scheduler").counter("repaired_shards").add()

    def _read_one(self, vol: VolumeInfo, idx: int, bid: int) -> bytes:
        unit = vol.units[idx]
        node = self.nodes.get(unit.node_id)
        if node is None:
            raise ConnectionError(f"node {unit.node_id} unknown")
        return node.get_shard(unit.vuid, bid)

    def _drain_reads(self, futs: dict, out: dict, need: int | None = None) -> list:
        """Drain a {key: Future-of-bytes} fan-out under ONE shared
        read_deadline: successes land in `out` and feed the repair-traffic
        byte accounting; absent/unreachable/hung reads are returned as
        leftover keys, counted by failure class
        (cfs_scheduler_probe_fail{reason}) so a silent hang and a real bug
        stop being indistinguishable. The one timeout/cancel/classify
        block both _probe and _copy_direct ride — their semantics must
        never diverge.

        `need` is how many successes the decode strictly requires: bytes
        beyond it are HEDGES (straggler insurance) and count to
        repair_bytes_hedged instead of repair_bytes_downloaded, so
        bytes-per-repaired-shard stays an honest numerator. None = every
        read is required."""
        reg = registry("scheduler")
        deadline = time.monotonic() + self.read_deadline
        leftover = []
        got = 0
        for key, f in futs.items():
            try:
                data = f.result(timeout=max(0.0, deadline - time.monotonic()))
            except FutureTimeout:
                f.cancel()  # queued laggards release their pool slot
                reg.counter("probe_fail", {"reason": "timeout"}).add()
                leftover.append(key)
                continue
            except Exception as e:
                reg.counter("probe_fail",
                            {"reason": classify_io_error(e)}).add()
                leftover.append(key)
                continue
            out[key] = data
            got += 1
            if need is not None and got > need:
                reg.counter("repair_bytes_hedged").add(len(data))
            else:
                reg.counter("repair_bytes_downloaded").add(len(data))
        return leftover

    def _probe(self, vol: VolumeInfo, bid: int, idxs,
               span=None, need: int | None = None) -> dict[int, bytes]:
        """Read the given stripe positions CONCURRENTLY via _drain_reads;
        the whole fan-out lands on the span as a `download` stage."""
        idxs = list(idxs)
        if not idxs:
            return {}
        t0 = time.perf_counter()
        futs = {i: self._shard_pool.submit(self._read_one, vol, i, bid)
                for i in idxs}
        reads: dict[int, bytes] = {}
        self._drain_reads(futs, reads, need=need)
        if span is not None:
            span.add_stage("download", start=t0)
        return reads

    def _gather(self, vol: VolumeInfo, t, bid: int, span=None):
        """Read every readable global shard of a stripe; infer shard_len.
        Decode needs only N rows — the extra M reads are hedges and are
        accounted as such (_drain_reads need=N)."""
        reads = self._probe(vol, bid, range(t.N + t.M), span=span, need=t.N)
        if len(reads) < t.N:
            raise RuntimeError(f"stripe {vol.vid}/{bid}: {len(reads)} < N={t.N} readable")
        shard_len = len(next(iter(reads.values())))
        stripe = np.zeros((t.N + t.M, shard_len), np.uint8)
        for idx, data in reads.items():
            stripe[idx] = np.frombuffer(data, np.uint8)
        return stripe, sorted(reads), shard_len

    # -- beta-fetch repair (regenerating modes, codec/pm.py) -------------------

    def _read_combined(self, vol: VolumeInfo, idx: int, bid: int,
                       coeffs: bytes) -> bytes:
        unit = vol.units[idx]
        node = self.nodes.get(unit.node_id)
        if node is None:
            raise ConnectionError(f"node {unit.node_id} unknown")
        return node.get_shard_combined(unit.vuid, bid, coeffs)

    def _gather_beta(self, vol: VolumeInfo, t, bid: int, fail: int,
                     span=None):
        """Beta-fetch gather for a SINGLE lost shard of a regenerating
        stripe: the layout-aware helper set (Tactic.helper_set — same-AZ
        first) each ships its beta = shard/alpha combined payload
        (BlobNode.get_shard_combined). Returns (helpers, payloads (d, beta))
        or None when the survivors can't field d helpers or any helper read
        fails — the caller then falls back to the full-stripe gather, which
        needs only N of the survivors."""
        from chubaofs_tpu.codec import pm

        reg = registry("scheduler")

        def usable(i: int) -> bool:
            u = vol.units[i]
            if u.node_id not in self.nodes:
                return False
            d = self.cm.disks.get(u.disk_id)
            return d is None or d.status == DISK_NORMAL

        alive = [i for i in range(t.global_count)
                 if i != fail and usable(i)]
        helpers = t.helper_set(fail, alive)
        if not helpers:
            reg.counter("repair_beta_fallback",
                        {"reason": "helpers_short"}).add()
            return None
        kernel = pm.get_kernel(t.total, t.N)
        coeffs = kernel.helper_coeffs(fail).tobytes()
        t0 = time.perf_counter()
        futs = {i: self._shard_pool.submit(
                    self._read_combined, vol, i, bid, coeffs)
                for i in helpers}
        reads: dict[int, bytes] = {}
        # every helper is load-bearing (the repair matrix inverts exactly
        # these d rows): need=len so none of these bytes count as hedged
        self._drain_reads(futs, reads, need=len(helpers))
        if span is not None:
            span.add_stage("download", start=t0)
        if len(reads) < len(helpers):
            reg.counter("repair_beta_fallback", {"reason": "read_fail"}).add()
            return None
        payloads = np.stack(
            [np.frombuffer(reads[i], np.uint8) for i in helpers])
        from chubaofs_tpu.codec.codemode import CodeMode

        reg.counter("repair_helper_bytes",
                    {"mode": CodeMode(vol.code_mode).name}).add(
            int(payloads.size))
        return helpers, payloads

    def _repair_regenerating(self, vol: VolumeInfo, t, bid: int,
                             fail: int) -> bool:
        """Single-loss beta repair: d combined sub-shard reads, ONE
        (alpha, d) matmul decode through the codec service, write back.
        Returns False (nothing written) when the beta path can't run —
        _repair_global then handles the stripe generically."""
        from chubaofs_tpu.codec import pm

        span = trace.current_span()
        got = self._gather_beta(vol, t, bid, fail, span=span)
        if got is None:
            return False
        helpers, payloads = got
        kernel = pm.get_kernel(t.total, t.N)
        mat = kernel.repair_matrix(fail, helpers)
        fixed = self.codec.matmul(mat, payloads).result()
        self._write_back(vol, fail, bid, fixed.reshape(-1).tobytes())
        registry("scheduler").counter("repair_beta_shards").add()
        return True

    # -- disk-level migrate (bulk; the 10k-stripe batch path) ------------------

    def _migrate_disk(self, task: Task, lease: int | None = None):
        """Move every stripe position off a disk.

        Order matters: GATHER (and copy/reconstruct) the rows through the OLD
        units first — for a drop of a healthy disk that's a plain read-copy —
        and only then re-home the units in clustermgr. A crash mid-task
        leaves every uncommitted unit's old mapping intact and the task
        retryable. The prepare/commit split is also the cross-unit pipeline:
        while unit k's reconstructs drain through the device, unit k+1's
        survivor downloads are already in flight — with few bids per unit,
        this (not the intra-unit window) is where the overlap comes from."""
        source_broken = self.cm.disks[task.disk_id].status != DISK_NORMAL
        affected = self.cm.volumes_on_disk(task.disk_id)
        # bounded prepare-ahead: holding every unit's reconstructed rows at
        # once would scale memory with the whole disk, not the window.
        # window <= 1 means the SERIAL control path — depth 1, no cross-unit
        # overlap either, so the bench A/B measures what it claims to
        window = self.repair_window or 0
        depth = max(2, window) if window > 1 else 1
        pending: deque = deque()
        for vol, unit in affected:
            # a disk migrate routinely outlives one lease: renew per unit so
            # a HEALTHY worker never races the reaper; a lost lease (we were
            # reaped and possibly re-leased) aborts — the work is someone
            # else's now, and idempotent write-back keeps the abort safe
            if lease is not None and \
                    not self.sched.renew_lease(task.task_id, lease):
                raise RuntimeError(
                    f"lease {lease} lost mid-migrate of disk {task.disk_id}")
            pending.append(
                self._prepare_unit(vol, unit, task.disk_id, source_broken))
            if len(pending) >= depth:
                self._commit_unit(pending.popleft(), task.disk_id)
        while pending:
            self._commit_unit(pending.popleft(), task.disk_id)
        self.cm.set_disk_status(task.disk_id, DISK_DROPPED)

    def _balance_unit(self, task: Task):
        """Move ONE volume unit off an (otherwise healthy) overloaded disk."""
        vol = self.cm.get_volume(task.vid)
        unit = next((u for u in vol.units if u.disk_id == task.disk_id), None)
        if unit is None:
            # a previous attempt already re-homed the mapping but may have
            # died mid-copy (mapping updates before the shard writes): sweep
            # the volume's stripes through the repair plane rather than
            # declaring victory over a silently degraded stripe
            self._enqueue_missing(vol)
            return
        source_broken = self.cm.disks[task.disk_id].status != DISK_NORMAL
        prep = self._prepare_unit(vol, unit, task.disk_id, source_broken)
        self._commit_unit(prep, task.disk_id,
                          dest_disk_id=task.dest_disk_id)

    def _enqueue_missing(self, vol: VolumeInfo):
        """Probe every stripe position of every bid in the volume; feed any
        missing/unreadable position to the repair topic."""
        t = vol.tactic()
        bids: set[int] = set()
        for u in vol.units:
            node = self.nodes.get(u.node_id)
            if node is None:
                continue
            try:
                bids.update(m.bid for m in node.list_shards(u.vuid))
            except Exception:
                continue
        for bid in sorted(bids):
            have = self._probe(vol, bid, range(t.total))
            bad = [i for i in range(t.total) if i not in have]
            if bad:
                self.sched.proxy.send_shard_repair(vol.vid, bid, bad,
                                                   "balance_retry")

    def _copy_direct(self, vol: VolumeInfo, unit, bids: list[int],
                     rows: dict[int, bytes]) -> list[int]:
        """Healthy-source fast path: CONCURRENT bounded reads of the unit's
        own rows via _drain_reads (a serial loop here would pay
        read_deadline per slow bid, not per unit). Returns the bids that
        still need the gather/reconstruct pipeline."""
        node = self.nodes.get(unit.node_id)
        if node is None:
            return list(bids)
        futs = {bid: self._shard_pool.submit(node.get_shard, unit.vuid, bid)
                for bid in bids}
        return self._drain_reads(futs, rows)

    def _gather_for_unit(self, vol: VolumeInfo, t, unit, bid: int,
                         span=None):
        """Mode-aware stripe gather for the migrate/rebuild pipeline: a
        regenerating volume first tries the beta-fetch for the migrating
        unit's row (d combined payloads instead of a full-stripe gather —
        the bulk-rebuild path is where nearly all repair bytes move) and
        falls back to the full gather when helpers can't cover it."""
        if t.is_regenerating and unit.index < t.global_count:
            got = self._gather_beta(vol, t, bid, unit.index, span=span)
            if got is not None:
                return ("beta",) + got
        return ("full", self._gather(vol, t, bid, span=span))

    def _stripe_row(self, vol: VolumeInfo, t, unit, bid: int, gathered,
                    rows: dict[int, bytes], futures: dict[int, object]):
        """Turn one gathered stripe into the migrating unit's row: a present
        survivor copies, a lost global shard becomes a (batchable) device
        reconstruct future, a lost local parity re-encodes its AZ stripe.
        A beta-gather (regenerating modes) becomes the (alpha, d) repair
        matmul — batchable on the device exactly like the RS decodes."""
        from concurrent.futures import Future

        if gathered[0] == "beta":
            _, helpers, payloads = gathered
            from chubaofs_tpu.codec import pm

            kernel = pm.get_kernel(t.total, t.N)
            mat = kernel.repair_matrix(unit.index, helpers)
            mm = self.codec.matmul(mat, payloads)
            # _commit_unit resolves futures as result()[unit.index]: deliver
            # the single rebuilt row under that key (a dict indexes the same
            # way a full stripe array does)
            out: Future = Future()
            idx = unit.index

            def _fin(f: Future, out=out, idx=idx):
                if f.exception():
                    out.set_exception(f.exception())
                else:
                    out.set_result({idx: f.result().reshape(-1)})

            mm.add_done_callback(_fin)
            futures[bid] = out
            registry("scheduler").counter("repair_beta_shards").add()
            return
        stripe, present, _ = gathered[1]
        missing = [i for i in range(t.N + t.M) if i not in present]
        if unit.index in present:
            rows[bid] = stripe[unit.index].tobytes()
        elif unit.index < t.global_count:
            # repair with the FULL missing set: zero-filled absent rows
            # must never be treated as survivors
            futures[bid] = self.codec.reconstruct_tactic(t, stripe, missing)
        else:
            # LRC local parity: complete the globals, then re-encode
            # this AZ's local stripe to regenerate the lost row
            if missing:
                stripe = self.codec.reconstruct(t.N, t.M, stripe, missing).result()
            local_n = (t.N + t.M) // t.az_count
            local_m = t.L // t.az_count
            for idx, _, _ in t.local_stripes():
                if unit.index in idx:
                    full = self.codec.encode(
                        local_n, local_m, stripe[idx[:local_n]]
                    ).result()
                    pos = idx[local_n:].index(unit.index)
                    rows[bid] = full[local_n + pos].tobytes()
                    break

    def _rebuild_rows(self, vol: VolumeInfo, t, unit, bids: list[int],
                      rows: dict[int, bytes], futures: dict[int, object]):
        """The windowed rebuild pipeline (the _put_pipelined window pattern
        applied to repair-GET): up to repair_window stripes' survivor
        gathers run on the stripe pool while earlier stripes' reconstructs
        drain through the codec service's device batches — downloads never
        idle waiting on decode, decode never starves waiting on the network.
        Consumption is bid order, so write-back order is deterministic.
        repair_window <= 1 degenerates to the serial control path."""
        if not bids:
            return
        span = trace.current_span()
        window = self.repair_window
        if window <= 1:
            for bid in bids:
                self._stripe_row(vol, t, unit, bid,
                                 self._gather_for_unit(vol, t, unit, bid,
                                                       span=span),
                                 rows, futures)
            return

        def gather_job(bid: int):
            # the task span follows the gather onto the pool worker so its
            # download stage (and any failpoint evidence) lands on the trace
            if span is not None:
                trace.push_span(span)
            try:
                return self._gather_for_unit(vol, t, unit, bid, span=span)
            finally:
                if span is not None:
                    trace.pop_span()

        occ = registry("scheduler").summary("rebuild_window_occupancy",
                                            buckets=BATCH_BUCKETS)
        pending: deque = deque()
        it = iter(bids)
        nxt = next(it, None)
        while pending or nxt is not None:
            while nxt is not None and len(pending) < window:
                pending.append((nxt, self._stripe_pool.submit(gather_job, nxt)))
                nxt = next(it, None)
            occ.observe(len(pending))
            bid, f = pending.popleft()
            self._stripe_row(vol, t, unit, bid, f.result(), rows, futures)

    def _prepare_unit(self, vol: VolumeInfo, unit, source_disk_id: int,
                      source_broken: bool) -> dict:
        """Phase 1 of a unit move: gather/copy every row and SUBMIT the
        reconstructs (decode futures left in flight — the codec service
        batches them into shared device calls, and the caller may start the
        next unit's downloads while they drain). No cluster state changes
        here: a crash after prepare leaves the old mapping untouched."""
        t = vol.tactic()
        # every bid in this volume, seen from any unit (source included when healthy)
        bids: set[int] = set()
        for u in vol.units:
            if u.disk_id == source_disk_id and source_broken:
                continue
            node = self.nodes.get(u.node_id)
            if node is None:
                continue
            try:
                bids.update(m.bid for m in node.list_shards(u.vuid))
            except Exception:
                continue
        # source copies or reconstruct futures. Tombstones TRAVEL with the
        # unit — enumerated DIRECTLY from the source chunk (they are
        # invisible to list_shards, so deriving them from live bids would
        # drop any delete whose bid no reachable unit still serves) — a bid
        # deleted at the source must stay deleted at the destination.
        src_node = self.nodes.get(unit.node_id)
        tombstoned: set[int] = set()
        if src_node is not None:
            try:
                tombstoned = src_node.tombstones_of(unit.vuid)
            except Exception:
                pass
        rows: dict[int, bytes] = {}
        futures: dict[int, object] = {}
        work = [b for b in sorted(bids) if b not in tombstoned]
        if not source_broken:
            work = self._copy_direct(vol, unit, work, rows)
        self._rebuild_rows(vol, t, unit, work, rows, futures)
        return {"vol": vol, "unit": unit, "rows": rows, "futures": futures,
                "tombstoned": tombstoned}

    def _commit_unit(self, prep: dict, source_disk_id: int,
                     dest_disk_id: int | None = None):
        """Phase 2: resolve the in-flight decodes, then re-home the unit in
        clustermgr and write everything to the new disk. The mapping update
        stays AFTER all reads/decodes so a failed prepare never half-moves."""
        vol, unit = prep["vol"], prep["unit"]
        rows, tombstoned = prep["rows"], prep["tombstoned"]
        for bid, fut in prep["futures"].items():
            rows[bid] = fut.result()[unit.index].tobytes()

        dest = dest_disk_id
        if dest is not None:
            # a destination pinned at scheduling time may have gone stale
            d = self.cm.disks.get(dest)
            if d is None or d.status != DISK_NORMAL or \
                    dest in {u.disk_id for u in vol.units}:
                dest = None
        if dest is None:
            dest = self._dest_for(vol, source_disk_id)
        old_vuid, old_node_id = unit.vuid, unit.node_id
        new_unit = self.cm.update_volume_unit(vol.vid, unit.index, dest)
        dest_node = self.nodes[new_unit.node_id]
        dest_node.create_vuid(new_unit.vuid, new_unit.disk_id)
        for bid, payload in rows.items():
            dest_node.put_shard(new_unit.vuid, bid, payload)
        registry("scheduler").counter("repaired_shards").add(len(rows))
        for bid in tombstoned:
            dest_node.tombstone_shard(new_unit.vuid, bid)
        # the move must FREE the source: drop the superseded chunk (best
        # effort — an unreachable/broken source just leaks until re-imaged)
        old_node = self.nodes.get(old_node_id)
        if old_node is not None:
            try:
                old_node.drop_vuid(old_vuid)
            except Exception:
                pass

    def _dest_for(self, vol: VolumeInfo, source_disk_id: int) -> int:
        vol_disks = {u.disk_id for u in vol.units}
        return self.sched.pick_dest_disk(
            exclude=vol_disks | {source_disk_id},
            az=self.cm.disks[source_disk_id].az,
        )
