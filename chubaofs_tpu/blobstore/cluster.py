"""MiniCluster — an in-process blobstore cluster for tests and local use.

Reference analog: master/mocktest + docker-compose bring-up (SURVEY §4) — the
reference validates multi-node behavior with in-process fakes speaking the real
interfaces. Here every component is the REAL implementation wired directly:
N blobnodes with D disks each, one clustermgr, one proxy, one access gateway,
one scheduler + repair worker, all sharing one CodecService.
"""

from __future__ import annotations

import os

from chubaofs_tpu.blobstore.access import Access
from chubaofs_tpu.blobstore.blobnode import BlobNode
from chubaofs_tpu.blobstore.clustermgr import ClusterMgr
from chubaofs_tpu.blobstore.proxy import Proxy
from chubaofs_tpu.blobstore.scheduler import RepairWorker, Scheduler
from chubaofs_tpu.codec.service import CodecService


class MiniCluster:
    def __init__(
        self,
        root: str,
        n_nodes: int = 6,
        disks_per_node: int = 2,
        azs: int = 1,
        persist_cm: bool = True,
        codec: CodecService | None = None,
        cache: "BlobCache | None" = None,
    ):
        """codec: inject a shared/mesh-backed CodecService (e.g. one built
        with a jax Mesh so access PUT/GET and scheduler repair run their
        device math dp/sp-sharded across every chip); default single-device.
        cache: inject a blobstore.cache.BlobCache for the tiered read plane;
        default comes from the environment (CFS_CACHE_MB > 0), so daemon
        deployments and the capacity harness opt in with one knob."""
        from chubaofs_tpu.blobstore.cache import BlobCache

        self.root = root
        self._owns_codec = codec is None  # injected services outlive us
        self.codec = codec or CodecService()
        if cache is None:
            cache = BlobCache.from_env(os.path.join(root, "cache"))
        self.cache = cache
        self.cm = ClusterMgr(os.path.join(root, "cm") if persist_cm else None)
        self.nodes: dict[int, BlobNode] = {}
        for n in range(1, n_nodes + 1):
            roots = [os.path.join(root, f"node{n}", f"disk{d}") for d in range(disks_per_node)]
            node = BlobNode(node_id=n, disk_roots=roots)
            self.nodes[n] = node
            az = (n - 1) % azs
            self.cm.register_disks([
                {"disk_id": disk_id, "node_id": n, "az": az}
                for disk_id in node.disks])
        self.proxy = Proxy(self.cm, data_dir=os.path.join(root, "proxy"))
        self.access = Access(self.cm, self.proxy, self.nodes, codec=self.codec,
                             cache=self.cache)
        self.scheduler = Scheduler(self.cm, self.proxy, self.nodes,
                                   codec=self.codec, cache=self.cache)
        self.worker = RepairWorker(self.scheduler, self.nodes, codec=self.codec)

    def run_background_once(self) -> dict:
        """One tick of every background loop (the 16-ticker scheduleTask analog):
        detection first (heartbeats, heartbeat expiry, lease reaping, the
        budgeted scrub), then the task planes, then host-local hygiene."""
        # heartbeats are per-node daemon work: a dead/closed engine simply
        # stops beating, which IS the signal the expiry below consumes
        for n in list(self.nodes.values()):
            try:
                n.heartbeat(self.cm)
            except Exception:
                pass
        dead_disks = self.scheduler.check_node_health()
        reaped = self.scheduler.reap_expired()
        scrubbed = self.scheduler.run_scrub()
        inspected = self.scheduler.inspect_volumes()
        polled = self.scheduler.poll_repair_topic()
        tier_msgs = self.scheduler.run_tier()
        disk_tasks = self.scheduler.check_disks()
        balance_task = self.scheduler.check_balance()
        ran = 0
        while self.worker.run_once():
            ran += 1
        deleted = self.scheduler.run_deleter()
        # compaction is host-local work: a dark/dead node skips its own sweep
        # without stalling the cluster's (the daemon analog runs it per host)
        compacted = 0
        for n in self.nodes.values():
            try:
                compacted += n.compact_once()
            except Exception:
                pass
        return {
            "inspect_msgs": inspected,
            "repair_msgs": polled,
            "tier_msgs": tier_msgs,
            "disk_tasks": len(disk_tasks),
            "balance_tasks": 1 if balance_task else 0,
            "tasks_ran": ran,
            "deletes": deleted,
            "compacted_bytes": compacted,
            "hb_expired_disks": len(dead_disks),
            "leases_reaped": reaped,
            "scrub_findings": scrubbed,
        }

    def close(self):
        if self._owns_codec:  # never kill a shared/injected service
            self.codec.close()
        self.access.close()
        self.worker.close()
        for node in self.nodes.values():
            node.close()
        self.cm.close()
