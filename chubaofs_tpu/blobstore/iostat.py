"""Shared-memory IO stats (blobstore/common/iostat analog).

Reference counterpart: common/iostat/iostat.go:50,151-168 — blobnode emits
read/write iops + byte + latency counters into mmap'd files under /dev/shm so
node-side viewers can watch disk IO without scraping HTTP. Kept: a fixed
little-endian counter block in a memory-mapped file, writer increments with
atomic-enough single-writer semantics, reader side decodes the same struct.
Layout (8 x u64): rcnt, rbytes, rlat_us, rpending, wcnt, wbytes, wlat_us,
wpending.
"""

from __future__ import annotations

import mmap
import os
import struct

_FIELDS = ("rcnt", "rbytes", "rlat_us", "rpending",
           "wcnt", "wbytes", "wlat_us", "wpending")
_BLOCK = struct.Struct("<8Q")


def _shm_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


class IOStat:
    """Single-writer counter block; one per (module, disk).

    The same counters also mirror into the `iostat` role registry (gauges
    labeled by block name) so a daemon's /metrics carries them — the shm
    block stays the node-side zero-HTTP view, the registry the scrape view."""

    def __init__(self, name: str, path: str | None = None):
        self.name = name
        self.path = path or os.path.join(_shm_dir(), f"cfs-iostat-{name}")
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            os.ftruncate(fd, _BLOCK.size)
            self._mm = mmap.mmap(fd, _BLOCK.size)
        finally:
            os.close(fd)
        self._vals = dict.fromkeys(_FIELDS, 0)
        from chubaofs_tpu.utils.exporter import registry

        # gauges bound ONCE (labels never change): _flush runs per IO and
        # must not pay a registry-lock lookup per field per operation
        lab = {"name": name}
        self._gauges = [(f, registry("iostat").gauge(f, lab))
                        for f in _FIELDS]

    def _flush(self):
        self._mm[:] = _BLOCK.pack(*(self._vals[f] for f in _FIELDS))
        for f, g in self._gauges:
            g.set(self._vals[f])

    def read_begin(self):
        self._vals["rpending"] += 1
        self._flush()

    def read_done(self, nbytes: int, lat_us: int):
        v = self._vals
        v["rcnt"] += 1
        v["rbytes"] += nbytes
        v["rlat_us"] += lat_us
        v["rpending"] = max(0, v["rpending"] - 1)
        self._flush()

    def write_begin(self):
        self._vals["wpending"] += 1
        self._flush()

    def write_done(self, nbytes: int, lat_us: int):
        v = self._vals
        v["wcnt"] += 1
        v["wbytes"] += nbytes
        v["wlat_us"] += lat_us
        v["wpending"] = max(0, v["wpending"] - 1)
        self._flush()

    def close(self):
        self._mm.close()
        # a closed block's mirror gauges must not render as a live idle
        # node on every later scrape
        from chubaofs_tpu.utils.exporter import registry

        lab = {"name": self.name}
        for f, _ in self._gauges:
            registry("iostat").unregister(f, lab)

    @staticmethod
    def view(path: str) -> dict:
        """Reader side: decode a counter block (the iostat viewer's read)."""
        with open(path, "rb") as f:
            raw = f.read(_BLOCK.size)
        return dict(zip(_FIELDS, _BLOCK.unpack(raw)))
