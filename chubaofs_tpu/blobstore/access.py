"""Access — the stateless blobstore gateway: PUT / GET / DELETE.

Reference counterpart: blobstore/access (stream_put.go:45-442, stream_get.go:112,
server_location.go). Semantics kept:

  * PUT splits the object into blobs of at most MAX_BLOB_SIZE, picks a code mode
    by size (SelectCodeMode analog), allocates a volume + bids, EC-encodes, and
    writes shards to blobnodes with a put-quorum; shards that fail the write are
    queued on the repair topic (stream_put.go:377-397).
  * GET reads data shards directly and falls back to on-the-fly reconstruction
    from parity when shards are missing/corrupt (stream_get.go:427-430,
    getDataShardOnly :527), emitting repair messages for what it found broken.
  * Locations are HMAC-signed tokens (server_location.go) carrying the blob map.

TPU-native difference: all codec math goes through the batching CodecService, so
concurrent PUT/GET streams share fused-kernel device batches instead of each
paying a dispatch.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import threading
import time
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass, field

import numpy as np

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore.blobnode import BlobNode
from chubaofs_tpu.blobstore.clustermgr import ClusterMgr, VolumeInfo
from chubaofs_tpu.blobstore.proxy import Proxy
from chubaofs_tpu.codec.codemode import CodeMode, get_tactic
from chubaofs_tpu.codec.service import CodecService, default_service
from chubaofs_tpu.utils.auditlog import record_slow_op
from chubaofs_tpu.utils.breaker import CircuitBreaker
from chubaofs_tpu.utils.locks import SanitizedLock
from chubaofs_tpu.utils.exporter import BATCH_BUCKETS, registry

MAX_BLOB_SIZE = 4 * 1024 * 1024


class AccessError(Exception):
    pass


class QuorumError(AccessError):
    pass


class VolumeFullError(AccessError):
    """Quorum failed because the volume's chunks are full — rotate volumes."""


class LocationError(AccessError):
    pass


class DiskPunished(AccessError):
    """Disk is in its punish window after repeated errors/timeouts — writes
    fail fast instead of queueing behind a wedged device (stream_put.go:303-340
    punishDisk analog)."""


class _PipelineAborted(Exception):
    """Internal: a later pipeline stage was skipped because an earlier blob's
    quorum already failed — never user-visible (the first real error wins)."""


@dataclass(frozen=True)
class CodeModePolicy:
    """One enabled size band for a code mode (access/codemode.go:24-45 analog)."""

    mode: CodeMode
    min_size: int = 0
    max_size: int = 1 << 62


def default_policies(az_count: int) -> list[CodeModePolicy]:
    """Size-tiered, AZ-aware policy table. Small blobs favor low shard-count
    modes (less per-shard overhead); large blobs favor wide stripes; clusters
    with >=2 AZs put LRC modes on the live path so repairs stay AZ-local
    (codemode.go:119-126)."""
    K, M_ = 1024, 1024 * 1024
    if az_count >= 3:
        return [
            CodeModePolicy(CodeMode.EC6P6, 0, 128 * K),
            CodeModePolicy(CodeMode.EC12P9, 128 * K + 1, M_),
            CodeModePolicy(CodeMode.EC6P3L3, M_ + 1),  # LRC archive tier
        ]
    if az_count == 2:
        return [
            CodeModePolicy(CodeMode.EC6P10L2, 0, M_),
            CodeModePolicy(CodeMode.EC16P20L2, M_ + 1),  # LRC archive tier
        ]
    return [
        CodeModePolicy(CodeMode.EC3P3, 0, 128 * K),
        CodeModePolicy(CodeMode.EC6P3, 128 * K + 1, M_),
        CodeModePolicy(CodeMode.EC12P4, M_ + 1),
    ]


def select_code_mode(size: int, policies: list[CodeModePolicy] | None = None) -> CodeMode:
    """Policy-table code-mode choice (stream_put.go:64 SelectCodeMode analog)."""
    for p in policies or default_policies(1):
        if p.min_size <= size <= p.max_size:
            return p.mode
    raise AccessError(f"no code-mode policy covers size {size}")


@dataclass
class Blob:
    bid: int
    vid: int
    size: int


@dataclass
class Location:
    cluster_id: int
    code_mode: int
    size: int
    blobs: list[Blob] = field(default_factory=list)
    crc: int = 0
    signature: str = ""

    def to_json(self) -> str:
        d = {
            "cluster_id": self.cluster_id,
            "code_mode": self.code_mode,
            "size": self.size,
            "blobs": [b.__dict__ for b in self.blobs],
            "crc": self.crc,
            "signature": self.signature,
        }
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "Location":
        d = json.loads(s)
        blobs = [Blob(**b) for b in d.pop("blobs")]
        return cls(**{**d, "blobs": blobs})


class Access:
    """One gateway instance. nodes maps node_id -> BlobNode (transport-pluggable)."""

    def __init__(
        self,
        cm: ClusterMgr,
        proxy: Proxy,
        nodes: dict[int, BlobNode],
        codec: CodecService | None = None,
        secret: bytes = b"chubaofs-tpu-location-secret",
        cluster_id: int = 1,
        max_workers: int = 16,
        policies: list[CodeModePolicy] | None = None,
        per_disk_cap: int = 4,
        write_deadline: float = 10.0,
        read_deadline: float = 3.0,
        punish_secs: float = 30.0,
        qos=None,
        cache=None,
    ):
        self.cm = cm
        self.proxy = proxy
        self.nodes = nodes
        # optional blobstore.cache.BlobCache (ISSUE 12): zipfian GET traffic
        # serves its hot head from here instead of an EC shard gather per
        # read; None keeps the pre-cache read path byte-identical
        self.cache = cache
        self.codec = codec or default_service()
        self.secret = secret
        self.cluster_id = cluster_id
        if policies is None:
            azs = {d.az for d in cm.disks.values()} or {0}
            policies = default_policies(len(azs))
        self.policies = policies
        # failure containment (stream_put.go:303-351): bounded in-flight writes
        # per disk, a hard deadline per stripe write, and a punish window after
        # errors so one wedged blobnode can't exhaust the pool or stall
        # unrelated PUTs
        self.per_disk_cap = per_disk_cap
        self.write_deadline = write_deadline
        # direct-read patience before a shard is handed to the degraded
        # path: a wedged blobnode turns into a reconstruct, not a stall
        self.read_deadline = read_deadline
        self.punish_secs = punish_secs
        self.qos = qos  # optional utils.ratelimit.KeyedLimiter ("put"/"get" bytes)
        self.qos_timeout = 30.0  # max throttle wait before failing the request
        self._disk_sems: dict[int, threading.Semaphore] = {}
        self._punished: dict[int, float] = {}
        self._punish_lock = SanitizedLock(name="access.punish")
        # client-side breaker around control-plane (allocator/proxy) calls:
        # a dead allocator fails PUTs fast instead of stacking every request
        # behind its timeouts (stream_put.go:68 hystrix analog)
        self._alloc_breaker = CircuitBreaker("proxy-alloc", failures=5,
                                             window=10.0, cooldown=5.0)
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="access")
        # reads NEVER share the write pool: stripe writes can legitimately
        # hold slots up to write_deadline (wedged-disk containment), and a GET
        # queued behind them would trade its millisecond latency for seconds
        self._read_pool = ThreadPoolExecutor(max_workers=max_workers,
                                             thread_name_prefix="access-read")
        # background integrity probes get their OWN small executors: a probe
        # against a wedged blobnode may pin its worker for the wedge duration,
        # and that must starve neither PUT stripes nor GET hedges
        self._probe_pool = ThreadPoolExecutor(max_workers=2,
                                              thread_name_prefix="access-probe")
        self._probe_io = ThreadPoolExecutor(max_workers=4,
                                            thread_name_prefix="access-probe-io")
        self._probing: set[tuple[int, int]] = set()  # (vid, bid) dedupe
        self._probe_lock = SanitizedLock(name="access.probe")
        # data-path pipeline: bounded encode->write overlap window for
        # multi-blob PUTs, and blob-level GET readahead depth. 0 = serial.
        self.pipeline_window = int(os.environ.get("CFS_PIPELINE_WINDOW", "3"))
        # how many blobs may be ENCODED ahead of the write window: wide
        # enough that the codec service still forms full device batches
        # (window-sized encode submission would cap batches at 2-4 jobs),
        # bounded so a 1000-blob object doesn't materialize 1000 stripes
        self.encode_ahead = int(os.environ.get("CFS_PUT_ENCODE_AHEAD", "16"))
        self.max_blob_size = MAX_BLOB_SIZE
        # blob-level pipeline stages get their OWN executor: a PUT stage
        # blocks on a codec future plus shard fan-outs running on self._pool
        # (and a GET stage on self._read_pool) — running stages on either of
        # those pools would let W blocked stages starve their own shard IO
        self._pipe_pool = ThreadPoolExecutor(max_workers=8,
                                             thread_name_prefix="access-pipe")

    # -- failure containment --------------------------------------------------

    def _sem(self, disk_id: int) -> threading.Semaphore:
        with self._punish_lock:
            sem = self._disk_sems.get(disk_id)
            if sem is None:
                sem = threading.Semaphore(self.per_disk_cap)
                self._disk_sems[disk_id] = sem
            return sem

    def _is_punished(self, disk_id: int) -> bool:
        with self._punish_lock:
            return self._punished.get(disk_id, 0.0) > time.monotonic()

    def punish_disk(self, disk_id: int, reason: str = "") -> None:
        with self._punish_lock:
            self._punished[disk_id] = time.monotonic() + self.punish_secs
        registry("access").counter(
            "disk_punish", {"reason": reason or "error"}).add()

    def clear_punishments(self) -> None:
        """Drop every active punish window (ops lever): once an AZ/host
        recovery is CONFIRMED, writes may trust it again immediately instead
        of waiting out punish_secs — otherwise a second failure inside the
        window sees the healed AZ as still dark and blobs land with two AZs'
        worth of shards missing."""
        with self._punish_lock:
            self._punished.clear()

    # -- location signing ----------------------------------------------------

    def _sign(self, loc: Location) -> str:
        payload = json.dumps(
            [loc.cluster_id, loc.code_mode, loc.size, [(b.bid, b.vid, b.size) for b in loc.blobs], loc.crc]
        ).encode()
        return hmac.new(self.secret, payload, hashlib.sha256).hexdigest()

    def _check_sig(self, loc: Location):
        if not hmac.compare_digest(self._sign(loc), loc.signature):
            raise LocationError("bad location signature")

    # -- PUT -----------------------------------------------------------------

    def put(self, data: bytes, code_mode: CodeMode | int | None = None) -> Location:
        from chubaofs_tpu.blobstore import trace

        if self.qos is not None and not self.qos.wait("put", len(data), timeout=self.qos_timeout):
            registry("access").counter("qos_reject", {"op": "put"}).add()
            raise AccessError("put bandwidth limit exceeded")
        with trace.child_of(trace.current_span(), "access.put") as span, \
                registry("access").tp("put"):
            span.set_tag("size", len(data))
            err: Exception | None = None
            try:
                loc = self._put(data, code_mode)
                return loc
            except Exception as e:
                err = e
                raise
            finally:
                span.append_track_log("access", err=err)
                record_slow_op("access", "put",
                               time.perf_counter() - span.start, span=span,
                               err=type(err).__name__ if err else "")

    def _put(self, data: bytes, code_mode: CodeMode | int | None = None) -> Location:
        from chubaofs_tpu.blobstore import trace

        if not data:
            raise AccessError("empty put")
        span = trace.current_span()
        t_prep = time.perf_counter()
        mode = (
            int(code_mode)
            if code_mode is not None
            else int(select_code_mode(len(data), self.policies))
        )
        loc = Location(cluster_id=self.cluster_id, code_mode=mode, size=len(data), crc=zlib.crc32(data))

        blobs = [data[i : i + self.max_blob_size]
                 for i in range(0, len(data), self.max_blob_size)]
        if span is not None:  # crc + blob split: the host-prepare stage
            span.add_stage("prepare", start=t_prep)
        t_alloc = time.perf_counter()
        first_bid, _ = self._alloc_breaker.call(self.proxy.alloc_bids, len(blobs))
        if span is not None:
            span.add_stage("alloc", start=t_alloc)
        t = get_tactic(mode)
        window = int(self.pipeline_window)
        if window >= 1 and len(blobs) > 1:
            loc.blobs.extend(self._put_pipelined(t, mode, blobs, first_bid,
                                                 window))
        else:
            loc.blobs.extend(self._put_serial(t, mode, blobs, first_bid))
        loc.signature = self._sign(loc)
        return loc

    @staticmethod
    def _cancel_encodes(enc_futs: dict) -> None:
        """Best-effort cancel of encode-ahead futures a failed pipeline will
        never consume: queued codec jobs are dropped before device work
        (the service's running-handshake makes this race-free); running
        ones finish and are discarded — waste bounded by encode_ahead."""
        for f in enc_futs.values():
            f.cancel()
        enc_futs.clear()

    def _encode_blob(self, t, blob: bytes):
        """Submit one blob to the codec service; returns the stripe future.
        One composed-matrix device pass yields global AND local parity."""
        shard_len = t.shard_size(len(blob))
        mat = np.zeros((t.N, shard_len), np.uint8)
        flat = mat.reshape(-1)
        flat[: len(blob)] = np.frombuffer(blob, np.uint8)
        return self.codec.encode_tactic(t, mat)

    def _write_blob(self, t, mode: int, vol: VolumeInfo, bid: int,
                    stripe: np.ndarray) -> VolumeInfo:
        """Stripe write with the full-volume rotation retry; returns the
        volume the blob actually landed on. The grant set rotates across
        active_vols volumes that fill in LOCKSTEP, so a re-alloc after
        retiring one full volume may hand back one of its equally-full
        siblings — allow one rotation per granted volume before a fresh
        replacement is guaranteed; the final attempt propagates."""
        rotations = getattr(self.proxy, "active_vols", 1) + 1
        for _ in range(rotations):
            try:
                self._write_stripe(t, vol, bid, stripe)
                return vol
            except VolumeFullError:
                # rotate: retire the full volume, take another, retry
                self.cm.set_volume_status(vol.vid, "idle")
                self.proxy.invalidate(mode)
                vol = self._alloc_breaker.call(self.proxy.alloc_volume, mode)
        self._write_stripe(t, vol, bid, stripe)
        return vol

    def _put_serial(self, t, mode: int, blobs: list[bytes],
                    first_bid: int) -> list[Blob]:
        """Pre-pipeline path (pipeline_window=0 or single blob): encode all
        blobs first (they batch inside the codec service), then fan shard
        writes out per blob, one blob at a time."""
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        futures = []
        metas = []
        for i, blob in enumerate(blobs):
            t_alloc = time.perf_counter()
            vol = self._alloc_breaker.call(self.proxy.alloc_volume, mode)
            if span is not None:
                span.append_track_log("proxy", start=t_alloc)
                span.add_stage("alloc", start=t_alloc)
            futures.append(self._encode_blob(t, blob))
            metas.append((first_bid + i, vol, len(blob)))

        out = []
        for fut, (bid, vol, size) in zip(futures, metas):
            t_enc = time.perf_counter()
            stripe = fut.result()  # (total, shard_len), locals included
            if span is not None:
                span.append_track_log("codec", start=t_enc)
                # wait-for-stripe: codec queue + device batch, as the PUT
                # experiences it (the codec side adds its own host/device
                # sub-stages to the same span)
                span.add_stage("encode", start=t_enc)
            vol = self._write_blob(t, mode, vol, bid, stripe)
            out.append(Blob(bid=bid, vid=vol.vid, size=size))
        return out

    def _put_pipelined(self, t, mode: int, blobs: list[bytes], first_bid: int,
                       window: int) -> list[Blob]:
        """Windowed encode->write pipeline (the tentpole): volume alloc and
        encode submission for blob i+1..i+W overlap blob i's shard fan-out,
        with at most `window` stripes in flight — so the codec never starves
        waiting on the network and the network never idles waiting on the
        codec. Blob order in the returned list is bid order regardless of
        completion order. A quorum failure aborts the window cleanly: stages
        not yet started are skipped (no orphaned writes, no repair-queue spam
        for blobs the client will never see), in-flight ones finish, and the
        first failing blob's error is raised."""
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        if span is not None:  # pipeline shape rides the span record
            span.set_tag("pipeline_window", window)
            span.set_tag("encode_ahead", self.encode_ahead)
        reg = registry("access")
        occ = reg.summary("put_pipeline_occupancy", buckets=BATCH_BUCKETS)
        abort = threading.Event()
        vols: list[VolumeInfo | None] = [None] * len(blobs)
        write_secs = [0.0] * len(blobs)

        def stage(i: int, enc_fut, vol: VolumeInfo, bid: int):
            if abort.is_set():
                raise _PipelineAborted()
            # the request span follows the stage onto the pipe worker so
            # codec/blobnode track entries keep landing on the PUT's trace
            if span is not None:
                trace.push_span(span)
            try:
                t_enc = time.perf_counter()
                stripe = enc_fut.result()
                if span is not None:
                    span.append_track_log("codec", start=t_enc)
                    # encode-ahead wait as THIS stage saw it (queue depth
                    # already bought most of it during older blobs' writes)
                    span.add_stage("encode", start=t_enc)
                if abort.is_set():
                    raise _PipelineAborted()
                t_w = time.perf_counter()
                vols[i] = self._write_blob(t, mode, vol, bid, stripe)
                write_secs[i] = time.perf_counter() - t_w
            except _PipelineAborted:
                raise
            except BaseException:
                abort.set()
                raise
            finally:
                if span is not None:
                    trace.pop_span()

        inflight: deque = deque()  # (blob index, stage future)
        first_err: tuple[int, Exception] | None = None

        def reap_oldest():
            nonlocal first_err
            i, f = inflight.popleft()
            try:
                f.result()
            except _PipelineAborted:
                pass
            except Exception as e:
                if first_err is None or i < first_err[0]:
                    first_err = (i, e)

        t_wall = time.perf_counter()
        # encodes run AHEAD of the write window (bounded by encode_ahead):
        # the codec service still gathers full device batches — submitting
        # encodes window-at-a-time would cap every batch at 2-4 jobs — while
        # blob i's stripe is on the wire and blob i+1..i+W's stages drain
        enc_futs: dict[int, object] = {}
        next_enc = 0

        def encode_up_to(limit: int):
            nonlocal next_enc
            while next_enc < min(limit, len(blobs)):
                enc_futs[next_enc] = self._encode_blob(t, blobs[next_enc])
                next_enc += 1

        ahead = max(window, self.encode_ahead)
        try:
            for i, blob in enumerate(blobs):
                while len(inflight) >= window:
                    reap_oldest()
                if abort.is_set():
                    break
                encode_up_to(i + ahead)
                # alloc for blob i rides the caller thread while blob i-1's
                # (and older, up to the window) fan-outs are still in flight
                t_alloc = time.perf_counter()
                vol = self._alloc_breaker.call(self.proxy.alloc_volume, mode)
                if span is not None:
                    span.append_track_log("proxy", start=t_alloc)
                    span.add_stage("alloc", start=t_alloc)
                inflight.append(
                    (i, self._pipe_pool.submit(stage, i, enc_futs.pop(i), vol,
                                               first_bid + i)))
                occ.observe(len(inflight))
        except BaseException:
            # a CALLER-side failure mid-window (alloc breaker open, cluster
            # can't place a volume) must honor the same abort contract as a
            # stage failure: stop unstarted stages, drain in-flight ones —
            # never leave workers writing blobs the client will not see.
            # A stage error collected while draining is the root cause (it
            # likely tripped the breaker the caller then hit) and wins.
            abort.set()
            while inflight:
                reap_oldest()
            self._cancel_encodes(enc_futs)
            if first_err is not None:
                raise first_err[1]
            raise
        while inflight:
            reap_oldest()
        if first_err is not None or abort.is_set():
            self._cancel_encodes(enc_futs)
        if first_err is not None:
            raise first_err[1]
        if abort.is_set() or any(v is None for v in vols):
            raise AccessError("put pipeline aborted")  # defensive: unreachable
        # realized overlap: sum of per-stripe write times over the wall clock
        # of the whole pipelined phase — >1.0 means stripes actually
        # overlapped on the wire, ~1.0 means the window degenerated to serial
        wall = time.perf_counter() - t_wall
        busy = sum(write_secs)
        if wall > 0 and busy > 0:
            reg.summary("put_overlap_ratio",
                        buckets=BATCH_BUCKETS).observe(busy / wall)
        reg.counter("put_pipeline_blobs").add(len(blobs))
        return [Blob(bid=first_bid + i, vid=vols[i].vid, size=len(b))
                for i, b in enumerate(blobs)]

    def _write_stripe(self, t, vol: VolumeInfo, bid: int, stripe: np.ndarray):
        from chubaofs_tpu.blobstore import trace
        from chubaofs_tpu.blobstore.blobnode import ChunkFull

        # the stripe-write fan-out is the blobnode hop as the gateway sees
        # it; one track entry covers the whole shard fan-out (stream_put.go
        # logs the same aggregate)
        span = trace.current_span()
        t_hop = time.perf_counter()
        deadline = time.monotonic() + self.write_deadline
        started = [False] * t.total

        def write_one(idx: int):
            started[idx] = True
            unit = vol.units[idx]
            if self._is_punished(unit.disk_id):
                raise DiskPunished(f"disk {unit.disk_id} punished")
            node = self.nodes[unit.node_id]
            sem = self._sem(unit.disk_id)
            budget = deadline - time.monotonic()
            if budget <= 0 or not sem.acquire(timeout=budget):
                # concurrency cap exhausted within the deadline: the disk is
                # wedged — punish it so later PUTs fail fast
                self.punish_disk(unit.disk_id, "cap_exhausted")
                raise DiskPunished(f"disk {unit.disk_id} at concurrency cap")
            try:
                chaos.failpoint("access.write_shard", node=unit.node_id)
                node.create_vuid(unit.vuid, unit.disk_id)
                node.put_shard(unit.vuid, bid, stripe[idx].tobytes())
            except ChunkFull:
                raise  # full != broken: rotate the volume, don't punish
            except Exception:
                self.punish_disk(unit.disk_id, "error")
                raise
            finally:
                sem.release()
            return idx

        futs = [self._pool.submit(self._try, write_one, i) for i in range(t.total)]
        results = []
        for idx, f in enumerate(futs):
            budget = deadline + 0.25 - time.monotonic()  # workers self-deadline
            try:
                results.append(f.result(timeout=max(0.01, budget)))
            except FutureTimeout:
                # a RUNNING write that outlives the deadline is the wedged-disk
                # signal (stream_put.go:343-346 punishDiskWith on timeout); a
                # task still queued behind a busy pool says nothing about its
                # disk — punishing it would blacklist healthy devices
                if started[idx]:
                    self.punish_disk(vol.units[idx].disk_id, "timeout")
                results.append(TimeoutError("stripe write deadline"))
        if span is not None:
            span.append_track_log("blobnode", start=t_hop)
            span.add_stage("write", start=t_hop)  # whole shard fan-out
        ok = {i for i, r in zip(range(t.total), results) if r is None}
        failed = sorted(set(range(t.total)) - ok)
        # quorum counts global-stripe shards only (stream_put.go:226,362:
        # maxWrittenIndex = N+M — local parities never satisfy the quorum)
        written = len([i for i in ok if i < t.global_count])
        if written < t.put_quorum and not self._one_dark_az(t, ok):
            if any(isinstance(r, ChunkFull) for r in results):
                raise VolumeFullError(f"volume {vol.vid} chunks full")
            raise QuorumError(
                f"wrote {written}/{t.global_count} global shards, quorum "
                f"{t.put_quorum}; failures: {failed}"
            )
        if failed:
            # queue missing shards for background repair (stream_put.go:377-397)
            self.proxy.send_shard_repair(vol.vid, bid, failed, "put_failed")

    @staticmethod
    def _one_dark_az(t, ok: set[int]) -> bool:
        """Tolerate exactly one fully-dark AZ at >=3 AZs, iff every other AZ is
        fully written (stream_put.go:405-437)."""
        if t.az_count < 3:
            return False
        all_fine = all_down = 0
        for az in range(t.az_count):
            idx = t.shards_in_az(az)
            wrote = sum(1 for i in idx if i in ok)
            if wrote == len(idx):
                all_fine += 1
            if wrote == 0:
                all_down += 1
        return all_fine == t.az_count - 1 and all_down == 1

    @staticmethod
    def _try(fn, *args):
        try:
            fn(*args)
            return None
        except Exception as e:
            return e

    # -- GET -----------------------------------------------------------------

    def get(self, loc: Location | str, offset: int = 0, size: int | None = None) -> bytes:
        from chubaofs_tpu.blobstore import trace

        if isinstance(loc, str):
            loc = Location.from_json(loc)
        if self.qos is not None:
            # charge the real read size: a default full-object get is loc.size
            want = size if size is not None else max(0, loc.size - offset)
            if not self.qos.wait("get", max(1, want), timeout=self.qos_timeout):
                registry("access").counter("qos_reject", {"op": "get"}).add()
                raise AccessError("get bandwidth limit exceeded")
        with trace.child_of(trace.current_span(), "access.get") as span, \
                registry("access").tp("get"):
            err: Exception | None = None
            try:
                return self._get(loc, offset, size)
            except Exception as e:
                err = e
                raise
            finally:
                span.append_track_log("access", err=err)
                record_slow_op("access", "get",
                               time.perf_counter() - span.start, span=span,
                               err=type(err).__name__ if err else "")

    def _get(self, loc: Location | str, offset: int = 0, size: int | None = None) -> bytes:
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        t_prep = time.perf_counter()
        if isinstance(loc, str):
            loc = Location.from_json(loc)
        self._check_sig(loc)
        if size is None:
            size = loc.size - offset
        if offset < 0 or size < 0 or offset + size > loc.size:
            raise AccessError(f"range [{offset}, {offset+size}) outside object of {loc.size}")
        # read-amp ledger (window bytes the CALLER asked for; the shard
        # reads below count what the backend actually moved for them —
        # cfs-top's RDAMP column is the window ratio of the two)
        registry("access").counter(
            "read_bytes", {"kind": "requested"}).add(size)

        segs = []  # (blob, intra-blob offset, length) the range touches
        pos = 0
        for blob in loc.blobs:
            blob_start, blob_end = pos, pos + blob.size
            pos = blob_end
            if blob_end <= offset or blob_start >= offset + size:
                continue
            lo = max(0, offset - blob_start)
            hi = min(blob.size, offset + size - blob_start)
            segs.append((blob, lo, hi - lo))
        if span is not None:  # location parse + sig check + range plan
            span.add_stage("prepare", start=t_prep)
        window = int(self.pipeline_window)
        if len(segs) > 1 and window >= 1:
            return self._get_readahead(loc.code_mode, segs, window)
        if len(segs) == 1:  # whole-blob/single-blob GET: no reassembly copy
            blob, lo, n = segs[0]
            return self._read_blob(loc.code_mode, blob, lo, n)
        out = bytearray()
        for blob, lo, n in segs:
            out += self._read_blob(loc.code_mode, blob, lo, n)
        return bytes(out)

    def _get_readahead(self, mode: int, segs: list, window: int) -> bytes:
        """Multi-blob ranged GET with readahead: the next blobs' shard
        gathers are prefetched on the pipe pool (their shard reads still ride
        the read pool) while the current blob's bytes are consumed, bounded
        by the same pipeline window as PUT. Byte order is segment order —
        results are consumed strictly FIFO however the gathers complete."""
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        reg = registry("access")
        occ = reg.summary("get_readahead_occupancy", buckets=BATCH_BUCKETS)

        def gather(blob, lo, n):
            if span is not None:
                trace.push_span(span)
            try:
                return self._read_blob(mode, blob, lo, n)
            finally:
                if span is not None:
                    trace.pop_span()

        q: deque = deque()
        nxt = 0
        out = bytearray()
        try:
            while q or nxt < len(segs):
                while nxt < len(segs) and len(q) < window:
                    q.append(self._pipe_pool.submit(gather, *segs[nxt]))
                    if nxt > 0:  # segment 0 is the current read, not readahead
                        reg.counter("get_readahead_prefetch").add()
                    nxt += 1
                occ.observe(len(q))
                out += q.popleft().result()
        except BaseException:
            for f in q:  # queued prefetches must not run for a dead request
                f.cancel()
            raise
        return bytes(out)

    def _read_blob(self, mode: int, blob: Blob, offset: int, size: int) -> bytes:
        """Tiered read: cache -> hot Replica3 copy -> EC cold path. Every
        lookup feeds the cache's heat accounting; blobs that cross the
        promote threshold are reported to the hot-blob topic, where the
        scheduler's tier sweep copies them into the replica engine."""
        cache = self.cache
        fill_ver = None
        f_lo, f_len = offset, size
        if cache is not None:
            cached = cache.get(blob.vid, blob.bid, offset, size)
            if cache.promote_signal(blob.vid, blob.bid):
                try:
                    self.proxy.send_blob_hot(blob.vid, blob.bid, blob.size)
                except Exception:
                    pass  # advisory: lost heat re-accumulates next epoch
            if cached is not None and len(cached) == size:
                return bytes(cached)
            # version captured BEFORE the backend read: a DELETE racing
            # this miss invalidates the version and the fill is dropped.
            # The backend window is rounded OUT to cache-block boundaries
            # (clipped to the blob) so a ranged miss fills exactly the
            # blocks it touches — the next overlapping range hits.
            fill_ver = cache.fill_version(blob.vid, blob.bid)
            blk = cache.block
            f_lo = (offset // blk) * blk
            f_len = min(blob.size,
                        ((offset + size + blk - 1) // blk) * blk) - f_lo
        hot = self.cm.hot_location(blob.vid, blob.bid)
        if hot is not None:
            data = self._read_blob_hot(hot, f_lo, f_len)
            if data is not None:
                if fill_ver is not None:
                    cache.fill(blob.vid, blob.bid, fill_ver, data,
                               offset=f_lo, total=blob.size)
                return (data if f_len == size
                        else data[offset - f_lo: offset - f_lo + size])
        data = self._read_blob_ec(mode, blob, f_lo, f_len)
        if fill_ver is not None:
            cache.fill(blob.vid, blob.bid, fill_ver, data,
                       offset=f_lo, total=blob.size)
        return (data if f_len == size
                else data[offset - f_lo: offset - f_lo + size])

    def _read_blob_hot(self, hot: tuple[int, int], offset: int,
                       size: int) -> bytes | None:
        """One direct read of the Replica3 copy's data shard (shard 0 IS the
        blob bytes — systematic RS(1,2), exact-size shards). Any failure
        falls back to the authoritative EC copy: the hot tier accelerates,
        it never gates availability."""
        hot_vid, hot_bid = hot
        reg = registry("cache")
        try:
            vol = self.cm.get_volume(hot_vid)
            unit = vol.units[0]
            node = self.nodes.get(unit.node_id)
            if node is None:
                raise ConnectionError(f"hot node {unit.node_id} unknown")
            chaos.failpoint("access.read_shard", node=unit.node_id)
            data = node.get_shard(unit.vuid, hot_bid, offset=offset, size=size)
            if len(data) != size:
                raise AccessError("short hot read")
        except Exception:
            reg.counter("tier_fallbacks").add()
            return None
        reg.counter("tier_hits").add()
        registry("access").counter(
            "read_bytes", {"kind": "shards_read"}).add(size)
        return bytes(data)

    def _read_blob_ec(self, mode: int, blob: Blob, offset: int, size: int) -> bytes:
        t = get_tactic(mode)
        vol = self.cm.get_volume(blob.vid)
        shard_len = t.shard_size(blob.size)

        # fast path: ranged sub-shard reads of only the data shards the byte
        # range touches (blobnode serves CRC-framed sub-ranges natively),
        # issued CONCURRENTLY — a full-stripe GET pays one shard's latency,
        # not N of them (stream_get.go fans reads out the same way)
        first_shard = offset // shard_len
        last_shard = (offset + size - 1) // shard_len

        def read_one(idx: int):
            lo = max(offset, idx * shard_len) - idx * shard_len
            hi = min(offset + size, (idx + 1) * shard_len) - idx * shard_len
            return self._read_shard(vol, idx, blob.bid, lo, hi - lo)

        # every direct read races a deadline: a shard that cannot answer in
        # read_deadline (wedged node/disk) is treated as missing and the
        # degraded path reconstructs around it — the stall is bounded even
        # when the node never errors (stream_get races laggards the same way)
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        t_hop = time.perf_counter()
        idxs = list(range(first_shard, last_shard + 1))
        futs = [self._read_pool.submit(read_one, i) for i in idxs]
        deadline = time.monotonic() + self.read_deadline
        pieces = []
        slow: set[int] = set()  # timed out, node possibly wedged
        for i, f in zip(idxs, futs):
            try:
                pieces.append(f.result(timeout=max(0.0, deadline - time.monotonic())))
            except FutureTimeout:
                pieces.append(None)
                slow.add(i)
        if span is not None:
            span.append_track_log("blobnode", start=t_hop)
        if all(p is not None for p in pieces):
            data = b"".join(pieces)
            if span is not None:  # fan-out + reassembly: the read stage
                span.add_stage("read", start=t_hop)
            return data
        if span is not None:
            span.add_stage("read", start=t_hop)  # the failed direct attempt
        for f in futs:  # queued laggards must not hold pool workers
            f.cancel()
        # hand the degraded path everything the direct phase learned: the
        # sub-range bytes it DID read (reused verbatim — never refetched),
        # the shards that errored (excluded from the survivor gather), and
        # the ones that hung (deprioritized, probed asynchronously)
        have = {i: p for i, p in zip(idxs, pieces) if p is not None}
        failed_direct = {i for i, p in zip(idxs, pieces)
                         if p is None and i not in slow}
        return self._read_blob_degraded(t, vol, blob, shard_len, offset, size,
                                        have=have, failed=failed_direct,
                                        deprioritize=slow)

    def _recover_locals_inplace(self, t, vol, blob, stripe, present: list,
                                shard_len: int,
                                deadline: float | None = None) -> None:
        """Repair missing GLOBAL shards via their AZ-local stripes, updating
        stripe/present in place. Each AZ is independent: damage within an
        AZ's local-parity budget is fixed reading ONLY that AZ's shards.
        `deadline` (monotonic) bounds the parity fetches: this runs on the
        latency-critical degraded path, so a wedged local-parity holder is
        abandoned like any other straggler, never waited out."""
        pres = set(present)
        for idx_list, local_n, local_m in t.local_stripes():
            globals_in_az = [g for g in idx_list if g < t.N + t.M]
            recoverable = [g for g in globals_in_az if g not in pres]
            if not recoverable:
                continue  # nothing this AZ's stripe could win back
            locals_in_az = [g for g in idx_list if g >= t.N + t.M]
            az_reads: dict[int, np.ndarray] = {
                g: stripe[g] for g in globals_in_az if g in pres
            }
            futs = {g: self._read_pool.submit(
                self._read_shard, vol, g, blob.bid, 0, shard_len)
                for g in locals_in_az}
            for g, fut in futs.items():
                budget = (max(0.0, deadline - time.monotonic())
                          if deadline is not None else None)
                try:
                    data = fut.result(timeout=budget)
                except FutureTimeout:
                    fut.cancel()
                    continue
                if data is not None:
                    az_reads[g] = np.frombuffer(data, np.uint8)
            az_bad = [g for g in idx_list if g not in az_reads]
            if len(az_bad) > local_m:
                continue
            sub = np.zeros((len(idx_list), shard_len), np.uint8)
            pos = {g: p for p, g in enumerate(idx_list)}
            for g, d in az_reads.items():
                sub[pos[g]] = d
            fixed = self.codec.reconstruct(
                local_n, local_m, sub, [pos[g] for g in az_bad]
            ).result()
            for g in recoverable:
                stripe[g] = fixed[pos[g]]
                present.append(g)

    def _read_shard(
        self, vol: VolumeInfo, idx: int, bid: int, offset: int, size: int,
        count: bool = True,
    ) -> bytes | None:
        from chubaofs_tpu.blobstore.blobnode import classify_io_error

        unit = vol.units[idx]
        node = self.nodes.get(unit.node_id)
        if node is None:
            registry("access").counter(
                "read_fail", {"reason": "no_node"}).add()
            return None
        try:
            chaos.failpoint("access.read_shard", node=unit.node_id)
            data = node.get_shard(unit.vuid, bid, offset=offset, size=size)
            if len(data) != size:
                registry("access").counter(
                    "read_fail", {"reason": "short"}).add()
                return None
            if count:
                # count=False for background probes: read_amp measures bytes
                # moved ON BEHALF OF the GET window, not repair-plane sweeps
                registry("access").counter(
                    "read_bytes", {"kind": "shards_read"}).add(size)
            return data
        except Exception as e:
            # the caller's contract stays None-on-failure (degraded path
            # reconstructs around it) but the CLASS of failure is no longer
            # discarded: a fleet of {timeout}s and a fleet of {error}s need
            # different pages (same taxonomy as scheduler probe_fail)
            registry("access").counter(
                "read_fail", {"reason": classify_io_error(e)}).add()
            return None

    def _read_blob_degraded(self, t, vol, blob, shard_len, offset, size,
                            have: dict[int, bytes] | None = None,
                            failed: set[int] | None = None,
                            deprioritize: set[int] | None = None) -> bytes:
        """Degraded read, range-scoped first: reconstruct ONLY the in-window
        shards the direct phase could not serve, from a survivor gather over
        just the window's byte columns (row-sliced decode matrix — decode
        cost scales with the window, not the stripe). Deep damage — the
        global stripe can't reach N survivors, so AZ-local parities are
        needed — falls back to the full-stripe gather, which itself launches
        only the survivors it selects (never the old `read_hedge`-deep
        speculative parity fan-out). Read-only: durable healing stays with
        the repair plane via the shard-repair topic."""
        have = dict(have or {})
        slow = set(deprioritize or ())
        failed = set(failed or ())
        if t.is_regenerating:
            # PM sub-unit layout: a shard-byte window couples to a column
            # range in EVERY one of the survivor's alpha sub-units, which
            # the single-range windowed gather can't express — regenerating
            # stripes take the full-stripe path (any-N decode) directly
            out = None
        else:
            out = self._degraded_window(t, vol, blob, shard_len, offset,
                                        size, have, slow, failed)
        if out is not None:
            return out
        return self._degraded_full(t, vol, blob, shard_len, offset, size,
                                   slow)

    def _gather_survivors(self, vol, bid: int, candidates: list[int],
                          needed: int, lo: int,
                          n: int) -> tuple[dict[int, bytes], list[int]]:
        """Hedged sub-range gather of exactly `needed` shard reads from
        `candidates` (preference order). Only the reads the selection wants
        are ever launched — a FAILED read immediately launches the next
        candidate to keep gather depth, and a read silent past read_deadline
        launches a hedge replacement while the original keeps running (slow-
        but-alive may still answer first) — so unselected candidates (the
        parity tail of the list) are never fetched unless a selected read
        lets the gather down. Returns (idx -> bytes, failed idxs)."""
        from concurrent.futures import FIRST_COMPLETED, wait

        got: dict[int, bytes] = {}
        failures: list[int] = []
        if needed <= 0:
            return got, failures
        pending: dict = {}
        launched: dict = {}  # future -> launch time (hang-hedge input)
        hedged: set = set()  # futures already replaced for being slow
        next_i = 0

        def launch() -> None:
            nonlocal next_i
            if next_i >= len(candidates):
                return
            idx = candidates[next_i]
            next_i += 1
            f = self._read_pool.submit(self._read_shard, vol, idx, bid, lo, n)
            pending[f] = idx
            launched[f] = time.monotonic()

        for _ in range(min(needed, len(candidates))):
            launch()
        # overall gather budget: stragglers can be slow-but-alive, so this
        # is the generous write_deadline, not the per-read read_deadline
        gather_deadline = time.monotonic() + self.write_deadline
        while pending and len(got) < needed:
            # wake for the earliest of: gather budget, or the moment an
            # un-hedged in-flight read crosses read_deadline
            now = time.monotonic()
            timeout = gather_deadline - now
            nxt_slow = min((launched[f] + self.read_deadline
                            for f in pending if f not in hedged), default=None)
            if nxt_slow is not None:
                timeout = min(timeout, nxt_slow - now)
            done, _ = wait(pending, return_when=FIRST_COMPLETED,
                           timeout=max(0.0, timeout))
            if not done:
                now = time.monotonic()
                if now >= gather_deadline:
                    break  # budget exhausted: abandon what never answered
                # an in-flight read exceeded read_deadline without FAILING —
                # a hung-but-silent replica. Launch a replacement from the
                # not-yet-tried candidates (the original keeps running), so
                # gather depth holds against hangs exactly as against
                # failures.
                for f in list(pending):
                    if (f in hedged
                            or now - launched[f] < self.read_deadline):
                        continue
                    hedged.add(f)
                    launch()
                continue
            for fut in done:
                idx = pending.pop(fut)
                launched.pop(fut, None)
                was_hedged = fut in hedged  # replacement already launched
                hedged.discard(fut)
                data = fut.result()
                if data is not None:
                    got[idx] = data
                else:
                    failures.append(idx)
                    if not was_hedged:
                        launch()  # keep gather depth
        for fut in pending:  # abandon stragglers (queued ones cancel cleanly)
            fut.cancel()
        return got, failures

    def _degraded_window(self, t, vol, blob, shard_len, offset, size,
                         have: dict[int, bytes], slow: set[int],
                         failed_direct: set[int]) -> bytes | None:
        """Range-scoped degraded read: decode ONLY the in-window shards the
        direct phase is missing, over only the window's byte columns. RS is
        column-independent, so t.N survivor rows sliced to the SAME columns
        decode the missing rows' slice exactly (RSKernel.window_matrix).
        Returns None when the gather can't reach N global survivors — deep
        damage, which the full-stripe path (with AZ-local recovery) owns."""
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        t_gather = time.perf_counter()
        first = offset // shard_len
        last = (offset + size - 1) // shard_len

        def window_of(idx: int) -> tuple[int, int]:
            lo = max(offset, idx * shard_len) - idx * shard_len
            hi = min(offset + size, (idx + 1) * shard_len) - idx * shard_len
            return lo, hi

        need = [i for i in range(first, last + 1) if i not in have]
        # the union byte-column window the decode must cover
        col_lo = min(window_of(i)[0] for i in need)
        col_hi = max(window_of(i)[1] for i in need)
        width = col_hi - col_lo
        # survivors the direct phase already fetched, column-sliced — only
        # reads fully covering the decode window count (edge shards of the
        # byte range may cover less; those shards just aren't reused)
        reuse: dict[int, bytes] = {}
        for i, data in have.items():
            lo_i, hi_i = window_of(i)
            if lo_i <= col_lo and hi_i >= col_hi:
                reuse[i] = data[col_lo - lo_i: col_hi - lo_i]
        # candidates in preference order: untouched data shards first, then
        # parity; shards that just FAILED are excluded, known-slow go last.
        # The gather fetches exactly the survivors it selects — unselected
        # parity is never read (no speculative parity fan-out).
        candidates = [i for i in range(t.N + t.M)
                      if i not in reuse and i not in failed_direct
                      and i not in need]
        candidates.sort(key=lambda i: (i in slow, i))
        got, gather_failed = self._gather_survivors(
            vol, blob.bid, candidates, t.N - len(reuse), col_lo, width)
        got.update(reuse)
        if span is not None:
            span.add_stage("gather", start=t_gather)  # windowed sub-reads
        if len(got) < t.N:
            return None  # the full path re-proves and reports damage
        present = sorted(got)[: t.N]
        survivors = np.stack(
            [np.frombuffer(got[i], np.uint8) for i in present])
        t_dec = time.perf_counter()
        rows = self.codec.decode_rows(t.N, t.M, present, survivors,
                                      need).result()
        registry("access").counter(
            "read_bytes", {"kind": "decoded"}).add(len(need) * width)
        if span is not None:
            span.add_stage("decode", start=t_dec)  # row-sliced window decode
        # assemble: verbatim direct-phase bytes, decoded rows sliced to each
        # missing shard's own sub-window
        rowpos = {i: p for p, i in enumerate(need)}
        out = bytearray()
        for i in range(first, last + 1):
            if i in have:
                out += have[i]
            else:
                lo_i, hi_i = window_of(i)
                out += rows[rowpos[i],
                            lo_i - col_lo: hi_i - col_lo].tobytes()
        # the repair plane must hear what this read PROVED damaged; shards
        # it never touched are probed asynchronously (off the latency path)
        # so ranged reads don't narrow get_miss-driven healing
        damaged = sorted(failed_direct | set(gather_failed))
        self.proxy.send_shard_repair(vol.vid, blob.bid, damaged, "get_miss")
        touched = set(got) | set(have) | set(damaged)
        self._probe_unread(t, vol, blob, shard_len,
                           [i for i in range(t.N + t.M) if i not in touched])
        return bytes(out)

    def _degraded_full(self, t, vol, blob, shard_len, offset, size,
                       slow: set[int]) -> bytes:
        """Full-stripe degraded gather (stream_get.go:427 ReconstructData
        fallback) — the deep-damage path: whole shards are read because
        AZ-local stripes repair whole shards. The gather still launches only
        the t.N survivors it selects (failure replacement + hang-hedge per
        read); parity beyond the selection stays unread. When the global
        stripe alone can't reach N and the mode carries local parities,
        AZ-local stripes are tried next (work_shard_recover.go:517
        recoverByLocalStripe applied at READ time)."""
        from chubaofs_tpu.blobstore import trace

        span = trace.current_span()
        t_gather = time.perf_counter()
        total = t.N + t.M
        # data shards first (they skip the matmul); known-wedged ones last
        order = sorted(range(total), key=lambda i: (i in slow, i))
        gather_deadline = time.monotonic() + self.write_deadline
        got, failed = self._gather_survivors(vol, blob.bid, order, t.N,
                                             0, shard_len)
        stripe = np.zeros((total, shard_len), np.uint8)
        present: list[int] = []
        for i, data in got.items():
            stripe[i] = np.frombuffer(data, np.uint8)
            present.append(i)
        if span is not None:
            span.add_stage("gather", start=t_gather)  # hedged stripe reads
        # the repair plane must hear about everything the gather PROVED
        # damaged — including shards the local-stripe pass then fixes only
        # in memory (they are still broken on disk). Shards the hedge never
        # reached are probed ASYNCHRONOUSLY (off the latency path), so
        # hedging does not narrow get_miss-driven healing vs a full gather.
        damaged = sorted(failed)
        if len(present) < t.N and getattr(t, "L", 0):
            self._recover_locals_inplace(t, vol, blob, stripe, present,
                                         shard_len, deadline=gather_deadline)
        missing = [i for i in range(t.N + t.M) if i not in present]
        if len(present) < t.N:
            raise AccessError(
                f"blob {blob.bid}: only {len(present)} shards readable, need {t.N}"
            )
        t_dec = time.perf_counter()
        fixed = self.codec.reconstruct_tactic(
            t, stripe, missing, data_only=True).result()
        registry("access").counter("read_bytes", {"kind": "decoded"}).add(
            sum(shard_len for i in missing if i < t.N))
        if span is not None:
            span.add_stage("decode", start=t_dec)  # on-the-fly reconstruct
        self.proxy.send_shard_repair(vol.vid, blob.bid, damaged, "get_miss")
        self._probe_unread(t, vol, blob, shard_len,
                           [i for i in range(total)
                            if i not in present and i not in failed])
        data_region = fixed[: t.N].reshape(-1)
        return data_region[offset : offset + size].tobytes()

    def _probe_unread(self, t, vol, blob, shard_len,
                      unprobed: list[int]) -> None:
        """Launch the async integrity probe for shards a degraded read never
        touched. Probes ride their OWN executor (never the PUT/write pool: a
        wedged blobnode would pin write workers and stall unrelated stripe
        writes) and dedupe per (vid, bid): a burst of degraded GETs of one
        hot blob probes it once."""
        if not unprobed:
            return
        key = (vol.vid, blob.bid)
        with self._probe_lock:
            fresh = key not in self._probing
            if fresh:
                self._probing.add(key)
        if fresh:
            self._probe_pool.submit(self._probe_shards, t, vol, blob,
                                    shard_len, unprobed)

    def _probe_shards(self, t, vol, blob, shard_len, idxs: list[int]) -> None:
        """Background integrity probe of shards a hedged gather skipped or
        abandoned: full CRC-framed reads, failures reported to the repair
        plane. Keeps get_miss healing as wide as the old full-stripe gather
        without ever charging the GET's latency. Every read is bounded by
        read_deadline — a wedged node makes the probe REPORT, not hang."""
        try:
            futs = {self._probe_io.submit(
                self._read_shard, vol, i, blob.bid, 0, shard_len, False): i
                for i in idxs}
            bad = []
            for fut, i in futs.items():
                try:
                    data = fut.result(timeout=self.read_deadline)
                except FutureTimeout:
                    if fut.cancel():
                        # never started (probe-pool backlog): its health is
                        # UNKNOWN, not bad — the scrub sweeps cover it; a
                        # repair message here would heal shards nobody read
                        continue
                    data = None  # ran past its deadline: wedged, report it
                if data is None:
                    bad.append(i)
            if bad:
                try:
                    self.proxy.send_shard_repair(vol.vid, blob.bid, bad,
                                                 "get_probe")
                except Exception:
                    pass  # scrub/inspector sweeps remain the durable backstop
        finally:
            with self._probe_lock:
                self._probing.discard((vol.vid, blob.bid))

    # -- DELETE --------------------------------------------------------------

    def delete(self, loc: Location | str) -> None:
        if isinstance(loc, str):
            loc = Location.from_json(loc)
        self._check_sig(loc)
        for blob in loc.blobs:
            # write-through punch-out BEFORE the async delete fans out: once
            # invalidate returns (however long a chaos failpoint stretches
            # it), no cached copy is reachable — so by the time the deleter
            # punches shards, a GET can only see the backend's truth
            if self.cache is not None:
                self.cache.invalidate(blob.vid, blob.bid)
            self.proxy.send_blob_delete(blob.vid, blob.bid)

    def close(self) -> None:
        """Shut down the gateway's worker pools (racelint: unjoined-thread).
        wait=False: a wedged blobnode may pin a write worker up to
        write_deadline, and close() runs on teardown paths (MiniCluster,
        daemon reload) that must not inherit that stall; in-flight futures
        fail on their own deadlines."""
        self._pipe_pool.shutdown(wait=False)
        self._pool.shutdown(wait=False)
        self._read_pool.shutdown(wait=False)
        self._probe_pool.shutdown(wait=False)
        self._probe_io.shutdown(wait=False)
