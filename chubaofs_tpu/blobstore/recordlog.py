"""Task record log (blobstore/common/recordlog analog).

Reference counterpart: common/recordlog — the scheduler appends one JSON record
per finished background task (migrate/repair/delete) to a rotating file so
operators can audit what moved where; consumed by cli tooling. JSON-per-line
encoding over the shared RotatingFile rotor (utils/auditlog.py).
"""

from __future__ import annotations

import json

from chubaofs_tpu.utils.auditlog import RotatingFile


class RecordLog:
    def __init__(self, logdir: str, name: str = "record",
                 max_bytes: int = 4 << 20, backups: int = 4):
        self._rotor = RotatingFile(logdir, name, max_bytes, backups)

    def encode(self, record: dict):
        self._rotor.write_line(json.dumps(record, separators=(",", ":")))

    def records(self) -> list[dict]:
        """Read back every retained record, oldest first, across rotations."""
        return [json.loads(line) for line in self._rotor.read_lines()]

    def close(self):
        self._rotor.close()
