"""The erasure-coded blob store: access gateway, clustermgr, blobnode, proxy,
scheduler — equivalents of reference blobstore/* re-designed around the TPU
codec service (chubaofs_tpu/codec/service.py)."""
