"""Access HTTP gateway — the network face of the blobstore access layer.

Reference counterpart: blobstore/access/service.go (HTTP PUT/GET/DELETE
stream API) + api/access/client.go:248,388 (the typed client every consumer
uses). Kept: the three-verb surface (put returns a signed Location token the
caller must present back; get takes a byte range; delete is fire-and-ack),
JSON Location bodies, and a client whose put/get/delete signature matches the
in-process `Access` object so `sdk/data/blobstore`-style consumers are
transport-blind. Changed: the reference streams multi-blob bodies with
chunked encoding; blobs here ride whole HTTP bodies (the codec service under
the gateway already batches stripes for the TPU)."""

from __future__ import annotations

from chubaofs_tpu.blobstore.access import Access, AccessError, Location
from chubaofs_tpu.rpc.client import RPCClient
from chubaofs_tpu.rpc.errors import HTTPError
from chubaofs_tpu.rpc.router import Request, Response, Router
from chubaofs_tpu.rpc.server import RPCServer


def parse_http_range(rng: str, size: int) -> tuple[int, int] | None:
    """`bytes=lo-hi` / `bytes=lo-` / `bytes=-N` -> (offset, length), clipped
    to the object. None means syntactically valid but unsatisfiable (RFC
    9110: the caller answers 416); malformed raises ValueError (400)."""
    if not rng.startswith("bytes="):
        raise ValueError(f"unsupported range unit: {rng}")
    lo_s, dash, hi_s = rng[len("bytes="):].partition("-")
    if not dash or (not lo_s and not hi_s):
        raise ValueError(f"malformed range: {rng}")
    if lo_s == "":  # suffix form bytes=-N: the last N bytes
        length = int(hi_s)
        if length <= 0:
            return None
        lo = max(0, size - length)
        hi = size - 1
    else:
        lo = int(lo_s)
        hi = int(hi_s) if hi_s else size - 1
    if lo >= size or lo > hi:
        return None
    hi = min(hi, size - 1)
    return lo, hi - lo + 1


def build_router(access: Access) -> Router:
    r = Router()

    def put(req: Request):
        try:
            loc = access.put(req.body)
        except AccessError as e:
            raise HTTPError(500, msg=str(e), code="AccessError") from None
        return Response(200, {"Content-Type": "application/json"},
                        loc.to_json().encode())

    def get(req: Request):
        loc = req.q("location")
        rng = req.header("range")
        if rng:
            # HTTP Range surface (the S3-shaped path): 206 + Content-Range,
            # 416 on an unsatisfiable window — the ranged read underneath is
            # the byte-window shard gather, so the wire AND the backend both
            # move window bytes only
            try:
                obj_size = Location.from_json(loc).size
            except Exception:
                raise HTTPError(400, msg="bad location token",
                                code="LocationError") from None
            try:
                parsed = parse_http_range(rng, obj_size)
            except ValueError as e:
                raise HTTPError(400, msg=str(e), code="InvalidRange") from None
            if parsed is None:
                return Response(416, {"Content-Range": f"bytes */{obj_size}"})
            offset, size = parsed
            try:
                data = access.get(loc, offset, size)
            except AccessError as e:
                raise HTTPError(404, msg=str(e), code="AccessError") from None
            return Response(
                206,
                {"Content-Type": "application/octet-stream",
                 "Content-Range":
                     f"bytes {offset}-{offset + size - 1}/{obj_size}"},
                data)
        offset = int(req.q("offset", "0"))
        size = int(req.q("size", "-1"))
        try:
            data = access.get(loc, offset, None if size < 0 else size)
        except AccessError as e:
            raise HTTPError(404, msg=str(e), code="AccessError") from None
        return Response(200, {"Content-Type": "application/octet-stream"}, data)

    def delete(req: Request):
        try:
            access.delete(req.body.decode())
        except AccessError as e:
            raise HTTPError(500, msg=str(e), code="AccessError") from None
        return Response(200)

    def get_by_body(req: Request):
        # the Location token is long; it rides the body of a POST /get
        import json

        body = json.loads(req.body.decode())
        offset = int(body.get("offset", 0))
        size = int(body.get("size", -1))
        try:
            data = access.get(body["location"], offset,
                              None if size < 0 else size)
        except AccessError as e:
            raise HTTPError(404, msg=str(e), code="AccessError") from None
        return Response(200, {"Content-Type": "application/octet-stream"}, data)

    r.put("/put", put)
    r.post("/get", get_by_body)
    r.get("/get", get)
    r.post("/delete", delete)
    return r


class AccessGateway:
    """Standalone access server. `router_hook(router)` lets the caller mount
    extra routes (the blobstore daemon adds its admin surface this way)."""

    def __init__(self, access: Access, host: str = "127.0.0.1", port: int = 0,
                 router_hook=None):
        router = build_router(access)
        if router_hook is not None:
            router_hook(router)
        self.server = RPCServer(router, host=host, port=port, module="access")
        self.server.start()
        self.addr = self.server.addr

    def stop(self):
        self.server.stop()


class AccessClient:
    """api/access client analog; mirrors the in-process Access surface.
    `pooled=False` forces connect-per-request (the perfbench A/B control)."""

    def __init__(self, hosts: list[str], retries: int = 3, pooled: bool = True):
        self.rpc = RPCClient(hosts, retries=retries, pooled=pooled)

    def put(self, data: bytes) -> Location:
        status, _, body = self.rpc.do("PUT", "/put", data)
        if status != 200:
            raise AccessError(body.decode() or f"put failed: {status}")
        return Location.from_json(body.decode())

    def get(self, loc: Location | str, offset: int = 0,
            size: int | None = None) -> bytes:
        import json

        token = loc.to_json() if isinstance(loc, Location) else loc
        payload = json.dumps({"location": token, "offset": offset,
                              "size": -1 if size is None else size}).encode()
        status, _, body = self.rpc.do("POST", "/get", payload)
        if status != 200:
            raise AccessError(body.decode() or f"get failed: {status}")
        return body

    def get_range(self, loc: Location | str,
                  rng: str) -> tuple[int, dict, bytes]:
        """HTTP `Range:` GET — returns the raw (status, headers, body) so
        the caller sees 206/416 and Content-Range, the contract an S3-style
        frontend proxies through verbatim."""
        import urllib.parse

        token = loc.to_json() if isinstance(loc, Location) else loc
        return self.rpc.do(
            "GET", f"/get?location={urllib.parse.quote(token, safe='')}",
            headers={"Range": rng})

    def delete(self, loc: Location | str) -> None:
        token = loc.to_json() if isinstance(loc, Location) else loc
        status, _, body = self.rpc.do("POST", "/delete", token.encode())
        if status != 200:
            raise AccessError(body.decode() or f"delete failed: {status}")
